"""etcdctl: the command-line client (ref: etcdctl/ctlv3/ctl.go and
etcdctl/ctlv3/command/*.go — put/get/del/txn/watch/compaction, lease,
member, endpoint, snapshot, lock/elect, move-leader, defrag, alarm,
auth/user/role, check perf, make-mirror, version; output printers
simple/json/table per command/printer.go).

`python -m etcd_tpu.etcdctl <cmd> ...`; `main(argv)` for in-proc use.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import version as ver
from ..client.client import Client, ClientError
from ..client.util import prefix_end as _prefix_end
from ..server import api as sapi


class CtlError(Exception):
    pass


def _parse_endpoints(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "://" in part:
            part = part.split("://", 1)[1]
        host, port = part.rsplit(":", 1)
        out.append((host, int(port)))
    if not out:
        raise CtlError("no endpoints")
    return out




# -- printers (etcdctl/ctlv3/command/printer*.go) ------------------------------


class Printer:
    def __init__(self, fmt: str, hex_: bool = False) -> None:
        self.fmt = fmt
        self.hex = hex_

    def _b(self, b: bytes) -> str:
        return b.hex() if self.hex else b.decode("utf-8", "replace")

    def _json(self, obj: Any) -> None:
        from ..v3rpc.wire import enc

        print(json.dumps(enc(obj) if not isinstance(obj, (dict, list)) else obj))

    def kv(self, kv: sapi.KeyValue, value_only: bool = False) -> None:
        if value_only:
            print(self._b(kv.value))
        else:
            print(self._b(kv.key))
            print(self._b(kv.value))

    def get(self, resp: sapi.RangeResponse, opts) -> None:
        if self.fmt == "json":
            self._json(resp)
            return
        if opts.count_only:
            print(resp.count)
            return
        if self.fmt == "fields":
            for kv in resp.kvs:
                print(f'"Key" : "{self._b(kv.key)}"')
                print(f'"CreateRevision" : {kv.create_revision}')
                print(f'"ModRevision" : {kv.mod_revision}')
                print(f'"Version" : {kv.version}')
                print(f'"Value" : "{self._b(kv.value)}"')
                print(f'"Lease" : {kv.lease}')
            return
        for kv in resp.kvs:
            if opts.keys_only:
                print(self._b(kv.key))
            else:
                self.kv(kv, value_only=opts.print_value_only)

    def put(self, resp: sapi.PutResponse) -> None:
        if self.fmt == "json":
            self._json(resp)
            return
        print("OK")
        if resp.prev_kv is not None:
            self.kv(resp.prev_kv)

    def delete(self, resp: sapi.DeleteRangeResponse) -> None:
        if self.fmt == "json":
            self._json(resp)
            return
        print(resp.deleted)
        for kv in resp.prev_kvs:
            self.kv(kv)

    def txn(self, resp: sapi.TxnResponse) -> None:
        if self.fmt == "json":
            self._json(resp)
            return
        print("SUCCEEDED" if resp.succeeded else "FAILURE")
        for op in resp.responses:
            if op.response_range is not None:
                self.get(op.response_range, argparse.Namespace(
                    count_only=False, keys_only=False, print_value_only=False
                ))
            elif op.response_put is not None:
                self.put(op.response_put)
            elif op.response_delete_range is not None:
                self.delete(op.response_delete_range)

    def members(self, members: List[Dict]) -> None:
        if self.fmt == "json":
            self._json({"members": members})
            return
        if self.fmt == "table":
            hdr = ["ID", "NAME", "PEER ADDRS", "IS LEARNER"]
            rows = [
                [f"{m.get('id', 0):x}", m.get("name", ""),
                 ",".join(m.get("peer_urls", [])),
                 str(bool(m.get("is_learner", False))).lower()]
                for m in members
            ]
            _table(hdr, rows)
            return
        for m in members:
            print(
                f"{m.get('id', 0):x}, started, {m.get('name', '')}, "
                f"{','.join(m.get('peer_urls', []))}, "
                f"{str(bool(m.get('is_learner', False))).lower()}"
            )

    def status(self, ep: str, st: Dict) -> None:
        if self.fmt == "json":
            self._json([{"Endpoint": ep, "Status": st}])
            return
        hdr = ["ENDPOINT", "ID", "IS LEADER", "RAFT TERM",
               "RAFT INDEX", "RAFT APPLIED INDEX", "DB SIZE"]
        rows = [[
            ep, f"{st.get('member_id', 0):x}",
            str(bool(st.get("is_leader", False))).lower(),
            str(st.get("raft_term", 0)), str(st.get("committed_index", 0)),
            str(st.get("applied_index", 0)), str(st.get("db_size", 0)),
        ]]
        _table(hdr, rows)


def _table(hdr: List[str], rows: List[List[str]]) -> None:
    widths = [
        max(len(hdr[i]), *(len(r[i]) for r in rows)) if rows else len(hdr[i])
        for i in range(len(hdr))
    ]

    def line(ch: str = "-", junction: str = "+") -> str:
        return junction + junction.join(ch * (w + 2) for w in widths) + junction

    def fmt_row(cells: List[str]) -> str:
        return "| " + " | ".join(
            c.ljust(w) for c, w in zip(cells, widths)
        ) + " |"

    print(line())
    print(fmt_row(hdr))
    print(line())
    for r in rows:
        print(fmt_row(r))
    print(line())


# -- txn grammar (etcdctl/ctlv3/command/txn_command.go) ------------------------


def parse_txn(lines: List[str]) -> sapi.TxnRequest:
    """Three blank-line-separated stanzas: compares, success ops,
    failure ops."""
    stanzas: List[List[str]] = [[]]
    for ln in lines:
        ln = ln.strip()
        if not ln:
            if stanzas[-1]:
                stanzas.append([])
            continue
        if ln.startswith(("compares:", "success requests", "failure requests")):
            continue
        stanzas[-1].append(ln)
    while stanzas and not stanzas[-1]:
        stanzas.pop()
    while len(stanzas) < 3:
        stanzas.append([])
    cmps, succ, fail = stanzas[0], stanzas[1], stanzas[2]
    return sapi.TxnRequest(
        compare=[_parse_compare(c) for c in cmps],
        success=[_parse_op(o) for o in succ],
        failure=[_parse_op(o) for o in fail],
    )


def _parse_compare(line: str) -> sapi.Compare:
    import re

    m = re.match(
        r'(value|version|mod|create|c_rev|m_rev|lease)\("([^"]*)"\)\s*'
        r"(=|!=|<|>)\s*\"?([^\"]*)\"?$",
        line,
    )
    if m is None:
        raise CtlError(f"bad compare: {line!r}")
    target_s, key, op_s, val = m.groups()
    target = {
        "value": sapi.CompareTarget.VALUE,
        "version": sapi.CompareTarget.VERSION,
        "create": sapi.CompareTarget.CREATE,
        "c_rev": sapi.CompareTarget.CREATE,
        "mod": sapi.CompareTarget.MOD,
        "m_rev": sapi.CompareTarget.MOD,
        "lease": sapi.CompareTarget.LEASE,
    }[target_s]
    result = {
        "=": sapi.CompareResult.EQUAL,
        "!=": sapi.CompareResult.NOT_EQUAL,
        "<": sapi.CompareResult.LESS,
        ">": sapi.CompareResult.GREATER,
    }[op_s]
    cmp = sapi.Compare(target=target, result=result, key=key.encode())
    if target == sapi.CompareTarget.VALUE:
        cmp.value = val.encode()
    elif target == sapi.CompareTarget.VERSION:
        cmp.version = int(val)
    elif target == sapi.CompareTarget.CREATE:
        cmp.create_revision = int(val)
    elif target == sapi.CompareTarget.MOD:
        cmp.mod_revision = int(val)
    elif target == sapi.CompareTarget.LEASE:
        cmp.lease = int(val)
    return cmp


def _parse_op(line: str) -> sapi.RequestOp:
    parts = line.split()
    if not parts:
        raise CtlError("empty op")
    cmd, args = parts[0], parts[1:]
    if cmd == "put" and len(args) >= 2:
        return sapi.RequestOp(
            request_put=sapi.PutRequest(
                key=args[0].encode(), value=" ".join(args[1:]).encode()
            )
        )
    if cmd == "get" and len(args) >= 1:
        end = args[1].encode() if len(args) > 1 else b""
        return sapi.RequestOp(
            request_range=sapi.RangeRequest(key=args[0].encode(), range_end=end)
        )
    if cmd == "del" and len(args) >= 1:
        end = args[1].encode() if len(args) > 1 else b""
        return sapi.RequestOp(
            request_delete_range=sapi.DeleteRangeRequest(
                key=args[0].encode(), range_end=end
            )
        )
    raise CtlError(f"bad op: {line!r}")


# -- command implementations ---------------------------------------------------


def _client(args) -> Client:
    tls_info = None
    if getattr(args, "cacert", "") or getattr(args, "cert", "") or \
            getattr(args, "insecure_skip_tls_verify", False):
        from ..pkg.tlsutil import TLSInfo

        tls_info = TLSInfo(
            trusted_ca_file=args.cacert,
            client_cert_file=args.cert,
            client_key_file=args.key,
            insecure_skip_verify=args.insecure_skip_tls_verify,
        )
    c = Client(
        _parse_endpoints(args.endpoints),
        request_timeout=args.command_timeout,
        tls_info=tls_info,
    )
    if args.user:
        if ":" in args.user:
            user, pw = args.user.split(":", 1)
        else:
            user, pw = args.user, args.password or ""
        c.authenticate(user, pw)
    return c


def _range_args(args) -> Tuple[bytes, Optional[bytes]]:
    key = args.key.encode()
    if getattr(args, "prefix", False):
        return key, _prefix_end(key)
    end = getattr(args, "range_end", None)
    return key, end.encode() if end else None


def cmd_put(args, pr: Printer) -> int:
    c = _client(args)
    try:
        resp = c.put(
            args.key.encode(), args.value.encode(),
            lease=int(args.lease, 16) if args.lease else 0,
            prev_kv=args.prev_kv,
        )
        pr.put(resp)
        return 0
    finally:
        c.close()


def cmd_get(args, pr: Printer) -> int:
    c = _client(args)
    try:
        key, end = _range_args(args)
        order = {
            "ASCEND": sapi.SortOrder.ASCEND, "DESCEND": sapi.SortOrder.DESCEND,
            "": sapi.SortOrder.NONE,
        }[args.order.upper() if args.order else ""]
        target = {
            "KEY": sapi.SortTarget.KEY, "VERSION": sapi.SortTarget.VERSION,
            "CREATE": sapi.SortTarget.CREATE, "MOD": sapi.SortTarget.MOD,
            "VALUE": sapi.SortTarget.VALUE,
        }[(args.sort_by or "KEY").upper()]
        resp = c.get(
            key, end, revision=args.rev, limit=args.limit,
            serializable=args.consistency == "s",
            count_only=args.count_only, keys_only=args.keys_only,
            sort_order=order, sort_target=target,
        )
        pr.get(resp, args)
        return 0
    finally:
        c.close()


def cmd_del(args, pr: Printer) -> int:
    c = _client(args)
    try:
        key, end = _range_args(args)
        resp = c.delete(key, end, prev_kv=args.prev_kv)
        pr.delete(resp)
        return 0
    finally:
        c.close()


def cmd_txn(args, pr: Printer, stdin=None) -> int:
    lines = (stdin or sys.stdin).read().splitlines()
    req = parse_txn(lines)
    c = _client(args)
    try:
        pr.txn(c.txn(req))
        return 0
    finally:
        c.close()


def cmd_compaction(args, pr: Printer) -> int:
    c = _client(args)
    try:
        c.compact(args.revision, physical=args.physical)
        print(f"compacted revision {args.revision}")
        return 0
    finally:
        c.close()


def cmd_watch(args, pr: Printer) -> int:
    c = _client(args)
    try:
        key, end = _range_args(args)
        h = c.watch(key, end, start_rev=args.rev)
        seen = 0
        while args.max_events <= 0 or seen < args.max_events:
            got = h.get(timeout=0.5)
            if got is None:
                continue
            _, events = got
            from ..storage.mvcc.kv import EventType

            for ev in events:
                name = "PUT" if ev.type == EventType.PUT else "DELETE"
                print(name)
                print(ev.kv.key.decode("utf-8", "replace"))
                if ev.type == EventType.PUT:
                    print(ev.kv.value.decode("utf-8", "replace"))
                seen += 1
                if 0 < args.max_events <= seen:
                    break
        h.cancel()
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        c.close()


def cmd_lease(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.lease_cmd == "grant":
            r = c.lease_grant(args.ttl)
            print(f"lease {r.id:016x} granted with TTL({r.ttl}s)")
        elif args.lease_cmd == "revoke":
            c.lease_revoke(int(args.id, 16))
            print(f"lease {int(args.id, 16):016x} revoked")
        elif args.lease_cmd == "keep-alive":
            lid = int(args.id, 16)
            if args.once:
                ttl = c.lease_keep_alive_once(lid)
                print(f"lease {lid:016x} keepalived with TTL({ttl})")
            else:
                stop = c.lease_keep_alive(lid)
                try:
                    for _ in range(args.max_keepalives or 1 << 62):
                        time.sleep(0.5)
                except KeyboardInterrupt:
                    pass
                finally:
                    stop()
        elif args.lease_cmd == "timetolive":
            d = c.lease_time_to_live(int(args.id, 16), keys=args.keys)
            lid = int(args.id, 16)
            if d.get("ttl", -1) < 0:
                print(f"lease {lid:016x} already expired")
            else:
                msg = (
                    f"lease {lid:016x} granted with TTL({d['granted_ttl']}s), "
                    f"remaining({d['ttl']}s)"
                )
                if args.keys:
                    # The server reports attached keys as plain strings
                    # (LeaseItem.key).
                    ks = [k.decode("utf-8", "replace")
                          if isinstance(k, bytes) else k
                          for k in d.get("keys", [])]
                    msg += f", attached keys({ks})"
                print(msg)
        elif args.lease_cmd == "list":
            ids = c._request("LeaseLeases", {}).get("leases", [])
            print(f"found {len(ids)} leases")
            for lid in ids:
                print(f"{lid:016x}")
        return 0
    finally:
        c.close()


def cmd_member(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.member_cmd == "list":
            pr.members(c.member_list())
        elif args.member_cmd == "add":
            peer_urls = args.peer_urls.split(",")
            from ..embed.config import member_id_from_urls

            # Token must match the cluster's --initial-cluster-token or
            # the booting member derives a different self-ID.
            mid = member_id_from_urls(args.peer_urls, args.cluster_token)
            members = c.member_add(
                mid, name=args.member_name, peer_urls=peer_urls,
                is_learner=args.learner,
            )
            print(f"Member {mid:x} added to cluster")
            pr.members(members)
        elif args.member_cmd == "remove":
            members = c.member_remove(int(args.id, 16))
            print(f"Member {int(args.id, 16):x} removed from cluster")
        elif args.member_cmd == "promote":
            c.member_promote(int(args.id, 16))
            print(f"Member {int(args.id, 16):x} promoted in cluster")
        return 0
    finally:
        c.close()


def cmd_endpoint(args, pr: Printer) -> int:
    eps = _parse_endpoints(args.endpoints)
    rc = 0
    for ep in eps:
        c = Client([ep], request_timeout=args.command_timeout)
        epname = f"{ep[0]}:{ep[1]}"
        try:
            if args.ep_cmd == "health":
                t0 = time.monotonic()
                c.get(b"health")
                dt = time.monotonic() - t0
                print(f"{epname} is healthy: successfully committed proposal: took = {dt * 1000:.6f}ms")
            elif args.ep_cmd == "status":
                pr.status(epname, c.status())
            elif args.ep_cmd == "hashkv":
                d = c.hash_kv(args.rev)
                print(f"{epname}, {d['hash']}, {d.get('compact_revision', 0)}")
        except Exception as e:  # noqa: BLE001
            print(f"{epname} is unhealthy: failed to commit proposal: {e}")
            rc = 1
        finally:
            c.close()
    return rc


def cmd_snapshot(args, pr: Printer) -> int:
    if args.snap_cmd == "save":
        c = _client(args)
        try:
            blob = c.snapshot()
            with open(args.file, "wb") as f:
                f.write(blob)
            print(f"Snapshot saved at {args.file}")
            return 0
        finally:
            c.close()
    print(
        "etcdctl snapshot restore/status are deprecated; "
        "use `python -m etcd_tpu.etcdutl snapshot " + args.snap_cmd + "`",
        file=sys.stderr,
    )
    from ..etcdutl import main as utl_main

    rest = ["snapshot", args.snap_cmd, *args.rest]
    return utl_main(rest)


def cmd_alarm(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.alarm_cmd == "list":
            resp = c.alarm(sapi.AlarmRequest(action=sapi.AlarmAction.GET))
        else:  # disarm
            resp = c.alarm(
                sapi.AlarmRequest(
                    action=sapi.AlarmAction.DEACTIVATE,
                    alarm=sapi.AlarmType.NONE, member_id=0,
                )
            )
        for am in resp.alarms:
            print(f"memberID:{am.member_id} alarm:{am.alarm.name}")
        return 0
    finally:
        c.close()


def cmd_auth(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.auth_cmd == "enable":
            c.auth_enable()
            print("Authentication Enabled")
        elif args.auth_cmd == "disable":
            c.auth_disable()
            print("Authentication Disabled")
        elif args.auth_cmd == "status":
            d = c._request("AuthStatus", {})
            print(f"Authentication Status: {d.get('enabled', False)}")
            print(f"AuthRevision: {d.get('auth_revision', 0)}")
        return 0
    finally:
        c.close()


def cmd_user(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.user_cmd == "add":
            name = args.name
            pw = args.new_user_password
            if pw is None and ":" in name:
                name, pw = name.split(":", 1)
            c.auth_op(sapi.AuthRequest(op="user_add", name=name, password=pw or ""))
            print(f"User {name} created")
        elif args.user_cmd == "delete":
            c.auth_op(sapi.AuthRequest(op="user_delete", name=args.name))
            print(f"User {args.name} deleted")
        elif args.user_cmd == "get":
            d = c._request("UserGet", {"name": args.name})
            print(f"User: {args.name}")
            print(f"Roles: {' '.join(d.get('roles', []))}")
        elif args.user_cmd == "list":
            for u in c._request("UserList", {}).get("users", []):
                print(u)
        elif args.user_cmd == "passwd":
            c.auth_op(
                sapi.AuthRequest(
                    op="user_change_password", name=args.name,
                    password=args.new_user_password or "",
                )
            )
            print("Password updated")
        elif args.user_cmd == "grant-role":
            c.auth_op(
                sapi.AuthRequest(op="user_grant_role", name=args.name, role=args.role)
            )
            print(f"Role {args.role} is granted to user {args.name}")
        elif args.user_cmd == "revoke-role":
            c.auth_op(
                sapi.AuthRequest(op="user_revoke_role", name=args.name, role=args.role)
            )
            print(f"Role {args.role} is revoked from user {args.name}")
        return 0
    finally:
        c.close()


def cmd_role(args, pr: Printer) -> int:
    c = _client(args)
    try:
        if args.role_cmd == "add":
            c.auth_op(sapi.AuthRequest(op="role_add", role=args.role))
            print(f"Role {args.role} created")
        elif args.role_cmd == "delete":
            c.auth_op(sapi.AuthRequest(op="role_delete", role=args.role))
            print(f"Role {args.role} deleted")
        elif args.role_cmd == "get":
            d = c._request("RoleGet", {"role": args.role})
            print(f"Role {args.role}")
            print("KV Read:")
            perms = d.get("perms", [])
            for p in perms:
                if p["type"] in (0, 2):
                    print(f"\t{bytes.fromhex(p['key']).decode('utf-8', 'replace')}")
            print("KV Write:")
            for p in perms:
                if p["type"] in (1, 2):
                    print(f"\t{bytes.fromhex(p['key']).decode('utf-8', 'replace')}")
        elif args.role_cmd == "list":
            for r in c._request("RoleList", {}).get("roles", []):
                print(r)
        elif args.role_cmd == "grant-permission":
            key = args.key.encode()
            end = b""
            if args.prefix:
                end = _prefix_end(key)
            elif args.range_end:
                end = args.range_end.encode()
            ptype = {"read": 0, "write": 1, "readwrite": 2}[args.perm_type]
            c.auth_op(
                sapi.AuthRequest(
                    op="role_grant_permission", role=args.role,
                    perm_type=ptype, key=key, range_end=end,
                )
            )
            print(f"Role {args.role} updated")
        elif args.role_cmd == "revoke-permission":
            c.auth_op(
                sapi.AuthRequest(
                    op="role_revoke_permission", role=args.role,
                    key=args.key.encode(),
                    range_end=args.range_end.encode() if args.range_end else b"",
                )
            )
            print(f"Permission of key {args.key} is revoked from role {args.role}")
        return 0
    finally:
        c.close()


def _lock_pull_flags(args, raw_argv: Optional[List[str]] = None) -> None:
    """argparse.REMAINDER swallows everything after the lockname, so
    `lock name --ttl 5 cmd ...` lands the flags in exec_command.  Pull
    the lock command's own flags back out of the head of the remainder
    (the reference registers them on the command so position doesn't
    matter: etcdctl/ctlv3/command/lock_command.go).  Extraction stops at
    the first non-flag token — that token starts the exec command, whose
    own flags are passed through verbatim — or at a literal `--`.
    argparse strips a leading `--` out of the REMAINDER itself, so the
    raw argv is consulted to honor a `--` placed right before it."""
    spec = {"--ttl": ("ttl", int), "--hold-seconds": ("hold_seconds", float)}
    rest = list(args.exec_command or [])
    if (raw_argv and rest
            and raw_argv[-len(rest) - 1:] == ["--", *rest]):
        return  # user wrote `lock name -- cmd...`: all verbatim
    out: list = []
    i = 0
    while i < len(rest):
        tok = rest[i]
        if tok == "--":
            out.extend(rest[i + 1:])
            break
        hit = None
        for flag, (attr, conv) in spec.items():
            val = None
            if tok == flag:
                if i + 1 >= len(rest):
                    raise SystemExit(f"flag needs an argument: {flag}")
                val, step = rest[i + 1], 2
            elif tok.startswith(flag + "="):
                val, step = tok.split("=", 1)[1], 1
            if val is not None:
                try:
                    hit = (attr, conv(val), step)
                except ValueError:
                    raise SystemExit(
                        f"invalid argument {val!r} for {flag} flag")
                break
        if hit is None:
            out.extend(rest[i:])
            break
        setattr(args, hit[0], hit[1])
        i += hit[2]
    args.exec_command = out


def cmd_lock(args, pr: Printer) -> int:
    """Drives the server-side Lock/Unlock RPCs (v3lock.go) — the lock
    logic runs in the server, the CLI only owns the session lease."""
    from ..client.concurrency import Session

    _lock_pull_flags(args, getattr(args, "_raw_argv", None))
    c = _client(args)
    s = None
    try:
        s = Session(c, ttl=args.ttl)
        key = c.lock(args.lockname.encode(), s.lease_id,
                     timeout=args.command_timeout)
        print(key.decode("utf-8", "replace"), flush=True)
        if args.exec_command:
            import subprocess

            env = dict(os.environ)
            env["ETCD_LOCK_KEY"] = key.decode("utf-8", "replace")
            kvs = c.get(key).kvs
            env["ETCD_LOCK_REV"] = str(kvs[0].mod_revision if kvs else 0)
            try:
                rc = subprocess.call(args.exec_command, env=env)
            except KeyboardInterrupt:
                # Ordinary shutdown: release like the reference's
                # SIGINT path (lock_command.go:80-88,117).
                c.unlock(key)
                s.close()
                return 0
            except OSError as e:
                # Spawn failure is the crash analog: do NOT
                # unlock/revoke — the lock survives until the session
                # lease TTL expires (the reference releases a crashed
                # holder via lease expiry; deliberate divergence from
                # lock_command.go:99, which unlocks even on spawn
                # error, so a typo'd command cannot silently release a
                # lock another process may still believe it fenced).
                print(f"etcdctl lock: exec failed: {e}", file=sys.stderr)
                return 1
            # The command ran: unlock and propagate its exit code
            # (lock_command.go:94-104 unlocks before returning the
            # command's error; getExitCodeFromError keeps the code).
            c.unlock(key)
            s.close()
            return rc
        # Hold until interrupted (the reference blocks).
        try:
            time.sleep(args.hold_seconds)
        except KeyboardInterrupt:
            pass  # fall through to the ordinary-shutdown unlock
        c.unlock(key)
        s.close()
        return 0
    except KeyboardInterrupt:
        # Ctrl-C while still waiting to acquire: withdraw the claim by
        # revoking the session lease (its queued ownership key dies with
        # it), mirroring the reference's SIGINT context-cancel path.
        if s is not None:
            s.close()
        return 0
    finally:
        c.close()


def cmd_elect(args, pr: Printer) -> int:
    """Drives the server-side Campaign/Leader/Resign RPCs
    (v3election.go)."""
    from ..client.concurrency import Session

    c = _client(args)
    try:
        if args.listen:
            kv = c.election_leader(args.election.encode())
            print(kv.value.decode("utf-8", "replace"))
            return 0
        s = Session(c, ttl=args.ttl)
        leader = c.campaign(args.election.encode(), s.lease_id,
                            (args.proposal or "default").encode(),
                            timeout=args.command_timeout)
        print(bytes.fromhex(leader["key"]).decode("utf-8", "replace"))
        time.sleep(args.hold_seconds)
        c.resign(leader)
        s.close()
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        c.close()


def cmd_move_leader(args, pr: Printer) -> int:
    c = _client(args)
    try:
        target = int(args.target_id, 16)
        c.move_leader(target)
        print(f"Leadership transferred to {target:x}")
        return 0
    finally:
        c.close()


def cmd_defrag(args, pr: Printer) -> int:
    rc = 0
    for ep in _parse_endpoints(args.endpoints):
        c = Client([ep], request_timeout=args.command_timeout)
        try:
            c.defragment()
            print(f"Finished defragmenting etcd member[{ep[0]}:{ep[1]}]")
        except Exception as e:  # noqa: BLE001
            print(f"Failed to defragment etcd member[{ep[0]}:{ep[1]}] ({e})")
            rc = 1
        finally:
            c.close()
    return rc


def cmd_check_datascale(args, pr: Printer) -> int:
    """ref: etcdctl/ctlv3/command/check.go:297-440 check datascale —
    storage cost of holding a workload's keys (the reference reads RSS
    from /metrics; the backend db size is the in-repo analog)."""
    loads = {
        "s": 2000, "small": 2000,
        "m": 20000, "medium": 20000,
        "l": 200000, "large": 200000,
        "xl": 600000, "xLarge": 600000,
    }
    limit = loads.get(args.load)
    if limit is None:
        print(f"unknown load option {args.load!r}")
        return 2
    prefix = args.prefix.encode()
    c = _client(args)
    try:
        rr = c.get(prefix, range_end=_prefix_end(prefix), limit=1)
        if rr.kvs:
            print(f"prefix {args.prefix!r} has keys; delete them first")
            return 1
        size_before = c.status().get("db_size", 0)
        import random as _rand

        from ..pkg.report import Report

        rep = Report()
        val = b"x" * 512
        print(f"Start data scale check for work load "
              f"[{limit} key-value pairs, 1024 bytes per key-value].")
        t0 = time.monotonic()
        for _ in range(limit):
            k = prefix + _rand.getrandbits(63).to_bytes(8, "big").hex().encode()
            s = time.monotonic()
            try:
                c.put(k.ljust(len(prefix) + 512, b"0"), val)
                rep.results(time.monotonic() - s)
            except Exception as e:  # noqa: BLE001
                rep.results(time.monotonic() - s, e)
        dt = time.monotonic() - t0
        size_after = c.status().get("db_size", 0)
        dresp = c.delete(prefix, _prefix_end(prefix))
        if args.auto_compact and dresp.header.revision > 1:
            c.compact(dresp.header.revision, physical=True)
        if args.auto_defrag:
            c.defragment()
        st = rep.stats()
        used = max(0, size_after - size_before)
        pct = st.percentiles_ms
        verdict = "PASS:" if st.errors == 0 else f"FAIL: {st.errors} errors;"
        print(f"{verdict} Put {limit} kvs in {dt:.2f}s ({st.qps:.1f}/s), "
              f"p50 {pct.get('50', 0):.1f}ms, p99 {pct.get('99', 0):.1f}ms")
        print(f"Approximate backend bytes used : {used / 1024 / 1024:.2f} MB")
        return 0 if st.errors == 0 else 1
    finally:
        c.close()


def cmd_check_perf(args, pr: Printer) -> int:
    """ref: etcdctl/ctlv3/command/check.go checkPerf."""
    loads = {"s": (50, 1), "m": (200, 10), "l": (500, 50)}
    writes, clients = loads.get(args.load, loads["s"])
    if args.duration:
        # scale writes to the requested window at the same rate
        writes = max(writes, int(writes * args.duration / 10))
    c = _client(args)
    from ..pkg.report import Report

    rep = Report()
    t0 = time.monotonic()
    slow = 0
    for i in range(writes):
        s = time.monotonic()
        try:
            c.put(f"__check_perf__{i % 128}".encode(), b"x" * 100)
            dt_one = time.monotonic() - s
            rep.results(dt_one)
            if dt_one > 0.5:
                slow += 1
        except Exception as e:  # noqa: BLE001
            rep.results(time.monotonic() - s, e)
    dt = time.monotonic() - t0
    c.delete(b"__check_perf__", _prefix_end(b"__check_perf__"))
    c.close()
    st = rep.stats()
    print(f"{writes} writes in {dt:.2f}s ({st.qps:.1f}/s), "
          f"p50 {st.percentiles_ms['50']:.2f}ms, "
          f"p99 {st.percentiles_ms['99']:.2f}ms")
    ok = True
    if st.errors:
        print(f"FAIL: {st.errors} errors")
        ok = False
    if slow > writes * 0.05:
        print(f"FAIL: {slow} writes slower than 500ms")
        ok = False
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


def cmd_make_mirror(args, pr: Printer) -> int:
    from ..client.mirror import Syncer

    src = _client(args)
    dst = Client(_parse_endpoints(args.destination),
                 request_timeout=args.command_timeout)
    try:
        sy = Syncer(src, prefix=args.prefix.encode() if args.prefix else b"")
        count = sy.mirror_to(
            dst,
            dest_prefix=args.dest_prefix.encode() if args.dest_prefix else None,
            max_txns=args.max_txns,
        )
        print(count)
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        src.close()
        dst.close()


# -- argparse wiring -----------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="etcdctl")
    p.add_argument("--endpoints", default="127.0.0.1:2379")
    p.add_argument("-w", "--write-out", default="simple",
                   choices=["simple", "json", "table", "fields"])
    p.add_argument("--hex", action="store_true")
    p.add_argument("--user", default="")
    p.add_argument("--password", default="")
    p.add_argument("--dial-timeout", type=float, default=2.0)
    p.add_argument("--command-timeout", type=float, default=5.0)
    p.add_argument("--cacert", default="")
    p.add_argument("--cert", default="")
    p.add_argument("--key", default="")
    p.add_argument("--insecure-skip-tls-verify", action="store_true")
    sub = p.add_subparsers(dest="cmd")

    sp = sub.add_parser("put")
    sp.add_argument("key")
    sp.add_argument("value")
    sp.add_argument("--lease", default="")
    sp.add_argument("--prev-kv", action="store_true")

    sp = sub.add_parser("get")
    sp.add_argument("key")
    sp.add_argument("range_end", nargs="?", default=None)
    sp.add_argument("--prefix", action="store_true")
    sp.add_argument("--rev", type=int, default=0)
    sp.add_argument("--limit", type=int, default=0)
    sp.add_argument("--sort-by", dest="sort_by", default="")
    sp.add_argument("--order", default="")
    sp.add_argument("--consistency", default="l", choices=["l", "s"])
    sp.add_argument("--count-only", action="store_true")
    sp.add_argument("--keys-only", action="store_true")
    sp.add_argument("--print-value-only", action="store_true")

    sp = sub.add_parser("del")
    sp.add_argument("key")
    sp.add_argument("range_end", nargs="?", default=None)
    sp.add_argument("--prefix", action="store_true")
    sp.add_argument("--prev-kv", action="store_true")

    sub.add_parser("txn")

    sp = sub.add_parser("compaction")
    sp.add_argument("revision", type=int)
    sp.add_argument("--physical", action="store_true")

    sp = sub.add_parser("watch")
    sp.add_argument("key")
    sp.add_argument("range_end", nargs="?", default=None)
    sp.add_argument("--prefix", action="store_true")
    sp.add_argument("--rev", type=int, default=0)
    sp.add_argument("--max-events", type=int, default=0)  # 0 = forever

    sp = sub.add_parser("lease")
    lsub = sp.add_subparsers(dest="lease_cmd")
    x = lsub.add_parser("grant")
    x.add_argument("ttl", type=int)
    x = lsub.add_parser("revoke")
    x.add_argument("id")
    x = lsub.add_parser("keep-alive")
    x.add_argument("id")
    x.add_argument("--once", action="store_true")
    x.add_argument("--max-keepalives", type=int, default=0)
    x = lsub.add_parser("timetolive")
    x.add_argument("id")
    x.add_argument("--keys", action="store_true")
    lsub.add_parser("list")

    sp = sub.add_parser("member")
    msub = sp.add_subparsers(dest="member_cmd")
    msub.add_parser("list")
    x = msub.add_parser("add")
    x.add_argument("member_name")
    x.add_argument("--peer-urls", required=True)
    x.add_argument("--learner", action="store_true")
    x.add_argument("--cluster-token", default="etcd-cluster")
    x = msub.add_parser("remove")
    x.add_argument("id")
    x = msub.add_parser("promote")
    x.add_argument("id")

    sp = sub.add_parser("endpoint")
    esub = sp.add_subparsers(dest="ep_cmd")
    esub.add_parser("health")
    esub.add_parser("status")
    x = esub.add_parser("hashkv")
    x.add_argument("--rev", type=int, default=0)

    sp = sub.add_parser("snapshot")
    ssub = sp.add_subparsers(dest="snap_cmd")
    x = ssub.add_parser("save")
    x.add_argument("file")
    x = ssub.add_parser("restore")
    x.add_argument("rest", nargs=argparse.REMAINDER)
    x = ssub.add_parser("status")
    x.add_argument("rest", nargs=argparse.REMAINDER)

    sp = sub.add_parser("alarm")
    asub = sp.add_subparsers(dest="alarm_cmd")
    asub.add_parser("list")
    asub.add_parser("disarm")

    sp = sub.add_parser("auth")
    ausub = sp.add_subparsers(dest="auth_cmd")
    ausub.add_parser("enable")
    ausub.add_parser("disable")
    ausub.add_parser("status")

    sp = sub.add_parser("user")
    usub = sp.add_subparsers(dest="user_cmd")
    x = usub.add_parser("add")
    x.add_argument("name")
    x.add_argument("--new-user-password", default=None)
    x = usub.add_parser("delete")
    x.add_argument("name")
    x = usub.add_parser("get")
    x.add_argument("name")
    usub.add_parser("list")
    x = usub.add_parser("passwd")
    x.add_argument("name")
    x.add_argument("--new-user-password", default=None)
    x = usub.add_parser("grant-role")
    x.add_argument("name")
    x.add_argument("role")
    x = usub.add_parser("revoke-role")
    x.add_argument("name")
    x.add_argument("role")

    sp = sub.add_parser("role")
    rsub = sp.add_subparsers(dest="role_cmd")
    for c_ in ("add", "delete", "get"):
        x = rsub.add_parser(c_)
        x.add_argument("role")
    rsub.add_parser("list")
    x = rsub.add_parser("grant-permission")
    x.add_argument("role")
    x.add_argument("perm_type", choices=["read", "write", "readwrite"])
    x.add_argument("key")
    x.add_argument("range_end", nargs="?", default=None)
    x.add_argument("--prefix", action="store_true")
    x = rsub.add_parser("revoke-permission")
    x.add_argument("role")
    x.add_argument("key")
    x.add_argument("range_end", nargs="?", default=None)

    sp = sub.add_parser("lock")
    sp.add_argument("lockname")
    sp.add_argument("exec_command", nargs=argparse.REMAINDER)
    sp.add_argument("--ttl", type=int, default=10)
    sp.add_argument("--hold-seconds", type=float, default=0.0)

    sp = sub.add_parser("elect")
    sp.add_argument("election")
    sp.add_argument("proposal", nargs="?", default=None)
    sp.add_argument("--listen", "-l", action="store_true")
    sp.add_argument("--ttl", type=int, default=10)
    sp.add_argument("--hold-seconds", type=float, default=0.0)

    sp = sub.add_parser("move-leader")
    sp.add_argument("target_id")

    sub.add_parser("defrag")

    sp = sub.add_parser("check")
    csub = sp.add_subparsers(dest="check_cmd")
    x = csub.add_parser("perf")
    x.add_argument("--load", default="s", choices=["s", "m", "l"])
    x.add_argument("--duration", type=int, default=0)
    x = csub.add_parser("datascale")
    x.add_argument("--load", default="s")
    x.add_argument("--prefix", default="/etcdctl-check-datascale/")
    x.add_argument("--auto-compact", dest="auto_compact",
                   action="store_true")
    x.add_argument("--auto-defrag", dest="auto_defrag",
                   action="store_true")

    sp = sub.add_parser("make-mirror")
    sp.add_argument("destination")
    sp.add_argument("--prefix", default="")
    sp.add_argument("--dest-prefix", default="")
    sp.add_argument("--max-txns", type=int, default=0)  # 0 = run forever

    sub.add_parser("version")
    return p


_DISPATCH = {
    "put": cmd_put, "get": cmd_get, "del": cmd_del, "txn": cmd_txn,
    "compaction": cmd_compaction, "watch": cmd_watch, "lease": cmd_lease,
    "member": cmd_member, "endpoint": cmd_endpoint, "snapshot": cmd_snapshot,
    "alarm": cmd_alarm, "auth": cmd_auth, "user": cmd_user, "role": cmd_role,
    "lock": cmd_lock, "elect": cmd_elect, "move-leader": cmd_move_leader,
    "defrag": cmd_defrag, "make-mirror": cmd_make_mirror,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    args = parser.parse_args(argv)
    args._raw_argv = argv  # lock needs it: REMAINDER eats a leading `--`
    if args.cmd is None:
        parser.print_help()
        return 2
    if args.cmd == "version":
        print(f"etcdctl version: {ver.SERVER_VERSION}")
        print(f"API version: {ver.API_VERSION}")
        return 0
    if args.cmd == "check":
        ccmd = getattr(args, "check_cmd", None)
        if ccmd == "perf":
            return cmd_check_perf(args, Printer(args.write_out, args.hex))
        if ccmd == "datascale":
            return cmd_check_datascale(
                args, Printer(args.write_out, args.hex))
        parser.parse_args(["check", "--help"])
        return 2
    pr = Printer(args.write_out, args.hex)
    try:
        return _DISPATCH[args.cmd](args, pr)
    except CtlError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except ClientError as e:
        print(f"Error: {e.etype}: {e.msg}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
