"""Fleet observatory: device-side group-state distributions, host side.

Per-group Prometheus labels are a non-starter at G=65536 — the fix is
the Monarch/Dapper move of aggregating AT THE SOURCE: when
``BatchedConfig.fleet_summary`` is on, the jitted round also emits one
fixed-shape **SummaryFrame** — a flat int32 vector whose layout this
module defines (:class:`FleetLayout`) and ``batched/step.py`` builds on
device:

* log-bucketed histograms of per-row commit advance, commit backlog
  (``last - commit``) and leader-side inflight depth;
* per-replica-slot leader counts, role census, progress-state census,
  fenced-row count, term spread;
* a bounded **groups×time heat strip**: per-group-bin commit-delta and
  backlog sums (``min(G, FLEET_HEAT_BINS)`` bins, so the frame size
  never scales with G);
* a ``lax.top_k`` of the worst-backlogged rows with their (group id,
  lag, commit, applied, term, role, lead) — laggards are
  *identifiable*, not just counted.

Fleet visibility therefore costs one small SoA frame per round with
zero per-round host sync (the engine accumulates frames in the scan
carry exactly like the telemetry plane; the hosted rawnode fetches the
vector with the round's other state reads).

Host side, :class:`FleetHub` folds frames into ``etcd_tpu_fleet_*``
registry families, keeps a bounded heatmap ring dumped as a
``fleetheat_*`` artifact (absorbing the per-run CSV role of
``tools/rw_heatmaps.py`` for cluster-side heat), and raises **counted
anomaly flags**:

* ``commit_frozen`` — a top-K row whose commit has not moved for
  ``freeze_frames`` consecutive frames while it still has backlog and
  knows a leader (its own row IS the leader, or ``lead`` names one);
* ``leader_skew`` — a replica slot leading more than ``skew_ratio``
  times its fair share ``G/R`` (the trigger signal the ROADMAP item 5
  rebalancer consumes).

Import-light on purpose (numpy + pkg.metrics + obs.artifacts, no jax):
``step.py`` imports only the layout constants; the hub side never
touches device code.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..pkg import metrics as pmet
from .artifacts import KIND_FLEETHEAT, dump_path

# -----------------------------------------------------------------------------
# Frame layout (the device side in step.py builds the vector in exactly
# this field order — keep the two in sync via FleetLayout.fields).
# -----------------------------------------------------------------------------

# Log2 buckets: bucket 0 holds v == 0; bucket b (1..B-2) holds
# v in [2^(b-1), 2^b); the last bucket is open-ended (v >= 2^(B-2)).
FLEET_BUCKETS = 16
# Worst-backlog rows surfaced with full identity per frame.
FLEET_TOP_K = 8
# Heat-strip cap: per-group columns below this many groups, fixed-size
# group-range bins above it (the frame must not scale with G).
FLEET_HEAT_BINS = 128

# Role / progress-state names (state.py encodings; kept here so this
# module stays import-free of the batched package).
ROLE_NAMES = ("follower", "candidate", "leader", "precandidate")
PR_STATE_NAMES = ("probe", "replicate", "snapshot")

ACC_SUM = "sum"    # per-round deltas: accumulate by addition
ACC_LAST = "last"  # state snapshots: latest frame wins


def bucket_lower(i: int) -> int:
    """Lower bound of log bucket i (0, 1, 2, 4, ... 2^(B-2))."""
    return 0 if i == 0 else 1 << (i - 1)


def bucket_label(i: int) -> str:
    if i == 0:
        return "0"
    lo, hi = 1 << (i - 1), (1 << i) - 1
    if i == FLEET_BUCKETS - 1:
        return f">={lo}"
    return str(lo) if lo == hi else f"{lo}-{hi}"


BUCKET_BOUNDS = tuple(bucket_lower(i) for i in range(FLEET_BUCKETS))
BUCKET_LABELS = tuple(bucket_label(i) for i in range(FLEET_BUCKETS))


class FleetLayout:
    """Field offsets of the flat [L] int32 SummaryFrame for a given
    (rows, replicas, groups) shape. Rows are replica instances: the
    hosted rawnode owns one slot of every group (n_rows == G); the
    dense closed-loop engine owns all of them (n_rows == G*R)."""

    def __init__(self, n_rows: int, num_replicas: int,
                 num_groups: int) -> None:
        self.n_rows = int(n_rows)
        self.num_replicas = int(num_replicas)
        self.num_groups = int(num_groups)
        self.heat_bins = min(self.num_groups, FLEET_HEAT_BINS)
        self.top_k = max(1, min(FLEET_TOP_K, self.n_rows))
        b, r, hb, k = (FLEET_BUCKETS, self.num_replicas,
                       self.heat_bins, self.top_k)
        # (name, length, accumulate) in frame order.
        self.fields = (
            ("hist_commit_delta", b, ACC_SUM),
            ("hist_backlog", b, ACC_LAST),
            ("hist_inflight", b, ACC_LAST),
            ("hist_ring_occupancy", b, ACC_LAST),
            ("ring_occ_max", 1, ACC_LAST),
            ("leader_slot", r, ACC_LAST),
            ("role_census", len(ROLE_NAMES), ACC_LAST),
            ("pr_census", len(PR_STATE_NAMES), ACC_LAST),
            ("fenced", 1, ACC_LAST),
            ("term_min", 1, ACC_LAST),
            ("term_max", 1, ACC_LAST),
            ("term_sum", 1, ACC_LAST),
            ("heat_commit", hb, ACC_SUM),
            ("heat_backlog", hb, ACC_LAST),
            ("top_group", k, ACC_LAST),
            ("top_lag", k, ACC_LAST),
            ("top_commit", k, ACC_LAST),
            ("top_applied", k, ACC_LAST),
            ("top_term", k, ACC_LAST),
            ("top_role", k, ACC_LAST),
            ("top_lead", k, ACC_LAST),
        )
        self.offsets: Dict[str, tuple] = {}
        off = 0
        for name, length, _acc in self.fields:
            self.offsets[name] = (off, off + length)
            off += length
        self.size = off
        self._sum_mask = np.zeros(self.size, bool)
        for name, _length, acc in self.fields:
            if acc == ACC_SUM:
                s, e = self.offsets[name]
                self._sum_mask[s:e] = True

    def bin_starts(self) -> List[int]:
        """First group id of each heat column, EXACTLY mirroring the
        device mapping ``bin = g * heat_bins // num_groups`` (step.py):
        column i covers groups [starts[i], starts[i+1]) with a final
        sentinel of num_groups. When G % heat_bins != 0 the bins are
        NOT uniform — a ceil(G/bins) stride label would attribute a
        group's heat to the wrong column."""
        g, hb = self.num_groups, self.heat_bins
        # min g with g*hb//G == i  <=>  g >= ceil(i*G/hb).
        return [-(-i * g // hb) for i in range(hb)] + [g]

    def sum_mask(self) -> np.ndarray:
        """[L] bool: True where the accumulator ADDS frames (per-round
        deltas), False where the latest frame replaces (snapshots).
        Cached — callers (ingest_totals runs per drain) must not
        mutate it."""
        return self._sum_mask

    def slice(self, vec: np.ndarray, name: str) -> np.ndarray:
        s, e = self.offsets[name]
        return np.asarray(vec)[..., s:e]

    def decode(self, vec: np.ndarray) -> Dict[str, np.ndarray]:
        vec = np.asarray(vec)
        assert vec.shape[-1] == self.size, (
            f"frame length {vec.shape[-1]} != layout {self.size} "
            f"(rows={self.n_rows} R={self.num_replicas} "
            f"G={self.num_groups})")
        return {name: self.slice(vec, name) for name, _l, _a in
                self.fields}


# -----------------------------------------------------------------------------
# Registry families (etcd_tpu_fleet_*; registered lazily, shared
# process-wide like the telemetry families).
# -----------------------------------------------------------------------------

# Histogram le-boundaries == the device buckets' lower bounds, so
# folding a device bucket count as `count` observations of its lower
# bound lands every observation in exactly its own le bucket.
_HIST_BUCKETS = tuple(float(b) for b in BUCKET_BOUNDS)


def fleet_hist_family(name: str, help_: str,
                      registry: Optional[pmet.Registry] = None
                      ) -> pmet.Histogram:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        f"etcd_tpu_fleet_{name}", help_, ("member",),
        buckets=_HIST_BUCKETS))


def fleet_gauge(name: str, help_: str, labels=("member",),
                registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        f"etcd_tpu_fleet_{name}", help_, labels))


def fleet_anomaly_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_fleet_anomalies_total",
        "fleet anomaly flags raised from device summary frames and "
        "host persistence signals (kind: commit_frozen | leader_skew "
        "| member_limping | wal_pinned)",
        ("member", "kind")))


def fleet_frames_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_fleet_frames_total",
        "device fleet summary frames folded into the hub",
        ("member",)))


def register_families(registry: Optional[pmet.Registry] = None) -> None:
    """Force-register every etcd_tpu_fleet_* family (they are lazy
    otherwise) — dump_metrics' local mode uses this so the names show
    up before any member ever ingests a frame."""
    for name, help_ in (
        ("commit_delta", "per-row commit-index advance per round "
                         "(device log buckets)"),
        ("commit_backlog", "per-row last-commit backlog "
                           "(device log buckets)"),
        ("inflight_depth", "leader-side tracked-peer inflight depth "
                           "(device log buckets)"),
        ("ring_occupancy", "per-row log-ring occupancy last minus "
                           "compaction floor (device log buckets)"),
    ):
        fleet_hist_family(name, help_, registry)
    fleet_gauge("leader_groups",
                "groups led, by replica slot (device census)",
                ("member", "slot"), registry)
    fleet_gauge("role_rows", "replica rows by role (device census)",
                ("member", "role"), registry)
    fleet_gauge("pr_state_peers",
                "leader-side tracked peers by progress state",
                ("member", "state"), registry)
    fleet_gauge("fenced_rows",
                "durability-fenced rows (device census)",
                ("member",), registry)
    fleet_gauge("term_max", "highest term across rows", ("member",),
                registry)
    fleet_gauge("term_spread", "max-min term spread across rows",
                ("member",), registry)
    fleet_gauge("lag_max", "worst last-commit backlog across rows",
                ("member",), registry)
    fleet_gauge("ring_occ_max",
                "worst log-ring occupancy across rows (vs window W; "
                "the ring_full back-pressure high-water)",
                ("member",), registry)
    fleet_gauge("leader_skew_ratio",
                "max leaders-per-slot over the fair share G/R (x1000)",
                ("member",), registry)
    fleet_gauge("fsync_ewma_ms",
                "EWMA of this member's WAL fsync latency in ms x1000 "
                "(the member_limping gray-failure signal)",
                ("member",), registry)
    fleet_anomaly_counter(registry)
    fleet_frames_counter(registry)


# -----------------------------------------------------------------------------
# The hub
# -----------------------------------------------------------------------------


class FleetHub:
    """Folds device SummaryFrames into the registry, keeps the bounded
    groups×time heatmap ring, and raises counted anomaly flags."""

    def __init__(self, n_rows: int, num_replicas: int, num_groups: int,
                 member: str = "0",
                 registry: Optional[pmet.Registry] = None,
                 ring: int = 128,
                 dump_dir: Optional[str] = None,
                 freeze_frames: int = 8,
                 skew_ratio: float = 2.0,
                 skew_min_groups: int = 16,
                 limp_ms: float = 25.0,
                 limp_ops: int = 8) -> None:
        self.layout = FleetLayout(n_rows, num_replicas, num_groups)
        self.member = str(member)
        self.registry = registry or pmet.DEFAULT
        self.dump_dir = dump_dir
        self.freeze_frames = int(freeze_frames)
        self.skew_ratio = float(skew_ratio)
        self.skew_min_groups = int(skew_min_groups)
        # Gray-failure (limp) detection thresholds — mutable attrs so
        # harnesses can tune per-episode without a rebuild.
        self.limp_ms = float(limp_ms)
        self.limp_ops = int(limp_ops)
        self._fsync_ewma_ms: Optional[float] = None
        self._limp_streak = 0
        self._limping = False
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring))
        self._frames = 0
        self._last_totals: Optional[np.ndarray] = None
        # commit_frozen tracking: group -> [commit, consecutive frames]
        # (bounded by top_k — only rows the device surfaced can track).
        self._frozen: Dict[int, List[int]] = {}
        self._skewed = False
        self._anomaly_counts: Dict[str, int] = {}
        self._anomaly_log: deque = deque(maxlen=64)
        self.last_dump: Optional[str] = None
        self._latest: Optional[Dict[str, np.ndarray]] = None

        m = self.member
        register_families(self.registry)
        reg = self.registry
        self._h_delta = fleet_hist_family("commit_delta", "",
                                          reg).labels(m)
        self._h_backlog = fleet_hist_family("commit_backlog", "",
                                            reg).labels(m)
        self._h_inflight = fleet_hist_family("inflight_depth", "",
                                             reg).labels(m)
        self._h_ring_occ = fleet_hist_family("ring_occupancy", "",
                                             reg).labels(m)
        self._g_leader = [
            fleet_gauge("leader_groups", "", ("member", "slot"),
                        reg).labels(m, str(s))
            for s in range(self.layout.num_replicas)]
        self._g_role = {
            rn: fleet_gauge("role_rows", "", ("member", "role"),
                            reg).labels(m, rn)
            for rn in ROLE_NAMES}
        self._g_pr = {
            sn: fleet_gauge("pr_state_peers", "", ("member", "state"),
                            reg).labels(m, sn)
            for sn in PR_STATE_NAMES}
        self._g_fenced = fleet_gauge("fenced_rows", "", ("member",),
                                     reg).labels(m)
        self._g_term_max = fleet_gauge("term_max", "", ("member",),
                                       reg).labels(m)
        self._g_term_spread = fleet_gauge("term_spread", "",
                                          ("member",), reg).labels(m)
        self._g_lag_max = fleet_gauge("lag_max", "", ("member",),
                                      reg).labels(m)
        self._g_ring_occ_max = fleet_gauge("ring_occ_max", "",
                                           ("member",), reg).labels(m)
        self._g_skew = fleet_gauge("leader_skew_ratio", "",
                                   ("member",), reg).labels(m)
        self._g_fsync_ewma = fleet_gauge("fsync_ewma_ms", "",
                                         ("member",), reg).labels(m)
        self._c_anom = fleet_anomaly_counter(reg)
        self._c_frames = fleet_frames_counter(reg).labels(m)

    # -- gray-failure (limp) signal -------------------------------------------

    def observe_fsync(self, seconds: float) -> None:
        """Host persistence signal: one WAL fsync's wall time (the
        hosting layer calls this after every sync, inline or
        group-commit). A member whose fsyncs stay above ``limp_ms`` for
        ``limp_ops`` consecutive syncs is LIMPING — alive, acking,
        and slow: the gray-failure shape Huang et al. (HotOS'17) show
        health checks miss. Raises the counted ``member_limping``
        anomaly once per degradation episode (edge-triggered, re-arms
        after the member runs fast again), which the rebalancer
        (batched/rebalance.py) consumes to drain leadership off this
        member — as a follower it no longer holds any commit's
        critical path, the quorum forms from the healthy members."""
        ms = seconds * 1e3
        fire = False
        with self._lock:
            prev = self._fsync_ewma_ms
            self._fsync_ewma_ms = (
                ms if prev is None else 0.2 * ms + 0.8 * prev)
            ewma = self._fsync_ewma_ms
            if ms > self.limp_ms:
                self._limp_streak += 1
                if (self._limp_streak >= self.limp_ops
                        and not self._limping):
                    self._limping = True
                    fire = True
            else:
                self._limp_streak = 0
                self._limping = False  # re-arms on heal
            streak = self._limp_streak
        self._g_fsync_ewma.set(round(ewma * 1000))
        if fire:
            self._raise_anomaly("member_limping", {
                "fsync_ms": round(ms, 2),
                "ewma_ms": round(ewma, 2),
                "streak": streak,
                "threshold_ms": self.limp_ms,
            })

    def limp_state(self) -> Dict:
        with self._lock:
            return {
                "limping": self._limping,
                "fsync_ewma_ms": (round(self._fsync_ewma_ms, 3)
                                  if self._fsync_ewma_ms is not None
                                  else None),
                "slow_streak": self._limp_streak,
                "threshold_ms": self.limp_ms,
            }

    # -- ingest ---------------------------------------------------------------

    def ingest_round(self, vec: np.ndarray,
                     extra: Optional[Dict] = None) -> None:
        """Fold one per-round frame (delta fields are this round's)."""
        f = self.layout.decode(np.asarray(vec, np.int64))
        self._fold_hist(self._h_delta, f["hist_commit_delta"])
        self._fold_hist(self._h_backlog, f["hist_backlog"])
        self._fold_hist(self._h_inflight, f["hist_inflight"])
        self._fold_hist(self._h_ring_occ, f["hist_ring_occupancy"])
        self._g_ring_occ_max.set(int(f["ring_occ_max"][0]))
        for s, g in enumerate(self._g_leader):
            g.set(int(f["leader_slot"][s]))
        for i, rn in enumerate(ROLE_NAMES):
            self._g_role[rn].set(int(f["role_census"][i]))
        for i, sn in enumerate(PR_STATE_NAMES):
            self._g_pr[sn].set(int(f["pr_census"][i]))
        self._g_fenced.set(int(f["fenced"][0]))
        tmin, tmax = int(f["term_min"][0]), int(f["term_max"][0])
        self._g_term_max.set(tmax)
        self._g_term_spread.set(max(tmax - tmin, 0))
        self._g_lag_max.set(int(f["top_lag"][0]))
        self._c_frames.inc()
        top = self._top_entries(f)
        with self._lock:
            self._frames += 1
            self._latest = f
            self._ring.append({
                "frame": self._frames,
                "t": time.time(),
                "heat_commit": f["heat_commit"].astype(int).tolist(),
                "heat_backlog": f["heat_backlog"].astype(int).tolist(),
                "leader_slot": f["leader_slot"].astype(int).tolist(),
                "fenced": int(f["fenced"][0]),
                "top": top,
                **({"extra": extra} if extra else {}),
            })
        self._check_anomalies(f, top)

    def ingest_totals(self, vec: np.ndarray,
                      extra: Optional[Dict] = None) -> None:
        """Fold MONOTONE totals (the engine's in-device accumulator):
        ACC_SUM fields are cumulative sums — the delta against the
        previous drain folds as one round's worth; ACC_LAST fields
        already hold the latest snapshot."""
        vec = np.asarray(vec, np.int64)
        with self._lock:
            prev = self._last_totals
            self._last_totals = vec.copy()
        if prev is not None:
            mask = self.layout.sum_mask()
            vec = np.where(mask, np.maximum(vec - prev, 0), vec)
        self.ingest_round(vec, extra)

    def _fold_hist(self, child, counts: np.ndarray) -> None:
        """Fold device bucket counts into a registry histogram: each
        bucket's count lands as that many observations of its lower
        bound (_HIST_BUCKETS le-boundaries ARE the lower bounds, so
        every observation falls in exactly its own bucket). Snapshot
        histograms (backlog, inflight) re-measure current state each
        frame, so their _count reads rows×frames — quantile shape and
        rates stay meaningful; absolute counts are per-frame censuses.
        """
        for i, c in enumerate(counts.astype(int).tolist()):
            if c:
                child.observe_many(float(BUCKET_BOUNDS[i]), c)

    def _top_entries(self, f: Dict[str, np.ndarray]) -> List[Dict]:
        out = []
        for j in range(self.layout.top_k):
            lag = int(f["top_lag"][j])
            if lag <= 0:
                continue  # top_k pads with non-laggards; drop them
            out.append({
                "group": int(f["top_group"][j]),
                "lag": lag,
                "commit": int(f["top_commit"][j]),
                "applied": int(f["top_applied"][j]),
                "term": int(f["top_term"][j]),
                "role": ROLE_NAMES[int(f["top_role"][j]) % 4],
                "lead": int(f["top_lead"][j]),
            })
        return out

    # -- anomaly flags --------------------------------------------------------

    def raise_anomaly(self, kind: str, detail: Dict) -> None:
        """Host-raised counted anomaly (the hosting layer's lifecycle
        plane fires ``wal_pinned`` through this): same counter + log
        as the frame-derived flags, so consoles see one stream."""
        self._raise_anomaly(kind, detail)

    def _raise_anomaly(self, kind: str, detail: Dict) -> None:
        self._c_anom.labels(self.member, kind).inc()
        with self._lock:
            self._anomaly_counts[kind] = (
                self._anomaly_counts.get(kind, 0) + 1)
            self._anomaly_log.append(
                {"kind": kind, "t": time.time(), **detail})

    def _check_anomalies(self, f: Dict[str, np.ndarray],
                         top: List[Dict]) -> None:
        # commit_frozen: a surfaced laggard whose commit has not moved
        # for freeze_frames consecutive frames while backlog remains
        # and a leader exists (lead != 0 covers "I know a leader";
        # role == leader covers "I AM the leader").
        nxt: Dict[int, List[int]] = {}
        for e in top:
            if e["lead"] == 0 and e["role"] != "leader":
                continue  # leaderless: lag is expected, not anomalous
            g = e["group"]
            prev = self._frozen.get(g)
            if prev is not None and prev[0] == e["commit"]:
                cnt = prev[1] + 1
            else:
                cnt = 1
            nxt[g] = [e["commit"], cnt]
            if cnt == self.freeze_frames:
                self._raise_anomaly("commit_frozen", {
                    "group": g, "commit": e["commit"],
                    "lag": e["lag"], "frames": cnt})
        self._frozen = nxt

        # leader_skew: a slot leading beyond skew_ratio x fair share.
        lay = self.layout
        if lay.num_groups >= self.skew_min_groups:
            fair = lay.num_groups / lay.num_replicas
            mx = int(f["leader_slot"].max())
            ratio = mx / fair if fair else 0.0
            self._g_skew.set(round(ratio * 1000))
            if ratio > self.skew_ratio:
                if not self._skewed:
                    self._raise_anomaly("leader_skew", {
                        "slot": int(f["leader_slot"].argmax()),
                        "leading": mx,
                        "fair_share": round(fair, 1),
                        "ratio": round(ratio, 3)})
                self._skewed = True
            else:
                self._skewed = False  # edge-triggered: re-arms on heal

    # -- read side ------------------------------------------------------------

    def frames(self) -> int:
        with self._lock:
            return self._frames

    def anomalies(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._anomaly_counts)

    def anomaly_log(self) -> List[Dict]:
        with self._lock:
            return list(self._anomaly_log)

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> Dict:
        """Rollup for the admin 'fleet' op / fleet_console: the latest
        frame decoded plus anomaly state — everything a console needs
        without shipping the ring."""
        with self._lock:
            f = self._latest
            frames = self._frames
            ring_len = len(self._ring)
            anomalies = dict(self._anomaly_counts)
            anomaly_log = list(self._anomaly_log)[-8:]
        lay = self.layout
        out: Dict = {
            "member": self.member,
            "frames": frames,
            "rows": lay.n_rows,
            "groups": lay.num_groups,
            "replicas": lay.num_replicas,
            "heat_bins": lay.heat_bins,
            "heat_bin_starts": lay.bin_starts(),
            "bucket_labels": list(BUCKET_LABELS),
            "ring_len": ring_len,
            "anomalies": anomalies,
            "anomaly_log": anomaly_log,
            # Gray-failure signal (ISSUE 15): the rebalancer's
            # eviction trigger — LEVEL (currently limping), not just
            # the counted edge in `anomalies`.
            "limp": self.limp_state(),
        }
        if f is not None:
            out.update({
                "leader_slot": f["leader_slot"].astype(int).tolist(),
                "leaders_total": int(f["leader_slot"].sum()),
                "role_census": {
                    rn: int(f["role_census"][i])
                    for i, rn in enumerate(ROLE_NAMES)},
                "pr_census": {
                    sn: int(f["pr_census"][i])
                    for i, sn in enumerate(PR_STATE_NAMES)},
                "fenced": int(f["fenced"][0]),
                "term": {"min": int(f["term_min"][0]),
                         "max": int(f["term_max"][0]),
                         "sum": int(f["term_sum"][0])},
                "lag_max": int(f["top_lag"][0]),
                "ring_occ_max": int(f["ring_occ_max"][0]),
                "top": self._top_entries(f),
                "hist": {
                    "commit_delta":
                        f["hist_commit_delta"].astype(int).tolist(),
                    "backlog":
                        f["hist_backlog"].astype(int).tolist(),
                    "inflight":
                        f["hist_inflight"].astype(int).tolist(),
                    "ring_occupancy":
                        f["hist_ring_occupancy"].astype(int).tolist(),
                },
            })
        return out

    # -- heatmap artifact -----------------------------------------------------

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the groups×time heatmap ring (+ the rollup snapshot)
        as a JSON artifact; returns the path."""
        if path is None:
            path = dump_path(KIND_FLEETHEAT, self.member, reason,
                             self.dump_dir)
        lay = self.layout
        payload = {
            "member": self.member,
            "reason": reason,
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "heat_bins": lay.heat_bins,
            "heat_bin_starts": lay.bin_starts(),
            "num_groups": lay.num_groups,
            "rollup": self.snapshot(),
            "ring": self.records(),
        }
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
        with self._lock:
            self.last_dump = path
        return path
