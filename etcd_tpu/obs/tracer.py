"""Per-member proposal-lifecycle tracer (the obs package core).

A *span* is one sampled proposal's life on ONE member, keyed by
``(group, term, index)``: a dict of stage-name → ``time.monotonic_ns()``
stamps. The same key on different members yields the per-member
fragments ``tools/trace_merge.py`` joins into a cross-member timeline
— the leader fragment carries propose/fsync/send/commit/apply, each
follower fragment carries its own extract (receive proxy) / fsync /
send (ack) — so no trace id ever rides the wire.

Sampling is deterministic in ``(group, index)`` (seedable): every
member decides identically whether a proposal is traced, with no
coordination and no per-message flag. Default rate ~1/64.

Cost discipline: with tracing off the hot path pays a single
``is not None`` check per hook site. With it on, the round thread pays
three ``monotonic_ns`` reads per round plus one vectorized hash over
the round's (rare) persisted/committed entry arrays; stamps take a
plain lock that only the round and drain threads ever touch. Rings are
bounded; overflow increments ``etcd_tpu_trace_span_drops_total`` on
the shared registry instead of silently shedding.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# Canonical stage order: every stamp a member can take, in causal
# order. A member's fragment holds a subset ("propose" is origin-only;
# commit/apply arrive rounds after send). tools/trace_merge.py names
# the hops between adjacent present stages.
STAGES = (
    "propose",   # client payload enqueued on the leader (rawnode.propose)
    "stage",     # round staging began (inbox build; advance_round entry)
    "dispatch",  # device round dispatched (host->device staging done)
    "extract",   # device round done; host extraction began
    "fsync_wait",  # covering WAL group-commit fsync STARTED (the
    # extract->fsync_wait hop is record build + persistence-queue wait
    # — with the async WAL pipeline on, the time the entry sat in the
    # open buffer behind earlier waves)
    "fsync",     # covering WAL group-commit fsync COMPLETED
    "send",      # round's outbound batch handed to the transport
    "commit",    # commit watermark reached the entry (extraction time)
    "apply",     # state machine applied the entry
)
STAGE_INDEX = {s: i for i, s in enumerate(STAGES)}

# splitmix64-style mixing constants (golden-ratio increments); the
# point is only that group and index bits both reach every output bit.
_MIX_A = 0x9E3779B97F4A7C15
_MIX_B = 0xC2B2AE3D27D4EB4F
_M64 = (1 << 64) - 1

SpanKey = Tuple[int, int, int]  # (group, term, index)


def _mix(group: int, index: int, seed: int) -> int:
    h = ((group * _MIX_A) ^ (index * _MIX_B)) + seed & _M64
    h &= _M64
    h ^= h >> 33
    return h & _M64


class Tracer:
    """Bounded span collector for one member.

    ``sample``: trace 1-in-``sample`` proposals (1 = every proposal —
    tests and the check.sh trace smoke use that). ``seed`` shifts WHICH
    proposals are picked; every member of a cluster must share it (the
    join depends on all members sampling the same keys).
    """

    # Open spans (stamped but not yet applied) beyond this cap evict
    # oldest-first into the ring, flagged incomplete: a lost/truncated
    # proposal must not pin memory forever.
    OPEN_CAP = 4096

    def __init__(self, member: str = "0", sample: int = 64,
                 seed: int = 0, ring: int = 8192,
                 registry=None,
                 dump_dir: Optional[str] = None) -> None:
        self.member = str(member)
        self.sample = max(1, int(sample))
        self.seed = int(seed) & _M64
        self.dump_dir = dump_dir or os.environ.get(
            "ETCD_TPU_FLIGHTREC_DIR", "artifacts")
        self._lock = threading.Lock()
        self._open: Dict[SpanKey, Dict[str, int]] = {}
        self._ring: deque = deque(maxlen=int(ring))
        # Lazy import: batched.telemetry (the registry module for this
        # plane) transitively imports the hosting layer, which imports
        # this module — at construction time the cycle is long settled.
        from ..batched.telemetry import (
            trace_drop_counter,
            trace_span_counter,
        )

        self._spans_c = trace_span_counter(registry).labels(self.member)
        self._drops = trace_drop_counter(registry)
        self._drop_children: Dict[str, object] = {}
        self.last_dump: Optional[str] = None

    # -- sampling --------------------------------------------------------------

    def sampled(self, group: int, index: int) -> bool:
        """Deterministic sampling decision — identical on every member
        for the same (group, index), whatever order stamps arrive in."""
        return _mix(int(group), int(index), self.seed) % self.sample == 0

    def sampled_arr(self, groups: np.ndarray, idxs: np.ndarray) -> np.ndarray:
        """Vectorized ``sampled`` over parallel arrays (the round's
        entry-extraction path: one hash per persisted/committed entry,
        no Python loop until a hit)."""
        g = np.asarray(groups, np.uint64)
        i = np.asarray(idxs, np.uint64)
        h = (g * np.uint64(_MIX_A)) ^ (i * np.uint64(_MIX_B))
        h = h + np.uint64(self.seed)
        h = h ^ (h >> np.uint64(33))
        return (h % np.uint64(self.sample)) == 0

    # -- stamping --------------------------------------------------------------

    def _drop(self, cls: str) -> None:
        child = self._drop_children.get(cls)
        if child is None:
            child = self._drops.labels(self.member, cls)
            self._drop_children[cls] = child
        child.inc()

    def _stamp_locked(self, key: SpanKey, stage: str, t_ns: int) -> None:
        sp = self._open.get(key)
        if sp is None:
            if len(self._open) >= self.OPEN_CAP:
                old_key, old_sp = next(iter(self._open.items()))
                del self._open[old_key]
                self._retire_locked(old_key, old_sp, complete=False)
                self._drop("open_evict")
            sp = self._open[key] = {}
            self._spans_c.inc()
        if stage not in sp:
            sp[stage] = int(t_ns)
        if stage == "apply":
            del self._open[key]
            self._retire_locked(key, sp, complete=True)

    def stamp(self, group: int, term: int, index: int, stage: str,
              t_ns: Optional[int] = None) -> None:
        """Record one stage stamp; creates the span lazily (peer-side
        fragments have no ``propose``). First-stamp-wins per stage — a
        retransmitted append must not move an already-taken stamp."""
        if t_ns is None:
            t_ns = time.monotonic_ns()
        with self._lock:
            self._stamp_locked((int(group), int(term), int(index)),
                               stage, t_ns)

    def stamp_many(self, keys: Iterable[SpanKey], stage: str,
                   t_ns: Optional[int] = None) -> None:
        """One lock acquisition for a batch of keys sharing one stamp
        (the fsync/send/apply hooks stamp a whole Ready's traced keys
        at the same instant — that IS the semantics: one batch fsync /
        one outbound batch covers them all)."""
        keys = list(keys)
        if not keys:
            return
        if t_ns is None:
            t_ns = time.monotonic_ns()
        t_ns = int(t_ns)
        with self._lock:
            for g, t, i in keys:
                self._stamp_locked((int(g), int(t), int(i)), stage,
                                   t_ns)

    def _retire_locked(self, key: SpanKey, sp: Dict[str, int],
                       complete: bool) -> None:
        if len(self._ring) == self._ring.maxlen:
            self._drop("ring_evict")
        self._ring.append({
            "group": key[0], "term": key[1], "index": key[2],
            "complete": bool(complete), "stages": sp,
        })

    # -- readout ---------------------------------------------------------------

    def spans(self, include_open: bool = True) -> List[Dict]:
        """Retired spans (ring order) plus, optionally, still-open
        fragments — peers never see ``apply`` for entries the leader
        already answered, so the join needs the open set too."""
        with self._lock:
            out = list(self._ring)
            if include_open:
                out.extend(
                    {"group": k[0], "term": k[1], "index": k[2],
                     "complete": False, "stages": dict(sp)}
                    for k, sp in self._open.items()
                )
        return out

    def span_count(self) -> int:
        with self._lock:
            return len(self._ring) + len(self._open)

    def to_payload(self) -> Dict:
        """The dump/admin-op payload shape tools/trace_merge.py joins.
        ``monotonic_ns``/``wall_ns`` are a paired reading of the two
        clocks at capture time — a coarse cross-process anchor the
        merge refines with send/recv pair offsets."""
        t_mono = time.monotonic_ns()
        t_wall = time.time_ns()
        return {
            "member": self.member,
            "sample": self.sample,
            "seed": self.seed,
            "stage_names": list(STAGES),
            "monotonic_ns": t_mono,
            "wall_ns": t_wall,
            "spans": self.spans(include_open=True),
        }

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the span ring as JSON next to the flight recorders;
        returns the path."""
        if path is None:
            # Shared collision-free artifact naming (obs.artifacts):
            # keyed by kind+member, made unique by pid + sequence so
            # simultaneous multi-member (or same-second re-)dumps
            # never overwrite each other.
            from .artifacts import KIND_TRACERING, dump_path

            path = dump_path(KIND_TRACERING, self.member, reason,
                             self.dump_dir)
        payload = self.to_payload()
        payload["reason"] = reason
        payload["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        with self._lock:
            self.last_dump = path
        return path


def make_tracer(member: str,
                enabled: Optional[bool] = None,
                registry=None,
                dump_dir: Optional[str] = None) -> Optional[Tracer]:
    """Constructor for the hosting layer: returns a Tracer or None
    (tracing stays a single ``is not None`` on the hot path).
    ``enabled=None`` defers to ETCD_TPU_TRACE; True/False force it.
    ETCD_TPU_TRACE_SAMPLE (default 64) and ETCD_TPU_TRACE_SEED
    (default 0) tune sampling — the seed must match across members."""
    if enabled is None:
        enabled = os.environ.get(
            "ETCD_TPU_TRACE", "") not in ("", "0", "false")
    if not enabled:
        return None
    return Tracer(
        member=member,
        sample=int(os.environ.get("ETCD_TPU_TRACE_SAMPLE", "64")),
        seed=int(os.environ.get("ETCD_TPU_TRACE_SEED", "0")),
        registry=registry,
        dump_dir=dump_dir,
    )
