"""Sampled proposal-lifecycle tracing for the batched hosting path.

PR 4's telemetry plane answers *what happened* (counters, invariant
sweep, flight recorder); this package answers *where the time went*:
deterministically sampled proposals are stamped with monotonic clocks
at every pipeline stage — propose-enqueue, round staging, device
dispatch, Ready extraction, WAL fsync, outbound send, commit, apply —
on every member that touches them, keyed by ``(group, term, index)`` so
peer-side spans need no wire-format change (Dapper's causal join trick:
the identifiers already on the wire ARE the trace id).

Pieces:

* ``tracer.Tracer`` — lock-cheap per-member span collector with a
  bounded ring (drops are counted on pkg.metrics, never silent).
* ``export`` — Chrome-trace / Perfetto JSON exporter + validator.
* ``tools/trace_merge.py`` — joins per-member dumps into one timeline
  with cross-process clock-offset estimation from send/recv pairs.
* ``fleet`` — the fleet observatory (ISSUE 10): layout of the
  device-side group-state SummaryFrame plus the host FleetHub
  (``etcd_tpu_fleet_*`` families, groups×time heatmap ring, counted
  anomaly flags); ``tools/fleet_console.py`` renders a live cluster.
* ``artifacts`` — the one collision-free ``artifacts/`` naming scheme
  every observability dump (flightrec/tracering/fleetheat) shares.

Tracing is OFF by default and purely host-side: the jitted round
program and protocol state are bit-identical with it on or off
(tests/obs/test_tracing.py pins both). The fleet summary is likewise
OFF by default; it IS device-side, but a pure read — bit-parity is
pinned the same way (tests/batched/test_fleet.py).
"""

from .tracer import STAGES, Tracer, make_tracer  # noqa: F401
from .export import chrome_trace, validate_chrome_trace  # noqa: F401
from .fleet import FleetHub, FleetLayout  # noqa: F401
from .artifacts import dump_path  # noqa: F401
