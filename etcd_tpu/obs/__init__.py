"""Sampled proposal-lifecycle tracing for the batched hosting path.

PR 4's telemetry plane answers *what happened* (counters, invariant
sweep, flight recorder); this package answers *where the time went*:
deterministically sampled proposals are stamped with monotonic clocks
at every pipeline stage — propose-enqueue, round staging, device
dispatch, Ready extraction, WAL fsync, outbound send, commit, apply —
on every member that touches them, keyed by ``(group, term, index)`` so
peer-side spans need no wire-format change (Dapper's causal join trick:
the identifiers already on the wire ARE the trace id).

Pieces:

* ``tracer.Tracer`` — lock-cheap per-member span collector with a
  bounded ring (drops are counted on pkg.metrics, never silent).
* ``export`` — Chrome-trace / Perfetto JSON exporter + validator.
* ``tools/trace_merge.py`` — joins per-member dumps into one timeline
  with cross-process clock-offset estimation from send/recv pairs.

Tracing is OFF by default and purely host-side: the jitted round
program and protocol state are bit-identical with it on or off
(tests/obs/test_tracing.py pins both).
"""

from .tracer import STAGES, Tracer, make_tracer  # noqa: F401
from .export import chrome_trace, validate_chrome_trace  # noqa: F401
