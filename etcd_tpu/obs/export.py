"""Chrome-trace / Perfetto JSON export for tracer spans.

The Trace Event Format (the ``chrome://tracing`` JSON Perfetto still
loads) wants a ``traceEvents`` list of complete events: ``ph="X"``,
microsecond ``ts``/``dur``, ``pid``/``tid`` lanes, ``name``. We map
member → pid (one process lane per member — which is literally true in
the hosted deployment) and group → tid, and emit one slice per *hop*
(the interval between adjacent present stamps), so the span renders as
a flame of named hops rather than one opaque bar.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

from .tracer import STAGE_INDEX, STAGES

# Hop names keyed by (from_stage, to_stage): the slice between two
# adjacent stamps. Single-member hops only — the cross-member hops
# (leader send → peer extract, peer send → leader commit) exist only
# on the merged timeline and are named by tools/trace_merge.py.
HOP_NAMES: Dict[Tuple[str, str], str] = {
    ("propose", "stage"): "enqueue_wait",
    ("stage", "dispatch"): "stage",
    ("dispatch", "extract"): "step",
    ("extract", "fsync_wait"): "fsync_wait",
    ("fsync_wait", "fsync"): "fsync",
    # Dumps from before the fsync_wait split (ISSUE 13) carry one
    # combined hop; keep them renderable.
    ("extract", "fsync"): "fsync",
    ("fsync", "send"): "send",
    ("send", "commit"): "quorum_wait",
    ("commit", "apply"): "apply",
}


def _ordered_stamps(stages: Dict[str, int]) -> List[Tuple[str, int]]:
    return sorted(
        ((s, t) for s, t in stages.items() if s in STAGE_INDEX),
        key=lambda st: STAGE_INDEX[st[0]],
    )


def span_events(span: Dict, pid, offset_ns: int = 0) -> List[Dict]:
    """Per-hop complete events for one span fragment. ``offset_ns`` is
    added to every stamp (the merge tool's clock alignment)."""
    stamps = _ordered_stamps(span.get("stages", {}))
    key_args = {
        "group": span.get("group"), "term": span.get("term"),
        "index": span.get("index"),
        "complete": bool(span.get("complete", False)),
    }
    events: List[Dict] = []
    for (s0, t0), (s1, t1) in zip(stamps, stamps[1:]):
        name = HOP_NAMES.get((s0, s1), f"{s0}→{s1}")
        dur_us = max(t1 - t0, 0) / 1e3
        events.append({
            "name": name,
            "cat": "raft",
            "ph": "X",
            "ts": (t0 + offset_ns) / 1e3,
            "dur": dur_us,
            "pid": pid,
            "tid": int(span.get("group", 0)),
            "args": key_args,
        })
    return events


def chrome_trace(payloads: Iterable[Dict],
                 offsets_ns: Optional[Dict[str, int]] = None) -> Dict:
    """Build one Chrome-trace object from one or more tracer payloads
    (``Tracer.to_payload`` shape). ``offsets_ns`` maps member id → the
    clock offset to ADD to that member's stamps (reference member 0)."""
    offsets_ns = offsets_ns or {}
    events: List[Dict] = []
    members: List[str] = []
    for payload in payloads:
        member = str(payload.get("member", "0"))
        members.append(member)
        off = int(offsets_ns.get(member, 0))
        try:
            pid = int(member)
        except ValueError:
            pid = len(members)
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": f"member-{member}"},
        })
        for span in payload.get("spans", ()):
            events.extend(span_events(span, pid, off))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "etcd_tpu.obs",
            "members": members,
            "stage_names": list(STAGES),
            "clock_offsets_ns": {
                str(k): int(v) for k, v in offsets_ns.items()},
        },
    }


def validate_chrome_trace(obj: Dict) -> List[Dict]:
    """Assert ``obj`` is a loadable Chrome-trace object; returns the
    non-metadata events. Raises ValueError with the first violation —
    the trace smoke in tools/check.sh and the exporter tests both gate
    on this, so a malformed export can never silently ship."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError("trace object must carry a traceEvents list")
    events = obj["traceEvents"]
    if not isinstance(events, list):
        raise ValueError("traceEvents must be a list")
    slices: List[Dict] = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "M", "i", "b", "e"):
            raise ValueError(f"event {i}: unknown phase {ph!r}")
        if "pid" not in ev or "name" not in ev:
            raise ValueError(f"event {i}: missing pid/name")
        if ph == "M":
            continue
        for fld in ("ts", "tid"):
            if fld not in ev:
                raise ValueError(f"event {i}: missing {fld}")
        if not isinstance(ev["ts"], (int, float)) or ev["ts"] < 0:
            raise ValueError(f"event {i}: bad ts {ev['ts']!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"event {i}: bad dur {dur!r}")
        slices.append(ev)
    # Round-trip: the object must actually serialize (numpy scalars
    # smuggled into args are the classic failure).
    json.loads(json.dumps(obj))
    return slices
