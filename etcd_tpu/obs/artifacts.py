"""One timestamped ``artifacts/`` naming scheme for observability dumps.

Flight recorders (telemetry.TelemetryHub), trace rings (obs.tracer)
and fleet heatmaps (obs.fleet) all freeze evidence to disk on demand,
on invariant trips, and on chaos-checker failures — often for SEVERAL
members in the SAME wall-clock second. The pre-ISSUE-10 names keyed on
``{kind}_m{member}_{%Y%m%d-%H%M%S}_{reason}`` alone, so two dumps of
one member's ring within a second (an invariant trip racing the
checker-failure sweep, or a restart generation replacing a member
mid-second) silently overwrote each other. Every dump now routes
through :func:`dump_path`, which appends the writing process id and a
process-local monotone sequence number — collision-free within a
process by the counter, across processes by the pid — while keeping
the ``{kind}_m{member}_*_{reason}.json`` shape every existing glob
(tests, lint.yml artifact upload) matches.

Stdlib-only on purpose: telemetry.py is import-light (numpy +
pkg.metrics) and must stay that way.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Optional

# Process-local dump sequence; itertools.count is atomic under the GIL
# so concurrent member threads can't mint the same number.
_SEQ = itertools.count()

# Canonical kind prefixes (one per dump family — new dump families
# should add theirs here so the artifact namespace stays enumerable).
KIND_FLIGHTREC = "flightrec"
KIND_TRACERING = "tracering"
KIND_FLEETHEAT = "fleetheat"
KIND_RWGRID = "rwgrid"  # client-side R/W grid CSVs (tools/rw_heatmaps)


def artifact_dir(dump_dir: Optional[str] = None) -> str:
    """The dump directory: explicit argument, else
    ETCD_TPU_FLIGHTREC_DIR, else ``artifacts``."""
    return dump_dir or os.environ.get("ETCD_TPU_FLIGHTREC_DIR",
                                      "artifacts")


def dump_path(kind: str, member: str, reason: str,
              dump_dir: Optional[str] = None, ext: str = "json") -> str:
    """Collision-free artifact path ``{dir}/{kind}_m{member}_{ts}_
    p{pid}s{seq}_{reason}.{ext}`` (creates the directory)."""
    d = artifact_dir(dump_dir)
    os.makedirs(d, exist_ok=True)
    ts = time.strftime("%Y%m%d-%H%M%S")
    name = (f"{kind}_m{member}_{ts}_p{os.getpid()}s{next(_SEQ):03d}"
            f"_{reason}.{ext}")
    return os.path.join(d, name)
