"""Cross-member trace join + clock-offset estimation (library half
of ``tools/trace_merge.py`` — importable, so tools/hosted_bench.py can
build its SLO table in-process from the admin 'trace' payloads).

Spans are joined on ``(group, term, index)``; each member's
``monotonic_ns`` clock is its own epoch, so the merge first estimates
per-member clock offsets NTP-style from send/recv stamp pairs: for a
span originated on O with a peer fragment on P,

    forward  d_f = extract_P - send_O      (= offset_P +  net)
    backward d_b = commit_O  - send_P      (= -offset_P + net')

so ``offset_P ≈ (d_f - d_b) / 2`` per span; the estimator takes the
median over all shared spans (robust to the asymmetric processing time
baked into each direction). Members never directly paired fall back to
a BFS chain through members that are.

The hop table decomposes the commit path into named hops::

    enqueue_wait | stage | step | fsync_wait | fsync | send |
    net_to_peer | peer_fsync_wait | peer_fsync | peer_ack |
    ack_to_commit | apply

(``fsync_wait`` is the queue half — record build + time behind earlier
persistence waves, which the async WAL pipeline makes a real hop — and
``fsync`` the device half, stamped at the covering group-commit's
completion.) The hops telescope: their per-span sum equals the span's
propose→apply end-to-end exactly, so the table is a complete
decomposition of commit latency, not a sample of it.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from .export import chrome_trace

SpanKey = Tuple[int, int, int]

# Merged-timeline hop decomposition (origin stamps unless _P-suffixed).
HOPS = (
    ("enqueue_wait", "propose", "stage"),
    ("stage", "stage", "dispatch"),
    ("step", "dispatch", "extract"),
    ("fsync_wait", "extract", "fsync_wait"),
    ("fsync", "fsync_wait", "fsync"),
    ("send", "fsync", "send"),
    ("net_to_peer", "send", "extract_P"),
    ("peer_fsync_wait", "extract_P", "fsync_wait_P"),
    ("peer_fsync", "fsync_wait_P", "fsync_P"),
    ("peer_ack", "fsync_P", "send_P"),
    ("ack_to_commit", "send_P", "commit"),
    ("apply", "commit", "apply"),
)


def load_payload(path: str) -> Dict:
    with open(path) as f:
        obj = json.load(f)
    # Accept both a raw payload and the admin-op envelope.
    return obj.get("payload", obj)


def _index_spans(payloads: List[Dict]) -> Dict[SpanKey, Dict[str, Dict]]:
    """key -> member -> stages (first fragment per member wins)."""
    joined: Dict[SpanKey, Dict[str, Dict]] = defaultdict(dict)
    for p in payloads:
        member = str(p.get("member", "?"))
        for sp in p.get("spans", ()):
            key = (sp["group"], sp["term"], sp["index"])
            joined[key].setdefault(member, sp.get("stages", {}))
    return joined


def _origin(frags: Dict[str, Dict]) -> Optional[str]:
    """The member a span originated on (the one holding 'propose')."""
    for member, stages in frags.items():
        if "propose" in stages:
            return member
    return None


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2


def estimate_offsets(payloads: List[Dict]) -> Dict[str, int]:
    """Per-member clock offset (ns, ADD to that member's stamps) onto
    the first payload's member clock."""
    members = [str(p.get("member", "?")) for p in payloads]
    joined = _index_spans(payloads)
    # Pairwise offset samples: est[(o, p)] = offset of p's clock
    # relative to o's (add to p to land on o). Round-trip samples
    # (both directions observed) are kept apart from coarse one-way
    # samples (which assume net≈0 and are biased LOW by the one-way
    # latency): a pair uses the coarse population only when it has no
    # round-trip evidence at all — in-flight spans dominate a chaos
    # dump, and mixing them in would drag the median by ~net.
    samples: Dict[Tuple[str, str], List[float]] = defaultdict(list)
    coarse: Dict[Tuple[str, str], List[float]] = defaultdict(list)
    for frags in joined.values():
        o = _origin(frags)
        if o is None:
            continue
        so = frags[o]
        if "send" not in so:
            continue
        for m, sm in frags.items():
            if m == o or "extract" not in sm:
                continue
            d_f = sm["extract"] - so["send"]
            if "send" in sm and "commit" in so:
                d_b = so["commit"] - sm["send"]
                samples[(o, m)].append(-(d_f - d_b) / 2)
            else:
                coarse[(o, m)].append(-d_f)
    edges: Dict[Tuple[str, str], float] = {}
    for pair, xs in coarse.items():
        if pair not in samples:
            samples[pair] = xs
    for (o, m), xs in samples.items():
        off = _median(xs)
        edges[(o, m)] = off
        edges.setdefault((m, o), -off)
    # BFS from the reference member through estimated edges.
    ref = members[0]
    offsets: Dict[str, float] = {ref: 0.0}
    frontier = [ref]
    while frontier:
        cur = frontier.pop()
        for (a, b), off in edges.items():
            if a == cur and b not in offsets:
                offsets[b] = offsets[cur] + off
                frontier.append(b)
    for m in members:
        offsets.setdefault(m, 0.0)  # unpaired: no evidence, assume 0
    return {m: int(v) for m, v in offsets.items()}


def _ack_peer(frags: Dict[str, Dict], origin: str,
              offsets: Dict[str, int]) -> Optional[Tuple[str, Dict]]:
    """The quorum-forming peer: among peers holding extract/fsync/send,
    the one whose (aligned) ack left earliest — with a 3-member quorum
    the commit was driven by the fastest ack, so that peer's stamps are
    the ones on the critical path."""
    best = None
    for m, s in frags.items():
        if m == origin:
            continue
        if not all(k in s
                   for k in ("extract", "fsync_wait", "fsync", "send")):
            continue
        t = s["send"] + offsets.get(m, 0)
        if best is None or t < best[0]:
            best = (t, m, s)
    return (best[1], best[2]) if best else None


def hop_stats(payloads: List[Dict],
              offsets: Optional[Dict[str, int]] = None) -> Dict:
    """Per-hop latency distribution over the joined origin spans.

    The hop table is built from the FULLY-decomposed span subset
    (origin propose→apply complete AND a peer ack triple present):
    every hop then draws from the identical span population, so the
    per-span hop vectors telescope to that population's e2e exactly
    and the summed hop p50s track the e2e p50 tightly — a table where
    each hop samples whichever spans happen to carry its endpoints
    drifts from the e2e it claims to decompose. When nothing fully
    decomposes (single-member dump, all spans in flight) the table
    falls back to per-hop-available sampling, flagged by
    ``hops_population: "partial"``."""
    if offsets is None:
        offsets = estimate_offsets(payloads)
    joined = _index_spans(payloads)
    per_hop: Dict[str, List[float]] = defaultdict(list)
    partial_hop: Dict[str, List[float]] = defaultdict(list)
    e2e: List[float] = []
    e2e_commit: List[float] = []
    n_origin = 0
    n_decomposed = 0
    for frags in joined.values():
        o = _origin(frags)
        if o is None:
            continue
        n_origin += 1
        off_o = offsets.get(o, 0)
        st = {k: v + off_o for k, v in frags[o].items()}
        peer = _ack_peer(frags, o, offsets)
        if peer is not None:
            m, s = peer
            off_p = offsets.get(m, 0)
            for k in ("extract", "fsync_wait", "fsync", "send"):
                st[k + "_P"] = s[k] + off_p
        full = all(a in st and b in st for _n, a, b in HOPS)
        if full:
            n_decomposed += 1
        for name, a, b in HOPS:
            if a in st and b in st:
                dt_ms = (st[b] - st[a]) / 1e6
                partial_hop[name].append(dt_ms)
                if full:
                    per_hop[name].append(dt_ms)
        if "propose" in st and "apply" in st:
            e2e.append((st["apply"] - st["propose"]) / 1e6)
        if "propose" in st and "commit" in st:
            e2e_commit.append((st["commit"] - st["propose"]) / 1e6)
    hops_population = "decomposed"
    if n_decomposed == 0:
        per_hop = partial_hop
        hops_population = "partial"

    def dist(xs: List[float]) -> Dict[str, float]:
        if not xs:
            return {}
        xs = sorted(xs)
        pick = lambda q: xs[min(int(len(xs) * q), len(xs) - 1)]  # noqa: E731
        return {
            "n": len(xs),
            "p50_ms": round(pick(0.50), 3),
            "p90_ms": round(pick(0.90), 3),
            "p99_ms": round(pick(0.99), 3),
            "mean_ms": round(sum(xs) / len(xs), 3),
        }

    hops = {name: dist(per_hop[name]) for name, _a, _b in HOPS
            if per_hop[name]}
    hop_p50_sum = round(sum(d["p50_ms"] for d in hops.values()), 3)
    out = {
        "spans_joined": len(joined),
        "spans_origin": n_origin,
        "spans_peer_decomposed": n_decomposed,
        "hops_population": hops_population,
        "clock_offsets_ns": {str(k): int(v) for k, v in offsets.items()},
        "hops": hops,
        "hop_p50_sum_ms": hop_p50_sum,
        "e2e_apply": dist(e2e),
        "e2e_commit": dist(e2e_commit),
    }
    # Coverage compares the hop p50 sum against the e2e p50 of the
    # SAME population the table was built from: for the decomposed
    # subset each span's hop vector sums to its propose→apply exactly,
    # so the per-span totals ARE that subset's e2e and only
    # sum-of-p50s vs p50-of-sums aggregation slack remains.
    if hops_population == "decomposed" and hops:
        totals = [sum(v) for v in zip(*(per_hop[name] for name in hops))]
        out["e2e_decomposed"] = dist(totals)
        p50_pop = out["e2e_decomposed"]["p50_ms"]
        out["hop_coverage_of_e2e_p50"] = (
            round(hop_p50_sum / p50_pop, 3) if p50_pop > 0 else 1.0)
        # The commit decomposition proper: per span, the hops up to
        # ack_to_commit telescope to propose→commit EXACTLY, so under
        # means the sum of parts IS the whole (the identity the table
        # exists for). Under p50s the sum can undershoot: spans whose
        # totals are pinned by wave scheduling split a near-constant
        # budget differently across hops (anti-correlated shares), and
        # sum-of-medians < median-of-sums. Both are reported; budget
        # reading uses the p50 column, completeness uses the means.
        commit_hops = [n for n in hops if n != "apply"]
        c_totals = [sum(v) for v in zip(
            *(per_hop[name] for name in commit_hops))]
        mean_sum = sum(
            sum(per_hop[n]) / len(per_hop[n]) for n in commit_hops)
        c_mean = sum(c_totals) / len(c_totals)
        c_p50 = dist(c_totals)["p50_ms"]
        c_p50_sum = sum(hops[n]["p50_ms"] for n in commit_hops)
        out["commit_decomposition"] = {
            "hop_mean_sum_ms": round(mean_sum, 3),
            "e2e_commit_mean_ms": round(c_mean, 3),
            "coverage_of_commit_mean": (
                round(mean_sum / c_mean, 3) if c_mean > 0 else 1.0),
            "hop_p50_sum_ms": round(c_p50_sum, 3),
            "e2e_commit_p50_ms": c_p50,
            "coverage_of_commit_p50": (
                round(c_p50_sum / c_p50, 3) if c_p50 > 0 else 1.0),
        }
    elif e2e:
        p50 = out["e2e_apply"]["p50_ms"]
        out["hop_coverage_of_e2e_p50"] = (
            round(hop_p50_sum / p50, 3) if p50 > 0 else 1.0)
    return out


def hops_markdown(stats: Dict) -> str:
    lines = [
        "| hop | n | p50 ms | p90 ms | p99 ms | mean ms |",
        "|---|---|---|---|---|---|",
    ]
    for name, _a, _b in HOPS:
        d = stats["hops"].get(name)
        if not d:
            continue
        lines.append(
            f"| {name} | {d['n']} | {d['p50_ms']} | {d['p90_ms']} "
            f"| {d['p99_ms']} | {d['mean_ms']} |")
    for label in ("e2e_commit", "e2e_apply"):
        d = stats.get(label)
        if d:
            lines.append(
                f"| **{label}** | {d['n']} | {d['p50_ms']} "
                f"| {d['p90_ms']} | {d['p99_ms']} | {d['mean_ms']} |")
    lines.append("")
    lines.append(
        f"hop p50 sum {stats['hop_p50_sum_ms']} ms; coverage of "
        f"e2e(apply) p50: {stats.get('hop_coverage_of_e2e_p50', 'n/a')}")
    cd = stats.get("commit_decomposition")
    if cd:
        lines.append(
            f"commit decomposition: hop mean sum "
            f"{cd['hop_mean_sum_ms']} ms = "
            f"{cd['coverage_of_commit_mean']:.0%} of commit mean "
            f"(exact by construction); p50 sum {cd['hop_p50_sum_ms']} "
            f"ms = {cd['coverage_of_commit_p50']:.0%} of commit p50")
    return "\n".join(lines) + "\n"


def merge(payloads: List[Dict]) -> Tuple[Dict, Dict]:
    """(chrome_trace_object, hop_stats) for a set of member payloads,
    on the aligned clock."""
    offsets = estimate_offsets(payloads)
    trace = chrome_trace(payloads, offsets_ns=offsets)
    stats = hop_stats(payloads, offsets)
    return trace, stats


