"""etcdutl: offline operations on data dirs and snapshot files
(ref: etcdutl/etcdutl/*.go — snapshot restore/status, defrag, backup,
migrate, version; plus server/verify/verify.go:49-141 as the `verify`
subcommand).

All commands work on files only — no running member required.
`python -m etcd_tpu.etcdutl <cmd> ...`.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import struct
import sys
from typing import List, Optional

from .. import version as ver


def _open_backend(path: str):
    from ..storage import backend as bk

    return bk.open_backend(path)


# -- snapshot restore (etcdutl/snapshot/v3_snapshot.go) ------------------------


def snapshot_restore(
    snap_file: str,
    data_dir: str,
    name: str = "default",
    initial_cluster: str = "",
    initial_cluster_token: str = "etcd-cluster",
    skip_hash_check: bool = False,
) -> int:
    """Rebuild a member data dir from a snapshot db: place the db,
    reset membership buckets to the new cluster, zero the consistent
    index so the fresh cluster's log applies from entry 1
    (ref: v3_snapshot.go Restore — saveDB + saveWALAndSnap)."""
    from ..embed.config import member_id_from_urls
    from ..server.cindex import ConsistentIndex
    from ..server.membership import (
        CLUSTER_BUCKET, MEMBERS_BUCKET, REMOVED_BUCKET, Member, RaftCluster,
    )
    from ..storage import backend as bk

    if not os.path.exists(snap_file):
        raise FileNotFoundError(snap_file)
    if not skip_hash_check:
        # Integrity check before touching anything (the reference
        # verifies the snapshot's trailing hash; our snapshot is the
        # backend db, so ask the storage engine directly).
        _check_snapshot_integrity(snap_file)
    cluster_map = {}
    if initial_cluster:
        for part in initial_cluster.split(","):
            nm, url = part.strip().split("=", 1)
            cluster_map.setdefault(nm, []).append(url)
    else:
        cluster_map = {name: ["http://localhost:2380"]}
    if name not in cluster_map:
        raise ValueError(f"member {name!r} not in initial cluster")

    my_id = member_id_from_urls(
        ",".join(cluster_map[name]), initial_cluster_token
    )
    member_dir = os.path.join(data_dir, f"member-{my_id}")
    if os.path.exists(member_dir):
        raise FileExistsError(f"member dir {member_dir} already exists")
    os.makedirs(member_dir)
    db_path = os.path.join(member_dir, "db")
    shutil.copyfile(snap_file, db_path)

    be = _open_backend(db_path)
    try:
        with be.batch_tx.lock:
            for bucket in (MEMBERS_BUCKET, REMOVED_BUCKET):
                for k, _ in be.read_tx().range(bucket, b"", b"\xff" * 16):
                    be.batch_tx.delete(bucket, k)
        for nm, urls in sorted(cluster_map.items()):
            mid = member_id_from_urls(",".join(urls), initial_cluster_token)
            with be.batch_tx.lock:
                be.batch_tx.put(
                    MEMBERS_BUCKET, mid.to_bytes(8, "big"),
                    Member(id=mid, name=nm, peer_urls=urls).marshal(),
                )
        # Fresh raft log ⇒ the consistent-index guard must not skip it.
        ci = ConsistentIndex(be)
        ci.set_consistent_index(0, 0)
        be.force_commit()
    finally:
        be.close()
    print(f"restored snapshot to {member_dir} (member {my_id:x})")
    return 0


def _check_snapshot_integrity(snap_file: str) -> None:
    import sqlite3

    # Read-only immutable open: no copy, no wal/journal side files.
    conn = sqlite3.connect(
        f"file:{snap_file}?mode=ro&immutable=1", uri=True
    )
    try:
        rows = conn.execute("PRAGMA integrity_check").fetchall()
    except sqlite3.DatabaseError as e:
        raise ValueError(
            f"snapshot integrity check failed: {e} "
            f"(use --skip-hash-check to override)"
        )
    finally:
        conn.close()
    if rows != [("ok",)]:
        raise ValueError(
            f"snapshot integrity check failed: {rows!r} "
            f"(use --skip-hash-check to override)"
        )


def snapshot_status(snap_file: str, write_out: str = "simple") -> int:
    """ref: v3_snapshot.go Status — hash, revision, total keys, size."""
    from ..storage import backend as bk
    from ..storage.mvcc.kvstore import KVStore

    size = os.path.getsize(snap_file)
    h = hashlib.sha256()
    with open(snap_file, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    digest = int.from_bytes(h.digest()[:4], "big")
    # Open a COPY read-only to count keys/revision (opening mutates wal
    # files for sqlite; keep the snapshot pristine).
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        tmp = os.path.join(td, "db")
        shutil.copyfile(snap_file, tmp)
        be = _open_backend(tmp)
        try:
            kv = KVStore(be)
            rev = kv.rev()
            total = kv.index.count_all(rev)
        finally:
            be.close()
    if write_out == "json":
        print(json.dumps(
            {"hash": digest, "revision": rev, "totalKey": total,
             "totalSize": size}
        ))
    else:
        hdr = ["HASH", "REVISION", "TOTAL KEYS", "TOTAL SIZE"]
        row = [f"{digest:x}", str(rev), str(total), str(size)]
        w = [max(len(a), len(b)) for a, b in zip(hdr, row)]
        line = "+" + "+".join("-" * (x + 2) for x in w) + "+"
        print(line)
        print("| " + " | ".join(h_.ljust(x) for h_, x in zip(hdr, w)) + " |")
        print(line)
        print("| " + " | ".join(c.ljust(x) for c, x in zip(row, w)) + " |")
        print(line)
    return 0


def defrag(data_dir: str) -> int:
    """Offline defragment every member db under data_dir
    (ref: etcdutl defrag --data-dir)."""
    found = False
    for entry in sorted(os.listdir(data_dir)):
        db = os.path.join(data_dir, entry, "db")
        if not (entry.startswith("member-") and os.path.exists(db)):
            continue
        found = True
        be = _open_backend(db)
        try:
            be.defrag()
        finally:
            be.close()
        print(f"Finished defragmenting etcd data[{db}]")
    if not found:
        print(f"no member db found under {data_dir}", file=sys.stderr)
        return 1
    return 0


def backup(data_dir: str, backup_dir: str) -> int:
    """Consistent copy of a (stopped) member's data dir
    (ref: etcdctl backup / etcdutl migrate tooling)."""
    if os.path.exists(backup_dir) and os.listdir(backup_dir):
        print(f"backup dir {backup_dir} not empty", file=sys.stderr)
        return 1
    shutil.copytree(data_dir, backup_dir, dirs_exist_ok=True)
    print(f"backed up {data_dir} to {backup_dir}")
    return 0


SCHEMA_VERSION_KEY = b"storageVersion"


def migrate(data_dir: str, target_version: str, force: bool = False) -> int:
    """Storage schema up/down-migration marker
    (ref: etcdutl/etcdutl/migrate_command.go; schema/migration.go).
    The current schema is version-compatible across this framework's
    releases, so migration just validates + stamps the version."""
    from ..server.cindex import META_BUCKET
    from ..storage import backend as bk

    found = False
    for entry in sorted(os.listdir(data_dir)):
        db = os.path.join(data_dir, entry, "db")
        if not (entry.startswith("member-") and os.path.exists(db)):
            continue
        found = True
        be = _open_backend(db)
        try:
            cur = be.read_tx().get(META_BUCKET, SCHEMA_VERSION_KEY)
            cur_s = cur.decode() if cur else "3.6"
            if cur_s != target_version and not force:
                major_minor = lambda v: tuple(int(x) for x in v.split(".")[:2])
                if abs(major_minor(cur_s)[1] - major_minor(target_version)[1]) > 1:
                    print(
                        f"cannot migrate {cur_s} -> {target_version} "
                        f"(one minor version at a time; use --force)",
                        file=sys.stderr,
                    )
                    return 1
            with be.batch_tx.lock:
                be.batch_tx.put(
                    META_BUCKET, SCHEMA_VERSION_KEY, target_version.encode()
                )
            be.force_commit()
        finally:
            be.close()
        print(f"migrated {db} to storage version {target_version}")
    if not found:
        print(f"no member db found under {data_dir}", file=sys.stderr)
        return 1
    return 0


def verify(data_dir: str) -> bool:
    """Offline consistency check: WAL chain valid, and the backend's
    consistent index within the WAL's entry range
    (ref: server/verify/verify.go:49-141 VerifyIfEnabled)."""
    from ..native import walog as nwalog
    from ..server.cindex import ConsistentIndex
    from ..storage import wal as walmod

    ok = True
    for entry in sorted(os.listdir(data_dir)):
        mdir = os.path.join(data_dir, entry)
        if not entry.startswith("member-"):
            continue
        wal_dir = os.path.join(mdir, "wal")
        db = os.path.join(mdir, "db")
        if os.path.isdir(wal_dir):
            if not walmod.verify(wal_dir):
                print(f"{entry}: WAL chain INVALID")
                ok = False
                continue
            # Read-only scan (repair=False): never mutate under verify.
            last_index = 0
            for rtype, data, _seq, _meta in nwalog.read_all(
                wal_dir, repair=False
            ):
                if rtype == walmod.REC_ENTRY:
                    term, index, _t = walmod._ENTRY_HDR.unpack(
                        data[: walmod._ENTRY_HDR.size]
                    )
                    last_index = max(last_index, index)
            if os.path.exists(db):
                be = _open_backend(db)
                try:
                    ci = ConsistentIndex(be).consistent_index()
                finally:
                    be.close()
                # cindex may legitimately trail the WAL tail, but must
                # never exceed it (verify.go consistent-index invariant,
                # modulo snapshot-ahead state which drops WAL prefixes).
                if last_index and ci > last_index:
                    print(
                        f"{entry}: consistent index {ci} beyond WAL last "
                        f"index {last_index}"
                    )
                    ok = False
                    continue
            print(f"{entry}: OK (wal last={last_index})")
        elif os.path.exists(db):
            print(f"{entry}: OK (backend only)")
    return ok


# -- CLI -----------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(prog="etcdutl")
    p.add_argument("-w", "--write-out", default="simple",
                   choices=["simple", "json"])
    sub = p.add_subparsers(dest="cmd")

    sp = sub.add_parser("snapshot")
    ssub = sp.add_subparsers(dest="snap_cmd")
    x = ssub.add_parser("restore")
    x.add_argument("file")
    x.add_argument("--data-dir", required=True)
    x.add_argument("--name", default="default")
    x.add_argument("--initial-cluster", default="")
    x.add_argument("--initial-cluster-token", default="etcd-cluster")
    x.add_argument("--skip-hash-check", action="store_true")
    x = ssub.add_parser("status")
    x.add_argument("file")

    x = sub.add_parser("defrag")
    x.add_argument("--data-dir", required=True)

    x = sub.add_parser("backup")
    x.add_argument("--data-dir", required=True)
    x.add_argument("--backup-dir", required=True)

    x = sub.add_parser("migrate")
    x.add_argument("--data-dir", required=True)
    x.add_argument("--target-version", required=True)
    x.add_argument("--force", action="store_true")

    x = sub.add_parser("verify")
    x.add_argument("--data-dir", required=True)

    sub.add_parser("version")

    args = p.parse_args(argv)
    try:
        if args.cmd == "snapshot":
            if args.snap_cmd == "restore":
                return snapshot_restore(
                    args.file, args.data_dir, name=args.name,
                    initial_cluster=args.initial_cluster,
                    initial_cluster_token=args.initial_cluster_token,
                    skip_hash_check=args.skip_hash_check,
                )
            if args.snap_cmd == "status":
                return snapshot_status(args.file, args.write_out)
            p.parse_args(["snapshot", "--help"])
            return 2
        if args.cmd == "defrag":
            return defrag(args.data_dir)
        if args.cmd == "backup":
            return backup(args.data_dir, args.backup_dir)
        if args.cmd == "migrate":
            return migrate(args.data_dir, args.target_version, args.force)
        if args.cmd == "verify":
            return 0 if verify(args.data_dir) else 1
        if args.cmd == "version":
            print(f"etcdutl version: {ver.SERVER_VERSION}")
            print(f"API version: {ver.API_VERSION}")
            return 0
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
