"""The lessor: owner of all leases (ref: server/lease/lessor.go).

Semantics preserved from the reference:

* **Primary-only expiry** (lessor.go:146-183, 465-530): only a promoted
  (leader) lessor moves leases toward expiry; demoted lessors park every
  expiry at "forever". ``Promote(extend)`` refreshes all expiries to
  now+TTL+extend so a new leader never revokes a lease the old leader
  was still honoring; when many leases would expire in the same window
  it spreads them to keep the revoke rate bounded
  (leaseRevokeRate, lessor.go:491-529).
* **Expiry pipeline** (runLoop lessor.go:611-659): due leases surface
  on ``expired_leases()``; the server turns them into LeaseRevoke
  proposals, and the applied revoke calls ``revoke()`` which deletes
  attached keys through the RangeDeleter txn.
* **Checkpoints** (lessor.go:362-423, 742-795): long-TTL leases
  periodically persist remaining TTL via the Checkpointer so a leader
  change doesn't reset the countdown.
* **Persistence**: each lease is a record in the lease bucket
  (schema: key = big-endian int64 id); recovered on construction
  (initAndRecover lessor.go:797-829).
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..storage import backend as bk
from .lease_queue import LeaseQueue

NoLease = 0  # ref: lease.NoLease
FOREVER = float("inf")
MAX_TTL = 9_000_000_000  # ref: MaxLeaseTTL lessor.go:39
DEFAULT_MIN_TTL = 5  # seconds

LEASE_BUCKET = bk.Bucket("lease")

_LEASE_VAL = struct.Struct("<qqq")  # id, ttl, remaining_ttl

# ref: lessor.go:48-52 — max revokes per 500ms runLoop pass.
LEASE_REVOKE_RATE = 1000
# ref: lessor.go:54-57 — checkpoint batching.
LEASE_CHECKPOINT_RATE = 1000
DEFAULT_CHECKPOINT_INTERVAL = 300.0  # 5 min (lessor.go:60)
MAX_CHECKPOINT_BATCH = 1000


class LeaseNotFoundError(Exception):
    """ref: ErrLeaseNotFound."""


class LeaseExistsError(Exception):
    """ref: ErrLeaseExists."""


class NotPrimaryError(Exception):
    """ref: lease.ErrNotPrimary — renew/checkpoint demand the primary
    (expiry-tracking) lessor; distinct from a missing lease."""


class LeaseExpiredError(Exception):
    """ref: ErrLeaseTTLTooLarge/expired paths."""


class LeaseTTLTooLargeError(Exception):
    """ref: ErrLeaseTTLTooLarge."""


@dataclass(frozen=True)
class LeaseItem:
    """A key attached to a lease (ref: lease.LeaseItem)."""

    key: str


def _as_items(items) -> List["LeaseItem"]:
    if isinstance(items, LeaseItem):
        return [items]
    if isinstance(items, bytes):
        return [LeaseItem(items.decode("latin1"))]
    if isinstance(items, str):
        return [LeaseItem(items)]
    return [
        it if isinstance(it, LeaseItem) else LeaseItem(
            it.decode("latin1") if isinstance(it, bytes) else it
        )
        for it in items
    ]


class Lease:
    """ref: lessor.go:831-905 Lease."""

    def __init__(self, lease_id: int, ttl: int) -> None:
        self.id = lease_id
        self.ttl = ttl  # seconds
        self.remaining_ttl = 0  # checkpointed remainder; 0 = full TTL
        self._expiry_lock = threading.RLock()
        self._expiry: float = FOREVER
        self._items_lock = threading.Lock()
        self.item_set: Set[LeaseItem] = set()

    def expiry(self) -> float:
        with self._expiry_lock:
            return self._expiry

    def refresh(self, extend: float = 0.0) -> None:
        """expiry = now + extend + remaining TTL (ref: Lease.refresh)."""
        ttl = self.remaining_ttl if self.remaining_ttl > 0 else self.ttl
        with self._expiry_lock:
            self._expiry = time.monotonic() + extend + ttl

    def forever(self) -> None:
        with self._expiry_lock:
            self._expiry = FOREVER

    def remaining(self) -> float:
        with self._expiry_lock:
            if self._expiry == FOREVER:
                return FOREVER
            return self._expiry - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def keys(self) -> List[str]:
        with self._items_lock:
            return sorted(it.key for it in self.item_set)

    def persist_to(self, backend: bk.Backend) -> None:
        key = struct.pack(">q", self.id)
        val = _LEASE_VAL.pack(self.id, self.ttl, self.remaining_ttl)
        tx = backend.batch_tx
        with tx.lock:
            tx.put(LEASE_BUCKET, key, val)


class Lessor:
    """ref: lessor.go:146-246 lessor / NewLessor."""

    def __init__(
        self,
        backend: bk.Backend,
        min_lease_ttl: int = DEFAULT_MIN_TTL,
        checkpoint_interval: float = DEFAULT_CHECKPOINT_INTERVAL,
        expired_leases_retry_interval: float = 3.0,
        checkpoint_persist: bool = False,
        loop_interval: float = 0.5,
    ) -> None:
        self._lock = threading.RLock()
        self.b = backend
        self.min_lease_ttl = min_lease_ttl
        self.checkpoint_interval = checkpoint_interval
        self.expired_retry_interval = expired_leases_retry_interval
        self.checkpoint_persist = checkpoint_persist
        self.loop_interval = loop_interval

        self.lease_map: Dict[int, Lease] = {}
        self.item_map: Dict[LeaseItem, int] = {}
        self.expired_queue = LeaseQueue()
        self.checkpoint_queue = LeaseQueue()
        self._expired_pending: Dict[int, float] = {}  # id -> last surfaced

        self.range_deleter: Optional[Callable[[], object]] = None
        self.checkpointer: Optional[Callable[[int, int], None]] = None

        self.demoted_event = threading.Event()
        self._primary = False
        self._stopped = threading.Event()
        self._expired_c: List[List[Lease]] = []
        self._expired_cv = threading.Condition()

        self._init_and_recover()

        self._loop = threading.Thread(target=self._run_loop, daemon=True)
        self._loop.start()

    # -- recovery --------------------------------------------------------------

    def _init_and_recover(self) -> None:
        """ref: lessor.go:797-829 initAndRecover."""
        tx = self.b.batch_tx
        with tx.lock:
            tx.unsafe_create_bucket(LEASE_BUCKET)
        items = self.b.read_tx().range(
            LEASE_BUCKET, b"\x00" * 8, b"\xff" * 8, 0
        )
        for _k, v in items:
            lid, ttl, remaining = _LEASE_VAL.unpack(v)
            lease = Lease(lid, ttl)
            lease.remaining_ttl = remaining
            lease.forever()  # not primary yet
            self.lease_map[lid] = lease

    # -- grant / revoke --------------------------------------------------------

    def grant(self, lease_id: int, ttl: int) -> Lease:
        """ref: lessor.go:272-320 Grant."""
        if lease_id == NoLease:
            raise LeaseNotFoundError("cannot grant lease with id 0")
        if ttl > MAX_TTL:
            raise LeaseTTLTooLargeError(str(ttl))
        with self._lock:
            if lease_id in self.lease_map:
                raise LeaseExistsError(str(lease_id))
            lease = Lease(lease_id, max(ttl, self.min_lease_ttl))
            self.lease_map[lease_id] = lease
            lease.persist_to(self.b)
            if self._primary:
                lease.refresh()
                self.expired_queue.push(lease_id, lease.expiry())
                if self._should_checkpoint(lease):
                    self._schedule_checkpoint(lease)
            else:
                lease.forever()
            return lease

    def revoke(self, lease_id: int) -> None:
        """Delete the lease and all attached keys in one txn
        (ref: lessor.go:322-360 Revoke)."""
        with self._lock:
            lease = self.lease_map.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(str(lease_id))
            keys = lease.keys()
        txn = self.range_deleter() if self.range_deleter is not None else None
        if txn is not None:
            for key in keys:
                txn.delete_range(key.encode("latin1"), None)
        with self._lock:
            self.lease_map.pop(lease_id, None)
            for it in list(lease.item_set):
                self.item_map.pop(it, None)
            self.expired_queue.remove(lease_id)
            self.checkpoint_queue.remove(lease_id)
            self._expired_pending.pop(lease_id, None)
            # Delete from backend inside the same logical txn as the keys.
            tx = self.b.batch_tx
            with tx.lock:
                tx.delete(LEASE_BUCKET, struct.pack(">q", lease_id))
        if txn is not None:
            txn.end()

    # -- renew / checkpoint ----------------------------------------------------

    def renew(self, lease_id: int) -> int:
        """Returns the new TTL. Primary only (ref: lessor.go:425-463)."""
        with self._lock:
            if not self._primary:
                raise NotPrimaryError("not primary lessor")
            lease = self.lease_map.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(str(lease_id))
            # Clear the checkpointed remainder: a renewed lease restarts
            # its full TTL (ref: lessor.go:440-452).
            if lease.remaining_ttl > 0:
                lease.remaining_ttl = 0
                if self.checkpointer is not None:
                    self.checkpointer(lease_id, 0)
            lease.refresh()
            self.expired_queue.push(lease_id, lease.expiry())
            self._expired_pending.pop(lease_id, None)
            return lease.ttl

    def checkpoint(self, lease_id: int, remaining_ttl: int) -> None:
        """Apply a checkpoint (ref: lessor.go:362-390 Checkpoint)."""
        with self._lock:
            lease = self.lease_map.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(str(lease_id))
            if remaining_ttl >= lease.ttl:
                return
            lease.remaining_ttl = remaining_ttl
            if self.checkpoint_persist:
                lease.persist_to(self.b)
            if self._primary:
                lease.refresh()
                self.expired_queue.push(lease_id, lease.expiry())

    # -- attach / detach -------------------------------------------------------

    def attach(self, lease_id: int, items) -> None:
        """ref: lessor.go:532-556. `items`: List[LeaseItem] or a single
        key (bytes/str) — the mvcc write txn passes raw keys."""
        with self._lock:
            lease = self.lease_map.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(str(lease_id))
            with lease._items_lock:
                for it in _as_items(items):
                    lease.item_set.add(it)
                    self.item_map[it] = lease_id

    def detach(self, lease_id: int, items) -> None:
        """ref: lessor.go:565-583."""
        with self._lock:
            lease = self.lease_map.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(str(lease_id))
            with lease._items_lock:
                for it in _as_items(items):
                    lease.item_set.discard(it)
                    self.item_map.pop(it, None)

    def get_lease(self, item: LeaseItem) -> int:
        with self._lock:
            return self.item_map.get(item, NoLease)

    def lookup(self, lease_id: int) -> Optional[Lease]:
        with self._lock:
            return self.lease_map.get(lease_id)

    def leases(self) -> List[Lease]:
        with self._lock:
            return sorted(self.lease_map.values(), key=lambda l: l.id)

    # -- promote / demote ------------------------------------------------------

    def promote(self, extend: float = 0.0) -> None:
        """Become primary: refresh all expiries, rate-limit the expiry
        wave (ref: lessor.go:465-530 Promote)."""
        with self._lock:
            self._primary = True
            self.demoted_event.clear()
            leases = list(self.lease_map.values())
            for lease in leases:
                lease.refresh(extend)
                self.expired_queue.push(lease.id, lease.expiry())
                if self._should_checkpoint(lease):
                    self._schedule_checkpoint(lease)
            if len(leases) < LEASE_REVOKE_RATE:
                return  # no possibility of lease pile-up
            # Spread a thundering herd of expiries over 1-second
            # windows at 3/4 of the revoke rate, exactly the
            # reference's shape (lessor.go:484-517): piled-up leases
            # must not consume the entire revoke limit.
            leases.sort(key=lambda l: l.remaining())
            base_window = leases[0].remaining()
            next_window = base_window + 1.0
            expires = 0
            target_per_second = (3 * LEASE_REVOKE_RATE) // 4
            for lease in leases:
                rem = lease.remaining()
                if rem > next_window:
                    base_window = rem
                    next_window = base_window + 1.0
                    expires = 1
                    continue
                expires += 1
                if expires <= target_per_second:
                    continue
                rate_delay = 1.0 * (expires / target_per_second)
                # Leases n seconds past the base window only need the
                # difference to land in their spread slot.
                rate_delay -= rem - base_window
                next_window = base_window + rate_delay
                lease.refresh(rate_delay + extend)
                self.expired_queue.push(lease.id, lease.expiry())
                if self._should_checkpoint(lease):
                    self._schedule_checkpoint(lease)

    def demote(self) -> None:
        """ref: lessor.go:558-563 + runLoop demotec handling."""
        with self._lock:
            self._primary = False
            for lease in self.lease_map.values():
                lease.forever()
            self._expired_pending.clear()
            self.demoted_event.set()

    def is_primary(self) -> bool:
        with self._lock:
            return self._primary

    # -- expiry loop -----------------------------------------------------------

    def expired_leases(self, timeout: Optional[float] = None) -> List[Lease]:
        """Block for the next batch of expired leases
        (the ExpiredLeasesC read, ref: lessor.go:131-135)."""
        with self._expired_cv:
            if not self._expired_c:
                self._expired_cv.wait(timeout=timeout)
            if self._expired_c:
                return self._expired_c.pop(0)
            return []

    def _run_loop(self) -> None:
        """ref: lessor.go:611-659 runLoop: revoke expired + checkpoint
        scheduled every 500ms."""
        while not self._stopped.wait(self.loop_interval):
            self._revoke_expired()
            self._checkpoint_scheduled()

    def _revoke_expired(self) -> None:
        with self._lock:
            if not self._primary:
                return
            now = time.monotonic()
            limit = int(LEASE_REVOKE_RATE * self.loop_interval)
            batch: List[Lease] = []
            while len(batch) < limit:
                lid = self.expired_queue.peek_due(now)
                if lid is None:
                    break
                self.expired_queue.pop()
                lease = self.lease_map.get(lid)
                if lease is None:
                    continue
                if not lease.expired():
                    self.expired_queue.push(lid, lease.expiry())
                    continue
                # Don't re-surface a lease the server is already revoking;
                # retry after expiredLeaseRetryInterval (lessor.go:670-697).
                last = self._expired_pending.get(lid)
                if last is not None and now - last < self.expired_retry_interval:
                    self.expired_queue.push(lid, last + self.expired_retry_interval)
                    continue
                self._expired_pending[lid] = now
                self.expired_queue.push(lid, now + self.expired_retry_interval)
                batch.append(lease)
        if batch:
            with self._expired_cv:
                self._expired_c.append(batch)
                self._expired_cv.notify_all()

    def _should_checkpoint(self, lease: Lease) -> bool:
        """ref: lessor.go:742-753 shouldCheckpoint condition."""
        return (
            self.checkpointer is not None
            and self.checkpoint_interval > 0
            and lease.ttl > self.checkpoint_interval
        )

    def _schedule_checkpoint(self, lease: Lease) -> None:
        self.checkpoint_queue.push(
            lease.id, time.monotonic() + self.checkpoint_interval
        )

    def _checkpoint_scheduled(self) -> None:
        """ref: lessor.go:755-795 checkpointScheduledLeases."""
        with self._lock:
            if not self._primary or self.checkpointer is None:
                return
            now = time.monotonic()
            count = 0
            while count < MAX_CHECKPOINT_BATCH:
                lid = self.checkpoint_queue.peek_due(now)
                if lid is None:
                    break
                self.checkpoint_queue.pop()
                lease = self.lease_map.get(lid)
                if lease is None:
                    continue
                remaining = lease.remaining()
                if remaining == FOREVER:
                    continue
                self.checkpointer(lid, max(int(remaining), 0))
                self._schedule_checkpoint(lease)
                count += 1

    def stop(self) -> None:
        self._stopped.set()
        with self._expired_cv:
            self._expired_cv.notify_all()
        # Join so no loop iteration touches the backend after our owner
        # closes it (daemon threads in C calls at teardown can fault).
        if self._loop.is_alive():
            self._loop.join(timeout=5)
