"""TTL leases (ref: server/lease/).

Grant/Revoke/Renew/Checkpoint with primary-only expiry via a min-heap,
key attachment for revoke-deletes-keys semantics, and backend
persistence so leases survive restart.
"""

from .lessor import (  # noqa: F401
    Lease,
    LeaseExpiredError,
    LeaseNotFoundError,
    LeaseExistsError,
    Lessor,
    LeaseItem,
    NoLease,
    NotPrimaryError,
    FOREVER,
)
