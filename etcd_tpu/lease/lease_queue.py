"""Expiry/checkpoint priority queue (ref: server/lease/lease_queue.go).

A lazily-deduplicated min-heap of (time, lease id): stale heap items —
ones whose time no longer matches the lease's registry entry — are
dropped on pop, exactly like the reference's LeaseQueue which keeps one
live entry per lease and lets outdated ones expire on the way out.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Tuple


class LeaseQueue:
    def __init__(self) -> None:
        self._heap: List[Tuple[float, int]] = []
        self._registry: Dict[int, float] = {}  # id -> authoritative time

    def push(self, lease_id: int, when: float) -> None:
        self._registry[lease_id] = when
        heapq.heappush(self._heap, (when, lease_id))

    def remove(self, lease_id: int) -> None:
        self._registry.pop(lease_id, None)

    def peek_due(self, now: float) -> Optional[int]:
        """Next lease id due at `now`, or None. Pops stale entries."""
        while self._heap:
            when, lid = self._heap[0]
            live = self._registry.get(lid)
            if live is None or live != when:
                heapq.heappop(self._heap)  # superseded or removed
                continue
            if when > now:
                return None
            return lid
        return None

    def pop(self) -> Optional[int]:
        while self._heap:
            when, lid = heapq.heappop(self._heap)
            if self._registry.get(lid) == when:
                del self._registry[lid]
                return lid
        return None

    def __len__(self) -> int:
        return len(self._registry)

    def __contains__(self, lease_id: int) -> bool:
        return lease_id in self._registry
