"""id → waiter registry used to join a proposal with its apply result.

The server registers a request id before proposing it to raft; when the
committed entry is applied, the applier triggers the id with the result,
waking the RPC thread (ref: pkg/wait/wait.go:33-108, used from
server/etcdserver/v3_server.go:672-733). ``WaitTime`` is the
deadline-keyed variant used by the apply-wait gate
(ref: pkg/wait/wait_time.go).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class Wait:
    """Register unique ids, wait on them, trigger them with a value."""

    # Shard the registry lock the way the reference shards its map
    # (wait.go:42 defaultListElementLength) so hot proposal rates don't
    # serialize on one mutex.
    _SHARDS = 16

    def __init__(self) -> None:
        self._locks = [threading.Lock() for _ in range(self._SHARDS)]
        self._maps: list[Dict[int, "_Waiter"]] = [
            {} for _ in range(self._SHARDS)
        ]

    def register(self, wid: int) -> "_Waiter":
        s = wid % self._SHARDS
        with self._locks[s]:
            if wid in self._maps[s]:
                raise RuntimeError(f"dup id {wid:x}")
            w = _Waiter()
            self._maps[s][wid] = w
            return w

    def trigger(self, wid: int, value: Any) -> bool:
        s = wid % self._SHARDS
        with self._locks[s]:
            w = self._maps[s].pop(wid, None)
        if w is None:
            return False
        w.set(value)
        return True

    def is_registered(self, wid: int) -> bool:
        s = wid % self._SHARDS
        with self._locks[s]:
            return wid in self._maps[s]


class _Waiter:
    __slots__ = ("_event", "_value")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None

    def set(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("wait timed out")
        return self._value

    def done(self) -> bool:
        return self._event.is_set()


class WaitTime:
    """Wait until a logical deadline (an index) has been triggered.

    ``wait(deadline)`` returns an event that fires once ``trigger(t)``
    has been called with ``t >= deadline`` (ref: pkg/wait/wait_time.go:
    the apply-wait used by linearizable reads,
    server/etcdserver/v3_server.go:776-784).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last: int = 0
        self._pending: Dict[int, threading.Event] = {}

    def wait(self, deadline: int) -> threading.Event:
        with self._lock:
            ev = self._pending.get(deadline)
            if ev is None:
                ev = threading.Event()
                if deadline <= self._last:
                    ev.set()
                else:
                    self._pending[deadline] = ev
            return ev

    def trigger(self, deadline: int) -> None:
        with self._lock:
            self._last = max(self._last, deadline)
            ripe = [d for d in self._pending if d <= deadline]
            for d in ripe:
                self._pending.pop(d).set()
