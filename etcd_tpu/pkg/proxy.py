"""Fault-injecting TCP proxy (ref: pkg/proxy/server.go — the
delay/blackhole/reorder L4 proxy used by functional chaos and
integration tests).

Sits between two endpoints and forwards bytes with injectable faults:

* ``blackhole_tx/rx`` — silently drop traffic in one direction;
* ``delay_tx/rx(latency, jitter)`` — added latency per segment;
* ``pause_accept`` — refuse new connections;
* ``reset_listen`` — drop all current connections.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import List, Optional, Tuple


class ProxyServer:
    def __init__(self, listen: Tuple[str, int], target: Tuple[str, int]) -> None:
        self.target = target
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._stopped = threading.Event()
        self._accept_paused = False
        self._black_tx = False
        self._black_rx = False
        self._lat_tx = (0.0, 0.0)  # (latency, jitter) seconds
        self._lat_rx = (0.0, 0.0)
        self._rand = random.Random(0)

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(listen)
        self._listener.listen(64)
        self.addr: Tuple[str, int] = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # -- fault controls (ref: server.go Blackhole*/Delay*/Pause*) --------------

    def blackhole_tx(self) -> None:
        self._black_tx = True

    def unblackhole_tx(self) -> None:
        self._black_tx = False

    def blackhole_rx(self) -> None:
        self._black_rx = True

    def unblackhole_rx(self) -> None:
        self._black_rx = False

    def blackhole(self) -> None:
        self._black_tx = self._black_rx = True

    def unblackhole(self) -> None:
        self._black_tx = self._black_rx = False

    def delay_tx(self, latency: float, jitter: float = 0.0) -> None:
        self._lat_tx = (latency, jitter)

    def undelay_tx(self) -> None:
        self._lat_tx = (0.0, 0.0)

    def delay_rx(self, latency: float, jitter: float = 0.0) -> None:
        self._lat_rx = (latency, jitter)

    def undelay_rx(self) -> None:
        self._lat_rx = (0.0, 0.0)

    def pause_accept(self) -> None:
        self._accept_paused = True

    def unpause_accept(self) -> None:
        self._accept_paused = False

    def reset_listen(self) -> None:
        """Kill all live connections (ref: ResetListener)."""
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- forwarding ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                src, _ = self._listener.accept()
            except OSError:
                return
            if self._accept_paused or self._stopped.is_set():
                try:
                    src.close()
                except OSError:
                    pass
                continue
            try:
                dst = socket.create_connection(self.target, timeout=2.0)
            except OSError:
                src.close()
                continue
            with self._lock:
                self._conns.extend((src, dst))
            threading.Thread(
                target=self._pump, args=(src, dst, "tx"), daemon=True
            ).start()
            threading.Thread(
                target=self._pump, args=(dst, src, "rx"), daemon=True
            ).start()

    def _pump(self, a: socket.socket, b: socket.socket, direction: str) -> None:
        try:
            while not self._stopped.is_set():
                try:
                    chunk = a.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                black = self._black_tx if direction == "tx" else self._black_rx
                if black:
                    continue  # swallowed
                lat, jit = self._lat_tx if direction == "tx" else self._lat_rx
                if lat > 0:
                    time.sleep(max(0.0, lat + self._rand.uniform(-jit, jit)))
                try:
                    b.sendall(chunk)
                except OSError:
                    break
        finally:
            for s in (a, b):
                try:
                    s.close()
                except OSError:
                    pass

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self.reset_listen()
