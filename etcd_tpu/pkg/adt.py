"""Interval tree over byte-string (or int) ranges.

Used for auth range-permission checks and watcher key-range groups, the
same two consumers as the reference's red-black interval tree
(ref: pkg/adt/interval_tree.go; consumers auth/range_perm_cache.go and
server/storage/mvcc/watcher_group.go). This implementation is an
augmented treap — same O(log n) expected bounds, far less rotation
bookkeeping than red-black, and deterministic given the seeded RNG.

Intervals are half-open ``[begin, end)``. A nil/empty ``end`` of b"\\x00"
conventionally means "single key" at the caller level; callers pass
explicit ends here.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterator, List, Optional, Tuple


class _Inf:
    """True +inf interval endpoint: compares greater than every key of
    any type (bytes or int). Used for open-ended watch ranges, where any
    finite byte-string stand-in would miss keys sorting above it."""

    __slots__ = ()

    def __lt__(self, other):
        return False

    def __le__(self, other):
        return other is INF

    def __gt__(self, other):
        return other is not INF

    def __ge__(self, other):
        return True

    def __eq__(self, other):
        return other is INF

    def __hash__(self):
        return hash("adt.INF")

    def __repr__(self):
        return "INF"


INF = _Inf()


class Interval:
    __slots__ = ("begin", "end")

    def __init__(self, begin, end) -> None:
        if not begin < end:
            raise ValueError(f"invalid interval [{begin!r}, {end!r})")
        self.begin = begin
        self.end = end

    def intersects(self, other: "Interval") -> bool:
        return self.begin < other.end and other.begin < self.end

    def contains(self, other: "Interval") -> bool:
        return self.begin <= other.begin and other.end <= self.end

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Interval)
            and self.begin == other.begin
            and self.end == other.end
        )

    def __hash__(self) -> int:
        return hash((self.begin, self.end))

    def __repr__(self) -> str:
        return f"Interval({self.begin!r}, {self.end!r})"


def point_interval(p) -> Interval:
    """The single-point interval [p, p+\\0) for byte keys, [p, p+1) for ints."""
    if isinstance(p, (bytes, bytearray)):
        return Interval(bytes(p), bytes(p) + b"\x00")
    return Interval(p, p + 1)


class _Node:
    __slots__ = ("ivl", "value", "prio", "left", "right", "max_end")

    def __init__(self, ivl: Interval, value: Any, prio: int) -> None:
        self.ivl = ivl
        self.value = value
        self.prio = prio
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None
        self.max_end = ivl.end

    def pull(self) -> None:
        m = self.ivl.end
        if self.left is not None and self.left.max_end > m:
            m = self.left.max_end
        if self.right is not None and self.right.max_end > m:
            m = self.right.max_end
        self.max_end = m


def _key(ivl: Interval) -> Tuple:
    return (ivl.begin, ivl.end)


class IntervalTree:
    def __init__(self, seed: int = 0x5EED) -> None:
        self._root: Optional[_Node] = None
        self._len = 0
        self._rng = random.Random(seed)

    def __len__(self) -> int:
        return self._len

    # -- update ---------------------------------------------------------------

    def insert(self, ivl: Interval, value: Any) -> None:
        """Insert; an equal [begin,end) interval is replaced in place."""
        found = self._find(self._root, ivl)
        if found is not None:
            found.value = value
            return
        node = _Node(ivl, value, self._rng.getrandbits(30))
        self._root = self._insert(self._root, node)
        self._len += 1

    def _insert(self, root: Optional[_Node], node: _Node) -> _Node:
        if root is None:
            return node
        if node.prio > root.prio:
            node.left, node.right = self._split(root, _key(node.ivl))
            node.pull()
            return node
        if _key(node.ivl) < _key(root.ivl):
            root.left = self._insert(root.left, node)
        else:
            root.right = self._insert(root.right, node)
        root.pull()
        return root

    def _split(
        self, root: Optional[_Node], key: Tuple
    ) -> Tuple[Optional[_Node], Optional[_Node]]:
        if root is None:
            return None, None
        if _key(root.ivl) < key:
            a, b = self._split(root.right, key)
            root.right = a
            root.pull()
            return root, b
        a, b = self._split(root.left, key)
        root.left = b
        root.pull()
        return a, root

    def _merge(
        self, a: Optional[_Node], b: Optional[_Node]
    ) -> Optional[_Node]:
        if a is None:
            return b
        if b is None:
            return a
        if a.prio > b.prio:
            a.right = self._merge(a.right, b)
            a.pull()
            return a
        b.left = self._merge(a, b.left)
        b.pull()
        return b

    def delete(self, ivl: Interval) -> bool:
        node = self._find(self._root, ivl)
        if node is None:
            return False
        self._root = self._delete(self._root, ivl)
        self._len -= 1
        return True

    def _delete(self, root: Optional[_Node], ivl: Interval) -> Optional[_Node]:
        assert root is not None
        if _key(ivl) == _key(root.ivl):
            return self._merge(root.left, root.right)
        if _key(ivl) < _key(root.ivl):
            root.left = self._delete(root.left, ivl)
        else:
            root.right = self._delete(root.right, ivl)
        root.pull()
        return root

    def _find(self, root: Optional[_Node], ivl: Interval) -> Optional[_Node]:
        while root is not None:
            if _key(ivl) == _key(root.ivl):
                return root
            root = root.left if _key(ivl) < _key(root.ivl) else root.right
        return None

    # -- query ----------------------------------------------------------------

    def find(self, ivl: Interval) -> Optional[Any]:
        node = self._find(self._root, ivl)
        return node.value if node is not None else None

    def intersects(self, ivl: Interval) -> bool:
        node = self._root
        while node is not None:
            if node.ivl.intersects(ivl):
                return True
            if node.left is not None and node.left.max_end > ivl.begin:
                node = node.left
            else:
                node = node.right
        return False

    def stab(self, point) -> List[Any]:
        """Values of all intervals containing `point`."""
        return [v for _, v in self.stab_items(point)]

    def stab_items(self, point) -> List[Tuple[Interval, Any]]:
        return self.visit_items(point_interval(point))

    def visit(self, ivl: Interval, fn: Callable[[Interval, Any], bool]) -> None:
        """Call fn on every stored interval intersecting ivl, in sorted
        order; fn returning False stops the walk (ref semantics:
        pkg/adt/interval_tree.go Visit)."""
        self._visit(self._root, ivl, fn)

    def _visit(self, node: Optional[_Node], ivl: Interval, fn) -> bool:
        if node is None or node.max_end <= ivl.begin:
            return True
        if not self._visit(node.left, ivl, fn):
            return False
        if node.ivl.begin >= ivl.end:
            # Whole right spine is also >= end; stop descending right but
            # finish normally.
            return True
        if node.ivl.intersects(ivl) and not fn(node.ivl, node.value):
            return False
        return self._visit(node.right, ivl, fn)

    def visit_items(self, ivl: Interval) -> List[Tuple[Interval, Any]]:
        out: List[Tuple[Interval, Any]] = []

        def collect(i: Interval, v: Any) -> bool:
            out.append((i, v))
            return True

        self.visit(ivl, collect)
        return out

    def items(self) -> Iterator[Tuple[Interval, Any]]:
        def walk(node: Optional[_Node]):
            if node is None:
                return
            yield from walk(node.left)
            yield (node.ivl, node.value)
            yield from walk(node.right)

        yield from walk(self._root)
