"""Benchmark result collection: throughput + latency percentiles.

The load-generator analog of pkg/report/report.go — collect per-request
durations, then render totals, QPS, and p50/p90/p95/p99/p99.9.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class Stats:
    total_s: float
    count: int
    errors: int
    qps: float
    avg_ms: float
    min_ms: float
    max_ms: float
    percentiles_ms: Dict[str, float]

    def to_dict(self) -> Dict:
        return {
            "total_s": self.total_s,
            "count": self.count,
            "errors": self.errors,
            "qps": self.qps,
            "avg_ms": self.avg_ms,
            "min_ms": self.min_ms,
            "max_ms": self.max_ms,
            **{f"p{k}_ms": v for k, v in self.percentiles_ms.items()},
        }


class Report:
    PERCENTILES = (50, 90, 95, 99, 99.9)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._durations: List[float] = []
        self._errors = 0
        self._t0 = time.monotonic()

    def results(self, duration_s: float, err: Exception | None = None) -> None:
        with self._lock:
            if err is not None:
                self._errors += 1
            else:
                self._durations.append(duration_s)

    def timed(self, fn, *args, **kwargs):
        t0 = time.monotonic()
        try:
            out = fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — load generator records all
            self.results(time.monotonic() - t0, e)
            raise
        self.results(time.monotonic() - t0)
        return out

    def stats(self) -> Stats:
        with self._lock:
            durs = sorted(self._durations)
            errors = self._errors
        total = time.monotonic() - self._t0
        n = len(durs)
        if n == 0:
            return Stats(total, 0, errors, 0.0, 0.0, 0.0, 0.0,
                         {str(p): 0.0 for p in self.PERCENTILES})
        pct = {}
        for p in self.PERCENTILES:
            idx = min(n - 1, int(n * p / 100.0))
            pct[str(p)] = durs[idx] * 1000
        return Stats(
            total_s=total,
            count=n,
            errors=errors,
            qps=n / total if total > 0 else 0.0,
            avg_ms=sum(durs) / n * 1000,
            min_ms=durs[0] * 1000,
            max_ms=durs[-1] * 1000,
            percentiles_ms=pct,
        )

    def render(self) -> str:
        s = self.stats()
        lines = [
            f"Summary:",
            f"  Total:\t{s.total_s:.4f} s",
            f"  Requests:\t{s.count} (errors {s.errors})",
            f"  Throughput:\t{s.qps:.1f} req/s",
            f"  Avg:\t{s.avg_ms:.3f} ms   Min: {s.min_ms:.3f} ms   Max: {s.max_ms:.3f} ms",
            "Latency distribution:",
        ]
        for p, v in s.percentiles_ms.items():
            lines.append(f"  p{p}:\t{v:.3f} ms")
        return "\n".join(lines)
