"""TLS plumbing (ref: client/pkg/transport/listener.go TLSInfo,
tlsutil/ — cipher/cert helpers; listener.go:79 NewTLSListener,
listener.go:283 SelfCert).

``TLSInfo`` carries file paths + policy and builds ``ssl.SSLContext``s
for both directions; ``self_cert`` generates a self-signed CA + server
cert on disk (the --auto-tls path). Generation prefers the
``cryptography`` package and falls back to the ``openssl`` CLI, gated
so neither is a hard dependency.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class TLSInfo:
    """ref: transport/listener.go:146-170 TLSInfo fields."""

    cert_file: str = ""
    key_file: str = ""
    trusted_ca_file: str = ""
    client_cert_auth: bool = False
    insecure_skip_verify: bool = False
    server_name: str = ""
    # client cert presented when dialing (peer transport uses the same
    # cert both ways, listener.go ClientCertFile defaults to CertFile)
    client_cert_file: str = ""
    client_key_file: str = ""

    def empty(self) -> bool:
        return not (self.cert_file or self.key_file)

    def server_context(self) -> ssl.SSLContext:
        """ref: listener.go:340 ServerConfig."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.trusted_ca_file:
            ctx.load_verify_locations(self.trusted_ca_file)
        if self.client_cert_auth:
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """ref: listener.go:376 ClientConfig."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        if self.trusted_ca_file:
            ctx.load_verify_locations(self.trusted_ca_file)
        else:
            ctx.load_default_certs()
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        cert = self.client_cert_file or self.cert_file
        key = self.client_key_file or self.key_file
        if cert and key:
            ctx.load_cert_chain(cert, key)
        return ctx


def self_cert(dirpath: str, hosts: Optional[List[str]] = None,
              skip_verify: bool = True) -> TLSInfo:
    """Generate a self-signed cert+key under ``dirpath`` and return a
    TLSInfo for it (ref: listener.go:283 SelfCert — the --auto-tls /
    --peer-auto-tls path).

    ``skip_verify`` defaults True to match the reference: every member
    of a self-cert cluster generates its *own* cert, so peers cannot
    verify each other against any shared CA — SelfCert marks the info
    and ClientConfig sets InsecureSkipVerify (listener.go selfCert
    handling). The channel is encrypted but not authenticated. Pass
    ``skip_verify=False`` only when every party shares this one cert
    directory (e.g. test fixtures doing strict verification)."""
    hosts = hosts or ["127.0.0.1", "localhost"]
    os.makedirs(dirpath, exist_ok=True)
    cert_path = os.path.join(dirpath, "cert.pem")
    key_path = os.path.join(dirpath, "key.pem")
    if not (os.path.exists(cert_path) and os.path.exists(key_path)):
        try:
            _self_cert_cryptography(cert_path, key_path, hosts)
        except ImportError:
            _self_cert_openssl(cert_path, key_path, hosts)
    return TLSInfo(
        cert_file=cert_path,
        key_file=key_path,
        trusted_ca_file=cert_path,
        insecure_skip_verify=skip_verify,
    )


def _self_cert_cryptography(cert_path: str, key_path: str,
                            hosts: List[str]) -> None:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.ORGANIZATION_NAME, "etcd-tpu")])
    sans: List[x509.GeneralName] = []
    for h in hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    os.chmod(key_path, 0o600)


def _self_cert_openssl(cert_path: str, key_path: str,
                       hosts: List[str]) -> None:
    sans = []
    for h in hosts:
        try:
            ipaddress.ip_address(h)
            sans.append(f"IP:{h}")
        except ValueError:
            sans.append(f"DNS:{h}")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "ec",
         "-pkeyopt", "ec_paramgen_curve:prime256v1",
         "-keyout", key_path, "-out", cert_path,
         "-days", "365", "-nodes", "-subj", "/O=etcd-tpu",
         "-addext", "subjectAltName=" + ",".join(sans)],
        check=True, capture_output=True)
    os.chmod(key_path, 0o600)
