"""Heartbeat-interval contention detector.

The Ready loop records every heartbeat send per peer; if the gap since
the previous send exceeds ``max_duration`` the loop is running late
(disk or CPU contention) and a warning is surfaced (ref:
pkg/contention/contention.go, used at server/etcdserver/raft.go:357-370).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple


class TimeoutDetector:
    def __init__(self, max_duration: float) -> None:
        self.max_duration = max_duration
        self._lock = threading.Lock()
        self._records: Dict[int, float] = {}

    def reset(self) -> None:
        with self._lock:
            self._records.clear()

    def observe(self, which: int) -> Tuple[bool, float]:
        """Returns (ok, exceeded_seconds); ok=False when the gap since
        the previous observation of `which` exceeded max_duration."""
        now = time.monotonic()
        with self._lock:
            prev = self._records.get(which)
            self._records[which] = now
        if prev is None:
            return True, 0.0
        exceeded = (now - prev) - self.max_duration
        return exceeded <= 0, max(0.0, exceeded)
