"""FIFO job scheduler for the apply pipeline.

Jobs run strictly in submission order on one worker thread; ``stop``
drains nothing — it cancels pending jobs and joins the in-flight one,
mirroring the reference scheduler the server feeds ``applyAll`` through
(ref: pkg/schedule/schedule.go, used at server/etcdserver/server.go:742).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional


class FIFOScheduler:
    def __init__(self, name: str = "fifo") -> None:
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._scheduled = 0
        self._finished = 0
        self._lock = threading.Lock()
        self._stopped = False
        self._worker = threading.Thread(target=self._run, name=name, daemon=True)
        self._worker.start()

    def schedule(self, job: Callable[[], None]) -> None:
        with self._lock:
            if self._stopped:
                raise RuntimeError("scheduler stopped")
            self._scheduled += 1
            self._q.put(job)

    def pending(self) -> int:
        with self._lock:
            return self._scheduled - self._finished

    def scheduled(self) -> int:
        with self._lock:
            return self._scheduled

    def finished(self) -> int:
        with self._lock:
            return self._finished

    def wait_finish(self, n: int, timeout: float = 30.0) -> None:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.finished() >= n:
                return
            time.sleep(0.001)
        raise TimeoutError(f"scheduler did not finish {n} jobs")

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            # Cancel unstarted jobs: drain the queue and count them as
            # finished so pending() converges; only the in-flight job
            # (if any) runs to completion before join returns.
            cancelled = 0
            try:
                while True:
                    self._q.get_nowait()
                    cancelled += 1
            except queue.Empty:
                pass
            self._finished += cancelled
            self._q.put(None)
        self._worker.join()

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                job()
            except Exception:  # noqa: BLE001 — a failed job must not kill the pipeline
                import logging

                logging.getLogger("etcd_tpu.schedule").exception(
                    "scheduled job failed"
                )
            finally:
                with self._lock:
                    self._finished += 1
