"""Canonical RPC error table: one registry of every typed error the
server can return, each with a stable symbolic code, a gRPC status
code, and the canonical message (ref: api/v3rpc/rpctypes/error.go —
the single code<->error table etcd clients program against).

Servers serialize errors as ``{"code": symbol, "grpcCode": int,
"msg": str}``; clients look the symbol up here to rebuild the typed
exception and to drive retry/failover decisions off codes rather than
Python class names (the class name is still sent as ``type`` for
wire compatibility with older peers).
"""

from __future__ import annotations

import importlib
from enum import IntEnum
from typing import Dict, Optional, Tuple


class Code(IntEnum):
    """gRPC status codes (ref: google.golang.org/grpc/codes)."""

    OK = 0
    Canceled = 1
    Unknown = 2
    InvalidArgument = 3
    DeadlineExceeded = 4
    NotFound = 5
    AlreadyExists = 6
    PermissionDenied = 7
    ResourceExhausted = 8
    FailedPrecondition = 9
    Aborted = 10
    OutOfRange = 11
    Unimplemented = 12
    Internal = 13
    Unavailable = 14
    DataLoss = 15
    Unauthenticated = 16


# symbol -> (grpc code, canonical message, "module.path:ClassName").
# Symbols and messages mirror api/v3rpc/rpctypes/error.go; the class
# path names the exception this framework raises for that condition.
TABLE: Dict[str, Tuple[Code, str, str]] = {
    # KV / txn argument errors (rpctypes/error.go:24-34)
    "ErrCompacted": (
        Code.OutOfRange,
        "etcdserver: mvcc: required revision has been compacted",
        "etcd_tpu.storage.mvcc.kvstore:CompactedError"),
    "ErrFutureRev": (
        Code.OutOfRange,
        "etcdserver: mvcc: required revision is a future revision",
        "etcd_tpu.storage.mvcc.kvstore:FutureRevError"),
    "ErrNoSpace": (
        Code.ResourceExhausted,
        "etcdserver: mvcc: database space exceeded",
        "etcd_tpu.server.apply:NoSpaceError"),
    # Lease (rpctypes/error.go:36-38)
    "ErrLeaseNotFound": (
        Code.NotFound, "etcdserver: requested lease not found",
        "etcd_tpu.lease.lessor:LeaseNotFoundError"),
    "ErrLeaseExist": (
        Code.FailedPrecondition, "etcdserver: lease already exists",
        "etcd_tpu.lease.lessor:LeaseExistsError"),
    "ErrLeaseTTLTooLarge": (
        Code.OutOfRange, "etcdserver: too large lease TTL",
        "etcd_tpu.lease.lessor:LeaseTTLTooLargeError"),
    "ErrLeaseExpired": (
        Code.NotFound, "etcdserver: lease expired",
        "etcd_tpu.lease.lessor:LeaseExpiredError"),
    # Membership (rpctypes/error.go:42-49)
    "ErrMemberExist": (
        Code.FailedPrecondition, "etcdserver: member ID already exist",
        "etcd_tpu.server.membership:MemberExistsError"),
    "ErrMemberNotFound": (
        Code.NotFound, "etcdserver: member not found",
        "etcd_tpu.server.membership:MemberNotFoundError"),
    "ErrMemberRemoved": (
        Code.Unavailable,
        "etcdserver: the member has been permanently removed from the "
        "cluster",
        "etcd_tpu.server.membership:MemberRemovedError"),
    # Request admission (rpctypes/error.go:51-52)
    "ErrRequestTooLarge": (
        Code.InvalidArgument, "etcdserver: request is too large",
        "etcd_tpu.server.server:RequestTooLargeError"),
    "ErrTooManyRequests": (
        Code.ResourceExhausted, "etcdserver: too many requests",
        "etcd_tpu.server.server:TooManyRequestsError"),
    # Auth (rpctypes/error.go:54-70)
    "ErrRootUserNotExist": (
        Code.FailedPrecondition, "etcdserver: root user does not exist",
        "etcd_tpu.auth.store:RootUserNotExistError"),
    "ErrRootRoleNotExist": (
        Code.FailedPrecondition,
        "etcdserver: root user does not have root role",
        "etcd_tpu.auth.store:RootRoleNotGrantedError"),
    "ErrUserAlreadyExist": (
        Code.FailedPrecondition, "etcdserver: user name already exists",
        "etcd_tpu.auth.store:UserAlreadyExistError"),
    "ErrUserEmpty": (
        Code.InvalidArgument, "etcdserver: user name is empty",
        "etcd_tpu.auth.store:UserEmptyError"),
    "ErrUserNotFound": (
        Code.FailedPrecondition, "etcdserver: user name not found",
        "etcd_tpu.auth.store:UserNotFoundError"),
    "ErrRoleAlreadyExist": (
        Code.FailedPrecondition, "etcdserver: role name already exists",
        "etcd_tpu.auth.store:RoleAlreadyExistError"),
    "ErrRoleNotFound": (
        Code.FailedPrecondition, "etcdserver: role name not found",
        "etcd_tpu.auth.store:RoleNotFoundError"),
    "ErrAuthFailed": (
        Code.InvalidArgument,
        "etcdserver: authentication failed, invalid user ID or password",
        "etcd_tpu.auth.store:AuthFailedError"),
    "ErrPermissionDenied": (
        Code.PermissionDenied, "etcdserver: permission denied",
        "etcd_tpu.auth.store:PermissionDeniedError"),
    "ErrRoleNotGranted": (
        Code.FailedPrecondition,
        "etcdserver: role is not granted to the user",
        "etcd_tpu.auth.store:RoleNotGrantedError"),
    "ErrAuthNotEnabled": (
        Code.FailedPrecondition,
        "etcdserver: authentication is not enabled",
        "etcd_tpu.auth.store:AuthNotEnabledError"),
    "ErrInvalidAuthToken": (
        Code.Unauthenticated, "etcdserver: invalid auth token",
        "etcd_tpu.auth.store:InvalidAuthTokenError"),
    "ErrAuthOldRevision": (
        Code.InvalidArgument,
        "etcdserver: revision of auth store is old",
        "etcd_tpu.auth.store:AuthOldRevisionError"),
    "ErrAuthDisabled": (
        Code.FailedPrecondition,
        "etcdserver: authentication is disabled",
        "etcd_tpu.auth.store:AuthDisabledError"),
    # Cluster health / leadership (rpctypes/error.go:72-84)
    "ErrNoLeader": (
        Code.Unavailable, "etcdserver: no leader",
        "etcd_tpu.server.v3election:ElectionNoLeaderError"),
    "ErrNotLeader": (
        Code.FailedPrecondition, "etcdserver: not leader",
        "etcd_tpu.pkg.errors:NotLeaderError"),
    "ErrStopped": (
        Code.Unavailable, "etcdserver: server stopped",
        "etcd_tpu.server.server:StoppedError"),
    "ErrTimeout": (
        Code.Unavailable, "etcdserver: request timed out",
        "etcd_tpu.server.server:TimeoutError_"),
    "ErrCorrupt": (
        Code.DataLoss, "etcdserver: corrupt cluster",
        "etcd_tpu.server.apply:CorruptError"),
    "ErrCorruptCheck": (
        Code.DataLoss, "etcdserver: corruption check failed",
        "etcd_tpu.server.corrupt:CorruptCheckError"),
    # v3election (api/v3election)
    "ErrElectionNotLeader": (
        Code.FailedPrecondition, "etcdserver: not leader of election",
        "etcd_tpu.server.v3election:ElectionNotLeaderError"),
}

# Class name -> symbol (reverse index for serialization).
_CLASS_TO_SYMBOL: Dict[str, str] = {
    path.rsplit(":", 1)[1]: sym for sym, (_, _, path) in TABLE.items()
}
# Duplicate class names would silently shadow each other here; the
# round-trip test asserts this mapping stays 1:1.

# Symbols clients fail over to another endpoint on: exactly the
# Unavailable class (ref: client/v3 retry_interceptor.go — retries on
# codes.Unavailable), which captures no-leader/stopped/member-removed.
FAILOVER_SYMBOLS = frozenset(
    sym for sym, (code, _, _) in TABLE.items() if code == Code.Unavailable
)


def entry_for_exception(e: Exception) -> Optional[Tuple[str, Code, str]]:
    """(symbol, grpc code, canonical message) for a typed server error,
    or None for errors outside the canonical table."""
    sym = _CLASS_TO_SYMBOL.get(type(e).__name__)
    if sym is None:
        return None
    code, msg, _path = TABLE[sym]
    return sym, code, msg


def exception_for(symbol: str, msg: str = "") -> Optional[Exception]:
    """Rebuild the canonical typed exception for a symbol (client side).
    Returns None for unknown symbols (caller falls back to a generic
    error). Classes are resolved lazily to keep this module free of
    import cycles."""
    entry = TABLE.get(symbol)
    if entry is None:
        return None
    code, canonical_msg, path = entry
    mod_name, cls_name = path.rsplit(":", 1)
    cls = getattr(importlib.import_module(mod_name), cls_name)
    return cls(msg or canonical_msg)
