"""Shared error types spanning layers (ref: api/v3rpc/rpctypes/error.go
— one canonical table; the client failover set matches these by class
name, so every layer must raise the same classes)."""


class NotLeaderError(Exception):
    """ref: rpctypes.ErrNotLeader — retry against the leader."""
