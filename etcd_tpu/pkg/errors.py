"""Shared error types spanning layers (ref: api/v3rpc/rpctypes/error.go
— one canonical table; the client failover set matches these by class
name, so every layer must raise the same classes)."""


class NotLeaderError(Exception):
    """ref: rpctypes.ErrNotLeader — retry against the leader."""


class LearnerNotReadyError(Exception):
    """ref: rpctypes.ErrGRPCLearnerNotReady — can only promote a
    learner member which is in sync with the leader."""
