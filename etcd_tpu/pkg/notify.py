"""Broadcast notifier: many waiters, one event, re-armed per generation.

Waiters grab the current generation's event; ``notify()`` fires it and
installs a fresh one, so later waiters wait for the *next* occurrence —
the semantics of the reference's channel-swap notifier (ref:
pkg/notify/notify.go, used for firstCommitInTerm at
server/etcdserver/server.go:1835-1844).
"""

from __future__ import annotations

import threading


class Notifier:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._event = threading.Event()

    def receive(self) -> threading.Event:
        with self._lock:
            return self._event

    def notify(self) -> None:
        with self._lock:
            old, self._event = self._event, threading.Event()
        old.set()
