"""Request tracing: named steps with timestamps, logged only when slow.

The server threads a Trace through the apply/range/txn paths and logs it
only if total duration crosses a threshold, with per-step breakdown
(ref: pkg/traceutil/trace.go:56-153; the 100ms threshold use at
server/etcdserver/v3_server.go:752).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

_local = threading.local()


class Trace:
    def __init__(self, operation: str, logger: Optional[logging.Logger] = None,
                 **fields: Any) -> None:
        self.operation = operation
        self.logger = logger or logging.getLogger("etcd_tpu.trace")
        self.fields: Dict[str, Any] = dict(fields)
        self.start = time.monotonic()
        self.steps: List[tuple[str, float, Dict[str, Any]]] = []

    def step(self, msg: str, **fields: Any) -> None:
        self.steps.append((msg, time.monotonic(), fields))

    def add_field(self, **fields: Any) -> None:
        self.fields.update(fields)

    def duration(self) -> float:
        return time.monotonic() - self.start

    def log_if_long(self, threshold: float) -> bool:
        total = self.duration()
        if total < threshold:
            return False
        lines = [
            f"trace[{self.operation}] took {total*1000:.1f}ms "
            f"(threshold {threshold*1000:.0f}ms) {self.fields}"
        ]
        prev = self.start
        for msg, ts, fields in self.steps:
            lines.append(f"  step [{msg}] +{(ts-prev)*1000:.1f}ms {fields or ''}")
            prev = ts
        self.logger.warning("\n".join(lines))
        return True


def todo() -> Trace:
    """A throwaway trace for paths that don't carry one yet."""
    return Trace("TODO")


def get() -> Trace:
    """The ambient trace for this thread (or a fresh TODO trace)."""
    t = getattr(_local, "trace", None)
    return t if t is not None else todo()


def set_ambient(trace: Optional[Trace]) -> None:
    _local.trace = trace
