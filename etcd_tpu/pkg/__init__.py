"""Support libraries (analog of the reference's ``pkg/`` module).

Each submodule is a fresh, idiomatic-Python redesign of one reference
package (cited per-module); together they provide the host-side plumbing
the replicated server is built from: the id→event wait registry, FIFO
apply scheduler, request-id generator, interval tree (auth ranges and
watcher groups), request tracing, heartbeat-contention detection,
benchmark statistics, and broadcast notification.
"""
