"""Support libraries (analog of the reference's ``pkg/`` module).

Each submodule is a fresh, idiomatic-Python redesign of one reference
package (cited per-module); together they provide the host-side plumbing
the replicated server is built from: the id→event wait registry, FIFO
apply scheduler, request-id generator, interval tree (auth ranges and
watcher groups), request tracing, heartbeat-contention detection,
benchmark statistics, and broadcast notification.
"""

import os as _os


def env_flag(name: str) -> bool:
    """The ONE truthiness parse for boolean env knobs ("", "0" and
    "false" are off; anything else is on) — ETCD_TPU_WAL_PIPELINE,
    bench drivers and member processes must agree on it, so it lives
    here instead of being re-derived per call site."""
    return _os.environ.get(name, "") not in ("", "0", "false")
