"""Action-recorder test doubles (the server/mock analog).

The reference ships no-op recorders that unit tests substitute for the
server's storage / wait / v2 store dependencies, asserting WHICH
operations the server performed rather than their effects
(ref: server/mock/{mockstorage,mockwait,mockstore} and
client/pkg/testutil's Recorder). Same contract here, mirroring this
repo's interfaces (storage.ServerStorage, pkg.wait.Wait,
v2store.Store).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


@dataclass
class Action:
    """One recorded call (ref: testutil.Action)."""

    name: str
    params: Tuple = field(default_factory=tuple)


class Recorder:
    """Buffered action recorder (ref: testutil.RecorderBuffered); the
    ``stream=True`` variant blocks in ``wait`` until the expected
    number of actions arrives (ref: testutil.NewRecorderStream)."""

    def __init__(self, stream: bool = False) -> None:
        self._actions: List[Action] = []
        self._cv = threading.Condition()
        self._stream = stream

    def record(self, a: Action) -> None:
        with self._cv:
            self._actions.append(a)
            self._cv.notify_all()

    def actions(self) -> List[Action]:
        with self._cv:
            return list(self._actions)

    def wait(self, n: int, timeout: Optional[float] = 5.0) -> List[Action]:
        """Return once >= n actions were recorded (stream semantics);
        a buffered recorder returns whatever is there. A stream wait
        that times out RAISES — a short list would let the caller's
        assertion fail confusingly or pass vacuously (the reference's
        Recorder.Wait returns an error, testutil/recorder.go)."""
        with self._cv:
            if self._stream:
                if not self._cv.wait_for(
                        lambda: len(self._actions) >= n,
                        timeout=timeout):
                    raise TimeoutError(
                        f"recorded {len(self._actions)}/{n} actions "
                        f"within {timeout}s: {self._actions}")
            return list(self._actions[:n] if self._stream
                        else self._actions)


class StorageRecorder(Recorder):
    """No-op ServerStorage recording save/save_snap/release/sync
    (ref: mockstorage.storageRecorder)."""

    def save(self, hard_state, entries, must_sync: bool = True) -> None:
        self.record(Action("save"))

    def save_snap(self, snap) -> None:
        if snap is not None and snap.metadata.index:
            self.record(Action("save_snap", (snap.metadata.index,)))

    def release(self, snap) -> None:
        if snap is not None and snap.metadata.index:
            self.record(Action("release", (snap.metadata.index,)))

    def sync(self) -> None:
        self.record(Action("sync"))

    def close(self) -> None:
        self.record(Action("close"))


class WaitRecorder(Recorder):
    """pkg.wait.Wait recording register/trigger; waiters resolve
    immediately with None (ref: mockwait.WaitRecorder)."""

    def register(self, wid: int):
        self.record(Action("register", (wid,)))
        return _DoneWaiter()

    def trigger(self, wid: int, value: Any = None) -> bool:
        self.record(Action("trigger", (wid,)))
        return True

    def is_registered(self, wid: int) -> bool:
        return False


class _DoneWaiter:
    def wait(self, timeout: Optional[float] = None) -> Any:
        return None

    def set(self, value: Any) -> None:
        pass

    def done(self) -> bool:
        return True


class StoreRecorder(Recorder):
    """v2store.Store recorder: every API call is recorded and answered
    with a benign empty result (ref: mockstore.StoreRecorder). Only
    the surface EtcdServer's v2 apply path touches is materialized;
    unknown methods record via __getattr__ so new call sites cannot
    silently bypass the recorder."""

    def get(self, path, recursive=False, sorted_=False):
        self.record(Action("get", (path, recursive, sorted_)))
        return None

    def set(self, path, dir_=False, value="", **kw):
        self.record(Action("set", (path, dir_, value)))
        return None

    def update(self, path, value="", **kw):
        self.record(Action("update", (path, value)))
        return None

    def create(self, path, dir_=False, value="", unique=False, **kw):
        self.record(Action("create", (path, dir_, value, unique)))
        return None

    def delete(self, path, dir_=False, recursive=False, **kw):
        self.record(Action("delete", (path, dir_, recursive)))
        return None

    def compare_and_swap(self, path, prev_value, prev_index, value, **kw):
        self.record(Action(
            "compare_and_swap", (path, prev_value, prev_index, value)))
        return None

    def compare_and_delete(self, path, prev_value, prev_index, **kw):
        self.record(Action(
            "compare_and_delete", (path, prev_value, prev_index)))
        return None

    def watch(self, path, recursive=False, stream=False, since=0):
        self.record(Action("watch", (path,)))
        return None

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def _rec(*a, **kw):
            self.record(Action(name, a))
            return None

        return _rec
