"""Prometheus-style metrics registry with text exposition
(ref: the prometheus client usage throughout server/etcdserver/metrics.go,
server/storage/mvcc/metrics.go, rafthttp/metrics.go; served at /metrics
by embed/etcd.go:731 and etcdhttp).

Only the pieces etcd actually uses: Counter, Gauge, Histogram, const
labels, label children, and the `/metrics` text format. No external
dependency — the exposition format is the contract.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Default histogram buckets (prometheus DefBuckets).
DEF_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str, **kv: str):
        if kv:
            values = tuple(kv[n] for n in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(f"{self.name}: want {self.labelnames}, got {key}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                child._labelvalues = key  # type: ignore[attr-defined]
                self._children[key] = child
            return child

    def _new_child(self) -> "_Metric":
        return type(self)(self.name, self.help)

    def _samples(self) -> Iterable[Tuple[str, Sequence[str], Sequence[str], float]]:
        raise NotImplementedError

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        if self.labelnames:
            with self._lock:
                children = list(self._children.items())
            for key, child in children:
                for suffix, ln, lv, val in child._samples():
                    lines.append(
                        f"{self.name}{suffix}"
                        f"{_fmt_labels(tuple(self.labelnames) + tuple(ln), key + tuple(lv))}"
                        f" {_fmt_value(val)}"
                    )
        else:
            for suffix, ln, lv, val in self._samples():
                lines.append(
                    f"{self.name}{suffix}{_fmt_labels(ln, lv)} {_fmt_value(val)}"
                )
        return lines


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, labelnames)
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counter cannot decrease")
        with self._lock:
            self._value += v

    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self):
        yield ("", (), (), self.value())


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, labelnames)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        with self._lock:
            self._value -= v

    def set_to_current_time(self) -> None:
        self.set(time.time())

    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self):
        yield ("", (), (), self.value())


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEF_BUCKETS,
    ):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0

    def _new_child(self) -> "_Metric":
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, v: float) -> None:
        self.observe_many(v, 1)

    def observe_many(self, v: float, n: int) -> None:
        """Fold `n` observations of value `v` in one lock acquisition —
        the pre-bucketed ingest path for device-side histograms (the
        fleet summary frame arrives as bucket counts, not samples;
        calling observe() count-times would be O(rows) per frame)."""
        if n <= 0:
            return
        with self._lock:
            self._sum += v * n
            self._count += n
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += n
                    return
            self._counts[-1] += n

    def time(self):
        return _Timer(self)

    def _samples(self):
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            yield ("_bucket", ("le",), (_fmt_value(b),), cum)
        yield ("_bucket", ("le",), ("+Inf",), total)
        yield ("_sum", (), (), s)
        yield ("_count", (), (), total)


class _Timer:
    def __init__(self, h: Histogram):
        self.h = h

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.h.observe(time.monotonic() - self.t0)


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, m: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(m.name)
            if existing is not None:
                return existing
            self._metrics[m.name] = m
            return m

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


DEFAULT = Registry()


def counter(name: str, help_: str, labelnames: Sequence[str] = ()) -> Counter:
    return DEFAULT.register(Counter(name, help_, labelnames))  # type: ignore[return-value]


def gauge(name: str, help_: str, labelnames: Sequence[str] = ()) -> Gauge:
    return DEFAULT.register(Gauge(name, help_, labelnames))  # type: ignore[return-value]


def histogram(
    name: str,
    help_: str,
    labelnames: Sequence[str] = (),
    buckets: Sequence[float] = DEF_BUCKETS,
) -> Histogram:
    return DEFAULT.register(Histogram(name, help_, labelnames, buckets))  # type: ignore[return-value]
