"""Cluster-unique, roughly-time-ordered request id generator.

Layout (64 bits): [16-bit member prefix | 40-bit unix-millis | 8-bit
counter] — same shape and guarantees as the reference's generator
(ref: pkg/idutil/id.go:20-55): ids from different members never collide,
ids from one member are strictly increasing, and ~256 ids/ms/member are
available before the counter bleeds into the timestamp (which keeps
monotonicity, just borrows from future milliseconds).
"""

from __future__ import annotations

import threading
import time

_TS_BITS = 40
_CNT_BITS = 8
_SUFFIX_BITS = _TS_BITS + _CNT_BITS
_TS_MASK = (1 << _TS_BITS) - 1
_SUFFIX_MASK = (1 << _SUFFIX_BITS) - 1


class Generator:
    def __init__(self, member_id: int, now_ms: int | None = None) -> None:
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        self._prefix = (member_id & 0xFFFF) << _SUFFIX_BITS
        self._suffix = (now_ms & _TS_MASK) << _CNT_BITS
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._suffix = (self._suffix + 1) & _SUFFIX_MASK
            return self._prefix | self._suffix
