"""Minimal ordered map fallback for environments without
``sortedcontainers``.

``storage/mvcc/index.py`` needs a sorted key → value map with ranged
iteration (``irange``) — sortedcontainers' SortedDict where available.
Some deployment images don't ship it, and this repo's policy is to gate
missing third-party deps rather than require installs, so this module
provides the small subset the tree index actually uses, backed by a
plain dict plus a bisect-maintained sorted key list.

Complexity: lookups O(1), ranged scans O(log n + k), inserts/deletes of
NEW keys O(n) (list shift) vs sortedcontainers' O(log n) — acceptable
for the MVCC index at test/dev scale; production images should install
sortedcontainers and get the real thing via the import gate in
``index.py``.
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, Iterator, List, Optional, Tuple


class SortedDict:
    """The subset of sortedcontainers.SortedDict used by TreeIndex:
    get/setitem/delitem/pop/len/contains, key-ordered values()/items(),
    and irange(min, max, inclusive=(bool, bool))."""

    def __init__(self) -> None:
        self._keys: List[Any] = []
        self._data: Dict[Any, Any] = {}

    def __setitem__(self, key: Any, value: Any) -> None:
        if key not in self._data:
            bisect.insort(self._keys, key)
        self._data[key] = value

    def __getitem__(self, key: Any) -> Any:
        return self._data[key]

    def get(self, key: Any, default: Any = None) -> Any:
        return self._data.get(key, default)

    def __delitem__(self, key: Any) -> None:
        del self._data[key]
        i = bisect.bisect_left(self._keys, key)
        del self._keys[i]

    def pop(self, key: Any, default: Any = None) -> Any:
        if key in self._data:
            val = self._data[key]
            del self[key]
            return val
        return default

    def __contains__(self, key: Any) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._keys)

    def keys(self) -> List[Any]:
        return list(self._keys)

    def values(self) -> Iterator[Any]:
        return (self._data[k] for k in self._keys)

    def items(self) -> Iterator[Tuple[Any, Any]]:
        return ((k, self._data[k]) for k in self._keys)

    def irange(self, minimum: Optional[Any] = None,
               maximum: Optional[Any] = None,
               inclusive: Tuple[bool, bool] = (True, True),
               ) -> Iterator[Any]:
        lo = 0
        if minimum is not None:
            lo = (bisect.bisect_left(self._keys, minimum) if inclusive[0]
                  else bisect.bisect_right(self._keys, minimum))
        hi = len(self._keys)
        if maximum is not None:
            hi = (bisect.bisect_right(self._keys, maximum) if inclusive[1]
                  else bisect.bisect_left(self._keys, maximum))
        return iter(self._keys[lo:hi])
