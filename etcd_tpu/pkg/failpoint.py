"""gofail-style failpoints (ref: the gofail comment-macros compiled
into the reference's persistence path, etcdserver/raft.go:222-265
raftBeforeSave/raftAfterSave/raftBeforeSaveSnap/…, toggled at runtime
by the functional tester's RANDOM_FAILPOINTS via the agent endpoint).

Sites call ``fp("name")``; enabled actions:

* ``panic``        — raise FailpointPanic (crashes the calling loop)
* ``sleep(<ms>)``  — delay the caller
* ``error``        — raise FailpointError (recoverable error injection)
* a callable       — run arbitrary code at the site

Disabled sites cost one dict lookup.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Union

Action = Union[str, Callable[[], None]]


class FailpointPanic(BaseException):
    """Deliberate crash (BaseException so normal handlers don't eat it;
    the test harness catches it at thread top-level)."""


class FailpointError(Exception):
    """Recoverable injected error."""


_lock = threading.Lock()
_active: Dict[str, Action] = {}
_hits: Dict[str, int] = {}


def enable(name: str, action: Action = "panic") -> None:
    with _lock:
        _active[name] = action


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    with _lock:
        _active.clear()
        _hits.clear()


def status() -> List[str]:
    with _lock:
        return sorted(_active)


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def fp(name: str) -> None:
    """The failpoint site."""
    action = _active.get(name)
    if action is None:
        return
    with _lock:
        _hits[name] = _hits.get(name, 0) + 1
    if callable(action):
        action()
        return
    if action == "panic":
        raise FailpointPanic(name)
    if action == "error":
        raise FailpointError(name)
    m = re.match(r"sleep\((\d+)\)", action)
    if m:
        time.sleep(int(m.group(1)) / 1000.0)
        return
    raise ValueError(f"unknown failpoint action {action!r}")
