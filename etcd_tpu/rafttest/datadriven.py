"""Parser for the cockroachdb/datadriven test-file format used by the
reference's interaction tests (ref: raft/interaction_test.go:24-38).

File format:

    # comment
    cmd arg1 key=val key2=(v1,v2)
    optional input lines
    ----
    expected output (terminated by a blank line)

Outputs containing blank lines are wrapped in double separators::

    cmd
    ----
    ----
    multi-line output

    with blank lines
    ----
    ----
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple


@dataclass
class CmdArg:
    key: str
    vals: List[str] = field(default_factory=list)


@dataclass
class TestData:
    pos: str = ""
    cmd: str = ""
    cmd_args: List[CmdArg] = field(default_factory=list)
    input: str = ""
    expected: str = ""


def _parse_args(tokens: List[str]) -> List[CmdArg]:
    args = []
    for tok in tokens:
        if "=" in tok:
            key, val = tok.split("=", 1)
            if val.startswith("(") and val.endswith(")"):
                vals = [v.strip() for v in val[1:-1].split(",") if v.strip()]
            else:
                vals = [val]
            args.append(CmdArg(key=key, vals=vals))
        else:
            args.append(CmdArg(key=tok))
    return args


def _tokenize(line: str) -> List[str]:
    """Split on whitespace, but keep parenthesized value lists intact even
    if they contain spaces (e.g. ``voters=(1, 2, 3)``)."""
    tokens: List[str] = []
    cur: List[str] = []
    depth = 0
    for ch in line:
        if ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch.isspace() and depth == 0:
            if cur:
                tokens.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if cur:
        tokens.append("".join(cur))
    return tokens


def parse_file(path: str) -> List[TestData]:
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().split("\n")

    datas: List[TestData] = []
    i = 0
    n = len(lines)
    while i < n:
        line = lines[i]
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            i += 1
            continue
        # Directive line.
        d = TestData(pos=f"{path}:{i + 1}")
        tokens = _tokenize(stripped)
        d.cmd = tokens[0]
        d.cmd_args = _parse_args(tokens[1:])
        i += 1
        # Input lines until the ---- separator.
        input_lines: List[str] = []
        while i < n and lines[i].strip() != "----":
            input_lines.append(lines[i])
            i += 1
        d.input = "\n".join(input_lines).strip()
        if i >= n:
            raise ValueError(f"{d.pos}: missing ---- separator")
        i += 1  # consume ----
        # Double-separator form allows blank lines in the output.
        if i < n and lines[i].strip() == "----":
            i += 1
            out_lines: List[str] = []
            while i < n:
                if (
                    lines[i].strip() == "----"
                    and i + 1 < n
                    and lines[i + 1].strip() == "----"
                ):
                    i += 2
                    break
                out_lines.append(lines[i])
                i += 1
            d.expected = "\n".join(out_lines)
        else:
            out_lines = []
            while i < n and lines[i].strip() != "":
                out_lines.append(lines[i])
                i += 1
            d.expected = "\n".join(out_lines)
        datas.append(d)
    return datas


def run_file(
    path: str, handler: Callable[[TestData], str]
) -> List[Tuple[TestData, str]]:
    """Run every directive through handler; returns (data, actual) for any
    mismatches (empty list == full parity)."""
    failures = []
    for d in parse_file(path):
        actual = handler(d)
        if actual.rstrip("\n") != d.expected.rstrip("\n"):
            failures.append((d, actual))
    return failures
