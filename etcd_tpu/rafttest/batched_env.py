"""Replay the reference's raft/testdata interaction traces through the
BATCHED DEVICE ENGINE, asserting state equivalence against the host
oracle at every directive boundary.

Why state parity and not textual parity (the written justification the
round-4 review asked for): the trace files' expected text encodes two
things beyond consensus semantics —

1. the reference's internal LOG LINES (``INFO 1 became leader...``),
   emitted at exact points inside raft.go step functions. The device
   engine is an SoA kernel; it has no logger, and synthesizing the
   ~30 distinct formats from state deltas would test the synthesizer,
   not the engine (our host oracle already reproduces them
   byte-for-byte — tests/raft/test_trace_parity.py);
2. the reference's READY BOUNDARIES: one logical transition is split
   across several Readys by rawnode.go's scheduling (e.g.
   confchange_v1_add_single.txt shows entries+commit in one Ready and
   the MsgApp in the NEXT). The batched engine fuses
   deliver→tick→propose→emit into one device round per design
   (SURVEY §7.3); making it reproduce Go's Ready splits would mean
   re-implementing rawnode.go's scheduler around the kernel — a
   textual-parity adapter, not an engine property.

So the parity chain is: reference text ≡ host oracle text
(byte-for-byte, existing suite) AND host oracle state ≡ device engine
state after EVERY directive of every trace (this module): term, vote,
commit, role, lead, last index, log floor, per-index entry terms, and
the applied state machine (the appender history's index/term/content
and conf state). Every directive of all 11 traces is replayed — none
excluded.

ref: raft/interaction_test.go:24-38, rafttest/interaction_env.go.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..batched.node import BatchedNode, ProposalDroppedError
from ..raft.confchange import ConfChangeError
from ..raft.errors import RaftError
from ..raft.types import (
    ConfChange,
    ConfChangeTransition,
    ConfChangeV2,
    ConfState,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    conf_changes_from_string,
)
from .datadriven import TestData


class _BNode:
    """One trace node: a BatchedNode plus the env-side app state the
    oracle's InteractionEnv keeps (appender history) and the buffered
    Readys between eager device rounds and trace process-ready."""

    def __init__(self, node: BatchedNode, history: List[Snapshot]):
        self.node = node
        self.history = history
        self.readys: List = []  # translated Readys awaiting process-ready


class BatchedInteractionEnv:
    """Directive-for-directive twin of rafttest's InteractionEnv over
    the batched device engine (state-parity harness; see module doc).

    ``capacity`` (R) must cover every node the trace will add — the
    batched layout compiles replica capacity as a static shape
    (membership is masks, capacity is not; ref: BatchedNode docstring).
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.nodes: List[_BNode] = []
        self.messages: List[Message] = []  # in-flight, like env.messages

    # -- directive dispatch ----------------------------------------------------

    def handle(self, d: TestData) -> None:
        handler = {
            "_breakpoint": lambda d: None,
            "log-level": lambda d: None,  # text-only directive
            "raft-log": lambda d: None,  # read-only (oracle renders)
            "raft-state": lambda d: None,
            "status": lambda d: None,
            "add-nodes": self._add_nodes,
            "campaign": self._campaign,
            "compact": self._compact,
            "deliver-msgs": self._deliver_msgs,
            "process-ready": self._process_ready,
            "stabilize": self._stabilize,
            "tick-heartbeat": self._tick_heartbeat,
            "transfer-leadership": self._transfer_leadership,
            "propose": self._propose,
            "propose-conf-change": self._propose_conf_change,
        }.get(d.cmd)
        if handler is None:
            raise ValueError(f"unknown command {d.cmd}")
        try:
            handler(d)
        except (RaftError, ValueError):
            # The oracle renders these into the expected text; for
            # state parity the failed directive is a no-op.
            pass

    # -- node lifecycle --------------------------------------------------------

    def _add_nodes(self, d: TestData) -> None:
        n = int(d.cmd_args[0].key)
        cs = ConfState()
        index = 0
        data = b""
        for arg in d.cmd_args[1:]:
            for val in arg.vals:
                if arg.key == "voters":
                    cs.voters.append(int(val))
                elif arg.key == "learners":
                    cs.learners.append(int(val))
                elif arg.key == "index":
                    index = int(val)
                elif arg.key == "content":
                    data = val.encode()
        bootstrap = bool(data or index or cs.voters or cs.learners)
        from ..batched.rawnode import RowRestore

        for _ in range(n):
            node_id = 1 + len(self.nodes)
            restore = None
            if bootstrap:
                restore = RowRestore(
                    term=0, vote=0, commit=index, applied=index,
                    snap_index=index, snap_term=1,
                )
            node = BatchedNode(
                node_id,
                peers=list(range(1, self.capacity + 1)),
                election_tick=3,
                heartbeat_tick=1,
                window=64,
                max_ents_per_msg=8,
                max_props_per_round=4,
                pre_vote=False,  # default_raft_config has no prevote
                check_quorum=False,
                restore=restore,
                boot_conf_state=cs.clone(),
                capacity=self.capacity,
            )
            snap = Snapshot(
                data=data,
                metadata=SnapshotMetadata(
                    conf_state=cs.clone(), index=index,
                    term=1 if bootstrap else 0,
                ),
            )
            self.nodes.append(_BNode(node, [snap]))

    # -- directives ------------------------------------------------------------

    def _campaign(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        self.nodes[idx].node.campaign()
        self._drain(idx)

    def _compact(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        new_first = int(d.cmd_args[1].key)
        bn = self.nodes[idx]
        # Go's Storage.Compact(i) discards entries <= i; the device twin
        # moves the ring floor there, with the latest applied snapshot
        # available for any straggler (snapOverrideStorage semantics).
        bn.node.compact(new_first, bn.history[-1])

    def _deliver_msgs(self, d: TestData) -> None:
        recipients: List[Tuple[int, bool]] = []
        for arg in d.cmd_args:
            if not arg.vals:
                recipients.append((int(arg.key), False))
            elif arg.key == "drop":
                for val in arg.vals:
                    recipients.append((int(val), True))
        for rid, drop in recipients:
            msgs = [m for m in self.messages if m.to == rid]
            self.messages = [m for m in self.messages if m.to != rid]
            if drop:
                continue
            for m in msgs:
                self._step(rid - 1, m)
            self._drain(rid - 1)

    def _step(self, idx: int, m: Message) -> None:
        try:
            self.nodes[idx].node.step(m)
        except (RaftError, ProposalDroppedError):
            pass

    def _process_ready(self, d: TestData) -> None:
        for idx in self._node_idxs(d):
            self._flush_readys(idx)

    def _stabilize(self, d: TestData) -> None:
        idxs = self._node_idxs(d) or list(range(len(self.nodes)))
        ids = [i + 1 for i in idxs]
        while True:
            done = True
            for idx in idxs:
                self._drain(idx)
                if self.nodes[idx].readys:
                    done = False
                    self._flush_readys(idx)
            for idx in idxs:
                nid = idx + 1
                if any(m.to == nid for m in self.messages):
                    done = False
                    msgs = [m for m in self.messages if m.to == nid]
                    self.messages = [
                        m for m in self.messages if m.to != nid
                    ]
                    for m in msgs:
                        self._step(idx, m)
                    self._drain(idx)
            # Messages addressed to nodes outside the stabilize set
            # stay in flight (the oracle behaves the same way).
            if done and not any(
                self.nodes[i].readys for i in idxs
            ) and not any(m.to in ids for m in self.messages):
                return

    def _tick_heartbeat(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        self.nodes[idx].node.tick()  # heartbeat_tick == 1
        self._drain(idx)

    def _transfer_leadership(self, d: TestData) -> None:
        from_id = to_id = 0
        for arg in d.cmd_args:
            if arg.key == "from":
                from_id = int(arg.vals[0])
            elif arg.key == "to":
                to_id = int(arg.vals[0])
        self.nodes[from_id - 1].node.transfer_leadership(from_id, to_id)
        self._drain(from_id - 1)

    def _propose(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        self.nodes[idx].node.propose(d.cmd_args[1].key.encode(),
                                     timeout=0.05)
        self._drain(idx)

    def _propose_conf_change(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        v1 = False
        transition = ConfChangeTransition.ConfChangeTransitionAuto
        for arg in d.cmd_args[1:]:
            for val in arg.vals:
                if arg.key == "v1":
                    v1 = val.lower() == "true"
                elif arg.key == "transition":
                    transition = {
                        "auto": ConfChangeTransition.ConfChangeTransitionAuto,
                        "implicit":
                            ConfChangeTransition.ConfChangeTransitionJointImplicit,
                        "explicit":
                            ConfChangeTransition.ConfChangeTransitionJointExplicit,
                    }[val]
        ccs = conf_changes_from_string(d.input)
        if v1:
            cc = ConfChange(type=ccs[0].type, node_id=ccs[0].node_id)
            self.nodes[idx].node.propose_conf_change(cc, timeout=0.05)
        else:
            cc2 = ConfChangeV2(transition=transition, changes=ccs)
            self.nodes[idx].node.propose_conf_change(cc2, timeout=0.05)
        self._drain(idx)

    # -- engine plumbing -------------------------------------------------------

    def _drain(self, idx: int) -> None:
        """Run device rounds until this node has no staged work,
        buffering the translated Readys for the trace's
        process-ready/stabilize directives to release.

        Committed entries apply AFTER the staged inbox fully drains —
        mirroring the oracle's ordering, where every in-flight message
        is stepped before process-ready applies (so e.g. two acks that
        commit past a self-removal all count under the pre-removal
        config, the exact scenario of confchange_v1_remove_leader.txt).
        Per-round apply would interleave mask uploads between messages
        the oracle steps as one batch."""
        bn = self.nodes[idx]
        pending: List = []
        progressed = True
        while progressed:
            progressed = False
            while bn.node.has_ready():
                rd = bn.node.ready(timeout=0)
                if rd is None:
                    break
                progressed = True
                pending.extend(rd.committed_entries)
                bn.readys.append(rd)
                bn.node.advance()
            if pending:
                self._apply_committed(bn, pending)
                pending = []
                progressed = True  # apply may poke/propose more work

    def _apply_committed(self, bn: _BNode, entries: List) -> None:
        """The env is the app: conf changes upload masks, every entry
        extends the appender history (process_ready.go:64-101).

        NB: an inbound snapshot install deliberately does NOT touch
        history — the reference env only appends History for committed
        entries, leaving a restored node's History at its boot state."""
        for ent in entries:
            update = b""
            cs: Optional[ConfState] = None
            # Conf-change application may raise (the traces include
            # deliberate error cases the oracle renders as text); the
            # entry still extends history with the prior config, like
            # the oracle's error path.
            if ent.type == EntryType.EntryConfChange:
                cc = ConfChange.unmarshal(ent.data)
                update = cc.context
                try:
                    cs = bn.node.apply_conf_change(cc)
                except (RaftError, ValueError, ConfChangeError):
                    cs = None
            elif ent.type == EntryType.EntryConfChangeV2:
                cc2 = ConfChangeV2.unmarshal(ent.data)
                update = cc2.context
                try:
                    cs = bn.node.apply_conf_change(cc2)
                except (RaftError, ValueError, ConfChangeError):
                    cs = None
            else:
                update = ent.data
            last = bn.history[-1]
            snap = Snapshot(data=last.data + update)
            snap.metadata.index = ent.index
            snap.metadata.term = ent.term
            snap.metadata.conf_state = (
                cs or last.metadata.conf_state
            ).clone()
            bn.history.append(snap)
        # The latest applied state backs outbound MsgSnap
        # (snapOverrideStorage: always the newest app snapshot).
        bn.node.set_app_snapshot(bn.history[-1])

    def _flush_readys(self, idx: int) -> None:
        """Trace-level process-ready: release buffered messages into
        the in-flight pool (persist/apply already happened at drain —
        the device engine is its own storage)."""
        bn = self.nodes[idx]
        self._drain(idx)
        for rd in bn.readys:
            self.messages.extend(rd.messages)
        bn.readys.clear()

    @staticmethod
    def _node_idxs(d: TestData) -> List[int]:
        return [int(a.key) - 1 for a in d.cmd_args if not a.vals]


# -- state comparison ----------------------------------------------------------


def state_divergences(oracle_env, batched_env: BatchedInteractionEnv,
                      check_conf: bool = True) -> List[str]:
    """Compare the host-oracle InteractionEnv and the batched env node
    by node; returns human-readable divergences (empty == parity)."""
    out: List[str] = []
    if len(oracle_env.nodes) != len(batched_env.nodes):
        return [
            f"node count: oracle={len(oracle_env.nodes)} "
            f"batched={len(batched_env.nodes)}"
        ]
    for i, (on, bn) in enumerate(zip(oracle_env.nodes, batched_env.nodes)):
        nid = i + 1
        r = on.rawnode.raft
        rn = bn.node.rn

        def chk(name: str, want, got) -> None:
            if want != got:
                out.append(
                    f"node {nid} {name}: oracle={want} batched={got}")

        chk("term", int(r.term), int(rn.m_term[0]))
        chk("vote", int(r.vote), int(rn.m_vote[0]))
        chk("commit", int(r.raft_log.committed), int(rn.m_commit[0]))
        chk("role", int(r.state.value), int(rn.m_role[0]))
        chk("lead", int(r.lead), int(rn.m_lead[0]))
        if check_conf:
            # A committed conf change applies at drain time in the
            # device env but at process-ready in the oracle; its
            # side-effect proposals (auto-leave) can transiently extend
            # the device log, so the log BOUNDS are a quiescent check.
            chk("last_index", int(r.raft_log.last_index()),
                int(rn.m_last[0]))
            chk("first_index", int(r.raft_log.first_index()),
                int(rn.m_snap[0]) + 1)
        # Entry terms over the shared visible window.
        lo = max(int(r.raft_log.first_index()), int(rn.m_snap[0]) + 1)
        hi = min(int(r.raft_log.last_index()), int(rn.m_last[0]))
        w = rn.cfg.window
        for idx2 in range(lo, hi + 1):
            want_t = int(r.raft_log.term(idx2))
            got_t = int(rn.m_ring[0, idx2 % w])
            if want_t != got_t:
                out.append(
                    f"node {nid} log[{idx2}].term: oracle={want_t} "
                    f"batched={got_t}")
        # Applied state machine (appender history) — compared only at
        # quiescent boundaries: the device env applies committed
        # entries at drain time, the oracle at process-ready.
        oh, bh = on.history[-1], bn.history[-1]
        if check_conf:
            chk("applied.index", oh.metadata.index, bh.metadata.index)
            chk("applied.data", oh.data, bh.data)
            chk("conf.voters", sorted(oh.metadata.conf_state.voters),
                sorted(bh.metadata.conf_state.voters))
            chk("conf.learners",
                sorted(oh.metadata.conf_state.learners),
                sorted(bh.metadata.conf_state.learners))
            chk("conf.voters_outgoing",
                sorted(oh.metadata.conf_state.voters_outgoing),
                sorted(bh.metadata.conf_state.voters_outgoing))
    return out
