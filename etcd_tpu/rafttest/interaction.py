"""Interaction environment for data-driven raft testing
(ref: raft/rafttest/interaction_env.go and the handler files).

Semantics — including output formatting, indentation, quiet levels and
error rendering — mirror the reference so that the upstream testdata
traces replay unchanged.
"""

from __future__ import annotations

from typing import List, Optional

from ..raft.errors import RaftError
from ..raft.logger import Logger
from ..raft.raft import Config
from ..raft.rawnode import RawNode
from ..raft.storage import MemoryStorage
from ..raft.tracker import progress_map_str
from ..raft.types import (
    ConfChange,
    ConfChangeTransition,
    ConfChangeV2,
    Entry,
    EntryType,
    Message,
    Snapshot,
    SnapshotMetadata,
    ConfState,
    conf_changes_from_string,
    is_empty_hard_state,
    is_empty_snap,
)
from ..raft.util import (
    default_entry_formatter,
    describe_entries,
    describe_message,
    describe_ready,
)
from .datadriven import TestData

NO_LIMIT = (1 << 64) - 1
MAX_INT32 = (1 << 31) - 1

LVL_NAMES = ["DEBUG", "INFO", "WARN", "ERROR", "FATAL", "NONE"]


class RedirectLogger(Logger):
    """Level-gated logger writing into a string buffer
    (ref: rafttest/interaction_env_logger.go)."""

    def __init__(self):
        self.parts: List[str] = []
        self.lvl = 0  # 0=DEBUG 1=INFO 2=WARN 3=ERROR 4=FATAL 5=NONE

    # direct (ungated) writes, like fmt.Fprintf(env.Output, ...)
    def write(self, s: str) -> None:
        self.parts.append(s)

    def getvalue(self) -> str:
        return "".join(self.parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self.parts)

    def reset(self) -> None:
        self.parts = []

    def _printf(self, lvl: int, fmt: str, args) -> None:
        if self.lvl <= lvl:
            msg = fmt % args if args else fmt
            if not msg.endswith("\n"):
                msg += "\n"
            self.parts.append(f"{LVL_NAMES[lvl]} {msg}")

    def debugf(self, fmt, *args):
        self._printf(0, fmt, args)

    def infof(self, fmt, *args):
        self._printf(1, fmt, args)

    def warningf(self, fmt, *args):
        self._printf(2, fmt, args)

    def errorf(self, fmt, *args):
        self._printf(3, fmt, args)

    def error(self, *args):
        if self.lvl <= 3:
            self.parts.append("ERROR " + " ".join(str(a) for a in args) + "\n")

    def fatalf(self, fmt, *args):
        self._printf(4, fmt, args)

    def panicf(self, fmt, *args):
        self._printf(4, fmt, args)
        raise RuntimeError(fmt % args if args else fmt)


class _HistorySnapshotStorage(MemoryStorage):
    """MemoryStorage whose snapshot() returns the most recent snapshot in
    the node's history (ref: interaction_env_handler_add_nodes.go
    snapOverrideStorage)."""

    def __init__(self, env: "InteractionEnv", node_id: int):
        super().__init__()
        self._env = env
        self._node_id = node_id

    def snapshot(self) -> Snapshot:
        snaps = self._env.nodes[self._node_id - 1].history
        return snaps[-1]


class Node:
    def __init__(self, rawnode: RawNode, storage: MemoryStorage, config: Config,
                 history: List[Snapshot]):
        self.rawnode = rawnode
        self.storage = storage
        self.config = config
        self.history = history


def default_raft_config(node_id: int, applied: int, storage) -> Config:
    """ref: rafttest/interaction_env.go:89-99."""
    return Config(
        id=node_id,
        applied=applied,
        election_tick=3,
        heartbeat_tick=1,
        storage=storage,
        max_size_per_msg=NO_LIMIT,
        max_inflight_msgs=MAX_INT32,
    )


class InteractionEnv:
    """ref: rafttest/interaction_env.go:43-49."""

    def __init__(self, on_config=None):
        self.on_config = on_config
        self.nodes: List[Node] = []
        self.messages: List[Message] = []  # in-flight
        self.output = RedirectLogger()

    # -- top-level dispatch ---------------------------------------------------

    def handle(self, d: TestData) -> str:
        self.output.reset()
        err: Optional[BaseException] = None
        try:
            handler = {
                "_breakpoint": lambda d: None,
                "add-nodes": self._handle_add_nodes,
                "campaign": self._handle_campaign,
                "compact": self._handle_compact,
                "deliver-msgs": self._handle_deliver_msgs,
                "process-ready": self._handle_process_ready,
                "log-level": self._handle_log_level,
                "raft-log": self._handle_raft_log,
                "raft-state": self._handle_raft_state,
                "stabilize": self._handle_stabilize,
                "status": self._handle_status,
                "tick-heartbeat": self._handle_tick_heartbeat,
                "transfer-leadership": self._handle_transfer_leadership,
                "propose": self._handle_propose,
                "propose-conf-change": self._handle_propose_conf_change,
            }.get(d.cmd)
            if handler is None:
                raise ValueError("unknown command")
            handler(d)
        except (RaftError, ValueError) as e:
            err = e
        if err is not None:
            self.output.write(str(err))
        if len(self.output) == 0:
            return "ok"
        if self.output.lvl == len(LVL_NAMES) - 1:
            if err is not None:
                return str(err)
            return "ok (quiet)"
        return self.output.getvalue()

    def _with_indent(self, f) -> None:
        """Indent all output produced by f by two spaces
        (ref: interaction_env.go:63-73)."""
        orig = self.output.parts
        self.output.parts = []
        f()
        produced = "".join(self.output.parts)
        self.output.parts = orig
        for line in produced.splitlines():
            self.output.write("  " + line + "\n")

    # -- handlers -------------------------------------------------------------

    def _handle_add_nodes(self, d: TestData) -> None:
        n = int(d.cmd_args[0].key)
        snap = Snapshot()
        for arg in d.cmd_args[1:]:
            for val in arg.vals:
                if arg.key == "voters":
                    snap.metadata.conf_state.voters.append(int(val))
                elif arg.key == "learners":
                    snap.metadata.conf_state.learners.append(int(val))
                elif arg.key == "index":
                    snap.metadata.index = int(val)
                elif arg.key == "content":
                    snap.data = val.encode()
        self.add_nodes(n, snap)

    def add_nodes(self, n: int, snap: Snapshot) -> None:
        """ref: interaction_env_handler_add_nodes.go:67-133."""
        bootstrap = bool(
            snap.data
            or snap.metadata.index
            or snap.metadata.term
            or snap.metadata.conf_state.voters
            or snap.metadata.conf_state.learners
        )
        for _ in range(n):
            node_id = 1 + len(self.nodes)
            s = _HistorySnapshotStorage(self, node_id)
            if bootstrap:
                if snap.metadata.index <= 1:
                    raise ValueError("index must be specified as > 1 due to bootstrap")
                snap.metadata.term = 1
                s.apply_snapshot(
                    Snapshot(
                        data=snap.data,
                        metadata=SnapshotMetadata(
                            conf_state=snap.metadata.conf_state.clone(),
                            index=snap.metadata.index,
                            term=snap.metadata.term,
                        ),
                    )
                )
                fi = s.first_index()
                if fi != snap.metadata.index + 1:
                    raise ValueError(
                        f"failed to establish first index {snap.metadata.index + 1}; got {fi}"
                    )
            cfg = default_raft_config(node_id, snap.metadata.index, s)
            if self.on_config is not None:
                self.on_config(cfg)
            cfg.logger = self.output
            rn = RawNode(cfg)
            node_snap = Snapshot(
                data=snap.data,
                metadata=SnapshotMetadata(
                    conf_state=snap.metadata.conf_state.clone(),
                    index=snap.metadata.index,
                    term=snap.metadata.term,
                ),
            )
            self.nodes.append(Node(rn, s, cfg, [node_snap]))

    def _handle_campaign(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        self.nodes[idx].rawnode.campaign()

    def _handle_compact(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        new_first_index = int(d.cmd_args[1].key)
        self.nodes[idx].storage.compact(new_first_index)
        self.raft_log(idx)

    def _handle_deliver_msgs(self, d: TestData) -> None:
        recipients = []  # (id, drop)
        for arg in d.cmd_args:
            if not arg.vals:
                recipients.append((int(arg.key), False))
            elif arg.key == "drop":
                for val in arg.vals:
                    recipients.append((int(val), True))
        if self.deliver_msgs(recipients) == 0:
            self.output.write("no messages\n")

    def deliver_msgs(self, recipients) -> int:
        """ref: interaction_env_handler_deliver_msgs.go:70-96."""
        n = 0
        for rid, drop in recipients:
            msgs = [m for m in self.messages if m.to == rid]
            self.messages = [m for m in self.messages if m.to != rid]
            n += len(msgs)
            for msg in msgs:
                if drop:
                    self.output.write("dropped: ")
                self.output.write(
                    describe_message(msg, default_entry_formatter) + "\n"
                )
                if drop:
                    continue
                to_idx = msg.to - 1
                try:
                    self.nodes[to_idx].rawnode.step(msg)
                except RaftError as e:
                    self.output.write(str(e) + "\n")
        return n

    def _handle_process_ready(self, d: TestData) -> None:
        idxs = self._node_idxs(d)
        for idx in idxs:
            if len(idxs) > 1:
                self.output.write(f"> {idx + 1} handling Ready\n")
                self._with_indent(lambda idx=idx: self.process_ready(idx))
            else:
                self.process_ready(idx)

    def process_ready(self, idx: int) -> None:
        """The canonical Ready-handling sequence: persist HardState and
        entries, apply snapshot, apply committed entries (an "appender"
        state machine recorded into history), collect messages, Advance
        (ref: interaction_env_handler_process_ready.go:43-105)."""
        node = self.nodes[idx]
        rn, s = node.rawnode, node.storage
        rd = rn.ready()
        self.output.write(describe_ready(rd, default_entry_formatter))
        if not is_empty_hard_state(rd.hard_state):
            s.set_hard_state(rd.hard_state)
        s.append(rd.entries)
        if not is_empty_snap(rd.snapshot):
            s.apply_snapshot(rd.snapshot)
        for ent in rd.committed_entries:
            update = b""
            cs: Optional[ConfState] = None
            if ent.type == EntryType.EntryConfChange:
                cc = ConfChange.unmarshal(ent.data)
                update = cc.context
                cs = rn.apply_conf_change(cc)
            elif ent.type == EntryType.EntryConfChangeV2:
                cc2 = ConfChangeV2.unmarshal(ent.data)
                cs = rn.apply_conf_change(cc2)
                update = cc2.context
            else:
                update = ent.data
            last_snap = node.history[-1]
            snap = Snapshot(data=last_snap.data + update)
            snap.metadata.index = ent.index
            snap.metadata.term = ent.term
            if cs is None:
                cs = node.history[-1].metadata.conf_state
            snap.metadata.conf_state = cs.clone()
            node.history.append(snap)
        self.messages.extend(rd.messages)
        rn.advance(rd)

    def _handle_log_level(self, d: TestData) -> None:
        name = d.cmd_args[0].key
        for i, s in enumerate(LVL_NAMES):
            if s.lower() == name.lower():
                self.output.lvl = i
                return
        raise ValueError(f"log levels must be either of {LVL_NAMES}")

    def _handle_raft_log(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        self.raft_log(idx)

    def raft_log(self, idx: int) -> None:
        s = self.nodes[idx].storage
        fi, li = s.first_index(), s.last_index()
        if li < fi:
            self.output.write(f"log is empty: first index={fi}, last index={li}")
            return
        ents = s.entries(fi, li + 1, NO_LIMIT)
        self.output.write(describe_entries(ents, default_entry_formatter))

    def _handle_raft_state(self, d: TestData) -> None:
        """ref: interaction_env_handler_raftstate.go:31-44."""
        for node in self.nodes:
            st = node.rawnode.status()
            voter = st.basic.id in st.config.voters.ids()
            status = "(Voter)" if voter else "(Non-Voter)"
            self.output.write(f"{st.basic.id}: {st.raft_state} {status}\n")

    def _handle_stabilize(self, d: TestData) -> None:
        idxs = self._node_idxs(d)
        self.stabilize(idxs)

    def stabilize(self, idxs: List[int]) -> None:
        """Run Ready handling and message delivery to a fixed point
        (ref: interaction_env_handler_stabilize.go:32-63)."""
        nodes = [self.nodes[i] for i in idxs] if idxs else list(self.nodes)
        while True:
            done = True
            for node in nodes:
                if node.rawnode.has_ready():
                    done = False
                    idx = node.rawnode.status().basic.id - 1
                    self.output.write(f"> {idx + 1} handling Ready\n")
                    self._with_indent(lambda idx=idx: self.process_ready(idx))
            for node in nodes:
                node_id = node.rawnode.status().basic.id
                if any(m.to == node_id for m in self.messages):
                    self.output.write(f"> {node_id} receiving messages\n")
                    self._with_indent(
                        lambda node_id=node_id: self.deliver_msgs([(node_id, False)])
                    )
                    done = False
            if done:
                return

    def _handle_status(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        st = self.nodes[idx].rawnode.status()
        self.output.write(progress_map_str(st.progress))

    def _handle_tick_heartbeat(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        for _ in range(self.nodes[idx].config.heartbeat_tick):
            self.nodes[idx].rawnode.tick()

    def _handle_transfer_leadership(self, d: TestData) -> None:
        from_id = to_id = 0
        for arg in d.cmd_args:
            if arg.key == "from":
                from_id = int(arg.vals[0])
            elif arg.key == "to":
                to_id = int(arg.vals[0])
        if from_id == 0 or from_id > len(self.nodes):
            raise ValueError('expected valid "from" argument')
        if to_id == 0 or to_id > len(self.nodes):
            raise ValueError('expected valid "to" argument')
        self.nodes[from_id - 1].rawnode.transfer_leader(to_id)

    def _handle_propose(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        if len(d.cmd_args) != 2 or d.cmd_args[1].vals:
            raise ValueError("expected exactly one key with no vals")
        self.nodes[idx].rawnode.propose(d.cmd_args[1].key.encode())

    def _handle_propose_conf_change(self, d: TestData) -> None:
        idx = int(d.cmd_args[0].key) - 1
        v1 = False
        transition = ConfChangeTransition.ConfChangeTransitionAuto
        for arg in d.cmd_args[1:]:
            for val in arg.vals:
                if arg.key == "v1":
                    v1 = val.lower() == "true"
                elif arg.key == "transition":
                    transition = {
                        "auto": ConfChangeTransition.ConfChangeTransitionAuto,
                        "implicit": ConfChangeTransition.ConfChangeTransitionJointImplicit,
                        "explicit": ConfChangeTransition.ConfChangeTransitionJointExplicit,
                    }.get(val)
                    if transition is None:
                        raise ValueError(f"unknown transition {val}")
                else:
                    raise ValueError(f"unknown command {arg.key}")
        ccs = conf_changes_from_string(d.input)
        if v1:
            if len(ccs) > 1 or transition != ConfChangeTransition.ConfChangeTransitionAuto:
                raise ValueError(
                    "v1 conf change can only have one operation and no transition"
                )
            cc = ConfChange(type=ccs[0].type, node_id=ccs[0].node_id)
            self.nodes[idx].rawnode.propose_conf_change(cc)
        else:
            cc2 = ConfChangeV2(transition=transition, changes=ccs)
            self.nodes[idx].rawnode.propose_conf_change(cc2)

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _node_idxs(d: TestData) -> List[int]:
        return [int(a.key) - 1 for a in d.cmd_args if not a.vals]
