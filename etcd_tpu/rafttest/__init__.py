"""Data-driven interaction-trace harness (ref: raft/rafttest/).

Replays the reference's ``raft/testdata/*.txt`` traces against the
etcd_tpu consensus core and compares output byte-for-byte — the parity
oracle named by the north star.
"""

from .datadriven import TestData, CmdArg, parse_file, run_file  # noqa: F401
from .interaction import InteractionEnv, RedirectLogger  # noqa: F401
