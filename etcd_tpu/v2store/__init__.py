"""v2 store: the legacy hierarchical TTL store
(ref: server/etcdserver/api/v2store/ — retained in 3.6 the way the
reference retains it: the public v2 API is removed (v2_deprecation.go),
the store survives for internal/membership uses and tooling)."""

from .store import (
    Event, EventHistory, NodeExtern, V2Error, V2Store,
    EcodeKeyNotFound, EcodeNodeExist, EcodeNotDir, EcodeNotFile,
    EcodeDirNotEmpty, EcodeTestFailed,
)

__all__ = [
    "Event", "EventHistory", "NodeExtern", "V2Error", "V2Store",
    "EcodeKeyNotFound", "EcodeNodeExist", "EcodeNotDir", "EcodeNotFile",
    "EcodeDirNotEmpty", "EcodeTestFailed",
]
