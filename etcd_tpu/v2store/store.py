"""The v2 hierarchical store (ref: api/v2store/store.go, node.go,
event.go, event_history.go, watcher_hub.go, ttl_key_heap.go).

Semantics preserved:

* a tree of dirs and value nodes addressed by "/"-paths;
* every mutation bumps the store index; nodes carry created/modified
  indexes;
* TTLs expire via a min-heap scanned on every access (DeleteExpiredKeys
  — the reference syncs on a clock tick; here expiry is checked on
  operations and an explicit ``delete_expired_keys``);
* Get with sorted/recursive; Set/Create/Update with prevExist,
  CompareAndSwap/CompareAndDelete with prevValue/prevIndex;
* in-order keys for dirs created with ``unique`` (POST semantics,
  node_extern.go);
* watchers with an event history ring so watches can start in the past
  (event_history.go, watcher_hub.go scanning).
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# v2 error codes (ref: error/error.go).
EcodeKeyNotFound = 100
EcodeTestFailed = 101
EcodeNotFile = 102
EcodeNotDir = 104
EcodeNodeExist = 105
EcodeRootROnly = 107
EcodeDirNotEmpty = 108

GET = "get"
SET = "set"
CREATE = "create"
UPDATE = "update"
DELETE = "delete"
CAS = "compareAndSwap"
CAD = "compareAndDelete"
EXPIRE = "expire"


class V2Error(Exception):
    def __init__(self, code: int, cause: str, index: int) -> None:
        super().__init__(f"v2 error {code}: {cause} (index {index})")
        self.code = code
        self.cause = cause
        self.index = index


@dataclass
class NodeExtern:
    """ref: node_extern.go NodeExtern."""
    key: str
    value: Optional[str] = None
    dir: bool = False
    created_index: int = 0
    modified_index: int = 0
    expiration: Optional[float] = None
    ttl: int = 0
    nodes: List["NodeExtern"] = field(default_factory=list)


@dataclass
class Event:
    """ref: event.go."""
    action: str
    node: NodeExtern
    prev_node: Optional[NodeExtern] = None
    etcd_index: int = 0


class _Node:
    def __init__(self, store: "V2Store", path: str, created: int,
                 parent: Optional["_Node"], value: Optional[str],
                 expire_at: Optional[float]) -> None:
        self.store = store
        self.path = path
        self.created_index = created
        self.modified_index = created
        self.parent = parent
        self.value = value  # None → dir
        self.children: Dict[str, _Node] = {}
        self.expire_at = expire_at

    @property
    def is_dir(self) -> bool:
        return self.value is None

    def expired(self, now: float) -> bool:
        return self.expire_at is not None and self.expire_at <= now

    def extern(self, recursive: bool = False, sorted_: bool = False,
               now: Optional[float] = None) -> NodeExtern:
        now = now if now is not None else time.time()
        ne = NodeExtern(
            key=self.path,
            value=None if self.is_dir else self.value,
            dir=self.is_dir,
            created_index=self.created_index,
            modified_index=self.modified_index,
        )
        if self.expire_at is not None:
            ne.expiration = self.expire_at
            ne.ttl = max(0, int(round(self.expire_at - now)))
        if self.is_dir:
            kids = [
                c for c in self.children.values() if not c.expired(now)
            ]
            if sorted_:
                kids.sort(key=lambda c: c.path)
            ne.nodes = [
                c.extern(recursive=recursive, sorted_=sorted_, now=now)
                if recursive else NodeExtern(
                    key=c.path, dir=c.is_dir,
                    value=None if c.is_dir else c.value,
                    created_index=c.created_index,
                    modified_index=c.modified_index,
                )
                for c in kids
            ]
        return ne


class _Watcher:
    def __init__(self, hub: "EventHistory", prefix: str, recursive: bool,
                 since: int) -> None:
        self.prefix = prefix
        self.recursive = recursive
        self.since = since
        self._cond = threading.Condition()
        self._event: Optional[Event] = None

    def _notify(self, ev: Event) -> bool:
        with self._cond:
            if self._event is None:
                self._event = ev
                self._cond.notify_all()
                return True
            return False

    def wait(self, timeout: Optional[float] = None) -> Optional[Event]:
        with self._cond:
            if self._event is None:
                self._cond.wait(timeout)
            return self._event


class EventHistory:
    """Ring of recent events for watch-from-index
    (ref: event_history.go, capacity 1000)."""

    def __init__(self, capacity: int = 1000) -> None:
        self.capacity = capacity
        self.events: List[Event] = []
        self.start_index = 0

    def add(self, ev: Event) -> None:
        self.events.append(ev)
        if len(self.events) > self.capacity:
            self.events.pop(0)
            self.start_index += 1

    def scan(self, prefix: str, recursive: bool, since: int) -> Optional[Event]:
        for ev in self.events:
            if ev.etcd_index < since:
                continue
            key = ev.node.key
            if (key == prefix or
                    (recursive and key.startswith(prefix.rstrip("/") + "/"))):
                return ev
        return None


def _normalize(path: str) -> str:
    path = "/" + path.strip("/")
    return path if path != "/" else "/"


class V2Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.index = 0
        self.root = _Node(self, "/", 0, None, None, None)
        self.history = EventHistory()
        self._watchers: List[_Watcher] = []
        self._ttl_heap: List[Tuple[float, str]] = []
        self.stats = {"gets": 0, "sets": 0, "deletes": 0, "expires": 0,
                      "cas": 0, "cad": 0, "creates": 0, "updates": 0}

    # -- serialization (ref: store.go Save/Recovery — the v2 store
    # rides raft snapshots so pre-snapshot state survives compaction) --

    def save(self) -> str:
        """JSON dump of the whole tree + index counter."""

        def enc(node: _Node) -> dict:
            out = {
                "p": node.path,
                "c": node.created_index,
                "m": node.modified_index,
            }
            if node.value is not None:
                out["v"] = node.value
            if node.expire_at is not None:
                out["e"] = node.expire_at
            if node.children:
                out["k"] = [enc(ch) for ch in node.children.values()]
            return out

        import json

        with self._lock:
            return json.dumps({"index": self.index, "root": enc(self.root)})

    def recovery(self, blob: str) -> None:
        """Replace the tree from a save() dump (store.go Recovery)."""
        import json

        d = json.loads(blob)

        def dec(obj: dict, parent: Optional[_Node]) -> _Node:
            node = _Node(self, obj["p"], obj["c"], parent,
                         obj.get("v"), obj.get("e"))
            node.modified_index = obj["m"]
            for ch in obj.get("k", []):
                child = dec(ch, node)
                node.children[child.path.rsplit("/", 1)[-1]] = child
                if child.expire_at is not None:
                    heapq.heappush(self._ttl_heap,
                                   (child.expire_at, child.path))
            return node

        with self._lock:
            self._ttl_heap = []
            self.index = d["index"]
            self.root = dec(d["root"], None)

    # -- internals -------------------------------------------------------------

    def _walk(self, path: str, create_dirs: bool = False) -> _Node:
        node = self.root
        if path == "/":
            return node
        parts = path.strip("/").split("/")
        now = time.time()
        for i, part in enumerate(parts):
            child = node.children.get(part)
            if child is not None and child.expired(now):
                self._expire_node(child)
                child = None
            if child is None:
                if not create_dirs:
                    raise V2Error(EcodeKeyNotFound, path, self.index)
                child = _Node(
                    self, node.path.rstrip("/") + "/" + part,
                    self.index, node, None, None,
                )
                node.children[part] = child
            if not child.is_dir and i < len(parts) - 1:
                raise V2Error(EcodeNotDir, child.path, self.index)
            node = child
        return node

    def _expire_node(self, node: _Node) -> None:
        if node.parent is not None:
            node.parent.children.pop(node.path.rsplit("/", 1)[1], None)
        self.index += 1
        self.stats["expires"] += 1
        ev = Event(EXPIRE, NodeExtern(
            key=node.path, modified_index=self.index,
            created_index=node.created_index,
        ), prev_node=node.extern(), etcd_index=self.index)
        self._publish(ev)

    def delete_expired_keys(self, now: Optional[float] = None) -> int:
        """ref: store.go DeleteExpiredKeys (clock-driven sync)."""
        now = now if now is not None else time.time()
        n = 0
        with self._lock:
            while self._ttl_heap and self._ttl_heap[0][0] <= now:
                _, path = heapq.heappop(self._ttl_heap)
                try:
                    node = self._walk(path)
                except V2Error:
                    continue
                if node.expired(now):
                    self._expire_node(node)
                    n += 1
        return n

    def _publish(self, ev: Event) -> None:
        self.history.add(ev)
        still = []
        for w in self._watchers:
            key = ev.node.key
            hit = key == w.prefix or (
                w.recursive and key.startswith(w.prefix.rstrip("/") + "/")
            )
            if hit and ev.etcd_index >= w.since:
                w._notify(ev)
            else:
                still.append(w)
        self._watchers = still

    # -- public API (store.go Store interface) ---------------------------------

    def get(self, path: str, recursive: bool = False,
            sorted_: bool = False) -> Event:
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            self.stats["gets"] += 1
            node = self._walk(path)
            return Event(
                GET, node.extern(recursive=recursive, sorted_=sorted_),
                etcd_index=self.index,
            )

    def set(self, path: str, dir_: bool = False,
            value: str = "", ttl: Optional[float] = None) -> Event:
        """Create-or-replace (ref: store.go Set)."""
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            prev = None
            try:
                prev = self._walk(path).extern()
            except V2Error:
                pass
            ev = self._create(path, dir_, value, ttl, replace=True,
                              action=SET)
            ev.prev_node = prev
            self.stats["sets"] += 1
            return ev

    def create(self, path: str, dir_: bool = False, value: str = "",
               ttl: Optional[float] = None, unique: bool = False) -> Event:
        """Fails if the node exists; unique appends an in-order key
        (POST, store.go Create)."""
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            if unique:
                path = path.rstrip("/") + f"/{self.index + 1:020d}"
            self.stats["creates"] += 1
            return self._create(path, dir_, value, ttl, replace=False,
                                action=CREATE)

    def _create(self, path: str, dir_: bool, value: str,
                ttl: Optional[float], replace: bool, action: str) -> Event:
        parent_path, _, name = path.rpartition("/")
        parent = self._walk(parent_path or "/", create_dirs=True)
        if not parent.is_dir:
            raise V2Error(EcodeNotDir, parent.path, self.index)
        existing = parent.children.get(name)
        now = time.time()
        if existing is not None and existing.expired(now):
            self._expire_node(existing)
            existing = None
        if existing is not None:
            if not replace:
                raise V2Error(EcodeNodeExist, path, self.index)
            if existing.is_dir:
                raise V2Error(EcodeNotFile, path, self.index)
        self.index += 1
        expire_at = now + ttl if ttl is not None else None
        node = _Node(self, path, self.index, parent,
                     None if dir_ else value, expire_at)
        parent.children[name] = node
        if expire_at is not None:
            heapq.heappush(self._ttl_heap, (expire_at, path))
        ev = Event(action, node.extern(), etcd_index=self.index)
        self._publish(ev)
        return ev

    def update(self, path: str, value: str = "",
               ttl: Optional[float] = None) -> Event:
        """Fails if missing (prevExist=true, store.go Update)."""
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            node = self._walk(path)
            prev = node.extern()
            if node.is_dir and value:
                raise V2Error(EcodeNotFile, path, self.index)
            self.index += 1
            if not node.is_dir:
                node.value = value
            node.modified_index = self.index
            node.expire_at = time.time() + ttl if ttl is not None else None
            if node.expire_at is not None:
                heapq.heappush(self._ttl_heap, (node.expire_at, path))
            self.stats["updates"] += 1
            ev = Event(UPDATE, node.extern(), prev_node=prev,
                       etcd_index=self.index)
            self._publish(ev)
            return ev

    def compare_and_swap(self, path: str, prev_value: Optional[str],
                         prev_index: int, value: str,
                         ttl: Optional[float] = None) -> Event:
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            node = self._walk(path)
            if node.is_dir:
                raise V2Error(EcodeNotFile, path, self.index)
            if ((prev_value is not None and node.value != prev_value) or
                    (prev_index and node.modified_index != prev_index)):
                raise V2Error(
                    EcodeTestFailed,
                    f"[{prev_value} != {node.value}] "
                    f"[{prev_index} != {node.modified_index}]",
                    self.index,
                )
            prev = node.extern()
            self.index += 1
            node.value = value
            node.modified_index = self.index
            if ttl is not None:
                node.expire_at = time.time() + ttl
                heapq.heappush(self._ttl_heap, (node.expire_at, path))
            self.stats["cas"] += 1
            ev = Event(CAS, node.extern(), prev_node=prev,
                       etcd_index=self.index)
            self._publish(ev)
            return ev

    def compare_and_delete(self, path: str, prev_value: Optional[str],
                           prev_index: int) -> Event:
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            node = self._walk(path)
            if node.is_dir:
                raise V2Error(EcodeNotFile, path, self.index)
            if ((prev_value is not None and node.value != prev_value) or
                    (prev_index and node.modified_index != prev_index)):
                raise V2Error(EcodeTestFailed, path, self.index)
            self.stats["cad"] += 1
            return self._delete_node(node, CAD)

    def delete(self, path: str, recursive: bool = False,
               dir_: bool = False) -> Event:
        path = _normalize(path)
        with self._lock:
            self.delete_expired_keys()
            node = self._walk(path)
            if node is self.root:
                raise V2Error(EcodeRootROnly, path, self.index)
            if node.is_dir:
                if not recursive and not dir_:
                    raise V2Error(EcodeNotFile, path, self.index)
                if node.children and not recursive:
                    raise V2Error(EcodeDirNotEmpty, path, self.index)
            self.stats["deletes"] += 1
            return self._delete_node(node, DELETE)

    def _delete_node(self, node: _Node, action: str) -> Event:
        prev = node.extern()
        node.parent.children.pop(node.path.rsplit("/", 1)[1], None)
        self.index += 1
        ev = Event(action, NodeExtern(
            key=node.path, modified_index=self.index,
            created_index=node.created_index,
        ), prev_node=prev, etcd_index=self.index)
        self._publish(ev)
        return ev

    # -- watch (watcher_hub.go) ------------------------------------------------

    def watch(self, prefix: str, recursive: bool = False,
              since: int = 0) -> _Watcher:
        prefix = _normalize(prefix)
        with self._lock:
            w = _Watcher(self.history, prefix, recursive,
                         since or self.index + 1)
            if since:
                past = self.history.scan(prefix, recursive, since)
                if past is not None:
                    w._notify(past)
                    return w
            self._watchers.append(w)
            return w
