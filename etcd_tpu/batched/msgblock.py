"""SoA message blocks: the hosted fast path for raft traffic.

At G=1024 a single round emits ~2*G messages per member; materializing
each as a Python ``Message`` (collect -> encode -> socket -> decode ->
per-message lock + stage) costs ~100us apiece, which is the entire
round budget — the hosted service rate was gated on it. Messages
instead stay as one packed numpy record array end-to-end: view-cast
straight out of the device outbox (step.pack_outbox emits records
pre-packed at wire widths), shipped as ONE frame per peer per round,
and scattered into the next round's inbox with vectorized first-wins
merging.

Entry payloads ride a **flat arena**, not per-record Python lists: one
``ent_term``/``ent_etype``/``ent_len`` SoA plus a single contiguous
payload buffer, with per-record extents derived from the cumsum of
``n_ents``. Every block operation — codec, split, validate, merge —
is offset math and bulk numpy slices; no per-entry ``struct.pack``
loops anywhere on the hot path. Entry indexes are implicit (MsgApp
entries are contiguous from ``index+1``). Only MsgSnap (app-state
payloads attached by the hosting layer at send time) takes the
per-message object path. This is the batched analog of the reference's
two rafthttp channels (ref: server/etcdserver/api/rafthttp/peer.go:
337-349), with the bulk append stream vectorized too.

Wire format (version 2, one frame)::

    u1  version (= WIRE_VERSION)
    u4  n_recs
    n_recs * REC_DTYPE records                 (36 B each)
    u4  n_ents  (must equal sum of rec.n_ents)
    n_ents * ENT_DTYPE entry headers           (9 B each)
    payload bytes (sum of ent_len)

A mismatched version byte, a length that disagrees with the counted
sections, or trailing bytes all raise ``ValueError`` — the transport
counts ``recv_corrupt`` and drops the connection, so a mixed-version
pod degrades to message loss (which raft tolerates) instead of
misparsing frames.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .step import (
    LANE_OF,
    NUM_WIRE_TYPES,
    T_APP,
    T_SNAP,
)

# Bump on any layout change: a frame whose leading byte disagrees is
# rejected at decode (never misparsed).
WIRE_VERSION = 2

# One wire record per message; packed little-endian, 36 bytes = 9 u32
# words — exactly the rows step.pack_outbox emits, so device outbox ->
# wire records is a view-cast, not a gather.
REC_DTYPE = np.dtype([
    ("row", "<u4"),          # receiver-side row (group id in hosting)
    ("to", "<u1"),           # target slot + 1 (member id)
    ("frm", "<u1"),          # sender slot + 1
    ("lane", "<u1"),         # inbox lane (KIND_*)
    ("type", "<u1"),         # wire type (T_*)
    ("reject", "<u1"),
    ("n_ents", "<u1"),       # entries in the arena section (T_APP);
    # one byte caps E at 255 — BatchedConfig.validate() enforces
    # max_ents_per_msg <= state.MAX_WIRE_ENTS so a config can't wrap it
    ("pad", "<u2"),          # word alignment; always 0 on the wire

    ("term", "<u4"),
    ("log_term", "<u4"),
    ("index", "<u4"),
    ("commit", "<u4"),
    ("reject_hint", "<u4"),
    ("ctx", "<u4"),          # 4-byte context word
])
REC_SIZE = REC_DTYPE.itemsize
assert REC_SIZE == 36 and REC_SIZE % 4 == 0

# Per-entry wire header in the entries section: term, etype, data len.
ENT_DTYPE = np.dtype([("term", "<u4"), ("etype", "<u1"), ("len", "<u4")])
ENT_SIZE = ENT_DTYPE.itemsize
_HEAD = struct.Struct("<BI")
_U4 = struct.Struct("<I")

# One entry as carried by a block: (term, etype, data).
BlockEnt = Tuple[int, int, bytes]

_MAX_T = NUM_WIRE_TYPES  # compat alias (LANE_OF's index range)

_EMPTY_U4 = np.empty(0, "<u4")
_EMPTY_U1 = np.empty(0, "<u1")
_EMPTY_I8 = np.empty(0, np.int64)


def ragged_ranges(starts, lens) -> np.ndarray:
    """Concatenated ``arange(s, s+l)`` for each (start, len) pair — the
    ragged-gather index builder (one repeat + one arange, no Python
    loop)."""
    lens = np.asarray(lens, np.int64)
    total = int(lens.sum())
    if total == 0:
        return _EMPTY_I8
    base = np.asarray(starts, np.int64) - (np.cumsum(lens) - lens)
    return np.repeat(base, lens) + np.arange(total, dtype=np.int64)


class MsgBlock:
    """A batch of messages as one structured record array plus a flat
    entry arena.

    ``ent_term``/``ent_etype``/``ent_len`` hold the entries of every
    record back to back in record order; ``payload`` is their data
    bytes, one contiguous buffer. Record i's entries occupy arena rows
    ``[starts[i], starts[i] + ent_counts[i])`` where ``starts`` is the
    exclusive cumsum of ``ent_counts``. For wire-parsed and
    collect-built blocks ``ent_counts == rec["n_ents"]``; a hand-built
    block whose arena disagrees with its counts is dropped by
    ``validate_block`` (a frame cannot lie — from_bytes enforces the
    totals)."""

    __slots__ = ("rec", "ent_term", "ent_etype", "ent_len", "payload",
                 "ent_counts", "_starts", "_pstarts")

    def __init__(self, rec: np.ndarray,
                 ents: Optional[List[Optional[List[BlockEnt]]]] = None,
                 *, ent_term: Optional[np.ndarray] = None,
                 ent_etype: Optional[np.ndarray] = None,
                 ent_len: Optional[np.ndarray] = None,
                 payload: bytes = b"",
                 ent_counts: Optional[np.ndarray] = None) -> None:
        self.rec = rec
        self._starts = None
        self._pstarts = None
        if ents is not None:
            # Compat constructor from per-record entry lists (tests,
            # hand-built blocks); the hot paths build arenas directly.
            counts = np.zeros(len(rec), np.int64)
            terms: List[int] = []
            etys: List[int] = []
            lens: List[int] = []
            parts: List[bytes] = []
            for i, lst in enumerate(ents):
                if not lst:
                    continue
                counts[i] = len(lst)
                for t, ety, d in lst:
                    terms.append(t)
                    etys.append(ety)
                    lens.append(len(d))
                    parts.append(d)
            self.ent_term = np.asarray(terms, "<u4")
            self.ent_etype = np.asarray(etys, "<u1")
            self.ent_len = np.asarray(lens, "<u4")
            self.payload = b"".join(parts)
            self.ent_counts = counts
            return
        self.ent_term = ent_term if ent_term is not None else _EMPTY_U4
        self.ent_etype = ent_etype if ent_etype is not None else _EMPTY_U1
        self.ent_len = ent_len if ent_len is not None else _EMPTY_U4
        self.payload = payload
        if ent_counts is not None:
            self.ent_counts = np.asarray(ent_counts, np.int64)
        elif len(self.ent_term):
            self.ent_counts = rec["n_ents"].astype(np.int64)
        else:
            self.ent_counts = np.zeros(len(rec), np.int64)

    def __len__(self) -> int:
        return len(self.rec)

    # -- arena offsets ---------------------------------------------------------

    def _ent_starts(self) -> np.ndarray:
        """Per-record exclusive cumsum of ent_counts (arena row of each
        record's first entry)."""
        if self._starts is None:
            self._starts = np.cumsum(self.ent_counts) - self.ent_counts
        return self._starts

    def _pay_starts(self) -> np.ndarray:
        """Per-entry exclusive cumsum of ent_len (payload byte offset
        of each entry's data)."""
        if self._pstarts is None:
            ln = self.ent_len.astype(np.int64)
            self._pstarts = np.cumsum(ln) - ln
        return self._pstarts

    # -- compat accessors ------------------------------------------------------

    def entry_list(self, i: int) -> Optional[List[BlockEnt]]:
        """Record i's entries as (term, etype, data) tuples, or None —
        the object-path shape (low-volume consumers only)."""
        c = int(self.ent_counts[i])
        if c == 0:
            return None
        s = int(self._ent_starts()[i])
        ps = self._pay_starts()
        out: List[BlockEnt] = []
        for j in range(s, s + c):
            a = int(ps[j])
            out.append((int(self.ent_term[j]), int(self.ent_etype[j]),
                        bytes(self.payload[a:a + int(self.ent_len[j])])))
        return out

    @property
    def ents(self) -> List[Optional[List[BlockEnt]]]:
        """Materialized per-record entry lists (compat/debug only —
        never on the hot path)."""
        return [self.entry_list(i) for i in range(len(self.rec))]

    # -- codec -----------------------------------------------------------------

    def to_bytes(self) -> bytes:
        ne = len(self.ent_term)
        hdr = np.empty(ne, ENT_DTYPE)
        hdr["term"] = self.ent_term
        hdr["etype"] = self.ent_etype
        hdr["len"] = self.ent_len
        return b"".join((
            _HEAD.pack(WIRE_VERSION, len(self.rec)),
            self.rec.tobytes(),
            _U4.pack(ne),
            hdr.tobytes(),
            self.payload,
        ))

    @classmethod
    def from_bytes(cls, b: bytes) -> "MsgBlock":
        if len(b) < _HEAD.size:
            raise ValueError("block frame too short")
        ver, n = _HEAD.unpack_from(b)
        if ver != WIRE_VERSION:
            raise ValueError(
                f"block wire version {ver} != {WIRE_VERSION}")
        off = _HEAD.size + n * REC_SIZE
        if len(b) < off + 4:
            raise ValueError(
                f"block frame truncated: {len(b)} < {off + 4} "
                f"for {n} recs")
        rec = np.frombuffer(b, REC_DTYPE, count=n, offset=_HEAD.size)
        (ne,) = _U4.unpack_from(b, off)
        counts = rec["n_ents"].astype(np.int64)
        if ne != int(counts.sum()):
            raise ValueError(
                f"entries section counts {ne} entries, records claim "
                f"{int(counts.sum())}")
        hoff = off + 4
        poff = hoff + ne * ENT_SIZE
        if len(b) < poff:
            raise ValueError("entries section truncated")
        hdr = np.frombuffer(b, ENT_DTYPE, count=ne, offset=hoff)
        pay_len = int(hdr["len"].astype(np.int64).sum())
        if len(b) != poff + pay_len:
            raise ValueError(
                f"block frame has {len(b) - poff - pay_len} bytes "
                "beyond the entry payloads")
        return cls(rec, ent_term=hdr["term"], ent_etype=hdr["etype"],
                   ent_len=hdr["len"], payload=b[poff:],
                   ent_counts=counts)

    # -- subset selection ------------------------------------------------------

    def take(self, sel) -> "MsgBlock":
        """Sub-block of the selected records (bool mask, index array,
        or slice), entries carried along. A contiguous slice keeps the
        arena as pure slices; anything else is one ragged gather."""
        rec = self.rec[sel]
        cnt = self.ent_counts[sel]
        tot = int(cnt.sum())
        if tot == 0:
            return MsgBlock(rec, ent_counts=cnt)
        if isinstance(sel, slice) and (sel.step is None or sel.step == 1):
            st = self._ent_starts()[sel]
            e0 = int(st[0])
            e1 = e0 + tot
            ps = self._pay_starts()
            p0 = int(ps[e0])
            p1 = int(ps[e1 - 1]) + int(self.ent_len[e1 - 1])
            return MsgBlock(
                rec, ent_term=self.ent_term[e0:e1],
                ent_etype=self.ent_etype[e0:e1],
                ent_len=self.ent_len[e0:e1],
                payload=self.payload[p0:p1], ent_counts=cnt)
        eidx = ragged_ranges(self._ent_starts()[sel], cnt)
        lens = self.ent_len[eidx]
        bidx = ragged_ranges(self._pay_starts()[eidx], lens)
        pay = np.frombuffer(self.payload, np.uint8)[bidx].tobytes()
        return MsgBlock(rec, ent_term=self.ent_term[eidx],
                        ent_etype=self.ent_etype[eidx], ent_len=lens,
                        payload=pay, ent_counts=cnt)

    def split_by_target(self) -> Dict[int, "MsgBlock"]:
        """Partition by target member id (slot+1)."""
        tos = np.unique(self.rec["to"])
        if len(tos) == 1:
            return {int(tos[0]): self}
        return {int(to): self.take(self.rec["to"] == to) for to in tos}


def validate_block(blk: "MsgBlock", n_rows: int, num_replicas: int,
                   max_ents: int) -> "MsgBlock":
    """Filter wire-controlled block records down to the well-formed
    subset; the rest are dropped, matching the object path's
    corrupt-frame-drop semantics (hosting.py decode).

    A record is well-formed iff row < n_rows, 1 <= frm <= R,
    lane == LANE_OF[type], n_ents <= max_ents, entries only on T_APP,
    never T_SNAP (snapshots carry app state the hosting layer must
    restore FIRST; a forged one would fast-forward raft state past
    entries whose data never arrived), and the arena actually backs
    the claimed entry count (a hand-built block could lie;
    from_bytes-parsed ones cannot — the totals are enforced at
    decode). Anything else would index the dense inbox out of range
    (crashing the member's round loop) or — worse, for frm=0 — wrap to
    a negative flat index and silently forge a message into a
    DIFFERENT group's inbox slot."""
    rec = blk.rec
    if len(rec) == 0:
        return blk
    typ = rec["type"]
    ok = (
        (rec["row"] < n_rows)
        & (rec["frm"] >= 1) & (rec["frm"] <= num_replicas)
        & (typ < _MAX_T) & (typ != T_SNAP)
        & (rec["lane"] == LANE_OF[np.minimum(typ, _MAX_T - 1)])
        & (rec["n_ents"] <= max_ents)
        & ((rec["n_ents"] == 0) | (typ == T_APP))
        & (rec["n_ents"] == blk.ent_counts)
    )
    # Block-level structural check: ent_counts must be backed by the
    # arena itself (a hand-built block can claim counts its arrays
    # don't hold — ent_counts defaults from rec["n_ents"], so the
    # per-record compare above can't see it). If the totals disagree,
    # per-record attribution is meaningless: keep only payload-free
    # records.
    if (int(blk.ent_counts.sum()) != len(blk.ent_term)
            or int(blk.ent_len.astype(np.int64).sum())
            != len(blk.payload)):
        ok &= rec["n_ents"] == 0
    if ok.all():
        return blk
    return blk.take(ok)


def block_messages(blk: "MsgBlock") -> "list":
    """Compat: materialize a block as (row, Message) tuples — for
    low-volume consumers (single-group nodes, trace harnesses) that
    want the object shape."""
    from ..raft.types import Entry, EntryType, Message, MessageType

    out = []
    for i, rec in enumerate(blk.rec):
        m = Message(
            type=MessageType(int(rec["type"])),
            to=int(rec["to"]),
            from_=int(rec["frm"]),
            term=int(rec["term"]),
            log_term=int(rec["log_term"]),
            index=int(rec["index"]),
            commit=int(rec["commit"]),
            reject=bool(rec["reject"]),
            reject_hint=int(rec["reject_hint"]),
        )
        cw = int(rec["ctx"])
        if cw:
            m.context = cw.to_bytes(4, "little")
        ents = blk.entry_list(i) if rec["n_ents"] else None
        if ents:
            m.entries = [
                Entry(index=int(rec["index"]) + 1 + j, term=term,
                      data=data, type=EntryType(etype))
                for j, (term, etype, data) in enumerate(ents)
            ]
        out.append((int(rec["row"]), m))
    return out


def compact_records(words: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Device-packed word rows -> wire records: one view-cast plus one
    boolean take. `words` is the [M, REC_WORDS] i32 output of
    step.pack_outbox (rows are REC_DTYPE bytes); returns the REC_DTYPE
    records selected by `mask` (a fresh, writable array)."""
    w = np.ascontiguousarray(words)
    rec = w.view(REC_DTYPE).reshape(w.shape[0])
    return rec[mask]


def collect_block(out_valid: np.ndarray, out: "object",
                  slots: np.ndarray) -> "tuple[MsgBlock, np.ndarray]":
    """Reference collect: slice the block-eligible messages out of a
    numpy-materialized outbox with per-field gathers.

    Kept as the differential twin of the packed path (step.pack_outbox
    + compact_records, which the hosted hot path uses) and for callers
    holding an already-materialized outbox. Returns (block,
    complex_mask) where complex_mask marks the slots that still need
    the per-message path (MsgSnap only). MsgApp entry payloads are NOT
    attached here (the arena lives in the caller)."""
    typ = np.asarray(out.type)
    n_ents = np.asarray(out.n_ents)
    simple = out_valid & (typ != T_SNAP)
    rows, tgt, k = np.nonzero(simple)
    rec = np.zeros(len(rows), REC_DTYPE)
    t = typ[rows, tgt, k]
    rec["row"] = rows
    rec["to"] = tgt + 1
    rec["frm"] = slots[rows] + 1
    rec["lane"] = LANE_OF[t]
    rec["type"] = t
    rec["reject"] = np.asarray(out.reject)[rows, tgt, k]
    rec["n_ents"] = np.where(t == T_APP, n_ents[rows, tgt, k], 0)
    rec["term"] = np.asarray(out.term)[rows, tgt, k]
    rec["log_term"] = np.asarray(out.log_term)[rows, tgt, k]
    rec["index"] = np.asarray(out.index)[rows, tgt, k]
    rec["commit"] = np.asarray(out.commit)[rows, tgt, k]
    rec["reject_hint"] = np.asarray(out.reject_hint)[rows, tgt, k]
    rec["ctx"] = np.asarray(out.ctx)[rows, tgt, k]
    return MsgBlock(rec), (out_valid & ~simple)


def merge_blocks(
    blocks: List[MsgBlock],
    num_replicas: int,
    num_kinds: int,
    dense: Dict[str, np.ndarray],
    land_entries=None,
) -> List[MsgBlock]:
    """Scatter queued block records into the dense inbox arrays.

    `dense` holds the flat-viewable per-field arrays ([n, R, K], plus
    ``ent_terms`` [n, R, K, E]); slots already filled (by the legacy
    per-message path) are respected. Per inbox key (row, sender, lane)
    at most one record lands per round; FIFO order across blocks is
    preserved: once a key has a deferred record, later records for
    that key stay queued behind it. Returns the residual blocks (in
    order).

    ``land_entries(blk, idx)`` is invoked once per block with the
    record indexes (into ``blk``) whose entry-carrying records LAND
    this round — the caller bulk-copies the payload slices into its
    arena at that moment (entries of a deferred record stay with it in
    the residual)."""
    valid = dense["valid"]
    n_keys = valid.size
    flat_valid = valid.reshape(-1)
    barred = np.zeros(n_keys, bool)
    residual: List[MsgBlock] = []
    flat = {f: a.reshape(-1) for f, a in dense.items()
            if f != "ent_terms"}
    ent_terms = dense.get("ent_terms")
    e_cap = ent_terms.shape[-1] if ent_terms is not None else 0
    flat_ents = (
        ent_terms.reshape(-1, e_cap) if ent_terms is not None else None
    )
    for blk in blocks:
        rec = blk.rec
        if len(rec) == 0:
            continue
        key = (
            (rec["row"].astype(np.int64) * num_replicas
             + (rec["frm"].astype(np.int64) - 1)) * num_kinds
            + rec["lane"]
        )
        # First occurrence of each key within this block...
        _, first_idx = np.unique(key, return_index=True)
        firstmask = np.zeros(len(key), bool)
        firstmask[first_idx] = True
        # ...that is neither already filled nor behind a deferred one.
        take = firstmask & ~flat_valid[key] & ~barred[key]
        idx = key[take]
        flat_valid[idx] = True
        flat["type"][idx] = rec["type"][take]
        flat["term"][idx] = rec["term"][take]
        flat["log_term"][idx] = rec["log_term"][take]
        flat["index"][idx] = rec["index"][take]
        flat["commit"][idx] = rec["commit"][take]
        flat["reject"][idx] = rec["reject"][take].astype(bool)
        flat["reject_hint"][idx] = rec["reject_hint"][take]
        flat["ctx"][idx] = rec["ctx"][take]
        if "n_ents" in flat:
            ne = rec["n_ents"][take]
            if ent_terms is not None:
                # The dense inbox carries at most e_cap entry terms per
                # slot; a record claiming more would land a count its
                # own ent_terms row can't back (the terms below are
                # already truncated to e_cap) — clamp so the inbox
                # stays self-consistent for every caller.
                ne = np.minimum(ne, e_cap)
            flat["n_ents"][idx] = ne
        land = np.nonzero(take & (blk.ent_counts > 0))[0]
        if len(land):
            if flat_ents is not None:
                # Bulk ragged scatter of the landing records' entry
                # terms (clamped to the inbox capacity per record).
                cl = np.minimum(blk.ent_counts[land], e_cap)
                rows_rep = np.repeat(key[land], cl)
                offs = ragged_ranges(np.zeros(len(land), np.int64), cl)
                eidx = ragged_ranges(blk._ent_starts()[land], cl)
                flat_ents[rows_rep, offs] = blk.ent_term[eidx]
            if land_entries is not None:
                land_entries(blk, land)
        rest = ~take
        if rest.any():
            barred[key[rest]] = True
            residual.append(blk.take(rest))
    return residual


def validate_records(rec: np.ndarray, n_rows: int,
                     num_replicas: int) -> np.ndarray:
    """Array-level validation (no entries): kept for callers/tests
    that stage payload-free records directly. See validate_block."""
    blk = validate_block(MsgBlock(rec), n_rows, num_replicas, 0)
    return blk.rec
