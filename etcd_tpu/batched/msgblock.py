"""SoA message blocks: the hosted fast path for payload-free raft
traffic.

At G=1024 a single heartbeat round emits ~2*G messages per member;
materializing each as a Python ``Message`` (collect -> encode -> socket
-> decode -> stage) costs ~100us apiece, which is the entire round
budget — the hosted service rate was gated on it. Payload-free message
types (heartbeats, acks, votes, empty appends, TimeoutNow) instead stay
as one packed numpy record array end-to-end: sliced straight out of the
device outbox, shipped as ONE frame per peer per round, and scattered
into the next round's inbox with vectorized first-wins merging.

Only MsgApp-with-entries and MsgSnap — the two types that carry bytes
the device never sees — take the per-message object path. This is the
batched analog of the reference's two rafthttp channels: the cheap
high-rate stream for small messages and the pipeline for big ones
(ref: server/etcdserver/api/rafthttp/peer.go:337-349).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .step import (
    KIND_APP,
    KIND_APP_RESP,
    KIND_HB,
    KIND_HB_RESP,
    KIND_VOTE,
    KIND_VOTE_RESP,
    T_APP,
    T_APP_RESP,
    T_HB,
    T_HB_RESP,
    T_PREVOTE,
    T_PREVOTE_RESP,
    T_SNAP,
    T_TIMEOUT_NOW,
    T_VOTE,
    T_VOTE_RESP,
)

# One wire record per message; packed little-endian, 33 bytes.
REC_DTYPE = np.dtype([
    ("row", "<u4"),          # receiver-side row (group id in hosting)
    ("to", "<u1"),           # target slot + 1 (member id)
    ("frm", "<u1"),          # sender slot + 1
    ("lane", "<u1"),         # inbox lane (KIND_*)
    ("type", "<u1"),         # wire type (T_*)
    ("reject", "<u1"),
    ("term", "<u4"),
    ("log_term", "<u4"),
    ("index", "<u4"),
    ("commit", "<u4"),
    ("reject_hint", "<u4"),
    ("ctx", "<u4"),          # 4-byte context word
])

# Wire type -> inbox lane, as a lookup table for vectorized use
# (mirrors rawnode._LANE).
_MAX_T = 32
LANE_OF = np.full(_MAX_T, -1, np.int8)
for _t, _lane in (
    (T_VOTE, KIND_VOTE), (T_PREVOTE, KIND_VOTE),
    (T_APP, KIND_APP), (T_SNAP, KIND_APP),
    (T_HB, KIND_HB), (T_TIMEOUT_NOW, KIND_HB),
    (T_VOTE_RESP, KIND_VOTE_RESP), (T_PREVOTE_RESP, KIND_VOTE_RESP),
    (T_APP_RESP, KIND_APP_RESP),
    (T_HB_RESP, KIND_HB_RESP),
):
    LANE_OF[_t] = _lane


def validate_records(rec: np.ndarray, n_rows: int,
                     num_replicas: int) -> np.ndarray:
    """Filter wire-controlled block records down to the well-formed
    subset; the rest are dropped, matching the object path's
    corrupt-frame-drop semantics (hosting.py decode).

    A record is well-formed iff row < n_rows, 1 <= frm <= R,
    lane < NUM_KINDS and lane == LANE_OF[type]. Anything else would
    index the dense inbox out of range (crashing the member's round
    loop) or — worse, for frm=0 — wrap to a negative flat index and
    silently forge a message into a DIFFERENT group's inbox slot.
    """
    if len(rec) == 0:
        return rec
    typ = rec["type"]
    # T_SNAP never legitimately rides a block (collect_block keeps it
    # on the object path, where hosting restores app state and WAL-logs
    # the snapshot BEFORE the device sees it); a forged one here would
    # fast-forward raft state past entries whose data never arrived.
    ok = (
        (rec["row"] < n_rows)
        & (rec["frm"] >= 1) & (rec["frm"] <= num_replicas)
        & (typ < _MAX_T) & (typ != T_SNAP)
        & (rec["lane"] == LANE_OF[np.minimum(typ, _MAX_T - 1)])
    )
    return rec if ok.all() else rec[ok]


class MsgBlock:
    """A batch of payload-free messages as one structured array."""

    __slots__ = ("rec",)

    def __init__(self, rec: np.ndarray) -> None:
        self.rec = rec

    def __len__(self) -> int:
        return len(self.rec)

    def to_bytes(self) -> bytes:
        return self.rec.tobytes()

    @classmethod
    def from_bytes(cls, b: bytes) -> "MsgBlock":
        if len(b) % REC_DTYPE.itemsize:
            raise ValueError(f"block frame not a multiple of "
                             f"{REC_DTYPE.itemsize}: {len(b)}")
        return cls(np.frombuffer(b, REC_DTYPE))

    def split_by_target(self) -> Dict[int, "MsgBlock"]:
        """Partition by target member id (slot+1)."""
        rec = self.rec
        out: Dict[int, MsgBlock] = {}
        for to in np.unique(rec["to"]):
            out[int(to)] = MsgBlock(rec[rec["to"] == to])
        return out


def block_messages(blk: "MsgBlock") -> "list":
    """Compat: materialize a block as (row, Message) tuples — for
    low-volume consumers (single-group nodes, trace harnesses) that
    want the object shape."""
    from ..raft.types import Message, MessageType

    out = []
    for rec in blk.rec:
        m = Message(
            type=MessageType(int(rec["type"])),
            to=int(rec["to"]),
            from_=int(rec["frm"]),
            term=int(rec["term"]),
            log_term=int(rec["log_term"]),
            index=int(rec["index"]),
            commit=int(rec["commit"]),
            reject=bool(rec["reject"]),
            reject_hint=int(rec["reject_hint"]),
        )
        cw = int(rec["ctx"])
        if cw:
            m.context = cw.to_bytes(4, "little")
        out.append((int(rec["row"]), m))
    return out


def collect_block(out_valid: np.ndarray, out: "object",
                  slots: np.ndarray) -> "tuple[MsgBlock, np.ndarray]":
    """Slice the simple messages out of a device outbox.

    `out` is the numpy-materialized outbox (fields [n, R, K]); returns
    (block, complex_mask) where complex_mask marks the slots that still
    need the per-message path (MsgApp with entries, MsgSnap).
    """
    typ = np.asarray(out.type)
    n_ents = np.asarray(out.n_ents)
    simple = out_valid & (
        ((typ != T_APP) & (typ != T_SNAP))
        | ((typ == T_APP) & (n_ents == 0))
    )
    rows, tgt, k = np.nonzero(simple)
    rec = np.empty(len(rows), REC_DTYPE)
    t = typ[rows, tgt, k]
    rec["row"] = rows
    rec["to"] = tgt + 1
    rec["frm"] = slots[rows] + 1
    rec["lane"] = LANE_OF[t]
    rec["type"] = t
    rec["reject"] = np.asarray(out.reject)[rows, tgt, k]
    rec["term"] = np.asarray(out.term)[rows, tgt, k]
    rec["log_term"] = np.asarray(out.log_term)[rows, tgt, k]
    rec["index"] = np.asarray(out.index)[rows, tgt, k]
    rec["commit"] = np.asarray(out.commit)[rows, tgt, k]
    rec["reject_hint"] = np.asarray(out.reject_hint)[rows, tgt, k]
    rec["ctx"] = np.asarray(out.ctx)[rows, tgt, k]
    return MsgBlock(rec), (out_valid & ~simple)


def merge_blocks(
    blocks: List[np.ndarray],
    num_replicas: int,
    num_kinds: int,
    dense: Dict[str, np.ndarray],
) -> List[np.ndarray]:
    """Scatter queued block records into the dense inbox arrays.

    `dense` holds the flat-viewable per-field arrays ([n, R, K]); slots
    already filled (by the legacy per-message path) are respected. Per
    inbox key (row, sender, lane) at most one record lands per round;
    FIFO order across blocks is preserved: once a key has a deferred
    record, later records for that key stay queued behind it. Returns
    the residual blocks (in order).
    """
    valid = dense["valid"]
    n_keys = valid.size
    flat_valid = valid.reshape(-1)
    barred = np.zeros(n_keys, bool)
    residual: List[np.ndarray] = []
    flat = {f: a.reshape(-1) for f, a in dense.items()}
    for rec in blocks:
        if len(rec) == 0:
            continue
        key = (
            (rec["row"].astype(np.int64) * num_replicas
             + (rec["frm"].astype(np.int64) - 1)) * num_kinds
            + rec["lane"]
        )
        # First occurrence of each key within this block...
        _, first_idx = np.unique(key, return_index=True)
        firstmask = np.zeros(len(key), bool)
        firstmask[first_idx] = True
        # ...that is neither already filled nor behind a deferred one.
        take = firstmask & ~flat_valid[key] & ~barred[key]
        idx = key[take]
        flat_valid[idx] = True
        flat["type"][idx] = rec["type"][take]
        flat["term"][idx] = rec["term"][take]
        flat["log_term"][idx] = rec["log_term"][take]
        flat["index"][idx] = rec["index"][take]
        flat["commit"][idx] = rec["commit"][take]
        flat["reject"][idx] = rec["reject"][take].astype(bool)
        flat["reject_hint"][idx] = rec["reject_hint"][take]
        flat["ctx"][idx] = rec["ctx"][take]
        rest = ~take
        if rest.any():
            barred[key[rest]] = True
            residual.append(rec[rest])
    return residual
