"""SoA message blocks: the hosted fast path for raft traffic.

At G=1024 a single round emits ~2*G messages per member; materializing
each as a Python ``Message`` (collect -> encode -> socket -> decode ->
per-message lock + stage) costs ~100us apiece, which is the entire
round budget — the hosted service rate was gated on it. Messages
instead stay as one packed numpy record array end-to-end: sliced
straight out of the device outbox, shipped as ONE frame per peer per
round, and scattered into the next round's inbox with vectorized
first-wins merging.

Since round 5 the block also carries MsgApp WITH entries: each record
has an ``n_ents`` count and the frame a trailing entries section
(entry indexes are implicit — MsgApp entries are contiguous from
``index+1``). Only MsgSnap (app-state payloads attached by the hosting
layer at send time) takes the per-message object path. This is the
batched analog of the reference's two rafthttp channels
(ref: server/etcdserver/api/rafthttp/peer.go:337-349), with the bulk
append stream vectorized too.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .step import (
    KIND_APP,
    KIND_APP_RESP,
    KIND_HB,
    KIND_HB_RESP,
    KIND_VOTE,
    KIND_VOTE_RESP,
    T_APP,
    T_APP_RESP,
    T_HB,
    T_HB_RESP,
    T_PREVOTE,
    T_PREVOTE_RESP,
    T_SNAP,
    T_TIMEOUT_NOW,
    T_VOTE,
    T_VOTE_RESP,
)

# One wire record per message; packed little-endian, 34 bytes.
REC_DTYPE = np.dtype([
    ("row", "<u4"),          # receiver-side row (group id in hosting)
    ("to", "<u1"),           # target slot + 1 (member id)
    ("frm", "<u1"),          # sender slot + 1
    ("lane", "<u1"),         # inbox lane (KIND_*)
    ("type", "<u1"),         # wire type (T_*)
    ("reject", "<u1"),
    ("n_ents", "<u1"),       # entries in the trailing section (T_APP);
    # one byte caps E at 255 — BatchedConfig.validate() enforces
    # max_ents_per_msg <= state.MAX_WIRE_ENTS so a config can't wrap it

    ("term", "<u4"),
    ("log_term", "<u4"),
    ("index", "<u4"),
    ("commit", "<u4"),
    ("reject_hint", "<u4"),
    ("ctx", "<u4"),          # 4-byte context word
])

# Per-entry wire header in the entries section: term, etype, data len.
_ENT_HDR = struct.Struct("<IBI")

# One entry as carried by a block: (term, etype, data).
BlockEnt = Tuple[int, int, bytes]

# Wire type -> inbox lane, as a lookup table for vectorized use
# (mirrors rawnode._LANE).
_MAX_T = 32
LANE_OF = np.full(_MAX_T, -1, np.int8)
for _t, _lane in (
    (T_VOTE, KIND_VOTE), (T_PREVOTE, KIND_VOTE),
    (T_APP, KIND_APP), (T_SNAP, KIND_APP),
    (T_HB, KIND_HB), (T_TIMEOUT_NOW, KIND_HB),
    (T_VOTE_RESP, KIND_VOTE_RESP), (T_PREVOTE_RESP, KIND_VOTE_RESP),
    (T_APP_RESP, KIND_APP_RESP),
    (T_HB_RESP, KIND_HB_RESP),
):
    LANE_OF[_t] = _lane


class MsgBlock:
    """A batch of messages as one structured array plus, for records
    with ``n_ents > 0``, their entry payloads (``ents[i]`` is the
    entry list of ``rec[i]`` or None)."""

    __slots__ = ("rec", "ents")

    def __init__(self, rec: np.ndarray,
                 ents: Optional[List[Optional[List[BlockEnt]]]] = None
                 ) -> None:
        self.rec = rec
        self.ents = ents if ents is not None else [None] * len(rec)

    def __len__(self) -> int:
        return len(self.rec)

    def to_bytes(self) -> bytes:
        parts = [struct.pack("<I", len(self.rec)), self.rec.tobytes()]
        for i in np.nonzero(self.rec["n_ents"])[0]:
            for term, etype, data in self.ents[i]:
                parts.append(_ENT_HDR.pack(term, etype, len(data)))
                parts.append(data)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, b: bytes) -> "MsgBlock":
        if len(b) < 4:
            raise ValueError("block frame too short")
        (n,) = struct.unpack_from("<I", b)
        off = 4 + n * REC_DTYPE.itemsize
        if len(b) < off:
            raise ValueError(
                f"block frame truncated: {len(b)} < {off} for {n} recs")
        rec = np.frombuffer(b, REC_DTYPE, count=n, offset=4)
        ents: List[Optional[List[BlockEnt]]] = [None] * n
        for i in np.nonzero(rec["n_ents"])[0]:
            lst: List[BlockEnt] = []
            for _ in range(int(rec["n_ents"][i])):
                if len(b) < off + _ENT_HDR.size:
                    raise ValueError("entries section truncated")
                term, etype, ln = _ENT_HDR.unpack_from(b, off)
                off += _ENT_HDR.size
                if len(b) < off + ln:
                    raise ValueError("entry payload truncated")
                lst.append((term, etype, b[off:off + ln]))
                off += ln
            ents[int(i)] = lst
        if off != len(b):
            raise ValueError(
                f"block frame has {len(b) - off} trailing bytes")
        return cls(rec, ents)

    def split_by_target(self) -> Dict[int, "MsgBlock"]:
        """Partition by target member id (slot+1)."""
        rec = self.rec
        out: Dict[int, MsgBlock] = {}
        for to in np.unique(rec["to"]):
            mask = rec["to"] == to
            out[int(to)] = MsgBlock(
                rec[mask],
                [e for e, keep in zip(self.ents, mask) if keep],
            )
        return out


def validate_block(blk: "MsgBlock", n_rows: int, num_replicas: int,
                   max_ents: int) -> "MsgBlock":
    """Filter wire-controlled block records down to the well-formed
    subset; the rest are dropped, matching the object path's
    corrupt-frame-drop semantics (hosting.py decode).

    A record is well-formed iff row < n_rows, 1 <= frm <= R,
    lane == LANE_OF[type], n_ents <= max_ents, entries only on T_APP,
    and never T_SNAP (snapshots carry app state the hosting layer must
    restore FIRST; a forged one would fast-forward raft state past
    entries whose data never arrived). Anything else would index the
    dense inbox out of range (crashing the member's round loop) or —
    worse, for frm=0 — wrap to a negative flat index and silently
    forge a message into a DIFFERENT group's inbox slot.
    """
    rec = blk.rec
    if len(rec) == 0:
        return blk
    typ = rec["type"]
    ok = (
        (rec["row"] < n_rows)
        & (rec["frm"] >= 1) & (rec["frm"] <= num_replicas)
        & (typ < _MAX_T) & (typ != T_SNAP)
        & (rec["lane"] == LANE_OF[np.minimum(typ, _MAX_T - 1)])
        & (rec["n_ents"] <= max_ents)
        & ((rec["n_ents"] == 0) | (typ == T_APP))
    )
    # Entries must actually be present for every counted record (a
    # hand-built block could lie; from_bytes-parsed ones cannot). Only
    # entry-carrying records need the Python check — the payload-free
    # majority stays vectorized.
    for i in np.nonzero(ok & (rec["n_ents"] > 0))[0]:
        e = blk.ents[i]
        if e is None or len(e) != int(rec["n_ents"][i]):
            ok[i] = False
    if ok.all():
        return blk
    return MsgBlock(rec[ok],
                    [e for e, keep in zip(blk.ents, ok) if keep])


def block_messages(blk: "MsgBlock") -> "list":
    """Compat: materialize a block as (row, Message) tuples — for
    low-volume consumers (single-group nodes, trace harnesses) that
    want the object shape."""
    from ..raft.types import Entry, EntryType, Message, MessageType

    out = []
    for i, rec in enumerate(blk.rec):
        m = Message(
            type=MessageType(int(rec["type"])),
            to=int(rec["to"]),
            from_=int(rec["frm"]),
            term=int(rec["term"]),
            log_term=int(rec["log_term"]),
            index=int(rec["index"]),
            commit=int(rec["commit"]),
            reject=bool(rec["reject"]),
            reject_hint=int(rec["reject_hint"]),
        )
        cw = int(rec["ctx"])
        if cw:
            m.context = cw.to_bytes(4, "little")
        if rec["n_ents"] and blk.ents[i]:
            m.entries = [
                Entry(index=int(rec["index"]) + 1 + j, term=term,
                      data=data, type=EntryType(etype))
                for j, (term, etype, data) in enumerate(blk.ents[i])
            ]
        out.append((int(rec["row"]), m))
    return out


def collect_block(out_valid: np.ndarray, out: "object",
                  slots: np.ndarray) -> "tuple[MsgBlock, np.ndarray]":
    """Slice the block-eligible messages out of a device outbox.

    `out` is the numpy-materialized outbox (fields [n, R, K]); returns
    (block, complex_mask) where complex_mask marks the slots that still
    need the per-message path (MsgSnap only — its app-state payload is
    attached by the hosting layer at send time). MsgApp entry payloads
    are NOT attached here (the arena lives in the caller); records
    carry n_ents and the caller fills ``block.ents`` in record order.
    """
    typ = np.asarray(out.type)
    n_ents = np.asarray(out.n_ents)
    simple = out_valid & (typ != T_SNAP)
    rows, tgt, k = np.nonzero(simple)
    rec = np.empty(len(rows), REC_DTYPE)
    t = typ[rows, tgt, k]
    rec["row"] = rows
    rec["to"] = tgt + 1
    rec["frm"] = slots[rows] + 1
    rec["lane"] = LANE_OF[t]
    rec["type"] = t
    rec["reject"] = np.asarray(out.reject)[rows, tgt, k]
    rec["n_ents"] = np.where(t == T_APP, n_ents[rows, tgt, k], 0)
    rec["term"] = np.asarray(out.term)[rows, tgt, k]
    rec["log_term"] = np.asarray(out.log_term)[rows, tgt, k]
    rec["index"] = np.asarray(out.index)[rows, tgt, k]
    rec["commit"] = np.asarray(out.commit)[rows, tgt, k]
    rec["reject_hint"] = np.asarray(out.reject_hint)[rows, tgt, k]
    rec["ctx"] = np.asarray(out.ctx)[rows, tgt, k]
    return MsgBlock(rec), (out_valid & ~simple)


def merge_blocks(
    blocks: List[MsgBlock],
    num_replicas: int,
    num_kinds: int,
    dense: Dict[str, np.ndarray],
    land_entries=None,
) -> List[MsgBlock]:
    """Scatter queued block records into the dense inbox arrays.

    `dense` holds the flat-viewable per-field arrays ([n, R, K], plus
    ``ent_terms`` [n, R, K, E]); slots already filled (by the legacy
    per-message path) are respected. Per inbox key (row, sender, lane)
    at most one record lands per round; FIFO order across blocks is
    preserved: once a key has a deferred record, later records for
    that key stay queued behind it. Returns the residual blocks (in
    order).

    ``land_entries(row, base_index, ents)`` is invoked for each record
    with entries that LANDS this round — the caller writes the entry
    payloads into its arena at that moment (entries of a deferred
    record stay with it in the residual).
    """
    valid = dense["valid"]
    n_keys = valid.size
    flat_valid = valid.reshape(-1)
    barred = np.zeros(n_keys, bool)
    residual: List[MsgBlock] = []
    flat = {f: a.reshape(-1) for f, a in dense.items()
            if f != "ent_terms"}
    ent_terms = dense.get("ent_terms")
    e_cap = ent_terms.shape[-1] if ent_terms is not None else 0
    flat_ents = (
        ent_terms.reshape(-1, e_cap) if ent_terms is not None else None
    )
    for blk in blocks:
        rec = blk.rec
        if len(rec) == 0:
            continue
        key = (
            (rec["row"].astype(np.int64) * num_replicas
             + (rec["frm"].astype(np.int64) - 1)) * num_kinds
            + rec["lane"]
        )
        # First occurrence of each key within this block...
        _, first_idx = np.unique(key, return_index=True)
        firstmask = np.zeros(len(key), bool)
        firstmask[first_idx] = True
        # ...that is neither already filled nor behind a deferred one.
        take = firstmask & ~flat_valid[key] & ~barred[key]
        idx = key[take]
        flat_valid[idx] = True
        flat["type"][idx] = rec["type"][take]
        flat["term"][idx] = rec["term"][take]
        flat["log_term"][idx] = rec["log_term"][take]
        flat["index"][idx] = rec["index"][take]
        flat["commit"][idx] = rec["commit"][take]
        flat["reject"][idx] = rec["reject"][take].astype(bool)
        flat["reject_hint"][idx] = rec["reject_hint"][take]
        flat["ctx"][idx] = rec["ctx"][take]
        if "n_ents" in flat:
            ne = rec["n_ents"][take]
            if ent_terms is not None:
                # The dense inbox carries at most e_cap entry terms per
                # slot; a record claiming more would land a count its
                # own ent_terms row can't back (the terms below are
                # already truncated to e_cap) — clamp so the inbox
                # stays self-consistent for every caller.
                ne = np.minimum(ne, e_cap)
            flat["n_ents"][idx] = ne
        if flat_ents is not None or land_entries is not None:
            for i in np.nonzero(take & (rec["n_ents"] > 0))[0]:
                ents = blk.ents[i]
                if ents is None:
                    continue
                if flat_ents is not None:
                    terms = [t for t, _e, _d in ents[:e_cap]]
                    flat_ents[key[i], :len(terms)] = terms
                if land_entries is not None:
                    land_entries(int(rec["row"][i]),
                                 int(rec["index"][i]), ents)
        rest = ~take
        if rest.any():
            barred[key[rest]] = True
            residual.append(MsgBlock(
                rec[rest],
                [e for e, keep in zip(blk.ents, rest) if keep],
            ))
    return residual


def validate_records(rec: np.ndarray, n_rows: int,
                     num_replicas: int) -> np.ndarray:
    """Array-level validation (no entries): kept for callers/tests
    that stage payload-free records directly. See validate_block."""
    blk = validate_block(MsgBlock(rec), n_rows, num_replicas, 0)
    return blk.rec
