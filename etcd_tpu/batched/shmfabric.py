"""Shared-memory ring fabric: the zero-copy peer transport (ISSUE 16).

The traced SLO table says the hosted commit path is transport-bound:
at G=1024 the ``net_to_peer`` + ``ack_to_commit`` hops alone are
~360ms of the ~500-600ms commit p50 (PR 9, re-confirmed PR 13), while
the device round, host staging and the WAL are each an order of
magnitude cheaper. Between co-hosted member processes that loop is
pure overhead: ``step.pack_outbox`` already emits wire-width
``REC_DTYPE`` words on device and the v2 msgblock codec is a pure
buffer view — serializing them into a socket only to ``np.frombuffer``
them back out on the same host is the paper's L3 rafthttp boundary
rebuilt as syscalls.

``ShmFabric`` replaces it with mmap'd SPSC rings:

* **One ordered lane per (src, dst) member pair**, each lane two
  file-backed mmap rings — a LIVE ring for payload-free records
  (heartbeats/acks/votes) and a BULK ring for entry-carrying MsgApp
  frames. Two rings per lane is the rafthttp two-channel discipline
  (ref: server/etcdserver/api/rafthttp/peer.go:337-349): a ring full
  of append payloads must never starve or drop liveness traffic, or
  followers churn leadership under load. The receiver drains every
  LIVE ring dry before taking a bounded batch from any BULK ring.
* **Zero-copy block frames**: the sender writes the block sections
  (REC_DTYPE records, ENT_DTYPE headers, flat payload) straight into
  the ring through numpy views over the mmap — one vectorized copy
  per section, no per-frame ``struct.pack``, no socket syscall, no
  intermediate ``bytes``. The receiver re-ingests with ONE owned copy
  out of the ring (``rn.step_block`` defers blocks to the next round,
  so a view into the ring would be overwritten under it) and
  ``MsgBlock.from_bytes`` over that copy is pure ``np.frombuffer``
  views. Frame bodies reuse the TCP layout (``u4 group-or-sentinel |
  block/message bytes``) so the object path (MsgSnap) rides the same
  rings.
* **SPSC by construction**: per ring, exactly one writing fabric and
  one reading fabric. ``wpos``/``rpos`` are monotone u64 byte counters
  in the ring header page (aligned 8-byte stores — atomic on every
  platform jax runs on); the writer publishes ``wpos`` only after the
  body copy completes, the reader advances ``rpos`` only after its
  copy-out, so neither side ever reads bytes the other may touch.
  Frames never wrap: a frame that would cross the ring end writes a
  wrap marker and restarts at offset 0, keeping every read a single
  contiguous view. (Writer-side entry is serialized by a per-lane
  lock: the member round thread and FaultyFabric's delayed-delivery
  pump both call ``send_block``.)
* **Drop-don't-block with counted losses** (ref:
  etcdserver/raft.go:108-111): ring full, oversize, unroutable and
  corrupt frames count on the shared
  ``etcd_tpu_router_loss_total{transport="shm"}`` registry — the same
  source of truth as InProcRouter/TCPRouter — and ``stats()`` reports
  this instance's deltas, so chaos checkers and the admin 'stats' op
  read all three fabrics identically.
* **Crash/restart composes** with ``FaultyFabric``/``ChaosHarness``
  through the same ``member._send``/``_send_block`` seam and an
  incarnation discipline on the rings themselves: positions are
  monotone and live in the shared header, so a restarted *writer*
  resumes after its crashed incarnation's last published frame
  (partial writes beyond ``wpos`` were never visible), and a
  restarted *reader* RESYNCS — frames addressed to the dead
  incarnation are walked, counted (``stale_drop``) and skipped, never
  delivered to the successor. Frames sent to a crashed peer meanwhile
  fill its rings and count as ``ring_full_drop``; nothing is silent.

Occupancy, high-water, frame and copied-byte counters per lane are
exported as the ``etcd_tpu_shm_*`` metric families and through
``lane_stats()`` (the fleet console's transport column).
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .msgblock import (
    ENT_DTYPE,
    ENT_SIZE,
    REC_DTYPE,
    REC_SIZE,
    WIRE_VERSION,
    MsgBlock,
)
from .telemetry import (
    router_loss_counter,
    shm_copy_bytes_counter,
    shm_frames_counter,
    shm_ring_depth_gauge,
    shm_ring_full_counter,
    shm_ring_high_water_gauge,
)

# Group-id sentinel marking SoA block frames — the same value as
# TCPRouter.BLOCK_SENTINEL so a frame body is transport-portable.
BLOCK_SENTINEL = 0xFFFFFFFF
# Ring-level marker: a length word of all-ones means "wrap to offset
# 0" (no frame body follows). Frame lengths are bounded far below it.
_WRAP = 0xFFFFFFFF

_HDR_BYTES = 4096  # one page: u8[cap, wpos, rpos, high_water, frames, bytes]
_IDX_CAP, _IDX_WPOS, _IDX_RPOS, _IDX_HW, _IDX_FRAMES, _IDX_BYTES = range(6)


def _align4(n: int) -> int:
    return (n + 3) & ~3


class ShmRing:
    """One file-backed mmap SPSC byte ring.

    Layout: a 4KiB header page (six u8 counters, see ``_IDX_*``)
    followed by ``capacity`` data bytes. ``wpos``/``rpos`` are monotone
    byte counts (never wrapped); ``pos % capacity`` is the data offset.
    The file is created zero-filled on first touch by either side —
    zero positions are a valid empty ring, so creation needs no
    cross-process handshake. Capacity is written once and verified by
    later openers (a size mismatch between two builds must fail loud,
    not misparse)."""

    def __init__(self, path: str, capacity: int) -> None:
        if capacity <= _HDR_BYTES:
            raise ValueError(f"ring capacity too small: {capacity}")
        self.path = path
        self.cap = int(capacity)
        size = _HDR_BYTES + self.cap
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            if os.fstat(fd).st_size < size:
                os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        buf = np.frombuffer(self._mm, np.uint8)
        self._h = buf[:48].view("<u8")
        self._data = buf[_HDR_BYTES:]
        self._pending = (0, 0, 0)  # writer scratch (wpos, skip, adv)
        self._adv = 0              # reader scratch (next rpos)
        # First toucher stamps the capacity; racing stampers write the
        # same value, so no lock is needed — but a DIFFERENT value
        # means two builds disagree on the ring geometry.
        if int(self._h[_IDX_CAP]) == 0:
            self._h[_IDX_CAP] = self.cap
        elif int(self._h[_IDX_CAP]) != self.cap:
            raise ValueError(
                f"{path}: ring capacity {int(self._h[_IDX_CAP])} != "
                f"configured {self.cap}")

    # -- writer side -----------------------------------------------------------

    def try_reserve(self, blen: int) -> Optional[int]:
        """Claim a contiguous data region for a ``blen``-byte body.
        Returns the data offset to write the body at (its u4 length
        word is already written), or None when the ring lacks space —
        the caller drops and counts. Publish with ``commit``."""
        adv = 4 + _align4(blen)
        if adv > self.cap:
            return None
        wpos = int(self._h[_IDX_WPOS])
        rpos = int(self._h[_IDX_RPOS])
        off = wpos % self.cap
        skip = self.cap - off if self.cap - off < adv else 0
        if adv + skip > self.cap - (wpos - rpos):
            return None
        if skip:
            if skip >= 4:
                self._data[off:off + 4].view("<u4")[0] = _WRAP
            off = 0
        self._pending = (wpos, skip, adv)
        self._data[off:off + 4].view("<u4")[0] = blen
        return off + 4

    def commit(self, blen: int) -> None:
        """Publish the frame reserved by the last ``try_reserve``:
        advance ``wpos`` past the wrap skip + frame in one store (the
        reader never sees a half-written frame — body bytes beyond
        ``wpos`` are invisible until this store lands)."""
        wpos, skip, adv = self._pending
        new = wpos + skip + adv
        self._h[_IDX_WPOS] = new
        depth = new - int(self._h[_IDX_RPOS])
        if depth > int(self._h[_IDX_HW]):
            self._h[_IDX_HW] = depth
        self._h[_IDX_FRAMES] = int(self._h[_IDX_FRAMES]) + 1
        self._h[_IDX_BYTES] = int(self._h[_IDX_BYTES]) + blen

    # -- reader side -----------------------------------------------------------

    def read_view(self) -> Optional[np.ndarray]:
        """Next frame body as a VIEW into the ring (u8 array), or None
        when empty. The view is valid only until ``advance`` — copy
        out anything that outlives this poll step. Corrupt geometry
        (a length the ring cannot hold) raises ValueError after
        resyncing to ``wpos`` so one bad frame costs the backlog, not
        the lane forever (the TCP drop-the-connection analog)."""
        while True:
            wpos = int(self._h[_IDX_WPOS])
            rpos = int(self._h[_IDX_RPOS])
            if rpos >= wpos:
                return None
            off = rpos % self.cap
            if self.cap - off < 4:
                self._h[_IDX_RPOS] = rpos + (self.cap - off)
                continue
            blen = int(self._data[off:off + 4].view("<u4")[0])
            if blen == _WRAP:
                self._h[_IDX_RPOS] = rpos + (self.cap - off)
                continue
            adv = 4 + _align4(blen)
            if adv > self.cap - off or rpos + adv > wpos:
                self._h[_IDX_RPOS] = wpos  # resync: skip the backlog
                raise ValueError(
                    f"{self.path}: corrupt frame length {blen} at "
                    f"rpos {rpos}")
            self._adv = rpos + adv
            return self._data[off + 4:off + 4 + blen]

    def advance(self) -> None:
        """Release the frame returned by the last ``read_view`` (the
        writer may reuse its bytes after this store)."""
        self._h[_IDX_RPOS] = self._adv

    def resync(self) -> Tuple[int, int]:
        """Reader (re)attach: walk the unread region, then skip it.
        Returns (frames, records) skipped — a restarted reader is a
        NEW incarnation, and frames addressed to its predecessor must
        drop *counted*, never deliver to the successor."""
        frames = records = 0
        while True:
            try:
                body = self.read_view()
            except ValueError:
                frames += 1
                break
            if body is None:
                break
            frames += 1
            if len(body) >= 9 and int(
                    body[:4].view("<u4")[0]) == BLOCK_SENTINEL:
                records += int(body[5:9].view("<u4")[0])
            else:
                records += 1
            self.advance()
        return frames, records

    # -- stats -----------------------------------------------------------------

    def depth(self) -> int:
        return int(self._h[_IDX_WPOS]) - int(self._h[_IDX_RPOS])

    def high_water(self) -> int:
        return int(self._h[_IDX_HW])

    def frames(self) -> int:
        return int(self._h[_IDX_FRAMES])

    def bytes_written(self) -> int:
        return int(self._h[_IDX_BYTES])


def lane_path(shm_dir: str, src: int, dst: int, cls: str) -> str:
    return os.path.join(shm_dir, f"lane-{src}-to-{dst}-{cls}.ring")


class ShmFabric:
    """Shared-memory peer fabric for one ``MultiRaftMember``.

    Mirrors the TCPRouter surface — ``add_peer``/``stats``/``stop``,
    programs ``member._send`` + ``member._send_block`` — so the
    hosting layer, AdminServer, FaultyFabric and ChaosHarness treat
    all three transports identically."""

    kind = "shm"
    LIVE, BULK = "live", "bulk"
    # Defaults sized for G<=1024: a round's block frame is ~2*G*36B +
    # entries, so the bulk ring holds tens of rounds of backlog before
    # drop-don't-block engages; the live ring's records are 36B each.
    BULK_BYTES = 4 << 20
    LIVE_BYTES = 1 << 20
    # Bulk frames drained per lane per poll iteration before the live
    # rings are re-checked (liveness-over-bulk on the read side too).
    BULK_BATCH = 8

    def __init__(self, member, shm_dir: str,
                 bulk_bytes: int = BULK_BYTES,
                 live_bytes: int = LIVE_BYTES,
                 poll_interval: float = 0.0005) -> None:
        from ..transport.codec import MAX_FRAME, decode_message, \
            encode_message

        self.member = member
        self.shm_dir = shm_dir
        os.makedirs(shm_dir, exist_ok=True)
        self._bulk_bytes = int(bulk_bytes)
        self._live_bytes = int(live_bytes)
        self._poll = float(poll_interval)
        self._enc, self._dec = encode_message, decode_message
        # Frames bigger than the codec cap or the target ring are
        # chunked/dropped like TCP's oversize discipline (per-ring:
        # a live frame must fit the live ring even when empty).
        self._max_frame = MAX_FRAME
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        # peer id -> (live ring, bulk ring, writer lock): outbound.
        self._out: Dict[int, Tuple[ShmRing, ShmRing,
                                   threading.Lock]] = {}
        # peer id -> (live ring, bulk ring): inbound (this side reads).
        self._in: Dict[int, Tuple[ShmRing, ShmRing]] = {}
        # Loss counters on the shared registry (ONE source of truth
        # across transports); stats() reads per-instance deltas.
        self._loss = router_loss_counter()
        self._children: Dict[str, Tuple[object, float]] = {}
        self._stats_lock = threading.Lock()
        # etcd_tpu_shm_* families: per-lane gauges/counters, label
        # children cached; counters carry per-instance bases so a
        # restarted member's fabric reports its own deltas.
        self._g_depth = shm_ring_depth_gauge()
        self._g_hw = shm_ring_high_water_gauge()
        self._c_frames = shm_frames_counter()
        self._c_copy = shm_copy_bytes_counter()
        self._c_full = shm_ring_full_counter()
        self._lane_children: Dict[Tuple[int, str], Tuple] = {}
        member._send = self.send
        member._send_block = self.send_block
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._rx_started = False

    # -- wiring ----------------------------------------------------------------

    def add_peer(self, peer_id: int,
                 addr: Optional[Tuple[str, int]] = None) -> None:
        """Open (creating if absent) both directions of the lane to
        ``peer_id``. ``addr`` is accepted and ignored — lanes are
        addressed by member id, which keeps the TCPRouter call shape.
        The inbound side resyncs: anything a prior incarnation of this
        member never drained is counted stale and skipped."""
        me = self.member.id
        with self._lock:
            if peer_id in self._out or peer_id == me:
                return
            out = (
                ShmRing(lane_path(self.shm_dir, me, peer_id, self.LIVE),
                        self._live_bytes),
                ShmRing(lane_path(self.shm_dir, me, peer_id, self.BULK),
                        self._bulk_bytes),
                threading.Lock(),
            )
            inn = (
                ShmRing(lane_path(self.shm_dir, peer_id, me, self.LIVE),
                        self._live_bytes),
                ShmRing(lane_path(self.shm_dir, peer_id, me, self.BULK),
                        self._bulk_bytes),
            )
            stale = 0
            for ring in inn:
                _frames, recs = ring.resync()
                stale += recs
            self._out[peer_id] = out
            self._in[peer_id] = inn
        if stale:
            self._count("stale_drop", stale)
        if not self._rx_started:
            self._rx_started = True
            self._rx.start()

    # -- loss accounting -------------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            ent = self._children.get(key)
            if ent is None:
                child = self._loss.labels(
                    "shm", str(self.member.id), key)
                ent = (child, child.value())
                self._children[key] = ent
        ent[0].inc(n)

    def stats(self) -> Dict[str, int]:
        """Loss/error counters for this fabric instance — the shm
        analog of TCPRouter.stats(): ring_full_drop, oversize_drop,
        no_route, recv_corrupt, deliver_error, stale_drop. Values are
        read back from the shared registry, scoped to this instance."""
        with self._stats_lock:
            items = list(self._children.items())
        return {k: int(child.value() - base)
                for k, (child, base) in items}

    def _lane_metrics(self, peer: int, cls: str):
        ent = self._lane_children.get((peer, cls))
        if ent is None:
            lab = (str(self.member.id), str(peer), cls)
            ent = (self._g_depth.labels(*lab), self._g_hw.labels(*lab),
                   self._c_frames.labels(*lab), self._c_copy.labels(*lab),
                   self._c_full.labels(*lab))
            self._lane_children[(peer, cls)] = ent
        return ent

    def lane_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-outbound-lane ring occupancy for the fleet console's
        transport column: depth (bytes backed up), high-water, frames
        and body bytes written over the lane's lifetime."""
        with self._lock:
            out = list(self._out.items())
        lanes: Dict[str, Dict[str, int]] = {}
        for peer, (live, bulk, _wl) in out:
            for cls, ring in ((self.LIVE, live), (self.BULK, bulk)):
                lanes[f"{peer}:{cls}"] = {
                    "depth": ring.depth(),
                    "high_water": ring.high_water(),
                    "frames": ring.frames(),
                    "bytes": ring.bytes_written(),
                }
        return lanes

    # -- outbound --------------------------------------------------------------

    def _write_block(self, peer: int, ring: ShmRing, wl, blk,
                     cls: str) -> None:
        """One block frame into the ring: sentinel word + the v2 wire
        sections, each copied through a numpy view over the mmap —
        the packed record array lands with one vectorized copy, no
        struct.pack, no intermediate bytes object."""
        n = len(blk.rec)
        ne = len(blk.ent_term)
        npay = len(blk.payload)
        blen = 4 + 5 + n * REC_SIZE + 4 + ne * ENT_SIZE + npay
        if blen > min(self._max_frame, ring.cap - 8):
            if n > 1:
                half = n // 2
                self._write_block(peer, ring, wl,
                                  blk.take(slice(0, half)), cls)
                self._write_block(peer, ring, wl,
                                  blk.take(slice(half, None)), cls)
            else:
                self._count("oversize_drop")
            return
        depth_g, hw_g, frames_c, copy_c, full_c = \
            self._lane_metrics(peer, cls)
        with wl:
            if self._stopped.is_set():
                return
            o = ring.try_reserve(blen)
            if o is None:
                full_c.inc()
                self._count("ring_full_drop", n)
                return
            data = ring._data
            data[o:o + 4].view("<u4")[0] = BLOCK_SENTINEL
            o += 4
            data[o] = WIRE_VERSION
            data[o + 1:o + 5].view("<u4")[0] = n
            o += 5
            if n:
                data[o:o + n * REC_SIZE].view(REC_DTYPE)[:] = blk.rec
                o += n * REC_SIZE
            data[o:o + 4].view("<u4")[0] = ne
            o += 4
            if ne:
                hdr = data[o:o + ne * ENT_SIZE].view(ENT_DTYPE)
                hdr["term"] = blk.ent_term
                hdr["etype"] = blk.ent_etype
                hdr["len"] = blk.ent_len
                o += ne * ENT_SIZE
            if npay:
                data[o:o + npay] = np.frombuffer(blk.payload, np.uint8)
            ring.commit(blen)
            depth_g.set(ring.depth())
            hw_g.set(ring.high_water())
        frames_c.inc()
        copy_c.inc(blen)

    def send_block(self, _from_id: int, blk) -> None:
        """Ship a SoA block: per target, the payload-free half rides
        the LIVE ring and the entry-carrying half the BULK ring — the
        same two-channel split as TCPRouter.send_block, on rings
        instead of priority queues."""
        if self._stopped.is_set():
            return
        rec = blk.rec
        tos = np.unique(rec["to"]).tolist()
        has_ents = rec["n_ents"] > 0
        any_ents = bool(has_ents.any())
        for to in tos:
            to = int(to)
            with self._lock:
                out = self._out.get(to)
            tmask = rec["to"] == to
            if out is None:
                self._count("no_route", int(tmask.sum()))
                continue
            live_ring, bulk_ring, wl = out
            if any_ents and (tmask & has_ents).any():
                live = blk.take(tmask & ~has_ents)
                bulk = blk.take(tmask & has_ents)
                if len(live):
                    self._write_block(to, live_ring, wl, live,
                                      self.LIVE)
                self._write_block(to, bulk_ring, wl, bulk, self.BULK)
            elif len(tos) == 1:
                self._write_block(to, live_ring, wl, blk, self.LIVE)
            else:
                self._write_block(to, live_ring, wl, blk.take(tmask),
                                  self.LIVE)

    def send(self, _from_id: int, batch: List[Tuple[int, "object"]]) -> None:
        """Object path (MsgSnap and other low-volume traffic): the
        encoded message rides the BULK ring in a TCP-shaped frame
        (``u4 group | codec bytes``). Rare by construction — the hot
        path is send_block — so a per-message encode is fine here."""
        if self._stopped.is_set():
            return
        for group, m in batch:
            to = int(m.to)
            with self._lock:
                out = self._out.get(to)
            if out is None:
                self._count("no_route")
                continue
            _live, bulk_ring, wl = out
            payload = self._enc(m)[4:]  # strip the codec length prefix
            blen = 4 + len(payload)
            if blen > min(self._max_frame, bulk_ring.cap - 8):
                self._count("oversize_drop")
                continue
            _dg, _hg, frames_c, copy_c, full_c = \
                self._lane_metrics(to, self.BULK)
            with wl:
                if self._stopped.is_set():
                    return
                o = bulk_ring.try_reserve(blen)
                if o is None:
                    full_c.inc()
                    self._count("ring_full_drop")
                    continue
                data = bulk_ring._data
                data[o:o + 4].view("<u4")[0] = group
                data[o + 4:o + blen] = np.frombuffer(payload, np.uint8)
                bulk_ring.commit(blen)
            frames_c.inc()
            copy_c.inc(blen)

    # -- inbound ---------------------------------------------------------------

    def _deliver(self, body: np.ndarray) -> None:
        """One frame off a ring. ``body`` is a view into the ring —
        the block path snapshots it ONCE into an owned buffer
        (step_block defers blocks to the next round) and decodes with
        pure frombuffer views over that copy."""
        group = int(body[:4].view("<u4")[0])
        if group == BLOCK_SENTINEL:
            owned = body[4:].tobytes()
            try:
                blk = MsgBlock.from_bytes(owned)
            except ValueError:
                self._count("recv_corrupt")
                return
            try:
                self.member.deliver_block(blk)
            except Exception:  # noqa: BLE001 — lossy-net semantics
                self._count("deliver_error")
            return
        try:
            m = self._dec(body[4:].tobytes())
        except Exception:  # noqa: BLE001 — corrupt frame: drop it
            self._count("recv_corrupt")
            return
        try:
            self.member.deliver(group, m)
        except Exception:  # noqa: BLE001 — lossy-net semantics
            self._count("deliver_error")

    def _drain(self, ring: ShmRing, budget: int) -> int:
        """Up to ``budget`` frames off one ring; returns frames
        delivered. A corrupt length resyncs the ring (read_view) and
        counts the lost backlog as one corrupt event."""
        done = 0
        while done < budget and not self._stopped.is_set():
            try:
                body = ring.read_view()
            except ValueError:
                self._count("recv_corrupt")
                return done + 1
            if body is None:
                return done
            self._deliver(body)
            ring.advance()
            done += 1
        return done

    def _recv_loop(self) -> None:
        """Receiver: every poll iteration drains ALL live rings dry
        first, then a bounded batch per bulk ring — liveness frames
        never queue behind an append backlog (the read-side half of
        the two-channel discipline)."""
        while not self._stopped.is_set():
            with self._lock:
                lanes = list(self._in.items())
            moved = 0
            for _pid, (live, _bulk) in lanes:
                moved += self._drain(live, 1 << 30)
            for _pid, (live, bulk) in lanes:
                moved += self._drain(bulk, self.BULK_BATCH)
                moved += self._drain(live, 1 << 30)
            if not moved:
                self._stopped.wait(self._poll)

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        """Stop the receiver and fence writers. The mmaps are left to
        the GC on purpose: numpy views exported to delivered blocks
        may outlive the fabric, and mmap.close() with live exports
        raises. Ring FILES persist — a restarted incarnation reopens
        them, resumes its write positions and resyncs its read
        positions (see add_peer)."""
        self._stopped.set()
        with self._lock:
            out = list(self._out.values())
        # Serialize with in-flight writers so no view write races the
        # teardown; after this, send/send_block return at the gate.
        for _live, _bulk, wl in out:
            with wl:
                pass
        if self._rx_started:
            self._rx.join(timeout=5)
