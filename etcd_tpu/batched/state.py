"""SoA state for the batched multi-Raft engine.

Layout: one *replica instance* per row. Instance ``i`` is replica slot
``i % R`` of group ``i // R``; the dense layout makes the network router
a transpose (see step.py). All arrays are int32/bool — terms, indexes
and counts fit comfortably, and int32 keeps the VPU lanes full.

State fields mirror the reference raft struct (ref: raft/raft.go:243-316)
and tracker.Progress (ref: raft/tracker/progress.go:30-80), with the
reference's per-peer maps flattened to ``[N, R]`` and the log flattened
to a ``[N, W]`` term ring (entry payloads live in the host arena; commit
decisions only ever touch (term, index), ref: SURVEY.md §7 "payload
bytes don't belong on the TPU").
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# Role encoding (matches etcd_tpu.raft.StateType).
FOLLOWER, CANDIDATE, LEADER, PRECANDIDATE = 0, 1, 2, 3

# Progress state encoding (matches tracker.ProgressStateType).
PROBE, REPLICATE, SNAPSHOT = 0, 1, 2

I32 = jnp.int32
I16 = jnp.int16
I8 = jnp.int8

# The SoA wire record (msgblock.REC_DTYPE) packs the per-message entry
# count as one byte; a config exceeding this would silently wrap the
# count on the wire (E=256 reads back as 0 entries).
MAX_WIRE_ENTS = 255

# The shippable deliver shapes (BatchedConfig.deliver_shape); "auto"
# resolves to one of these per platform at build time.
DELIVER_SHAPES = ("lanes", "merged", "vectorized")


def default_deliver_shape() -> str:
    """Platform default for deliver_shape="auto".

    CPU takes the vectorized shape (the ISSUE 14 same-day A/B winner —
    BENCH_NOTES r14); TPU-class backends keep the merged scans, the
    only shape ever tuned ON DEVICE (+4.4% vs lanes, BENCH_NOTES r5) —
    the r5 lesson is that CPU predictions invert on TPU, so vectorized
    must win a tools/tpu_batch.py --deliver-shape run over the live
    tunnel before it becomes the accelerator default. Anything else
    (unknown plugin) falls back to the original six lane scans."""
    import jax

    platform = jax.default_backend()
    if platform == "cpu":
        return "vectorized"
    if platform in ("tpu", "axon"):
        return "merged"
    return "lanes"


class BatchedConfig(NamedTuple):
    """Static (compile-time) engine configuration."""

    num_groups: int
    num_replicas: int  # R: replica slots per group (<= 8 keeps sorts cheap)
    window: int  # W: log-ring capacity per instance
    max_ents_per_msg: int  # E: entries carried by one MsgApp
    max_props_per_round: int  # P: proposals appended per instance per round
    election_timeout: int = 10
    heartbeat_timeout: int = 1
    max_inflight: int = 256
    pre_vote: bool = False
    check_quorum: bool = False
    # Advance snap_index toward the applied watermark each round,
    # keeping window//2 entries of tail for follower catch-up; laggards
    # beyond that take the snapshot path (ref: etcdserver's
    # SnapshotCount / CatchUpEntries policy, server.go:73,80).
    auto_compact: bool = False
    # Run the kernel with the instance axis MINOR ([R, N] / [W, N]
    # internally): on TPU the (8, 128) vector lanes then fill with the
    # huge N axis instead of the tiny R/W/K dims. The public layout
    # stays [N, ...]; the jitted round transposes at entry/exit.
    # bench.py probes both layouts and picks the faster one per device.
    lanes_minor: bool = False
    # Deliver shape: how one instance's [R, K] inbox is folded into
    # state (step.py _deliver_all). Semantically equivalent protocols
    # with DIFFERENT delivery orders (the shadow oracle mirrors
    # whichever is set; see step.py for each shape's order contract):
    #
    # * "lanes":      six length-R lax.scans, one per kind lane,
    #                 senders ascending (kind-major). Small bodies.
    # * "merged":     two length-R scans (request/response halves,
    #                 sender-major), 3x bigger fused bodies, a third of
    #                 the loop-carry round trips. The r5 on-TPU winner
    #                 (+4.4% vs lanes) — kept as the accelerator
    #                 fallback and differential baseline.
    # * "vectorized": NO sender scan. Response lanes fold as masked
    #                 segment reductions over the sender axis (one
    #                 commit recompute per lane); request lanes resolve
    #                 one effective winner per lane via a (term,
    #                 sender) tournament and apply the handler body
    #                 once, losers answered with scattered stale
    #                 nudges. The whole round is then one straight-line
    #                 fused region — no scan barriers between phases.
    # * "auto":       resolved per platform at engine/rawnode build
    #                 time (resolve_deliver_shape): CPU → vectorized,
    #                 TPU/axon → merged until tools/tpu_batch.py
    #                 --deliver-shape re-tunes ON DEVICE (the r5
    #                 lesson: CPU predictions inverted on TPU).
    deliver_shape: str = "auto"
    # Store the bounded hot lanes (role/vote/lead enums, vote tallies,
    # progress states, inflight counts) in int8/int16 between rounds:
    # the round kernel widens them to i32 at entry and narrows at exit,
    # so the protocol math is bit-identical while the per-round state
    # carry (HBM traffic on TPU) shrinks. Absolute term/index
    # watermarks (term, commit, last, match, next, log_term ring) stay
    # int32 — narrowing those would change wrap semantics.
    narrow_lanes: bool = False
    # Kernel telemetry plane (see batched/telemetry.py): the round
    # emits one extra SoA output block — per-instance event counters
    # plus an on-device invariant bitmap — accumulated in-kernel with
    # no extra host sync. Static (compile-time): with telemetry=False
    # the compiled round program is UNCHANGED (the telemetry code is
    # never traced); with telemetry=True protocol state is
    # bit-identical (the frame only reads state).
    telemetry: bool = False
    # Fleet observatory plane (see obs/fleet.py): the round also emits
    # one flat fixed-shape SummaryFrame — log-bucketed commit-progress/
    # backlog/inflight histograms, leader/role/progress censuses, term
    # spread, a bounded groups×time heat strip, and a lax.top_k of the
    # worst-backlogged rows with identities — aggregated ON DEVICE so
    # fleet visibility never costs G host-side series. Same contract
    # as `telemetry`: static, default off, fleet_summary=False compiles
    # the identical program, fleet_summary=True keeps protocol state
    # bit-identical (the frame is a pure read of round inputs/outputs).
    fleet_summary: bool = False
    # Device-resident apply plane (see batched/applyplane.py): the L2
    # storage layer — a fixed-capacity per-group KV/revision hash-slot
    # store, watch predicates as masked compares, client-lease TTL
    # expiry, and leader leases for quorum-free linearizable reads —
    # maintained as device tensors by a SEPARATE jitted apply program
    # dispatched over each round's committed entries. Static plane
    # contract, enforced structurally: none of the apply_* fields
    # enters the round-step compile key (make_step_round normalizes
    # them to defaults before keying), so apply_plane=False compiles
    # the identical round program and apply_plane=True keeps protocol
    # state bit-identical by construction.
    apply_plane: bool = False
    # KV slots per group row (C). A row whose live keys exceed C sets
    # its overflow flag: the host GroupKV tier (always byte-truth)
    # covers reads and snapshot capture for that row; device counters
    # record the spill (capacity/overflow contract, README).
    apply_capacity: int = 256
    # Watch predicate slots per group row (exact-key-hash compares over
    # the apply stream); <= 32 so the per-record match set packs into
    # one i32 bitmap lane of the event frame.
    apply_watch_slots: int = 8
    # Apply records per plane dispatch (A). A round that commits more
    # than A entries for one row dispatches the SAME compiled program
    # again — a batching granule, not a cap.
    apply_records: int = 8
    # Minimum remaining leader-lease ticks for the hosting layer to
    # serve a linearizable read locally (host-side routing threshold;
    # the lease lane itself is part of the round program regardless).
    lease_read_margin: int = 2

    @property
    def num_instances(self) -> int:
        return self.num_groups * self.num_replicas

    def validate(self) -> "BatchedConfig":
        """Bounds the wire/state layouts rely on; every engine/rawnode
        entry point calls this so a bad config fails loudly at build
        time instead of corrupting silently at runtime."""
        if not 0 < self.max_ents_per_msg <= MAX_WIRE_ENTS:
            raise ValueError(
                f"max_ents_per_msg={self.max_ents_per_msg} out of range: "
                f"the wire record packs n_ents as one byte "
                f"(1..{MAX_WIRE_ENTS}); larger appends would wrap the "
                "entry count on the SoA block path")
        if not 0 < self.num_replicas <= 127:
            raise ValueError(
                f"num_replicas={self.num_replicas} out of range: member "
                "ids (slot+1) ride one-byte wire fields and int8 lanes")
        if self.narrow_lanes and self.max_inflight > 32767:
            raise ValueError(
                f"max_inflight={self.max_inflight} does not fit the "
                "int16 inflight lane; lower it or disable narrow_lanes")
        if self.deliver_shape not in ("auto",) + DELIVER_SHAPES:
            raise ValueError(
                f"deliver_shape={self.deliver_shape!r} not in "
                f"{('auto',) + DELIVER_SHAPES}")
        if self.apply_plane:
            if self.apply_capacity < 1:
                raise ValueError(
                    f"apply_capacity={self.apply_capacity} must be >= 1")
            if not 0 < self.apply_watch_slots <= 32:
                raise ValueError(
                    f"apply_watch_slots={self.apply_watch_slots} out of "
                    "range 1..32: watch matches pack into one i32 "
                    "bitmap lane of the event frame")
            if self.apply_records < 1:
                raise ValueError(
                    f"apply_records={self.apply_records} must be >= 1")
            if self.lease_read_margin < 1:
                raise ValueError(
                    f"lease_read_margin={self.lease_read_margin} must "
                    "be >= 1: a zero margin serves a read on the tick "
                    "the lease dies")
        return self

    def apply_plane_key(self) -> "BatchedConfig":
        """The round-step compile-key normalization: the apply plane is
        a SEPARATE jitted program (applyplane.py), so none of its knobs
        may fork the round-step program. make_step_round strips them to
        defaults before keying step._step_round_jit — apply_plane
        on/off therefore share ONE compiled round by construction (the
        static-plane contract, and the reason the conftest compile-
        shape budget does not move)."""
        return self._replace(
            apply_plane=False,
            apply_capacity=256,
            apply_watch_slots=8,
            apply_records=8,
            lease_read_margin=2,
        )

    def resolved(self) -> "BatchedConfig":
        """Resolve deliver_shape="auto" to the platform default. Every
        engine/rawnode/step builder resolves BEFORE keying a compile
        (step._step_round_jit caches per config), so "auto" and its
        concrete resolution share one program."""
        if self.deliver_shape != "auto":
            return self
        return self._replace(deliver_shape=default_deliver_shape())


class BatchedState(NamedTuple):
    """Per-instance consensus state, all leading dim N = G*R."""

    # HardState + role (ref: raft.go:246-247,259,267)
    term: jnp.ndarray  # [N] i32
    vote: jnp.ndarray  # [N] i32, replica slot + 1; 0 = None
    role: jnp.ndarray  # [N] i32 (FOLLOWER/CANDIDATE/LEADER/PRECANDIDATE)
    lead: jnp.ndarray  # [N] i32, slot + 1; 0 = None

    # Log (ref: raft/log.go raftLog) — ring of terms plus watermarks.
    log_term: jnp.ndarray  # [N, W] i32; term of entry i at ring slot i % W
    snap_index: jnp.ndarray  # [N] i32: index covered by snapshot (= first-1)
    snap_term: jnp.ndarray  # [N] i32
    last: jnp.ndarray  # [N] i32: last log index
    commit: jnp.ndarray  # [N] i32
    applied: jnp.ndarray  # [N] i32

    # Ticks (ref: raft.go:285-303)
    election_elapsed: jnp.ndarray  # [N] i32
    heartbeat_elapsed: jnp.ndarray  # [N] i32
    randomized_timeout: jnp.ndarray  # [N] i32
    reset_count: jnp.ndarray  # [N] i32 (drives the deterministic timeout hash)

    # Leader-side per-peer progress (ref: tracker/progress.go)
    match: jnp.ndarray  # [N, R] i32
    next: jnp.ndarray  # [N, R] i32
    pr_state: jnp.ndarray  # [N, R] i32 (PROBE/REPLICATE/SNAPSHOT)
    probe_sent: jnp.ndarray  # [N, R] bool
    pending_snapshot: jnp.ndarray  # [N, R] i32
    recent_active: jnp.ndarray  # [N, R] bool
    inflight: jnp.ndarray  # [N, R] i32 — count+watermark degeneration of
    # the reference's ring buffer (ref: SURVEY.md §2.1 Inflights)

    # Votes (ref: tracker.go Votes): -1 not voted, 0 rejected, 1 granted
    votes: jnp.ndarray  # [N, R] i32

    # Membership (ref: tracker.Config / quorum/joint.go): incoming
    # voters, outgoing voters (joint), learners. in_joint gates the
    # second quorum half. Masks are uploaded by the host at the
    # confchange apply point (SURVEY §2.1 "host-side control plane"):
    # on the hosting path that is batched/membership.GroupConfStore —
    # committed EntryConfChangeV2 entries flip these lanes via one
    # bulk staged upload (rawnode.set_membership_many), enter-joint at
    # the joint entry's apply, auto-leave once the joint config
    # commits. voter_out nonzero while in_joint is false is illegal
    # (kernels.invariant_bits bit 8, voter_out_no_joint).
    voter: jnp.ndarray  # [N, R] bool
    voter_out: jnp.ndarray  # [N, R] bool (only meaningful when in_joint)
    learner: jnp.ndarray  # [N, R] bool
    in_joint: jnp.ndarray  # [N] bool

    # Durability fence (protocol-aware recovery, FAST'18): set at boot
    # for instances whose recovered WAL tail fell below the durable
    # watermark (acked bytes destroyed). A fenced instance neither
    # campaigns nor grants votes — its log/vote state can no longer
    # back the promises it made — but still accepts appends/heartbeats,
    # re-converging as a de-facto learner until the hosting layer lifts
    # the fence (durable log back at the watermark).
    fenced: jnp.ndarray  # [N] bool

    # Leader transfer (ref: raft.go:1339-1372; raft.leadTransferee).
    transferee: jnp.ndarray  # [N] i32, slot+1; 0 = no transfer pending
    transfer_sent: jnp.ndarray  # [N] bool — TimeoutNow already emitted

    # ReadIndex (ref: read_only.go:39-112, ReadOnlySafe): one pending
    # read batch per group; heartbeats carry read_seq as ctx, acks
    # accumulate until quorum.
    read_seq: jnp.ndarray  # [N] i32, incremented per accepted batch
    read_index: jnp.ndarray  # [N] i32, commit at request time; -1 none
    read_acks: jnp.ndarray  # [N, R] bool
    read_ready: jnp.ndarray  # [N] bool — quorum confirmed for read_seq
    # Request latch: a read asked for while a batch is in flight (or
    # before first commit-in-term) opens the next batch as soon as the
    # current one confirms — the device form of read_only.go's pending
    # queue (requests are never dropped).
    read_req_latch: jnp.ndarray  # [N] bool

    # Pending send flags consumed by the emit phase.
    send_append: jnp.ndarray  # [N, R] bool
    send_heartbeat: jnp.ndarray  # [N, R] bool
    send_vote_req: jnp.ndarray  # [N] bool
    vote_req_is_pre: jnp.ndarray  # [N] bool
    # Vote requests carry the transfer-campaign context flag
    # (ref: raft.go campaignTransfer → ignore leader lease).
    vote_req_transfer: jnp.ndarray  # [N] bool
    send_timeout_now: jnp.ndarray  # [N] bool (target = transferee)

    # Leader lease (ROADMAP item 5; the fence lane's clock-bound
    # tick-lane compare turned outward): remaining ticks for which this
    # leader may serve linearizable reads locally. Armed to
    # election_timeout whenever check_quorum proves a live quorum
    # (cq_fire & alive — the same evidence the reference's lease-based
    # read path leans on) or commit/ReadIndex progress confirms the
    # term; decremented each tick; zeroed on transfer/step-down. Safety
    # argument: a peer cannot be elected before ITS election_elapsed
    # reaches randomized_timeout >= election_timeout ticks of leader
    # silence, so a lease armed at election_timeout and counted in the
    # SAME tick currency expires no later than the first tick a rival
    # could win — ticks are per-member host time, not a synchronized
    # clock, which is exactly the reference caveat (clock drift bounds
    # apply; reads fall back to ReadIndex when the lane is cold).
    # Computed UNCONDITIONALLY (no apply_plane branch — the lane rides
    # every program, keeping on/off bit-identical); write-only w.r.t.
    # every protocol branch.
    lease_ticks: jnp.ndarray  # [N] i32


# Narrow storage dtype per hot lane (cfg.narrow_lanes). Values are
# bounded: roles 0..3, member ids 0..R+1 (R <= 127), vote tallies
# -1..1, progress states 0..2, inflight <= max_inflight (validated
# <= int16 max). Everything else keeps its wide dtype.
NARROW_DTYPES = {
    "role": I8,
    "vote": I8,
    "lead": I8,
    "transferee": I8,
    "votes": I8,
    "pr_state": I8,
    "inflight": I16,
}


def narrow_state(st: BatchedState) -> BatchedState:
    """Cast the bounded lanes to their narrow storage dtypes."""
    return st._replace(**{
        f: getattr(st, f).astype(dt) for f, dt in NARROW_DTYPES.items()
    })


def widen_state(st: BatchedState) -> BatchedState:
    """Cast narrow storage lanes back to i32 for the round kernel."""
    return st._replace(**{
        f: getattr(st, f).astype(I32) for f in NARROW_DTYPES
    })


def _slot_ids(cfg: BatchedConfig) -> np.ndarray:
    return np.arange(cfg.num_instances, dtype=np.int32) % cfg.num_replicas


def instance_slot(cfg: BatchedConfig) -> jnp.ndarray:
    """[N] replica slot of each instance (used as `self id - 1`)."""
    return jnp.asarray(_slot_ids(cfg))


def init_state(cfg: BatchedConfig, start_index: int = 0,
               iids=None) -> BatchedState:
    """All groups bootstrapped as followers at term 0 with R voters, log
    beginning at start_index (mirrors add-nodes bootstrap-from-snapshot,
    ref: rafttest/interaction_env_handler_add_nodes.go).

    `iids` (optional) gives each row its global instance id
    (group*R + slot): a hosting process that owns one replica slot of
    every group passes its own subset so the deterministic
    randomized-timeout hash matches the dense all-replica layout."""
    r, w = cfg.num_replicas, cfg.window
    if iids is None:
        iids = jnp.arange(cfg.num_instances, dtype=I32)
    else:
        iids = jnp.asarray(iids, I32)
    n = iids.shape[0]
    # Fresh buffers per field (no sharing): a buffer aliased into two
    # state fields cannot be donated to the round kernel ("attempt to
    # donate the same buffer twice"), and the round loop donates its
    # state carry so XLA reuses the SoA buffers between rounds.
    zeros_n = lambda: jnp.zeros((n,), I32)  # noqa: E731
    start = lambda: jnp.full((n,), start_index, I32)  # noqa: E731
    start0 = start()
    st = BatchedState(
        term=zeros_n(),
        vote=zeros_n(),
        role=jnp.full((n,), FOLLOWER, I32),
        lead=zeros_n(),
        log_term=jnp.zeros((n, w), I32),
        snap_index=start(),
        snap_term=jnp.where(start0 > 0, jnp.ones((n,), I32), zeros_n()),
        last=start(),
        commit=start(),
        applied=start(),
        election_elapsed=zeros_n(),
        heartbeat_elapsed=zeros_n(),
        # Per-instance randomized [et, 2et) from the start (reset_count
        # 0 of the deterministic hash) — a uniform value would make
        # every boot election a guaranteed split vote.
        randomized_timeout=cfg.election_timeout
        + ((iids + 1) * 7919 % cfg.election_timeout),
        reset_count=zeros_n(),
        match=jnp.zeros((n, r), I32),
        next=jnp.ones((n, r), I32) * (start0[:, None] + 1),
        pr_state=jnp.full((n, r), PROBE, I32),
        probe_sent=jnp.zeros((n, r), bool),
        pending_snapshot=jnp.zeros((n, r), I32),
        recent_active=jnp.zeros((n, r), bool),
        inflight=jnp.zeros((n, r), I32),
        votes=jnp.full((n, r), -1, I32),
        voter=jnp.ones((n, r), bool),
        voter_out=jnp.zeros((n, r), bool),
        learner=jnp.zeros((n, r), bool),
        in_joint=jnp.zeros((n,), bool),
        fenced=jnp.zeros((n,), bool),
        transferee=zeros_n(),
        transfer_sent=jnp.zeros((n,), bool),
        read_seq=zeros_n(),
        read_index=jnp.full((n,), -1, I32),
        read_acks=jnp.zeros((n, r), bool),
        read_ready=jnp.zeros((n,), bool),
        read_req_latch=jnp.zeros((n,), bool),
        send_append=jnp.zeros((n, r), bool),
        send_heartbeat=jnp.zeros((n, r), bool),
        send_vote_req=jnp.zeros((n,), bool),
        vote_req_is_pre=jnp.zeros((n,), bool),
        vote_req_transfer=jnp.zeros((n,), bool),
        send_timeout_now=jnp.zeros((n,), bool),
        lease_ticks=zeros_n(),
    )
    if cfg.narrow_lanes:
        st = narrow_state(st)
    return st
