"""Per-group membership state for the batched multi-raft hosting path.

The device already carries the joint-config lanes (``voter`` /
``voter_out`` / ``learner`` / ``in_joint`` in ``state.BatchedState``,
with the joint commit/vote kernels of ``kernels.py`` mirroring
raft/quorum/joint.go) — what was missing is the control plane that
drives them from the replicated log at hosting scale.
:class:`GroupConfStore` is that control plane, G groups at once:

* mask-native: membership lives as ``[G, R]`` numpy bool planes plus a
  ``[G]`` joint flag, the exact shape the device upload wants — a conf
  apply is a handful of row flips, and a thousand groups reconfiguring
  in one round stage as ONE bulk mask upload
  (``BatchedRawNode.set_membership_many``);
* joint-consensus semantics match the reference Changer
  (raft/confchange/confchange.go): enter-joint snapshots the incoming
  voters into the outgoing half, demotions defer to ``learner_next``
  until leave-joint, simple changes are limited to one voter delta, a
  change that would zero the electorate is refused;
* idempotent by log index: every apply carries the entry's index and
  is skipped at-or-below the per-group ``applied_index`` watermark, so
  boot-time WAL replay and the post-boot Ready re-delivery of the same
  committed suffix cannot double-apply a change;
* refusals are deterministic: an illegal change (double-enter-joint,
  leaving a non-joint config, zeroing the voters) is REFUSED — state
  untouched, reason returned — and every member refuses identically
  because they apply identical bytes at identical indexes (the
  reference zeroes the NodeID for the same reason, raft.go:896);
* audited: a bounded per-group history of applied configs feeds
  ``functional.checker.check_config_safety`` (committed configs never
  diverge, adjacent configs always share a quorum, joint always
  exits).

Import-light on purpose (numpy + raft.types only): the hosting layer
owns locking; this module is pure state + semantics.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..raft.types import (
    ConfChange,
    ConfChangeType,
    ConfChangeV2,
    ConfState,
    EntryType,
)

# Per-slot membership bits inside WAL conf records and history entries.
SLOT_VOTER = 1
SLOT_VOTER_OUT = 2
SLOT_LEARNER = 4
SLOT_LEARNER_NEXT = 8

# Per-group flags.
FLAG_JOINT = 1
FLAG_AUTO_LEAVE = 2


def conf_record_dtype(num_replicas: int) -> np.dtype:
    """Row layout of an RT_CONF_BATCH WAL record: the group's full
    config at `index` (the last conf entry applied), R-agnostic via a
    per-slot bit subarray."""
    return np.dtype([
        ("group", "<u4"), ("index", "<u8"), ("flags", "u1"),
        ("slots", "u1", (num_replicas,)),
    ])


def decode_conf_entry(data: bytes, etype: int) -> ConfChangeV2:
    """Committed conf-change entry bytes → ConfChangeV2 (V1 entries
    normalize through as_v2, exactly like the reference apply path)."""
    if etype == int(EntryType.EntryConfChange):
        return ConfChange.unmarshal(data).as_v2()
    if etype == int(EntryType.EntryConfChangeV2):
        return ConfChangeV2.unmarshal(data)
    raise ValueError(f"entry type {etype} is not a conf change")


class GroupConfStore:
    """Vectorized per-group membership configs (masks + joint flags),
    with reference joint-consensus apply semantics and a bounded
    applied-config history per group. Boot state mirrors
    ``state.init_state``: every slot a voter, no joint, no learners."""

    HISTORY = 64  # applied configs kept per group for the safety checker

    def __init__(self, num_groups: int, num_replicas: int) -> None:
        g, r = int(num_groups), int(num_replicas)
        self.g, self.r = g, r
        self.voter = np.ones((g, r), bool)
        self.voter_out = np.zeros((g, r), bool)
        self.learner = np.zeros((g, r), bool)
        # Demotions inside a joint config park here until leave-joint
        # (the reference's learners_next: an outgoing voter cannot be a
        # learner while its vote still counts in the old half).
        self.learner_next = np.zeros((g, r), bool)
        self.in_joint = np.zeros(g, bool)
        self.auto_leave = np.zeros(g, bool)
        # Log index of the last conf entry APPLIED per group (0 = boot
        # config). The idempotence watermark for replay/re-delivery.
        self.applied_index = np.zeros(g, np.int64)
        # Conf changes applied per group (refusals excluded).
        self.epoch = np.zeros(g, np.int64)
        self.refused = 0  # deterministic refusals (same on every member)
        self._history: List[Deque[Dict]] = [
            deque(maxlen=self.HISTORY) for _ in range(g)]

    # -- queries ---------------------------------------------------------------

    def is_default(self, group: int) -> bool:
        """True when the group still runs the boot all-voter config."""
        return bool(
            self.voter[group].all()
            and not self.voter_out[group].any()
            and not self.learner[group].any()
            and not self.in_joint[group]
        )

    def non_default_groups(self) -> np.ndarray:
        """Groups whose config differs from the boot all-voter default
        (the rows whose masks must be staged onto the device at boot)."""
        changed = (
            ~self.voter.all(axis=1)
            | self.voter_out.any(axis=1)
            | self.learner.any(axis=1)
            | self.in_joint
        )
        return np.nonzero(changed)[0]

    def conf_state(self, group: int) -> ConfState:
        """Reference-shaped ConfState (member ids = slot + 1) — rides
        outbound snapshot metadata so a rejoining member restores the
        config with the app state."""
        ids = lambda mask: (np.nonzero(mask)[0] + 1).tolist()  # noqa: E731
        return ConfState(
            voters=ids(self.voter[group]),
            learners=ids(self.learner[group]),
            voters_outgoing=ids(self.voter_out[group]),
            learners_next=ids(self.learner_next[group]),
            auto_leave=bool(self.auto_leave[group]),
        )

    def history(self, group: int) -> List[Dict]:
        return list(self._history[group])

    # -- apply -----------------------------------------------------------------

    def apply(self, group: int, index: int,
              cc: ConfChangeV2) -> Optional[str]:
        """Apply one committed conf-change entry. Returns None when the
        config changed, or a reason string when the change was skipped
        (stale replay) or deterministically refused (illegal). Masks
        are untouched on any non-None return; ``applied_index`` always
        advances to `index` — a refused entry is still an applied
        entry, and replaying it must refuse again, not retry."""
        if index <= self.applied_index[group]:
            return "stale"
        self.applied_index[group] = index
        err = self._apply_checked(group, cc)
        if err is not None:
            self.refused += 1
            return err
        self.epoch[group] += 1
        self._history[group].append({
            "index": int(index),
            "voters": tuple(np.nonzero(self.voter[group])[0] + 1),
            "voters_out": tuple(np.nonzero(self.voter_out[group])[0] + 1),
            "learners": tuple(np.nonzero(self.learner[group])[0] + 1),
            "joint": bool(self.in_joint[group]),
        })
        return None

    def _apply_checked(self, g: int, cc: ConfChangeV2) -> Optional[str]:
        bad = [c.node_id for c in cc.changes
               if not 1 <= c.node_id <= self.r]
        if bad:
            return f"targets {bad} outside replica capacity R={self.r}"
        if cc.leave_joint():
            return self._leave_joint(g)
        auto_leave, use_joint = cc.enter_joint()
        if use_joint:
            return self._enter_joint(g, auto_leave, cc)
        return self._simple(g, cc)

    def _leave_joint(self, g: int) -> Optional[str]:
        if not self.in_joint[g]:
            return "not in a joint config"
        # Deferred demotions become learners now that the old half's
        # votes stop counting (ref: confchange.go LeaveJoint).
        self.learner[g] |= self.learner_next[g]
        self.learner_next[g] = False
        self.voter_out[g] = False
        self.in_joint[g] = False
        self.auto_leave[g] = False
        return None

    def _enter_joint(self, g: int, auto_leave: bool,
                     cc: ConfChangeV2) -> Optional[str]:
        if self.in_joint[g]:
            return "already in a joint config"
        if not self.voter[g].any():
            return "can't make a zero-voter config joint"
        old_voter = self.voter[g].copy()
        old_learner = self.learner[g].copy()
        # Outgoing half = the incoming voters at entry (joint.go:49).
        self.voter_out[g] = old_voter
        err = self._apply_changes(g, cc, joint=True)
        if err is not None:
            # Roll back the halves touched above + by _apply_changes.
            self.voter[g] = old_voter
            self.learner[g] = old_learner
            self.voter_out[g] = False
            self.learner_next[g] = False
            return err
        self.in_joint[g] = True
        self.auto_leave[g] = bool(auto_leave)
        return None

    def _simple(self, g: int, cc: ConfChangeV2) -> Optional[str]:
        if self.in_joint[g]:
            # ref: confchange.go:135 — a simple change mid-joint would
            # edit the incoming half behind the outgoing snapshot's
            # back (observed live: a stale duplicate add-learner
            # applying inside a promote's joint window re-demoted the
            # freshly promoted voter).
            return "can't apply simple change in a joint config"
        old_voter = self.voter[g].copy()
        old_learner = self.learner[g].copy()
        err = self._apply_changes(g, cc, joint=False)
        if err is None and int(
                (self.voter[g] ^ old_voter).sum()) > 1:
            err = "more than one voter changed without entering joint"
        if err is not None:
            self.voter[g] = old_voter
            self.learner[g] = old_learner
            self.learner_next[g] = False
            return err
        return None

    def _apply_changes(self, g: int, cc: ConfChangeV2,
                       joint: bool) -> Optional[str]:
        for c in cc.changes:
            if c.node_id == 0:
                continue  # zeroed NodeID = refused upstream; no-op
            s = c.node_id - 1
            if c.type == ConfChangeType.ConfChangeAddNode:
                self.voter[g, s] = True
                self.learner[g, s] = False
                self.learner_next[g, s] = False
            elif c.type == ConfChangeType.ConfChangeAddLearnerNode:
                if joint and self.voter[g, s]:
                    # Demoting an incoming voter inside the joint
                    # entry: park as learner_next until leave-joint.
                    self.voter[g, s] = False
                    self.learner_next[g, s] = True
                else:
                    self.voter[g, s] = False
                    self.learner[g, s] = True
            elif c.type == ConfChangeType.ConfChangeRemoveNode:
                self.voter[g, s] = False
                self.learner[g, s] = False
                self.learner_next[g, s] = False
            elif c.type == ConfChangeType.ConfChangeUpdateNode:
                pass
            else:
                return f"unexpected conf change type {c.type}"
        if not self.voter[g].any():
            return "removed all voters"
        return None

    # -- snapshot restore ------------------------------------------------------

    def restore(self, group: int, index: int, cs: ConfState) -> bool:
        """Install the config carried by an inbound snapshot at
        `index` (ref: confchange/restore.go — the snapshot's ConfState
        supersedes whatever conf entries the skipped log held). Returns
        False when the snapshot is at-or-below the group's applied-conf
        watermark (nothing to do)."""
        if index <= self.applied_index[group]:
            return False
        mask = lambda ids: np.isin(  # noqa: E731
            np.arange(self.r) + 1, np.asarray(list(ids), int))
        self.voter[group] = mask(cs.voters)
        self.voter_out[group] = mask(cs.voters_outgoing)
        self.learner[group] = mask(cs.learners)
        self.learner_next[group] = mask(cs.learners_next)
        self.in_joint[group] = bool(cs.voters_outgoing)
        self.auto_leave[group] = bool(getattr(cs, "auto_leave", False))
        self.applied_index[group] = index
        self.epoch[group] += 1
        self._history[group].append({
            "index": int(index),
            "voters": tuple(sorted(cs.voters)),
            "voters_out": tuple(sorted(cs.voters_outgoing)),
            "learners": tuple(sorted(cs.learners)),
            "joint": bool(cs.voters_outgoing),
            # Snapshot restores SKIP the intermediate conf entries the
            # compacted log held — adjacency audits must re-anchor
            # here instead of flagging the jump as an illegal
            # transition (check_config_safety reads this).
            "restored": True,
        })
        return True

    # -- device masks ----------------------------------------------------------

    def masks(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                               np.ndarray, np.ndarray]:
        """Device-shaped mask planes for `rows` — the exact argument
        shape of ``BatchedRawNode.set_membership_many``. learner_next
        slots stay replication targets (they are outgoing voters), so
        they ride the voter_out plane only; the learner plane flips at
        leave-joint."""
        rows = np.asarray(rows, np.int64)
        return (self.voter[rows], self.voter_out[rows],
                self.learner[rows], self.in_joint[rows])

    # -- WAL record ------------------------------------------------------------

    def pack_groups(self, rows: np.ndarray) -> bytes:
        """Count-prefixed RT_CONF_BATCH payload: each row's full config
        at its applied-conf index. Full-state records (not deltas), so
        replay takes the LATEST record per group and needs nothing
        before it."""
        rows = np.asarray(rows, np.int64)
        dt = conf_record_dtype(self.r)
        rec = np.zeros(len(rows), dt)
        rec["group"] = rows
        rec["index"] = self.applied_index[rows]
        rec["flags"] = (
            self.in_joint[rows] * FLAG_JOINT
            + self.auto_leave[rows] * FLAG_AUTO_LEAVE
        )
        rec["slots"] = (
            self.voter[rows] * SLOT_VOTER
            + self.voter_out[rows] * SLOT_VOTER_OUT
            + self.learner[rows] * SLOT_LEARNER
            + self.learner_next[rows] * SLOT_LEARNER_NEXT
        )
        import struct

        return struct.pack("<I", len(rows)) + rec.tobytes()

    @staticmethod
    def unpack_groups(data: bytes,
                      num_replicas: int) -> Iterator[Tuple[int, int,
                                                           int,
                                                           np.ndarray]]:
        """Yield (group, index, flags, slots[R]) rows of an
        RT_CONF_BATCH record."""
        import struct

        (n,) = struct.unpack_from("<I", data)
        rec = np.frombuffer(data, conf_record_dtype(num_replicas),
                            count=n, offset=4)
        for i in range(n):
            yield (int(rec["group"][i]), int(rec["index"][i]),
                   int(rec["flags"][i]), rec["slots"][i])

    def load_record(self, group: int, index: int, flags: int,
                    slots: np.ndarray) -> None:
        """Install one replayed RT_CONF_BATCH row (latest record per
        group wins; caller feeds them in WAL order)."""
        self.voter[group] = (slots & SLOT_VOTER) != 0
        self.voter_out[group] = (slots & SLOT_VOTER_OUT) != 0
        self.learner[group] = (slots & SLOT_LEARNER) != 0
        self.learner_next[group] = (slots & SLOT_LEARNER_NEXT) != 0
        self.in_joint[group] = bool(flags & FLAG_JOINT)
        self.auto_leave[group] = bool(flags & FLAG_AUTO_LEAVE)
        self.applied_index[group] = index
