"""BatchedNode: the raft.Node plugin boundary served by the device
engine.

This is the `--raft-backend=tpu` construction path (ref: the single
raft-construction site in server/etcdserver/bootstrap.go:473-536 and
contrib/raftexample/raft.go:87): hosts that drive `raft.node.Node`
(raftexample, EtcdServer) can construct a ``BatchedNode`` instead and
run unchanged — same Ready/persist/send/Advance cycle, same Message
wire types — while the consensus math executes in the batched device
kernel (one group here; the multi-group hosting layer lives in
hosting.py).

Differences from the host Node, by design:
* proposals are forwarded to the leader host-side (the kernel has no
  MsgProp lane); with no known leader they are dropped, like the
  reference's ErrProposalDropped path (ref: raft/node.go:425-462);
* log compaction is host-controlled: the host calls ``compact(index)``
  after taking an app snapshot, which moves the device ring floor, and
  outbound MsgSnap messages carry that snapshot's data — keeping the
  floor and the app snapshot index equal by construction;
* conf changes ride the log as typed entries (types live in the host
  arena beside payloads); on apply, the host Changer computes the new
  config and uploads voter/learner/joint masks to the device.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..raft.errors import RaftError
from ..raft.raft import SoftState, StateType
from ..raft.rawnode import BasicStatus, Ready, Status
from ..raft.types import (
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    ConfState,
)
from .rawnode import BatchedRawNode, RowRestore
from .state import BatchedConfig, LEADER
from .step import T_SNAP


class ProposalDroppedError(RaftError):
    """ref: raft.ErrProposalDropped."""


class BatchedNode:
    """Single-group raft.Node over the batched device engine."""

    def __init__(
        self,
        node_id: int,
        peers: List[int],
        election_tick: int = 10,
        heartbeat_tick: int = 1,
        window: int = 256,
        max_ents_per_msg: int = 8,
        max_props_per_round: int = 8,
        pre_vote: bool = True,
        check_quorum: bool = True,
        restore: Optional[RowRestore] = None,
        boot_conf_state: Optional[ConfState] = None,
        capacity: int = 0,
    ) -> None:
        self.id = node_id
        self.peers = sorted(peers)
        assert self.peers == list(range(1, len(self.peers) + 1)), (
            "batched backend uses dense member ids 1..R"
        )
        # Replica capacity R is a compiled shape: provision headroom
        # beyond the boot peers so future member-adds have a slot
        # (spare slots are inert — the kernel's replication/electorate
        # sets are masked by voter|learner, so nothing is sent to them
        # until a conf change admits the member).
        r = max(capacity, len(self.peers))
        self.cfg = BatchedConfig(
            num_groups=1,
            num_replicas=r,
            window=window,
            max_ents_per_msg=max_ents_per_msg,
            max_props_per_round=max_props_per_round,
            election_timeout=election_tick,
            heartbeat_timeout=heartbeat_tick,
            pre_vote=pre_vote,
            check_quorum=check_quorum,
            auto_compact=False,  # host-controlled via compact()
        )
        self.rn = BatchedRawNode(
            self.cfg,
            groups=np.array([0], np.int32),
            slots=np.array([node_id - 1], np.int32),
            restore={0: restore} if restore else None,
        )
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stopped = False
        # Latest app snapshot (index, term, data): attached to outbound
        # MsgSnap; index == device ring floor by the compact() contract.
        self._app_snap: Optional[Snapshot] = None
        # Inbound snapshot data staged until the device confirms the
        # install (keyed by snapshot index).
        self._inbound_snaps: Dict[int, Snapshot] = {}
        # Host-side proposal forwards waiting for the next Ready.
        self._fwd: List[Message] = []
        # Last emitted SoftState (Ready carries it only on change).
        self._last_soft: Optional[SoftState] = None
        # ReadIndex waiters not yet bound to a device batch, and the
        # per-batch bindings (seq -> waiters). A waiter is only ever
        # served by a batch that opened at-or-after its request, so the
        # confirmed index covers its request time (linearizability).
        self._read_unbound: List[bytes] = []
        self._read_bound: Dict[int, List[bytes]] = {}
        # Host config mirror driving confchange mask computation
        # (the reference's ProgressTracker config half; progress lives
        # on-device).
        from ..raft.confchange import Changer, restore as cc_restore
        from ..raft.tracker import ProgressTracker

        self._conf_tracker = ProgressTracker(max_inflight=256)
        if restore is not None and getattr(restore, "conf_state", None):
            boot_cs = restore.conf_state
        elif boot_conf_state is not None:
            # Joiner boot: the caller dictates the starting config —
            # typically voterless (empty), so this member cannot
            # campaign or count its own vote until the admitting conf
            # change applies from the replicated log (the device twin
            # of Node.restart-with-empty-config semantics,
            # ref: etcdserver/bootstrap.go:513-521 RestartNode).
            boot_cs = boot_conf_state
        else:
            boot_cs = ConfState(voters=list(self.peers))
        if boot_cs.voters or boot_cs.learners or boot_cs.voters_outgoing:
            cc_restore(Changer(self._conf_tracker, 0), boot_cs)
        cs0 = self._conf_tracker.conf_state()
        # The device boots with ALL R slots as voters (init_state);
        # upload masks whenever the true config differs — including
        # when spare capacity slots exist beyond the boot peers.
        if (sorted(cs0.voters) != list(range(1, r + 1)) or cs0.learners
                or cs0.voters_outgoing):
            self.rn.set_membership(
                0,
                voters=[v - 1 for v in cs0.voters],
                voters_out=[v - 1 for v in cs0.voters_outgoing],
                learners=[v - 1 for v in cs0.learners],
                joint=bool(cs0.voters_outgoing),
            )

    def _current_conf_state(self) -> ConfState:
        """Membership as last applied (snapshot metadata must reflect
        conf changes, not the boot peer list)."""
        with self._lock:
            return self._conf_tracker.conf_state()

    def _self_tracked(self) -> bool:
        """Whether this member has a progress entry in the current
        config (voter of either half or learner) — the reference's
        condition for a leader to accept proposals (raft.go:1043)."""
        with self._lock:
            cs = self._conf_tracker.conf_state()
        return self.id in set(cs.voters) | set(
            cs.voters_outgoing) | set(cs.learners)

    # -- Node interface --------------------------------------------------------

    def tick(self) -> None:
        self.rn.tick()
        self._work.set()

    def campaign(self) -> None:
        self.rn.campaign([0])
        self._work.set()

    def _propose_entry(self, data: bytes, etype: EntryType,
                       timeout: Optional[float]) -> None:
        """Shared propose path: leaders queue for the next round,
        followers forward to the known leader over the wire, no-leader
        polls up to `timeout` before dropping (ref: node.go:464-501
        stepWithWaitOption)."""
        deadline = time.monotonic() + (timeout if timeout else 5.0)
        while True:
            if self.rn.is_leader(0):
                if not self._self_tracked():
                    # A leader removed from the config drops proposals
                    # (ref: raft.go:1043-1046 "not currently a member
                    # of the range"); the device propose gate refuses
                    # them too, so queueing would pend forever.
                    raise ProposalDroppedError(
                        "raft proposal dropped: leader removed from "
                        "config")
                self.rn.propose(0, data, etype=int(etype))
                self._work.set()
                return
            lead = self.rn.lead(0)
            if lead != 0:
                with self._lock:
                    self._fwd.append(Message(
                        type=MessageType.MsgProp, to=lead, from_=self.id,
                        entries=[Entry(data=data, type=etype)],
                    ))
                self._work.set()
                return
            if self._stopped or time.monotonic() >= deadline:
                raise ProposalDroppedError("no leader; proposal dropped")
            time.sleep(0.01)

    def propose(self, data: bytes, timeout: Optional[float] = None) -> None:
        self._propose_entry(data, EntryType.EntryNormal, timeout)

    def propose_conf_change(self, cc, timeout: Optional[float] = None) -> None:
        """Propose a membership change through the log; when it commits
        and the app calls apply_conf_change, the new masks upload to
        the device (ref: node.go ProposeConfChange; SURVEY §2.1
        'confchange: host-side control plane, emits new masks').

        Targets must be within the provisioned replica capacity R —
        the batched layout pre-provisions slots, add/remove toggles
        masks (capacity is a compile-time shape, membership is not)."""
        from ..raft.types import ConfChangeV2

        etype = (EntryType.EntryConfChangeV2
                 if isinstance(cc, ConfChangeV2)
                 else EntryType.EntryConfChange)
        self._propose_entry(cc.marshal(), etype, timeout)

    def apply_conf_change(self, cc) -> ConfState:
        """Apply a committed conf change: compute the new config with
        the same Changer the host raft uses (joint semantics included)
        and upload the masks to the device
        (ref: raft.go:896-905 applyConfChange → confchange.Changer)."""
        from ..raft.confchange import Changer

        cc2 = cc.as_v2()
        bad = [c.node_id for c in cc2.changes
               if not 1 <= c.node_id <= self.cfg.num_replicas]
        if bad:
            raise ValueError(
                f"conf-change targets {bad} outside provisioned replica "
                f"capacity R={self.cfg.num_replicas}")
        with self._lock:
            tr = self._conf_tracker
            changer = Changer(tracker=tr, last_index=int(self.rn.m_last[0]))
            if cc2.leave_joint():
                cfg, prs = changer.leave_joint()
            else:
                auto_leave, use_joint = cc2.enter_joint()
                if use_joint:
                    cfg, prs = changer.enter_joint(auto_leave, cc2.changes)
                else:
                    cfg, prs = changer.simple(cc2.changes)
            tr.config, tr.progress = cfg, prs
            cs = tr.conf_state()
            auto_leave = bool(cs.voters_outgoing) and tr.config.auto_leave
        self.rn.set_membership(
            0,
            voters=[v - 1 for v in cs.voters],
            voters_out=[v - 1 for v in cs.voters_outgoing],
            learners=[v - 1 for v in cs.learners],
            joint=bool(cs.voters_outgoing),
        )
        if self.rn.is_leader(0):
            # A leader contacts changed membership immediately
            # (ref: raft.go switchToConfig → maybeSendAppend), not at
            # the next heartbeat timeout — a joiner's catch-up must not
            # depend on tick cadence.
            self.rn.poke_append(0)
        if auto_leave and self.rn.is_leader(0):
            # The leader auto-proposes the empty change that exits an
            # implicit joint config (ref: raft.go advance() proposing
            # the zero ConfChangeV2 when autoLeave is pending).
            from ..raft.types import ConfChangeV2

            self.rn.propose(0, ConfChangeV2().marshal(),
                            etype=int(EntryType.EntryConfChangeV2))
        self._work.set()
        return cs

    def step(self, m: Message) -> None:
        if m.type == MessageType.MsgTransferLeader:
            # Forwarded from a follower: from_ carries the transferee
            # (raft.go stepLeader MsgTransferLeader convention).
            if self.rn.is_leader(0):
                self.rn.transfer_leader(0, m.from_ - 1)
                self._work.set()
            return
        if m.type == MessageType.MsgProp:
            # Forwarded proposal: accept if we lead, else re-forward once
            # more toward our view of the leader; drop without one.
            if self.rn.is_leader(0):
                if not self._self_tracked():
                    # Same gate as the local propose path: the device
                    # refuses appends from an untracked leader, so
                    # queueing would pend (and spin has_work) forever.
                    raise ProposalDroppedError(
                        "raft proposal dropped: leader removed from "
                        "config")
                for e in m.entries:
                    # Entry types survive forwarding (a follower's conf
                    # change must commit as EntryConfChange).
                    self.rn.propose(0, e.data, etype=int(e.type))
                self._work.set()
                return
            lead = self.rn.lead(0)
            if lead == 0 or lead == m.from_:
                raise ProposalDroppedError("no leader; proposal dropped")
            with self._lock:
                self._fwd.append(
                    Message(
                        type=MessageType.MsgProp, to=lead, from_=self.id,
                        entries=m.entries,
                    )
                )
            self._work.set()
            return
        if m.type == MessageType.MsgSnap:
            # Stash app data; the device confirms the install and the
            # Ready carries the snapshot to the host for restore.
            with self._lock:
                self._inbound_snaps[m.snapshot.metadata.index] = m.snapshot
            # The sender's ring floor (m.index) may sit BELOW the
            # attached app snapshot (compaction keeps a catch-up margin;
            # the app state is serialized at applied). Install at the
            # app snapshot's index — its state supersedes the log
            # entries in between, and the confirm/stash keys then agree.
            if m.snapshot.metadata.index > m.index:
                m.index = m.snapshot.metadata.index
                m.log_term = m.snapshot.metadata.term
        self.rn.step(0, m)
        self._work.set()

    def read_index(self, rctx: bytes) -> None:
        """Open (or join) a ReadIndex batch on the device; the
        confirmed index surfaces as Ready.read_states carrying `rctx`
        (ref: node.go:556-560 ReadIndex; batching matches the server's
        linearizableReadLoop one-round-many-waiters shape).

        Raises on a non-leader so callers retry against the leader
        instead of hanging (divergence from the reference, which
        forwards MsgReadIndex — the server read loop's retry/timeout
        machinery handles both shapes)."""
        if not self.rn.is_leader(0):
            raise ProposalDroppedError("read_index: not leader")
        with self._lock:
            self._read_unbound.append(rctx)
        self.rn.read_index(0)
        self._work.set()

    def transfer_leadership(self, lead: int, transferee: int) -> None:
        """ref: node.go:550-554 TransferLeadership. A non-leader
        forwards to its known leader over the wire, the reference's
        stepFollower MsgTransferLeader path (raft.go:1457-1464)."""
        if self.rn.is_leader(0):
            self.rn.transfer_leader(0, transferee - 1)
        else:
            lead_now = self.rn.lead(0)
            if lead_now == 0:
                return  # no leader; drop like the reference logs+drops
            with self._lock:
                self._fwd.append(Message(
                    type=MessageType.MsgTransferLeader, to=lead_now,
                    from_=transferee,
                ))
        self._work.set()

    def report_unreachable(self, vid: int) -> None:
        pass

    def report_snapshot(self, vid: int, failure: bool) -> None:
        pass

    def has_ready(self) -> bool:
        with self._lock:
            fwd = bool(self._fwd)
        return fwd or self.rn.has_work()

    def ready(self, timeout: Optional[float] = None) -> Optional[Ready]:
        """Run one device round over the staged inputs and translate the
        BatchedReady to the host Ready shape. Returns None when there is
        no work within `timeout`."""
        if not self.rn.has_work() and not self._fwd:
            if not self._work.wait(timeout):
                return None
        self._work.clear()
        if self._stopped:
            return None
        rd = self.rn.advance_round()

        entries = [
            Entry(index=i, term=t, data=d, type=EntryType(et))
            for (_row, i, t, d, et) in rd.entries
        ]
        committed = []
        for _row, items in rd.committed:
            committed.extend(
                Entry(index=i, term=t, data=d or b"", type=EntryType(et))
                for (i, t, d, et) in items
            )

        snapshot = Snapshot()
        if rd.snapshots:
            _row, idx, term = rd.snapshots[-1]
            with self._lock:
                stash = self._inbound_snaps.pop(idx, None)
                # Drop only staler stashes — a higher-index MsgSnap may
                # already be queued for a later round.
                for k in [k for k in self._inbound_snaps if k <= idx]:
                    del self._inbound_snaps[k]
            if stash is not None:
                snapshot = stash
                # An installed snapshot carries the sender's membership;
                # entries between our log and the snapshot (which may
                # include conf changes) are skipped, so the config must
                # be restored from the metadata — the device twin of
                # raft.restore() → confchange.Restore
                # (ref: raft.go:1589-1605, confchange/restore.go:155).
                cs = stash.metadata.conf_state
                if cs.voters or cs.learners or cs.voters_outgoing:
                    from ..raft.confchange import (
                        Changer,
                        restore as cc_restore,
                    )
                    from ..raft.tracker import ProgressTracker

                    with self._lock:
                        tr = ProgressTracker(max_inflight=256)
                        cc_restore(Changer(tr, idx), cs)
                        self._conf_tracker = tr
                    self.rn.set_membership(
                        0,
                        voters=[v - 1 for v in cs.voters],
                        voters_out=[v - 1 for v in cs.voters_outgoing],
                        learners=[v - 1 for v in cs.learners],
                        joint=bool(cs.voters_outgoing),
                    )
            else:
                snapshot = Snapshot(
                    metadata=SnapshotMetadata(
                        index=idx, term=term,
                        conf_state=self._current_conf_state(),
                    )
                )
            self.rn.install_snapshot_state(0, idx)

        messages = []
        all_msgs = list(rd.messages)
        if rd.msg_block is not None and len(rd.msg_block):
            from .msgblock import block_messages

            all_msgs.extend(block_messages(rd.msg_block))
        for _row, m in all_msgs:
            if int(m.type) == T_SNAP:
                with self._lock:
                    app = self._app_snap
                if app is None or app.metadata.index < m.snapshot.metadata.index:
                    # Floor moved without a matching app snapshot (only
                    # possible transiently); retry next heartbeat.
                    continue
                m.snapshot = app
            messages.append(m)
        with self._lock:
            messages.extend(self._fwd)
            self._fwd.clear()

        read_states = []
        if rd.read_opened or rd.read_states:
            from ..raft.read_only import ReadState

            with self._lock:
                # Bind unbound waiters to the batch that just opened:
                # it captured a commit index ≥ their request time.
                for _row, seq in rd.read_opened:
                    self._read_bound.setdefault(seq, []).extend(
                        self._read_unbound)
                    self._read_unbound = []
                for _row, seq, ridx in rd.read_states:
                    for rctx in self._read_bound.pop(seq, []):
                        read_states.append(
                            ReadState(index=ridx, request_ctx=rctx))

        hs = HardState(
            term=int(self.rn._round[0][0]),
            vote=int(self.rn._round[1][0]),
            commit=int(self.rn._round[2][0]),
        )
        # SoftState rides the Ready only when it changed — the
        # reference's newReady contract (raft/node.go:564-584), which
        # is how EtcdServer learns leadership transitions.
        soft = SoftState(
            lead=self.rn.lead(0),
            raft_state=StateType(int(self.rn.m_role[0])),
        )
        soft_out = None
        if self._last_soft is None or not soft.equal(self._last_soft):
            self._last_soft = soft
            soft_out = soft
        rd_out = Ready(
            hard_state=hs if rd.hardstates else HardState(),
            soft_state=soft_out,
            entries=entries,
            snapshot=snapshot,
            committed_entries=committed,
            messages=messages,
            must_sync=rd.must_sync,
            read_states=read_states,
        )
        return rd_out

    def advance(self) -> None:
        self.rn.advance()

    def create_snapshot(self, index: int, confstate: Optional[ConfState],
                        data: bytes) -> Snapshot:
        """Build a Snapshot at `index` (≤ committed) with the term taken
        from the device ring (ref: MemoryStorage.CreateSnapshot,
        raft/storage.go:180-199). Callable mid-Ready: the host applies
        committed entries before advance(), so the bound is the
        in-flight commit."""
        rn = self.rn
        bound = max(int(rn.applied[0]), rn.latest_commit(0))
        assert index <= bound, (index, bound)
        if index > rn.m_snap[0]:
            term = int(rn.latest_ring()[0, index % self.cfg.window])
        else:
            import jax

            term = int(jax.device_get(rn.state.snap_term)[0])
        return Snapshot(
            metadata=SnapshotMetadata(
                index=index, term=term,
                conf_state=confstate or self._current_conf_state(),
            ),
            data=data,
        )

    def compact(self, index: int, snapshot: Snapshot) -> None:
        """Host took an app snapshot at `index`: move the device ring
        floor there and keep the snapshot for lagging followers."""
        self._app_snap = snapshot
        self.rn.compact(0, index)

    def set_app_snapshot(self, snapshot: Snapshot) -> None:
        """Refresh the app snapshot backing outbound MsgSnap without
        moving the log floor — hosts that apply continuously keep this
        at their applied watermark so stragglers restore to the newest
        state (the snapOverrideStorage shape,
        ref: rafttest/interaction_env_handler_add_nodes.go)."""
        with self._lock:
            if (self._app_snap is None
                    or snapshot.metadata.index
                    >= self._app_snap.metadata.index):
                self._app_snap = snapshot

    def status(self) -> Status:
        role = int(self.rn.m_role[0])
        return Status(
            basic=BasicStatus(
                id=self.id,
                hard_state=HardState(
                    term=int(self.rn.m_term[0]),
                    vote=int(self.rn.m_vote[0]),
                    commit=int(self.rn.m_commit[0]),
                ),
                soft_state=SoftState(
                    lead=self.rn.lead(0),
                    raft_state=StateType(role),
                ),
                applied=int(self.rn.applied[0]),
            )
        )

    def stop(self) -> None:
        self._stopped = True
        self._work.set()
