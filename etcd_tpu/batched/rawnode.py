"""BatchedRawNode: the RawNode plugin contract over G groups at once.

This is the piece that turns the device step kernel into a *backend*:
the same logical cycle as the reference's RawNode —

    stage inputs → advance_round() → BatchedReady →
    persist (WAL) → apply → send → advance()

(ref: raft/rawnode.go:125-179 HasReady/Ready/Advance and the production
ordering in server/etcdserver/raft.go:158-315) — but for every group in
one device program. Entry payload bytes never touch the device: the
host keeps them in a per-row arena keyed by log index, assigns indexes
to proposals from the phase watermarks the kernel reports (StepAux),
and re-attaches payloads when draining committed ranges or building
outbound MsgApp messages.

A *row* is one replica instance this process hosts: (group, slot).
Topologies:

* hosting process (one replica slot of every group): rows = G,
  ``slots[i] = s`` constant, messages travel over the wire;
* in-proc all-replica engine (tests, single-process demos): rows = G*R.

Persistence contract per round (must_sync mirrors raft MustSync,
ref: raft/node.go:588-595): the caller drains ``BatchedReady`` to its
WAL and fsyncs BEFORE handing messages to the transport, then applies
committed entries, then calls ``advance()``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sentinels import warm_guard
from ..raft.types import (
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
)
from .msgblock import (
    MsgBlock,
    compact_records,
    merge_blocks,
    ragged_ranges,
    validate_block,
)
from .state import BatchedConfig, BatchedState, LEADER, I32, init_state
from .step import (
    KIND_APP,
    KIND_APP_RESP,
    KIND_HB,
    KIND_HB_RESP,
    KIND_VOTE,
    KIND_VOTE_RESP,
    NUM_KINDS,
    T_APP,
    T_APP_RESP,
    T_HB,
    T_HB_RESP,
    T_PREVOTE,
    T_PREVOTE_RESP,
    T_SNAP,
    T_TIMEOUT_NOW,
    T_VOTE,
    T_VOTE_RESP,
    MsgSlots,
    make_step_round,
    pack_outbox,
)

# Inbox lane for each wire type (lanes are capacity classes; handlers
# dispatch on the type field — see step.py).
_LANE = {
    T_VOTE: KIND_VOTE,
    T_PREVOTE: KIND_VOTE,
    T_APP: KIND_APP,
    T_SNAP: KIND_APP,
    T_HB: KIND_HB,
    T_TIMEOUT_NOW: KIND_HB,
    T_VOTE_RESP: KIND_VOTE_RESP,
    T_PREVOTE_RESP: KIND_VOTE_RESP,
    T_APP_RESP: KIND_APP_RESP,
    T_HB_RESP: KIND_HB_RESP,
}


@dataclass
class RowRestore:
    """Boot state for one row (from WAL replay / snapshot)."""

    term: int = 0
    vote: int = 0  # slot+1, 0 = none
    commit: int = 0
    applied: int = 0  # host app state watermark (snapshot index)
    snap_index: int = 0  # log floor
    snap_term: int = 0
    entries: List[Tuple[int, int, bytes]] = field(default_factory=list)
    # (index, term, data[, etype]) strictly ascending, > snap_index
    # Membership at the snapshot point (None → full-voter bootstrap;
    # committed conf entries in the tail re-apply through Ready).
    conf_state: Optional[object] = None
    # Durability fence (protocol-aware torn-tail recovery): the hosting
    # layer sets this when the recovered WAL tail fell below the
    # group's durable watermark — the row boots with campaigning and
    # vote-granting suppressed until set_fence(row, False) lifts it.
    fenced: bool = False


_EMPTY_I8 = np.empty(0, np.int64)


class EntryBatch:
    """SoA batch of entry records to persist: parallel numpy arrays
    (row, index, term, etype) plus the payload list, in row-ascending
    index-ascending order. Iterates as (row, index, term, data, etype)
    tuples — the legacy consumer shape — while the arrays feed the
    hosting layer's batched WAL serialization directly (one numpy
    header array + one payload join per persistence batch, no
    per-entry struct.pack)."""

    __slots__ = ("rows", "idx", "term", "etype", "datas")

    def __init__(self, rows: np.ndarray = _EMPTY_I8,
                 idx: np.ndarray = _EMPTY_I8,
                 term: np.ndarray = _EMPTY_I8,
                 etype: np.ndarray = _EMPTY_I8,
                 datas: Optional[List[bytes]] = None) -> None:
        self.rows = rows
        self.idx = idx
        self.term = term
        self.etype = etype
        self.datas = datas if datas is not None else []

    def __len__(self) -> int:
        return len(self.datas)

    def __iter__(self):
        return iter(zip(self.rows.tolist(), self.idx.tolist(),
                        self.term.tolist(), self.datas,
                        self.etype.tolist()))


@dataclass
class BatchedReady:
    """One round's outstanding work (ref: raft/node.go:52-90 Ready,
    batched). Drain order: hardstates+entries+snapshots → WAL fsync →
    apply committed → messages → advance()."""

    hardstates: List[Tuple[int, int, int, int]]  # (row, term, vote, commit)
    entries: "EntryBatch"  # (row, index, term, data, etype) records
    # Device-installed snapshot restores this round: (row, index, term).
    # App-state restore happened host-side when the MsgSnap was staged.
    snapshots: List[Tuple[int, int, int]]
    committed: List[Tuple[int, List[Tuple[int, int, Optional[bytes]]]]]
    # (row, [(index, term, data or None for internal/empty)])
    messages: List[Tuple[int, Message]]
    must_sync: bool
    # Payload-free outbound messages as one SoA block (see msgblock.py);
    # `messages` then carries only MsgApp-with-entries / MsgSnap.
    msg_block: Optional[MsgBlock] = None
    # Quorum-confirmed ReadIndex batches this round: (row, seq, index)
    # (ref: Ready.ReadStates, read_only.go advance).
    read_states: List[Tuple[int, int, int]] = field(default_factory=list)
    # Sampled trace keys (etcd_tpu.obs): (group, term, index) of traced
    # entries persisted this round (the hosting layer stamps fsync/send
    # on them) and of traced entries newly committed this round (apply
    # stamp). Empty lists when tracing is off — zero per-round cost.
    traced_entries: List[Tuple[int, int, int]] = field(default_factory=list)
    traced_commit: List[Tuple[int, int, int]] = field(default_factory=list)
    # Batches that OPENED this round: (row, seq). Hosts bind waiters to
    # the open batch so a later waiter is never served an earlier
    # batch's (stale) index.
    read_opened: List[Tuple[int, int]] = field(default_factory=list)
    # Ring term rows captured AT ROUND TIME for rows with outbound
    # MsgSnap: a pipelined drain worker processing this Ready later
    # must price the snapshot term from THIS round's ring — by then
    # latest_ring() reflects newer rounds and (with auto_compact) the
    # slot may have wrapped to a different entry's term.
    snap_rings: Dict[int, np.ndarray] = field(default_factory=dict)

    def contains_updates(self) -> bool:
        return bool(
            self.hardstates or self.entries or self.snapshots
            or self.committed or self.messages or self.read_states
            or (self.msg_block is not None and len(self.msg_block))
        )


class BatchedRawNode:
    """Thread-safe staging + single-threaded advance_round/advance.

    ``advance_round()`` runs one device round over the staged inputs and
    produces a BatchedReady; the caller persists/applies/sends, then
    calls ``advance()`` to commit the host mirrors. Only one
    round may be in flight at a time.
    """

    def __init__(
        self,
        cfg: BatchedConfig,
        groups: Optional[np.ndarray] = None,
        slots: Optional[np.ndarray] = None,
        restore: Optional[Dict[int, RowRestore]] = None,
        start_index: int = 0,
        mesh: Optional["object"] = None,
    ) -> None:
        # Resolve deliver_shape="auto" to the platform default so the
        # hosted path and the closed-loop engine pick the same compiled
        # round program for one logical config.
        self.cfg = cfg = cfg.validate().resolved()
        from .compile_cache import enable_compile_cache

        enable_compile_cache()
        r = cfg.num_replicas
        if groups is None:  # dense all-replica layout
            n = cfg.num_instances
            groups = np.arange(n, dtype=np.int32) // r
            slots = np.arange(n, dtype=np.int32) % r
        else:
            groups = np.asarray(groups, np.int32)
            slots = np.asarray(slots, np.int32)
        self.groups = groups
        self.slots = slots
        self.n = len(groups)
        iids = groups * r + slots
        # Row-axis sharding over a device mesh: rows (= groups for a
        # hosting member) are the data-parallel axis of multi-raft —
        # quorum reductions stay within a row, so the sharded step
        # needs NO cross-device collectives (SURVEY §2.1 parallelism
        # decomposition; the dryrun_multichip layout).
        self._shard = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            assert self.n % len(mesh.devices.flat) == 0, (
                f"rows {self.n} must divide the mesh "
                f"({len(mesh.devices.flat)} devices)")
            self._shard = NamedSharding(mesh, PartitionSpec("groups"))

        def dev(x):
            if self._shard is not None:
                # device_put accepts numpy directly and slices
                # host-side — no intermediate hop via the default
                # device before the mesh reshard.
                return jax.device_put(x, self._shard)
            return jnp.asarray(x)

        self._dev = dev
        self._slots_j = dev(slots)
        self._step = make_step_round(
            cfg, iids=dev(iids), slots=self._slots_j, with_aux=True,
            # Mesh-sharded rows must not pay a cross-shard collective
            # for the lane-occupancy skip (step._step_round_jit): the
            # sharded round's contract is ZERO collectives on the hot
            # path, and concurrent members' AllReduces deadlock.
            lane_skip=self._shard is None,
        )
        # Transfer-guard warmth is per (config, row count): the shared
        # round program recompiles per distinct row shape, and compiles
        # must run unguarded (they transfer host constants).
        self._wkey_step = f"round_step/{hash((cfg, True, self.n))}"

        self.state = init_state(cfg, start_index, iids=jnp.asarray(iids))
        if self._shard is not None:
            self.state = jax.tree.map(dev, self.state)
        # Host mirrors (updated in advance()).
        self.m_term = np.zeros(self.n, np.int64)
        self.m_vote = np.zeros(self.n, np.int64)
        self.m_commit = np.full(self.n, start_index, np.int64)
        self.m_last = np.full(self.n, start_index, np.int64)
        self.m_snap = np.full(self.n, start_index, np.int64)
        self.m_role = np.zeros(self.n, np.int64)
        self.m_lead = np.zeros(self.n, np.int64)
        # Consistent (term, role, lead) triple for observers: the
        # individual mirrors above are swapped by TWO statements in
        # advance(), so a foreign thread reading them pairwise can see
        # role from round k and term from round k-1 — a phantom
        # "leader at the old term". One tuple assignment is atomic.
        self.m_view: Tuple[np.ndarray, np.ndarray, np.ndarray] = (
            self.m_term, self.m_role, self.m_lead)
        self.m_ring = np.zeros((self.n, cfg.window), np.int64)
        # Leader-lease lane mirror (state.lease_ticks): the hosting
        # layer's lease-first read routing compares this against
        # cfg.lease_read_margin — one numpy read, zero device hops.
        self.m_lease_ticks = np.zeros(self.n, np.int64)
        self.applied = np.full(self.n, start_index, np.int64)
        self.stable = np.full(self.n, start_index, np.int64)

        # Payload arena: per row, index -> (term, data).
        self.arena: List[Dict[int, Tuple[int, bytes]]] = [
            {} for _ in range(self.n)
        ]
        # Sparse entry-type registry: index -> EntryType for the rare
        # non-Normal entries (conf changes); absent == EntryNormal.
        # The device only ever sees (term, index); types ride the host
        # arena like payloads do.
        self.etypes: List[Dict[int, int]] = [{} for _ in range(self.n)]

        # Monotone commit watermark guarding arena immutability (see
        # step(): inbound MsgApp must not overwrite committed payloads).
        self._commit_guard = np.full(self.n, start_index, np.int64)

        # Staging (guarded by _lock).
        self._lock = threading.Lock()
        self._pending: Dict[Tuple[int, int, int], deque] = {}
        self._blocks: deque = deque()  # staged MsgBlock record arrays
        self._props: List[deque] = [deque() for _ in range(self.n)]
        self._ticks = np.zeros(self.n, np.int64)
        self._campaign = np.zeros(self.n, bool)
        self._isolate = np.zeros(self.n, bool)
        self._transfer = np.zeros(self.n, np.int32)  # target slot+1
        self._read_req = np.zeros(self.n, bool)
        self._poked = False  # host staged send_append flags (poke_append)
        self._poke_rows = np.zeros(self.n, bool)
        # Staged device-state edits from foreign threads, applied at
        # the head of the next round ON the round thread (in-place
        # edits would race the round's state swap): row -> masks, and
        # row -> requested ring-floor index.
        self._pending_conf: Dict[int, Tuple] = {}
        self._pending_compact: Dict[int, int] = {}
        self._pending_fence: Dict[int, bool] = {}
        self._read_seen = np.zeros(self.n, np.int64)  # last surfaced seq
        self._read_seq_prev = np.zeros(self.n, np.int64)  # open detection
        self._snap_staged: Dict[int, Tuple[int, int]] = {}  # row->(idx,term)

        if restore:
            self._restore(restore)

        # In-flight round (between advance_round and advance).
        self._round: Optional[Tuple] = None

        # Per-round phase wall-seconds, always measured (four
        # perf_counter reads per round — noise next to a device round):
        # stage (inbox build), step (device round + host reads),
        # extract (post-round entry/commit extraction), collect
        # (outbound block assembly). The hosting layer folds these into
        # its phase histograms so the BENCH_NOTES phase breakdown is
        # reproducible from metrics.
        self.phase_last: Dict[str, float] = {
            "stage": 0.0, "step": 0.0, "extract": 0.0, "collect": 0.0}
        # Opt-in cumulative profile (ETCD_TPU_PROF=1): same keys plus a
        # round counter and the staging-lock acquire wait (stage_lock,
        # a subset of stage: time spent waiting for _lock against
        # proposer/transport threads — convoy, not work), read by
        # benches/BENCH_NOTES captures.
        self.prof: Optional[Dict[str, float]] = (
            {"stage": 0.0, "stage_lock": 0.0, "step": 0.0,
             "extract": 0.0, "collect": 0.0, "rounds": 0}
            if os.environ.get("ETCD_TPU_PROF") else None
        )

        # Telemetry plane (cfg.telemetry): the round returns an extra
        # frame; advance_round fetches it with the other host reads and
        # folds it into the attached hub (hosting layer sets one).
        self.telemetry_hub = None  # TelemetryHub, optional
        self.last_frame: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # Fleet observatory plane (cfg.fleet_summary): the round also
        # returns the flat SummaryFrame vector (obs/fleet.FleetLayout);
        # fetched with the round's other reads — no extra sync — and
        # folded into the attached FleetHub. Output position after
        # (state, outbox, aux[, telemetry]).
        self.fleet_hub = None  # obs.fleet.FleetHub, optional
        self.last_fleet: Optional[np.ndarray] = None
        self._fleet_idx = 3 + (1 if self.cfg.telemetry else 0)
        # Proposal-lifecycle tracer (etcd_tpu.obs.Tracer, optional —
        # hosting layer attaches one). Purely host-side: the device
        # program and protocol state are identical with it on or off;
        # the hot path pays one `is not None` per round when off.
        self.tracer = None

        # Device-resident apply plane (cfg.apply_plane, applyplane.py):
        # a SEPARATE jitted program folding each round's committed
        # entries into per-row KV/revision/watch/lease tensors —
        # dispatched right after committed-range extraction, where the
        # payload bytes are in hand. The round-step program is shared
        # with apply_plane=False by construction (make_step_round
        # strips the plane knobs from the compile key).
        self.plane = None
        if cfg.apply_plane:
            from .applyplane import init_plane, make_dispatch

            self.plane = init_plane(cfg, self.n)
            self._plane_step = make_dispatch(cfg, self.n)
            self._wkey_plane = f"apply_plane/{hash((cfg, self.n))}"
            # Watch events drained by the hosting layer (row, op,
            # key_hash, rev, wmask); bounded — watches are telemetry
            # consumers, and a stalled drain must not grow the heap.
            self.plane_events: deque = deque(maxlen=8192)
            # Host-accumulated plane stats (round thread writes, any
            # thread reads — GIL-atomic scalar swaps).
            self.plane_stats: Dict[str, int] = {
                "dispatches": 0, "puts": 0, "dels": 0, "expired": 0,
                "watch_events": 0, "slots_hw": 0, "overflow_rows": 0,
                "active_leases": 0,
            }
            # Staged plane edits from foreign threads, applied at the
            # head of advance_round ON the round thread (the staged-
            # edit idiom of _pending_conf): watch-slot arms and
            # snapshot-restored row images.
            self._pending_watch: Dict[Tuple[int, int], int] = {}
            self._pending_plane_rows: Dict[int, Tuple] = {}
            # Serializes the donated plane carry between the round
            # thread's dispatch and plane_capture's snapshot gather —
            # a gather racing a dispatch would read a donated (freed)
            # buffer.
            self._plane_mu = threading.Lock()
            # Host mirrors of the plane clock and the highest entry
            # index folded per row (round thread writes; any thread
            # reads — np scalar loads are GIL-atomic). The applied
            # watermark makes re-dispatch idempotent: a plane image
            # restored AHEAD of the host snapshot index (cadence
            # capture runs off the round thread's commit stream, which
            # leads the apply drain) must not double-fold the WAL tail
            # the host re-delivers on boot.
            self.m_plane_tick = np.zeros(self.n, np.int64)
            self.m_plane_applied = np.zeros(self.n, np.int64)
            # Exact host lessor mirror: (row, key bytes) -> absolute
            # plane-tick expiry, replayed from the same payload stream
            # at the same tick arithmetic as the device kernel. The
            # lease-read path masks host-tier bytes through it (the
            # device stores hashes only — byte honesty). Round thread
            # writes; readers do GIL-atomic gets.
            self.plane_lessor: Dict[Tuple[int, bytes], int] = {}

    # -- boot ------------------------------------------------------------------

    def _restore(self, restore: Dict[int, RowRestore]) -> None:
        """Rebuild device state from per-row WAL replay results."""
        cfg = self.cfg
        w = cfg.window
        term = np.zeros(self.n, np.int32)
        vote = np.zeros(self.n, np.int32)
        commit = np.zeros(self.n, np.int32)
        last = np.zeros(self.n, np.int32)
        snap_i = np.zeros(self.n, np.int32)
        snap_t = np.zeros(self.n, np.int32)
        ring = np.zeros((self.n, w), np.int32)
        fenced = np.zeros(self.n, bool)
        for row, rr in restore.items():
            fenced[row] = rr.fenced
            term[row] = rr.term
            vote[row] = rr.vote
            # A snapshot at snap_index proves snap_index was committed;
            # a stale persisted hardstate must not boot the row into
            # the illegal watermark order commit < snap_index.
            commit[row] = max(rr.commit, rr.snap_index)
            snap_i[row] = rr.snap_index
            snap_t[row] = rr.snap_term
            li = rr.snap_index
            for ent in rr.entries:
                idx, t, data = ent[0], ent[1], ent[2]
                ring[row, idx % w] = t
                self.arena[row][idx] = (t, data)
                if len(ent) > 3 and ent[3]:
                    self.etypes[row][idx] = int(ent[3])
                li = idx
            last[row] = li
            self.applied[row] = rr.applied
        st = self.state
        self.state = st._replace(
            term=self._dev(term),
            # vote is a narrow (int8) lane under cfg.narrow_lanes; keep
            # the restored field at the state's storage dtype so the
            # first round doesn't compile a second program.
            vote=self._dev(vote).astype(st.vote.dtype),
            commit=self._dev(commit),
            last=self._dev(last),
            snap_index=self._dev(snap_i),
            snap_term=self._dev(snap_t),
            log_term=self._dev(ring),
            fenced=self._dev(fenced),
            next=self._dev(
                np.repeat(last[:, None] + 1, cfg.num_replicas, axis=1)
            ),
        )
        self.m_term = term.astype(np.int64)
        self.m_vote = vote.astype(np.int64)
        self.m_commit = commit.astype(np.int64)
        self.m_last = last.astype(np.int64)
        self.m_snap = snap_i.astype(np.int64)
        self.m_ring = ring.astype(np.int64)
        self.stable = last.astype(np.int64)
        self._commit_guard = np.maximum(
            self._commit_guard, commit.astype(np.int64)
        )

    # -- staging ---------------------------------------------------------------

    def tick(self, rows: Optional[np.ndarray] = None) -> None:
        with self._lock:
            if rows is None:
                self._ticks += 1
            else:
                self._ticks[rows] += 1

    def campaign(self, rows) -> None:
        with self._lock:
            self._campaign[rows] = True

    def isolate(self, rows, on: bool = True) -> None:
        """Fault injection: cut rows off the network."""
        with self._lock:
            self._isolate[rows] = on

    def propose(self, row: int, data: bytes, etype: int = 0) -> None:
        """Queue a payload; it is appended (and assigned an index) in a
        round where this row is leader. `etype` tags non-Normal entries
        (conf changes) — the tag rides the host arena, never the
        device. Callers that need follower forwarding do it above this
        layer (see batched/node.py)."""
        # Enqueue timestamp rides the queue tuple only when tracing is
        # on (the span's propose stamp — sampling is decided later, at
        # index-assignment time, because the index IS the sample key).
        t_enq = 0 if self.tracer is None else time.monotonic_ns()
        with self._lock:
            self._props[row].append((data, int(etype), t_enq))

    def set_membership(self, row: int, voters, voters_out=(),
                       learners=(), joint: bool = False) -> None:
        """Upload new membership masks for one row — the confchange
        apply point (ref: confchange/confchange.go; the host computes
        slot sets, the device sees only masks).

        STAGED, not applied in place: callers run on apply/transport
        threads, and a read-modify-write of self.state here races the
        round thread's state swap in advance_round — the loser's
        update is silently lost (observed in the wild as a leader whose
        mask never admitted a new member, leaving the joiner dark
        forever). Masks are applied at the head of the next round, on
        the round thread, preserving the documented 'read by the next
        round' semantics."""
        r = self.cfg.num_replicas

        def mask(slots) -> np.ndarray:
            m = np.zeros((r,), bool)
            m[list(slots)] = True
            return m

        with self._lock:
            self._pending_conf[row] = (
                mask(voters), mask(voters_out), mask(learners),
                bool(joint),
            )

    def set_membership_many(self, rows, voter, voter_out, learner,
                            joint) -> None:
        """Bulk set_membership: stage mask planes for many rows under
        ONE lock acquisition — the conf-apply fast path when thousands
        of groups reconfigure in the same round (the hosting layer
        hands the GroupConfStore mask planes straight through). Same
        staged semantics: the device edit lands at the head of the next
        round on the round thread, as one vectorized ``.at[rows].set``.
        """
        rows = np.asarray(rows, np.int64)
        voter = np.asarray(voter, bool)
        voter_out = np.asarray(voter_out, bool)
        learner = np.asarray(learner, bool)
        joint = np.asarray(joint, bool)
        with self._lock:
            for i, row in enumerate(rows.tolist()):
                self._pending_conf[row] = (
                    voter[i], voter_out[i], learner[i], bool(joint[i]),
                )

    def transfer_leader(self, row: int, target_slot: int) -> None:
        """Stage a leadership handoff request on a leader row
        (ref: raft.go:1339 MsgTransferLeader; device _control phase)."""
        with self._lock:
            self._transfer[row] = target_slot + 1

    def read_index(self, row: int) -> None:
        """Stage a ReadIndex batch request on a leader row; the
        confirmed (seq, index) surfaces in BatchedReady.read_states
        (ref: raft.go:1078 MsgReadIndex → Ready.ReadStates)."""
        with self._lock:
            self._read_req[row] = True

    def set_fence(self, row: int, on: bool) -> None:
        """Stage a durability-fence flip for one row (hosting layer:
        lift when the durable log is back at the watermark, re-arm on
        a detected regression). STAGED like set_membership — the state
        edit lands at the head of the next round on the round thread,
        never racing the round's state swap."""
        with self._lock:
            self._pending_fence[row] = bool(on)

    def watch_set(self, row: int, wslot: int, key_hash: int) -> None:
        """Stage an exact-key watch into plane watch slot ``wslot`` of
        ``row`` (0 disarms). STAGED like set_fence: the device edit
        lands at the head of the next round on the round thread."""
        assert self.plane is not None, "apply plane is off"
        assert 0 <= wslot < self.cfg.apply_watch_slots
        with self._lock:
            self._pending_watch[(int(row), int(wslot))] = int(key_hash)

    def plane_restore_row(self, row: int, kv_key, kv_rev, kv_val,
                          kv_lease, rev: int, tick: int,
                          overflow: bool, applied: int = 0,
                          lessor=()) -> None:
        """Stage a full plane-row image (snapshot install / boot
        rebuild): fixed-width [C] i32 vectors + scalars, applied on the
        round thread before the next dispatch. ``applied`` is the
        highest entry index the image covers (dispatch skips at-or-
        below it); ``lessor`` is the row's (key bytes, expiry tick)
        mirror entries."""
        assert self.plane is not None, "apply plane is off"
        c = self.cfg.apply_capacity
        img = tuple(np.asarray(x, np.int32).reshape(c)
                    for x in (kv_key, kv_rev, kv_val, kv_lease))
        with self._lock:
            self._pending_plane_rows[int(row)] = img + (
                int(rev), int(tick), bool(overflow), int(applied),
                [(bytes(k), int(e)) for k, e in lessor])

    def drain_plane_events(self) -> List[Tuple[int, int, int, int, int]]:
        """Pop every pending (row, op, key_hash, rev, wmask) watch
        event (round thread appends; any thread drains — deque ops are
        GIL-atomic)."""
        if self.plane is None:
            return []
        evs = []
        try:
            while True:
                evs.append(self.plane_events.popleft())
        except IndexError:
            pass
        return evs

    def plane_capture(self, rows) -> List[Dict[str, object]]:
        """Snapshot-capture gather: ONE padded device gather for the
        whole build batch (hosting's _build_snapshots seam — the host
        dict walk does not survive large G). Returns one JSON-ready
        dict per requested row. Safe from any thread: _plane_mu
        excludes the dispatch that donates the plane carry."""
        assert self.plane is not None, "apply plane is off"
        from .applyplane import gather_rows

        rows = np.asarray(rows, np.int32).reshape(-1)
        m = len(rows)
        pad = np.zeros(max(m, 1), np.int32)
        pad[:m] = rows
        with self._plane_mu:
            g = gather_rows(self.plane, pad)
            jax.block_until_ready(g[0])
            parts = [np.asarray(x) for x in g]
            applied = self.m_plane_applied[rows].tolist()
            tick = self.m_plane_tick[rows].tolist()
            less = {int(r): [] for r in rows}
            for (r2, kb), exp in list(self.plane_lessor.items()):
                if r2 in less:
                    less[r2].append((kb, exp))
        kk, kr, kv, kl, rv, tk, ov = parts
        out = []
        for j, r in enumerate(rows.tolist()):
            out.append({
                "kv_key": kk[j].tolist(), "kv_rev": kr[j].tolist(),
                "kv_val": kv[j].tolist(), "kv_lease": kl[j].tolist(),
                "rev": int(rv[j]), "tick": int(tick[j]),
                "overflow": bool(ov[j]), "applied": int(applied[j]),
                "lessor": [[kb.hex(), int(e)] for kb, e in less[r]],
            })
        return out

    def pending_proposals(self, row: int) -> int:
        with self._lock:
            return len(self._props[row])

    def step(self, row: int, m: Message) -> None:
        """Stage an inbound wire message for `row`. MsgApp entry
        payloads go to the arena; MsgSnap app-state restore must already
        have happened (hosting layer) — here we stage the device-side
        ring restore."""
        t = int(m.type)
        lane = _LANE.get(t)
        if lane is None:
            raise ValueError(f"unroutable message type {m.type!r}")
        from_slot = m.from_ - 1
        if t == T_APP:
            with self._lock:
                ar = self.arena[row]
                et = self.etypes[row]
                for e in m.entries:
                    # Never clobber a committed entry's payload with a
                    # conflicting (necessarily stale) one — committed
                    # entries are immutable; only fill gaps there
                    # (post-snapshot resends).
                    if e.index > self._commit_guard[row] or e.index not in ar:
                        ar[e.index] = (e.term, e.data)
                        et.pop(e.index, None)
                        if int(e.type):
                            et[e.index] = int(e.type)
        if t == T_SNAP and m.index == 0:
            # Device ring-floor metadata normally rides in index/log_term
            # (the app snapshot in m.snapshot may sit at a HIGHER applied
            # index); fall back to the snapshot metadata when a caller
            # only filled the Snapshot (host-raft senders).
            m = Message(
                type=m.type, to=m.to, from_=m.from_, term=m.term,
                log_term=m.snapshot.metadata.term,
                index=m.snapshot.metadata.index,
            )
        with self._lock:
            self._pending.setdefault((row, from_slot, lane), deque()).append(m)

    def step_block(self, blk: MsgBlock) -> None:
        """Stage a batch of payload-free inbound messages (the SoA wire
        fast path — see msgblock.py). One lock acquisition per batch.

        Records are validated HERE, at ingest: row/frm/lane/type come
        straight off the wire, and a malformed record would otherwise
        crash the round loop (IndexError in _build_inbox) or scatter a
        forged message into another group's inbox slot via negative
        flat-index wraparound. Invalid records are dropped, matching
        the object path's corrupt-frame-drop semantics."""
        blk = validate_block(blk, self.n, self.cfg.num_replicas,
                             self.cfg.max_ents_per_msg)
        if len(blk) == 0:
            return
        with self._lock:
            self._blocks.append(blk)

    def install_snapshot_state(self, row: int, index: int,
                               applied_data_restored: bool = True) -> None:
        """Hosting layer notifies that app state for `row` was restored
        at `index` (from an inbound snapshot): advance the host applied
        watermark and drop arena entries at/below it."""
        with self._lock:
            if index > self.applied[row]:
                self.applied[row] = index
            ar = self.arena[row]
            for i in [i for i in ar if i <= index]:
                del ar[i]
                self.etypes[row].pop(i, None)

    def has_work(self) -> bool:
        with self._lock:
            if (
                self._pending or self._blocks or self._poked
                or self._pending_conf or self._pending_compact
                or self._pending_fence
                or (self.plane is not None
                    and (self._pending_watch
                         or self._pending_plane_rows))
                or self._ticks.any()
                or self._campaign.any()
                or self._transfer.any()
                or self._read_req.any()
            ):
                return True
            props = np.fromiter(
                (bool(q) for q in self._props), bool, count=self.n
            )
            return bool((props & (self.m_role == LEADER)).any())

    # -- the round -------------------------------------------------------------

    def advance_round(self) -> BatchedReady:
        assert self._round is None, "previous round not advanced"
        cfg = self.cfg
        r, e, w = cfg.num_replicas, cfg.max_ents_per_msg, cfg.window
        prof = self.prof
        tracer = self.tracer
        # Trace stamps use monotonic_ns (the tracer's clock domain, NOT
        # perf_counter): stage = staging begins, dispatch = device
        # round dispatched, extract = device done / host extraction.
        tr_stage = time.monotonic_ns() if tracer is not None else 0
        t0 = time.perf_counter()

        self._lock.acquire()
        if prof is not None:
            prof["stage_lock"] += time.perf_counter() - t0
        try:
            inbox = self._build_inbox()
            ticks = self._ticks > 0
            self._ticks = np.maximum(self._ticks - 1, 0)
            camp = self._campaign.copy()
            self._campaign[:] = False
            iso = self._isolate.copy()
            transfer = self._transfer.copy()
            self._transfer[:] = 0
            read_req = self._read_req.copy()
            self._read_req[:] = False
            poke_rows = (
                np.nonzero(self._poke_rows)[0] if self._poked else None
            )
            self._poke_rows[:] = False
            self._poked = False
            pend_conf = self._pending_conf
            self._pending_conf = {}
            pend_compact = self._pending_compact
            self._pending_compact = {}
            pend_fence = self._pending_fence
            self._pending_fence = {}
            pend_watch = pend_plane = None
            if self.plane is not None:
                pend_watch = self._pending_watch
                self._pending_watch = {}
                pend_plane = self._pending_plane_rows
                self._pending_plane_rows = {}
            props_n = np.fromiter(
                (min(len(q), cfg.max_props_per_round) for q in self._props),
                np.int32, count=self.n,
            )
        finally:
            self._lock.release()
        t1 = time.perf_counter()
        self.phase_last["stage"] = t1 - t0
        if prof is not None:
            prof["stage"] += t1 - t0
        t0 = t1

        # Host-staged device-state edits (membership masks, ring-floor
        # compaction, bcastAppend pokes), applied here on the round
        # thread — the only writer of self.state.
        conf_rows = None  # rows whose membership masks flip this round
        if pend_conf:
            st0 = self.state
            rows2 = np.fromiter(pend_conf, np.int32, len(pend_conf))
            vin = np.stack([pend_conf[r2][0] for r2 in rows2])
            vout = np.stack([pend_conf[r2][1] for r2 in rows2])
            lrn = np.stack([pend_conf[r2][2] for r2 in rows2])
            jnt = np.fromiter(
                (pend_conf[r2][3] for r2 in rows2), bool, len(rows2))
            ridx = jnp.asarray(rows2)
            self.state = st0._replace(
                voter=st0.voter.at[ridx].set(jnp.asarray(vin)),
                voter_out=st0.voter_out.at[ridx].set(jnp.asarray(vout)),
                learner=st0.learner.at[ridx].set(jnp.asarray(lrn)),
                in_joint=st0.in_joint.at[ridx].set(jnp.asarray(jnt)),
            )
            conf_rows = rows2
        if pend_fence:
            st0 = self.state
            rows2 = np.fromiter(pend_fence, np.int32, len(pend_fence))
            vals = np.fromiter((pend_fence[int(r2)] for r2 in rows2),
                               bool, len(rows2))
            self.state = st0._replace(
                fenced=st0.fenced.at[jnp.asarray(rows2)]
                .set(jnp.asarray(vals)),
            )
        if pend_compact:
            for row2, want in pend_compact.items():
                # No round in flight here (asserted above): the commit
                # watermark and floor mirrors are current.
                idx = int(min(want, int(self.m_commit[row2])))
                if idx <= int(self.m_snap[row2]):
                    continue
                t2 = int(self.latest_ring()[row2, idx % cfg.window])
                st0 = self.state
                self.state = st0._replace(
                    snap_index=st0.snap_index.at[row2].set(idx),
                    snap_term=st0.snap_term.at[row2].set(t2),
                )
                self.m_snap[row2] = max(self.m_snap[row2], idx)
        if poke_rows is not None and len(poke_rows):
            st0 = self.state
            self.state = st0._replace(
                send_append=st0.send_append.at[jnp.asarray(poke_rows)]
                .set(True)
            )
        # Staged plane edits (watch arms, snapshot-restored row
        # images) — the round thread is the only writer of self.plane,
        # same contract as self.state above.
        if pend_watch:
            keys = list(pend_watch)
            wr = jnp.asarray([k[0] for k in keys], jnp.int32)
            wc = jnp.asarray([k[1] for k in keys], jnp.int32)
            wv = jnp.asarray([pend_watch[k] for k in keys], jnp.int32)
            self.plane = self.plane._replace(
                watch_key=self.plane.watch_key.at[wr, wc].set(wv))
        if pend_plane:
            pl = self.plane
            rows2 = np.fromiter(pend_plane, np.int32, len(pend_plane))
            imgs = [pend_plane[int(r2)] for r2 in rows2]
            ridx = jnp.asarray(rows2)
            as_j = lambda i: jnp.asarray(  # noqa: E731
                np.stack([im[i] for im in imgs]))
            sc = lambda i, dt=np.int32: jnp.asarray(  # noqa: E731
                np.fromiter((im[i] for im in imgs), dt, len(imgs)))
            with self._plane_mu:
                self.plane = pl._replace(
                    kv_key=pl.kv_key.at[ridx].set(as_j(0)),
                    kv_rev=pl.kv_rev.at[ridx].set(as_j(1)),
                    kv_val=pl.kv_val.at[ridx].set(as_j(2)),
                    kv_lease=pl.kv_lease.at[ridx].set(as_j(3)),
                    rev=pl.rev.at[ridx].set(sc(4)),
                    tick=pl.tick.at[ridx].set(sc(5)),
                    overflow=pl.overflow.at[ridx].set(sc(6, bool)),
                )
                for r2 in rows2.tolist():
                    im = pend_plane[int(r2)]
                    self.m_plane_tick[r2] = im[5]
                    self.m_plane_applied[r2] = im[7]
                    # Lessor swap: drop every entry for the row, then
                    # install the image's (built as a list first — no
                    # structural iteration over a dict readers get()
                    # from).
                    stale = [k for k in self.plane_lessor
                             if k[0] == int(r2)]
                    for k in stale:
                        del self.plane_lessor[k]
                    for kb, exp in im[8]:
                        self.plane_lessor[(int(r2), kb)] = exp
        tr_dispatch = time.monotonic_ns() if tracer is not None else 0
        # Host->device staging happens OUTSIDE the transfer guard (it
        # is the intended, bulk transfer of the round); the guarded
        # region below is then pure warm device dispatch, where any
        # implicit transfer is a smuggled per-round sync and fails hard
        # under ETCD_TPU_TRANSFER_GUARD=disallow (analysis.sentinels).
        dev_in = (
            self._dev(ticks), self._dev(camp),
            self._dev(props_n), self._dev(iso),
            self._dev(transfer), self._dev(read_req),
        )
        with warm_guard(self._wkey_step):
            step_out = self._step(self.state, inbox, *dev_in)
            st, outbox, aux = step_out[:3]
            frame = step_out[3] if cfg.telemetry else None
            self.state = st
            # On-device outbox packing: a tiny second program turns the
            # [n, R, K] outbox fields into wire-width record words (rows
            # of msgblock.REC_DTYPE bytes) plus block/object masks, so
            # the host-side collect below is one view-cast + boolean
            # take instead of 14 fancy-indexed gathers.
            words_d, simple_d, cplx_d = pack_outbox(outbox, self._slots_j)

        # Device→host reads go through np.asarray, NOT jax.device_get:
        # this build's device_get pays a fixed ~4ms per buffer (measured
        # BENCH_NOTES r05 — 27 buffers made the round ~350ms, 100x the
        # 1.2ms step program), while np.asarray is a zero-copy view on
        # CPU and a plain single-buffer fetch elsewhere.
        jax.block_until_ready(st.term)
        (term, vote, commit, last, role, lead, snap_i, snap_t, ring,
         rd_seq, rd_idx, rd_ready,
         mid_seq, mid_idx, mid_ready, last_tick, lease_tk) = [
            np.asarray(x) for x in (
                st.term, st.vote, st.commit, st.last, st.role, st.lead,
                st.snap_index, st.snap_term, st.log_term,
                st.read_seq, st.read_index, st.read_ready,
                aux.read_seq, aux.read_index, aux.read_ready,
                aux.last_tick, st.lease_ticks,
            )
        ]
        words = np.asarray(words_d)
        simple = np.asarray(simple_d)
        cplx = np.asarray(cplx_d)
        if frame is not None:
            # Same host gather as the state reads above — the counters
            # were accumulated in-kernel; no extra sync happens here.
            tel_counters = np.asarray(frame.counters)
            tel_inv = np.asarray(frame.invariants)
            if conf_rows is not None and len(conf_rows):
                # Host-populated column (see telemetry.TM_NAMES): the
                # membership masks of these rows flipped at the head of
                # THIS round — count them where they were staged so the
                # flight recorder shows per-group conf applies in the
                # same frame stream as the device events.
                from .telemetry import TM_INDEX

                tel_counters = tel_counters.copy()
                tel_counters[np.asarray(conf_rows, np.int64),
                             TM_INDEX["conf_changes_applied"]] += 1
            self.last_frame = (tel_counters, tel_inv)
            if self.telemetry_hub is not None:
                from .telemetry import lane_summary

                self.telemetry_hub.ingest_round(
                    tel_counters, tel_inv,
                    extra={"outbox_lanes": lane_summary(
                        np.asarray(outbox.valid))})
        if cfg.fleet_summary:
            # Same host gather as the state reads above — the frame is
            # a round output already on device; no extra sync happens.
            fleet_vec = np.asarray(step_out[self._fleet_idx])
            self.last_fleet = fleet_vec
            if self.fleet_hub is not None:
                self.fleet_hub.ingest_round(fleet_vec)
        tr_extract = time.monotonic_ns() if tracer is not None else 0
        t1 = time.perf_counter()
        self.phase_last["step"] = t1 - t0
        if prof is not None:
            prof["step"] += t1 - t0
        t0 = t1

        term = term.astype(np.int64)
        vote = vote.astype(np.int64)
        commit = commit.astype(np.int64)
        last = last.astype(np.int64)
        ring64 = ring.astype(np.int64)

        # Everything below reads/writes the arena, so it runs under
        # _lock: inbound transport threads (step) must neither clobber
        # payloads mid-drain nor observe half-assigned proposals.
        with self._lock:
            # Freeze arena immutability at this round's commit BEFORE
            # reading payloads out (see step()'s _commit_guard check).
            self._commit_guard = np.maximum(self._commit_guard, commit)

            # -- proposals: pop exactly as many as the device appended
            # and assign their indexes (the propose phase spans
            # (last_tick, last]).
            for row in np.nonzero(last > last_tick)[0].tolist():
                q = self._props[row]
                n_app = int(last[row] - last_tick[row])
                base = int(last_tick[row])
                t_row = int(term[row])
                g_row = int(self.groups[row])
                ar = self.arena[row]
                ets = self.etypes[row]
                for j in range(n_app):
                    data, et, t_enq = q.popleft()
                    idx = base + 1 + j
                    ar[idx] = (t_row, data)
                    ets.pop(idx, None)
                    if et:
                        ets[idx] = et
                    if (tracer is not None and t_enq
                            and tracer.sampled(g_row, idx)):
                        # The origin stamp: index just got assigned, so
                        # the sampling decision exists only now; the
                        # stamp's time is the client enqueue instant.
                        tracer.stamp(g_row, t_row, idx, "propose", t_enq)

            # -- entry records to persist: per row the contiguous range
            # (lo-1, last] where lo is the first ring-changed index
            # this round (or stable+1) — range math fully vectorized,
            # Python only touches the actual entries (payload lookups).
            snap64 = snap_i.astype(np.int64)
            snap_rows = np.nonzero(snap64 > self.m_last)[0]
            # Device installed snapshots past our old log: ring floor
            # jumped. Record them; entries beyond follow.
            snapshots: List[Tuple[int, int, int]] = [
                (row, int(snap_i[row]), int(snap_t[row]))
                for row in snap_rows.tolist()
            ]
            restored = np.zeros(self.n, bool)
            restored[snap_rows] = True
            changed = ring64 != self.m_ring
            rows_changed = np.nonzero(
                changed.any(axis=1) | (last > self.stable) | restored
            )[0]
            entries = EntryBatch()
            if len(rows_changed):
                lastc = last[rows_changed]
                snapc = snap64[rows_changed]
                wgrid = np.arange(w, dtype=np.int64)
                # Log index currently held by ring slot p of each row.
                idxs = lastc[:, None] - ((lastc[:, None] - wgrid) % w)
                big = np.int64(1) << 62
                cand = np.where(
                    changed[rows_changed] & (idxs > snapc[:, None]),
                    idxs, big)
                lo = np.minimum(
                    self.stable[rows_changed] + 1, cand.min(axis=1))
                lo = np.maximum(lo, snapc + 1)
                cnt = np.maximum(lastc - lo + 1, 0)
                sel = cnt > 0
                if sel.any():
                    rows2 = rows_changed[sel]
                    cnt2 = cnt[sel]
                    eb_rows = np.repeat(rows2, cnt2)
                    eb_idx = ragged_ranges(lo[sel], cnt2)
                    eb_term = ring64[eb_rows, eb_idx % w]
                    datas: List[bytes] = []
                    etys: List[int] = []
                    for row, i, t in zip(eb_rows.tolist(),
                                         eb_idx.tolist(),
                                         eb_term.tolist()):
                        a = self.arena[row].get(i)
                        if a is not None and a[0] == t:
                            datas.append(a[1])
                            etys.append(self.etypes[row].get(i, 0))
                        else:
                            datas.append(b"")
                            etys.append(0)
                    entries = EntryBatch(
                        eb_rows, eb_idx, eb_term,
                        np.asarray(etys, np.int64), datas)

            # Sampled trace keys among this round's persisted entries
            # (leader appends and follower appends alike — both sides'
            # fragments come from the same extraction path): stamp the
            # round phases, hand the keys to the hosting layer for the
            # fsync/send stamps.
            traced_entries: List[Tuple[int, int, int]] = []
            if tracer is not None and len(entries):
                hits = np.nonzero(tracer.sampled_arr(
                    self.groups[entries.rows], entries.idx))[0]
                if len(hits):
                    traced_entries = list(zip(
                        self.groups[entries.rows[hits]].tolist(),
                        entries.term[hits].tolist(),
                        entries.idx[hits].tolist()))
                    tracer.stamp_many(traced_entries, "stage", tr_stage)
                    tracer.stamp_many(traced_entries, "dispatch",
                                      tr_dispatch)
                    tracer.stamp_many(traced_entries, "extract",
                                      tr_extract)

            # -- hardstate deltas
            hardstates = [
                (int(row), int(term[row]), int(vote[row]), int(commit[row]))
                for row in np.nonzero(
                    (term != self.m_term) | (vote != self.m_vote)
                    | (commit != self.m_commit)
                )[0]
            ]

            # -- committed ranges (applied, commit]
            committed: List[
                Tuple[int, List[Tuple[int, int, Optional[bytes]]]]
            ] = []
            traced_commit: List[Tuple[int, int, int]] = []
            com_rows = np.nonzero(commit > self.applied)[0]
            if len(com_rows):
                loc = np.maximum(self.applied[com_rows], snap64[com_rows])
                cntc = np.maximum(commit[com_rows] - loc, 0)
                selc = cntc > 0
                rows3 = com_rows[selc]
                cnt3 = cntc[selc]
                c_rows = np.repeat(rows3, cnt3)
                c_idx = ragged_ranges(loc[selc] + 1, cnt3)
                c_term = ring64[c_rows, c_idx % w]
                idx_l = c_idx.tolist()
                term_l = c_term.tolist()
                pos = 0
                for row, end in zip(rows3.tolist(),
                                    np.cumsum(cnt3).tolist()):
                    ar = self.arena[row]
                    ets = self.etypes[row]
                    items: List[Tuple[int, int, Optional[bytes], int]] = []
                    for k in range(pos, end):
                        i, t = idx_l[k], term_l[k]
                        a = ar.get(i)
                        ok = a is not None and a[0] == t
                        items.append((
                            i, t,
                            a[1] if ok and a[1] else None,
                            ets.get(i, 0) if ok else 0,
                        ))
                    pos = end
                    committed.append((row, items))
                if tracer is not None and len(c_idx):
                    hits = np.nonzero(tracer.sampled_arr(
                        self.groups[c_rows], c_idx))[0]
                    if len(hits):
                        traced_commit = list(zip(
                            self.groups[c_rows[hits]].tolist(),
                            c_term[hits].tolist(),
                            c_idx[hits].tolist()))
                        # Commit became observable at extraction time.
                        tracer.stamp_many(traced_commit, "commit",
                                          tr_extract)

            t1 = time.perf_counter()
            self.phase_last["extract"] = t1 - t0
            if prof is not None:
                prof["extract"] += t1 - t0
            t0 = t1

            # -- outbound messages (MsgApp payloads come from the arena)
            msg_block, messages = self._collect_messages(
                words, simple, cplx, outbox
            )
            t1 = time.perf_counter()
            self.phase_last["collect"] = t1 - t0
            if prof is not None:
                prof["collect"] += t1 - t0
                prof["rounds"] += 1

        must_sync = bool(
            entries
            or any(
                term[row] != self.m_term[row] or vote[row] != self.m_vote[row]
                for row, *_ in hardstates
            )
        )

        # Batches opened this round, then newly quorum-confirmed ones
        # (each surfaces exactly once; ref: read_only.go advance →
        # Ready.ReadStates).
        read_opened: List[Tuple[int, int]] = []
        for row in np.nonzero(rd_seq > self._read_seq_prev)[0]:
            read_opened.append((int(row), int(rd_seq[row])))
            self._read_seq_prev[row] = int(rd_seq[row])
        read_states: List[Tuple[int, int, int]] = []
        # Mid-round confirmations first (a latched reopen in _control
        # may have already replaced them in the end-of-round state).
        for row in np.nonzero(mid_ready & (mid_seq > self._read_seen))[0]:
            read_states.append(
                (int(row), int(mid_seq[row]), int(mid_idx[row])))
            self._read_seen[row] = int(mid_seq[row])
        newly = np.nonzero(rd_ready & (rd_seq > self._read_seen))[0]
        for row in newly:
            read_states.append(
                (int(row), int(rd_seq[row]), int(rd_idx[row])))
            self._read_seen[row] = int(rd_seq[row])

        # Apply-plane dispatch: fold this round's committed entries
        # (payload bytes in hand from the extraction above) and staged
        # ticks into the device KV/watch/lease tensors. After the lock:
        # it reads only local extraction results and self.plane, whose
        # single writer is this thread.
        if self.plane is not None and (committed or ticks.any()):
            self._plane_dispatch(committed, ticks)

        self._round = (term, vote, commit, last, role, lead,
                       snap_i.astype(np.int64), ring64,
                       lease_tk.astype(np.int64))
        snap_rings = {
            row: ring64[row].copy()
            for row, m in messages if int(m.type) == T_SNAP
        }
        return BatchedReady(
            hardstates=hardstates,
            entries=entries,
            snapshots=snapshots,
            committed=committed,
            messages=messages,
            must_sync=must_sync,
            msg_block=msg_block,
            read_states=read_states,
            read_opened=read_opened,
            snap_rings=snap_rings,
            traced_entries=traced_entries,
            traced_commit=traced_commit,
        )

    def advance(self) -> None:
        """Confirm the last Ready: host mirrors move to the new state
        (ref: rawnode.go:174-179 Advance)."""
        assert self._round is not None
        (term, vote, commit, last, role, lead, snap_i, ring64,
         lease_tk) = self._round
        with self._lock:
            # Under _lock: transport threads mutate self.applied via
            # install_snapshot_state, and read the mirrors.
            self.m_term, self.m_vote, self.m_commit = term, vote, commit
            self.m_last, self.m_role, self.m_lead = last, role, lead
            self.m_view = (term, role, lead)
            self.m_snap, self.m_ring = snap_i, ring64
            self.m_lease_ticks = lease_tk
            self.applied = np.maximum(self.applied, commit)
            self.stable = last.copy()
            # GC arena below the compaction floor.
            for row in range(self.n):
                fl = int(min(self.applied[row], snap_i[row]))
                ar = self.arena[row]
                if len(ar) > 2 * self.cfg.window:
                    for i in [i for i in ar if i <= fl]:
                        del ar[i]
                        self.etypes[row].pop(i, None)
            self._round = None

    # -- internals -------------------------------------------------------------

    def _plane_dispatch(self, committed, ticks: np.ndarray) -> None:
        """Fold one round's committed KV payloads + staged ticks into
        the device apply plane (round thread only). Rows committing
        more than A = cfg.apply_records entries redispatch the same
        compiled program with the next record chunk — shape-static by
        construction; the tick advance rides chunk 0 only."""
        from .applyplane import OP_PUT, fnv1a32, parse_payload

        cfg = self.cfg
        a, n = cfg.apply_records, self.n
        new_tick = self.m_plane_tick + ticks.astype(np.int64)
        recs: Dict[int, List[Tuple[int, int, int, int]]] = {}
        lessor = self.plane_lessor
        for row, items in committed:
            lst = []
            floor = int(self.m_plane_applied[row])
            top = floor
            for i, _t, d, et in items:
                if i <= floor:
                    # Already folded (a restored plane image can lead
                    # the host apply watermark; the boot replay and
                    # post-install tail re-deliver that span).
                    continue
                top = max(top, int(i))
                if et != 0 or not d:
                    # Conf entries and unknown payloads (arena holes)
                    # skip the KV tier — exactly the host loop's rule.
                    continue
                p = parse_payload(d)
                if p is None:
                    continue
                op, k, v, ttl = p
                lst.append((op, fnv1a32(k),
                            fnv1a32(v) if op == OP_PUT else 0,
                            ttl if op == OP_PUT else 0))
                # Lessor mirror: same record, same tick arithmetic as
                # the device kernel (chunk 0 advances the clock, so
                # every chunk applies at new_tick).
                if op == OP_PUT and ttl > 0:
                    lessor[(row, k)] = int(new_tick[row]) + ttl
                else:
                    lessor.pop((row, k), None)
            if top > floor:
                self.m_plane_applied[row] = top
            if lst:
                recs[row] = lst
        longest = max((len(v) for v in recs.values()), default=0)
        nchunks = max(1, -(-longest // a))
        stats = self.plane_stats
        self.m_plane_tick = new_tick
        frames = []
        with self._plane_mu:
            for ci in range(nchunks):
                ops = np.zeros((n, a), np.int32)
                keys = np.zeros((n, a), np.int32)
                vals = np.zeros((n, a), np.int32)
                ttls = np.zeros((n, a), np.int32)
                for row, lst in recs.items():
                    for j, (op, k, v, ttl) in enumerate(
                            lst[ci * a:(ci + 1) * a]):
                        ops[row, j] = op
                        keys[row, j] = k
                        vals[row, j] = v
                        ttls[row, j] = ttl
                ta = (ticks.astype(np.int32) if ci == 0
                      else np.zeros(n, np.int32))
                # Host→device staging outside the guard (the intended
                # bulk transfer); the guarded dispatch is pure warm
                # device work. Frame drain waits until AFTER the chunk
                # loop — one bulk sync per round, not one per chunk.
                din = tuple(jnp.asarray(x)
                            for x in (ops, keys, vals, ttls, ta))
                with warm_guard(self._wkey_plane):
                    self.plane, frame = self._plane_step(self.plane,
                                                         *din)
                frames.append(frame)
            jax.block_until_ready(self.plane.rev)
        got = jax.device_get(frames)
        for frame in got:
            stats["dispatches"] += 1
            stats["puts"] += int(frame.puts.sum())
            stats["dels"] += int(frame.dels.sum())
            stats["expired"] += int(frame.expired.sum())
            stats["slots_hw"] = max(
                stats["slots_hw"], int(frame.slots_used.max()))
            stats["overflow_rows"] = int(frame.overflow.sum())
            stats["active_leases"] = int(frame.leases.sum())
            hit = (frame.ev_op != 0) & (frame.ev_wmask != 0)
            rws, lanes = np.nonzero(hit)
            if len(rws):
                for r2, l2 in zip(rws.tolist(), lanes.tolist()):
                    self.plane_events.append((
                        int(r2), int(frame.ev_op[r2, l2]),
                        int(frame.ev_key[r2, l2]),
                        int(frame.ev_rev[r2, l2]),
                        int(frame.ev_wmask[r2, l2])))
                stats["watch_events"] += len(rws)

    # Residual block records are bounded: raft tolerates message loss,
    # so once the residual queue exceeds this many records per inbox
    # key on average, the OLDEST blocks are dropped (a key contested by
    # a busy object-path append stream would otherwise accumulate
    # residuals without bound — ADVICE r04).
    _RESIDUAL_RECORDS_PER_KEY = 4

    def _build_inbox(self):
        """Pop at most one pending message per (row, sender, lane) into
        a dense inbox. Caller holds _lock.

        Object-path messages are drained BEFORE queued blocks, so a
        block record can be overtaken by a later object-path message
        for the same (row, sender, lane). That cross-channel reordering
        is intentional — it mirrors the reference's two rafthttp
        channels, which give no cross-channel ordering either (ref:
        server/etcdserver/api/rafthttp/peer.go:337-349); raft tolerates
        reordering and loss on every link."""
        cfg = self.cfg
        r, e = cfg.num_replicas, cfg.max_ents_per_msg
        shape = (self.n, r, NUM_KINDS)
        valid = np.zeros(shape, bool)
        # Bounded lanes stage at their narrow storage dtypes under
        # cfg.narrow_lanes (step.NARROW_MSG_DTYPES: wire types < 32,
        # n_ents <= 255) so the staged inbox matches the dtype the
        # compiled round expects; the kernel widens at deliver entry.
        typ = np.zeros(shape,
                       np.int8 if cfg.narrow_lanes else np.int32)
        term = np.zeros(shape, np.int32)
        log_term = np.zeros(shape, np.int32)
        index = np.zeros(shape, np.int32)
        commit = np.zeros(shape, np.int32)
        reject = np.zeros(shape, bool)
        reject_hint = np.zeros(shape, np.int32)
        n_ents = np.zeros(shape,
                          np.int16 if cfg.narrow_lanes else np.int32)
        ctx = np.zeros(shape, np.int32)
        ent_terms = np.zeros(shape + (e,), np.int32)
        dead = []
        for key, q in self._pending.items():
            row, s, lane = key
            m: Message = q.popleft()
            if not q:
                dead.append(key)
            valid[row, s, lane] = True
            typ[row, s, lane] = int(m.type)
            term[row, s, lane] = m.term
            log_term[row, s, lane] = m.log_term
            index[row, s, lane] = m.index
            commit[row, s, lane] = m.commit
            reject[row, s, lane] = m.reject
            reject_hint[row, s, lane] = m.reject_hint
            n_ents[row, s, lane] = len(m.entries)
            if len(m.context) == 4:
                ctx[row, s, lane] = int.from_bytes(m.context, "little")
            for j, ent in enumerate(m.entries[:e]):
                ent_terms[row, s, lane, j] = ent.term
        for key in dead:
            del self._pending[key]
        if self._blocks:
            def land_entries(blk: MsgBlock, land: np.ndarray) -> None:
                # A block MsgApp's payloads enter the arena the moment
                # the record lands in the inbox — the block twin of
                # step()'s arena writes, same never-clobber-committed
                # rule (committed entries are immutable; only fill
                # gaps there, post-snapshot resends). One bulk call per
                # block: the arena slices come straight off the flat
                # entry arena (offset math, no per-entry parsing).
                rec = blk.rec
                rows_l = rec["row"][land].tolist()
                base_l = rec["index"][land].tolist()
                cnt = blk.ent_counts[land]
                # Gather ONLY the landed records' arena rows before the
                # Python conversion — a residual-heavy block re-merges
                # every round and must not pay for its deferred tail.
                eidx = ragged_ranges(blk._ent_starts()[land], cnt)
                term_l = blk.ent_term[eidx].tolist()
                ety_l = blk.ent_etype[eidx].tolist()
                len_l = blk.ent_len[eidx].tolist()
                ps_l = blk._pay_starts()[eidx].tolist()
                pay = blk.payload
                k = 0
                for row, base, c in zip(rows_l, base_l, cnt.tolist()):
                    ar = self.arena[row]
                    et = self.etypes[row]
                    guard = self._commit_guard[row]
                    for j in range(c):
                        i2 = base + 1 + j
                        if i2 > guard or i2 not in ar:
                            a = ps_l[k]
                            ar[i2] = (term_l[k], pay[a:a + len_l[k]])
                            et.pop(i2, None)
                            if ety_l[k]:
                                et[i2] = ety_l[k]
                        k += 1

            residual = merge_blocks(
                list(self._blocks), r, NUM_KINDS,
                {"valid": valid, "type": typ, "term": term,
                 "log_term": log_term, "index": index, "commit": commit,
                 "reject": reject, "reject_hint": reject_hint,
                 "ctx": ctx, "n_ents": n_ents, "ent_terms": ent_terms},
                land_entries=land_entries,
            )
            cap = self._RESIDUAL_RECORDS_PER_KEY * self.n * r * NUM_KINDS
            while len(residual) > 1 and sum(map(len, residual)) > cap:
                residual.pop(0)  # drop oldest whole block (loss is safe)
            self._blocks = deque(residual)
        inbox = MsgSlots(
            valid=self._dev(valid), type=self._dev(typ),
            term=self._dev(term), log_term=self._dev(log_term),
            index=self._dev(index), commit=self._dev(commit),
            reject=self._dev(reject), reject_hint=self._dev(reject_hint),
            n_ents=self._dev(n_ents), ctx=self._dev(ctx),
            ent_terms=self._dev(ent_terms),
        )
        return inbox

    def _collect_messages(self, words, simple, cplx, outbox):
        """Device-packed outbox → one SoA block for everything except
        MsgSnap (whose app-state payload the hosting layer attaches at
        send time). The record array is a view-cast of the packed word
        tensor (step.pack_outbox) compressed by the block mask; MsgApp
        entry payloads ride the block's flat arena, re-attached from
        the host arena with one ragged gather for the terms and one
        payload join."""
        e = self.cfg.max_ents_per_msg
        rec = compact_records(words, simple)
        block = MsgBlock(rec)
        napp = rec["n_ents"]
        app_sel = np.nonzero(napp)[0]
        if len(app_sel):
            counts = napp[app_sel].astype(np.int64)
            # Flat outbox slot of each entry-carrying record (for the
            # [M, E] ent_terms gather) and its per-entry offsets.
            flat_pos = np.nonzero(simple)[0][app_sel]
            offs = ragged_ranges(np.zeros(len(app_sel), np.int64),
                                 counts)
            etf = np.asarray(outbox.ent_terms).reshape(-1, e)
            terms = etf[np.repeat(flat_pos, counts), offs]
            idx_flat = (np.repeat(rec["index"][app_sel].astype(np.int64),
                                  counts) + 1 + offs)
            rows_rep = np.repeat(rec["row"][app_sel].astype(np.int64),
                                 counts)
            datas: List[bytes] = []
            etys = np.zeros(len(idx_flat), "<u1")
            k = 0
            for row, idx, et in zip(rows_rep.tolist(),
                                    idx_flat.tolist(), terms.tolist()):
                a = self.arena[row].get(idx)
                if a is not None and a[0] == et:
                    datas.append(a[1])
                    ety = self.etypes[row].get(idx, 0)
                    if ety:
                        etys[k] = ety
                else:
                    datas.append(b"")
                k += 1
            block = MsgBlock(
                rec, ent_term=terms.astype("<u4"), ent_etype=etys,
                ent_len=np.fromiter(map(len, datas), np.uint32,
                                    len(datas)),
                payload=b"".join(datas))
        msgs: List[Tuple[int, Message]] = []
        if cplx.any():
            # MsgSnap only (rare): materialize just the needed fields
            # for just these flat slots.
            p = np.nonzero(cplx)[0]
            fld = lambda name: (  # noqa: E731
                np.asarray(getattr(outbox, name)).reshape(-1)[p].tolist())
            k6 = NUM_KINDS
            r = self.cfg.num_replicas
            rows_c = (p // (r * k6)).tolist()
            tgts_c = ((p % (r * k6)) // k6).tolist()
            typs, terms_c, lts, idxs, cms, rejs, hints, ctxs = (
                fld("type"), fld("term"), fld("log_term"), fld("index"),
                fld("commit"), fld("reject"), fld("reject_hint"),
                fld("ctx"))
            for j, row in enumerate(rows_c):
                t = int(typs[j])
                m = Message(
                    type=MessageType(t),
                    to=tgts_c[j] + 1,
                    from_=int(self.slots[row]) + 1,
                    term=terms_c[j],
                    log_term=lts[j],
                    index=idxs[j],
                    commit=cms[j],
                    reject=bool(rejs[j]),
                    reject_hint=hints[j],
                )
                cw = ctxs[j]
                if cw:
                    # The device ctx word travels as 4 context bytes
                    # (the reference's Message.Context).
                    m.context = int(cw).to_bytes(4, "little")
                if t == T_SNAP:
                    # metadata only; the hosting layer attaches app
                    # data (at its applied watermark ≥ this floor)
                    # before the wire (see hosting.py / node.py).
                    m.snapshot = Snapshot(
                        metadata=SnapshotMetadata(
                            index=idxs[j], term=lts[j],
                        )
                    )
                msgs.append((row, m))
        return block, msgs

    # -- introspection ---------------------------------------------------------

    def peer_match(self) -> np.ndarray:
        """Leader-side [n, R] match snapshot — the promote catch-up
        gate's input (server.go:1446 isLearnerReady reads the same
        progress view). A plain np.asarray of the live device buffer:
        zero-copy on CPU, one bulk fetch elsewhere; called at admin
        cadence, never on the round hot path. Rows this process does
        not lead carry reset-stale values — callers gate on leadership
        first."""
        return np.asarray(self.state.match)

    def latest_ring(self) -> np.ndarray:
        """The newest known [n, W] term ring (in-flight round if any)."""
        return self._round[7] if self._round is not None else self.m_ring

    def latest_commit(self, row: int) -> int:
        arr = self._round[2] if self._round is not None else self.m_commit
        return int(arr[row])

    def compact(self, row: int, index: int) -> None:
        """Move the device ring floor to `index` (host took an app
        snapshot there). STAGED like set_membership: the state edit
        happens at the head of the next round on the round thread (an
        in-place edit here would race the round's state swap). The
        floor only rises; the clamp to the committed watermark and the
        ring-term read happen at apply time, against that round's
        state."""
        with self._lock:
            self._pending_compact[row] = max(
                self._pending_compact.get(row, 0), int(index))

    def poke_append(self, row: int) -> None:
        """Stage an immediate append/probe to every replication target
        of `row` — the device twin of the leader's bcastAppend on a
        config change (ref: raft.go switchToConfig → maybeSendAppend):
        a newly admitted member must be contacted now, not at the next
        heartbeat timeout. Staged host-side and applied to device state
        at the head of the next advance_round (on the round thread), so
        callers on other threads never race the round's state swap."""
        with self._lock:
            self._poke_rows[row] = True
            self._poked = True

    def leader_rows(self) -> np.ndarray:
        return np.nonzero(self.m_role == LEADER)[0]

    def is_leader(self, row: int) -> bool:
        return self.m_role[row] == LEADER

    def lead(self, row: int) -> int:
        """Leader member id (slot+1) as known by `row`, 0 if unknown."""
        return int(self.m_lead[row])
