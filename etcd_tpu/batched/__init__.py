"""Batched multi-Raft TPU engine — the north star (BASELINE.json).

Thousands to a million independent Raft groups are packed into
structure-of-arrays tensors and stepped in lockstep by one jitted XLA
program:

- ``state``:   per-replica-instance SoA state ``[N, ...]`` where
               ``N = groups × replicas`` (instance ``i`` is replica
               ``i % R`` of group ``i // R``); log tails are ``[N, W]``
               term rings; leader progress is ``[N, R]``.
- ``kernels``: the replica-axis reductions (quorum commit index as an
               order statistic, vote tallies as masked sums) and log-ring
               primitives, differentially tested against the scalar
               oracles in ``etcd_tpu.raft``.
- ``step``:    the vmapped, branch-free message handlers (ref:
               raft/raft.go stepLeader/stepFollower/stepCandidate) +
               tick/propose/emit phases and the all-device message router
               (a transpose over the dense (group, replica) layout).
- ``engine``:  the closed-loop MultiRaftEngine (bench/simulation: the
               whole network round-trips on device).
- ``rawnode``: BatchedRawNode — the production Ready contract (persist →
               apply → send → advance) with the host payload arena.
- ``node``:    BatchedNode — the raft.Node plugin boundary served by the
               device engine (the ``raft-backend=tpu`` construction path).
- ``hosting``: MultiRaftMember/MultiRaftCluster — G groups × R members
               served end-to-end (native WAL, per-group KV apply).
- ``faults``:  seeded chaos plane for the hosting path — per-link
               drop/dup/delay/reorder/partition, storage-failpoint
               crashes, torn-tail WAL injection, kill/restart cycles
               (the functional tester's fault matrix, batched).
- ``telemetry``: the device→host observability plane — kernel event
               counters + on-device invariant bitmap behind
               ``BatchedConfig.telemetry``, folded into the shared
               ``pkg.metrics`` registry by ``TelemetryHub`` with a
               bounded flight recorder (``artifacts/flightrec_*.json``).
"""

from .state import BatchedConfig, BatchedState, init_state  # noqa: F401
from .step import make_step_round  # noqa: F401
from .engine import MultiRaftEngine  # noqa: F401
from .rawnode import BatchedRawNode, BatchedReady, RowRestore  # noqa: F401
from .node import BatchedNode  # noqa: F401
from .hosting import MultiRaftCluster, MultiRaftMember  # noqa: F401
from .faults import (  # noqa: F401
    ChaosHarness,
    FaultPlan,
    FaultSpec,
    FaultyFabric,
    LeaderObserver,
)
from .telemetry import (  # noqa: F401
    INV_NAMES,
    TM_INDEX,
    TM_NAMES,
    TelemetryHub,
)
