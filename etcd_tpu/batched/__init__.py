"""Batched multi-Raft TPU engine — the north star (BASELINE.json).

Thousands to a million independent Raft groups are packed into
structure-of-arrays tensors and stepped in lockstep by one jitted XLA
program:

- ``state``:   per-replica-instance SoA state ``[N, ...]`` where
               ``N = groups × replicas`` (instance ``i`` is replica
               ``i % R`` of group ``i // R``); log tails are ``[N, W]``
               term rings; leader progress is ``[N, R]``.
- ``kernels``: the replica-axis reductions (quorum commit index as an
               order statistic, vote tallies as masked sums) and log-ring
               primitives, differentially tested against the scalar
               oracles in ``etcd_tpu.raft``.
- ``step``:    the vmapped, branch-free message handlers (ref:
               raft/raft.go stepLeader/stepFollower/stepCandidate) +
               tick/propose/emit phases and the all-device message router
               (a transpose over the dense (group, replica) layout).
- ``engine``:  the host-facing MultiRaftEngine with the
               HasReady → Ready → persist → send → Advance contract of
               ``raft.RawNode``, batched over all groups.
"""

from .state import BatchedConfig, BatchedState, init_state  # noqa: F401
from .step import make_step_round  # noqa: F401
from .engine import MultiRaftEngine  # noqa: F401
