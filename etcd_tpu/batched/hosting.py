"""Multi-raft hosting: G consensus groups served by R member processes,
each member stepping its replica slot of EVERY group in one device
program per round.

This is the scale-out shape the reference's raft library was designed
for but never shipped a host for ("systems which have thousands of Raft
groups per process", ref: raft/tracker/inflights.go:71-73): a
``MultiRaftMember`` owns

* a ``BatchedRawNode`` over rows = G groups (slot = this member),
* ONE write-ahead log for all groups (the native C++ segmented WAL,
  records framed with a group id; one fsync covers every group's
  hardstate+entries for the round — the batched analog of wal.Save,
  ref: server/storage/wal/wal.go:920-953),
* a per-group KV apply target (the 1k-shard KV service),
* a round loop enforcing the reference's ordering per group:
  persist (fsync) → apply → send → advance
  (ref: server/etcdserver/raft.go:226-268; apply-before-send lets
  outbound snapshot messages carry app state at an index ≥ the device
  ring floor),
* a per-group **durable watermark** WAL-recorded ahead of every entry
  batch, so ``_replay`` can detect destroyed fsync'd-acked bytes (torn
  tails beyond raft's durability model) and boot the damaged groups
  **fenced** — out of elections until the probe/snapshot catch-up
  restores the durable log ("Protocol-Aware Recovery for
  Consensus-Based Storage", FAST'18),
* an optional **async group-commit WAL pipeline** (``wal_pipeline``,
  ISSUE 13): persistence runs on a dedicated WAL-commit worker instead
  of inline in the Ready drain. Producers append pre-serialized record
  batches to an open double buffer and continue into the next device
  round immediately; the worker swaps the buffer, writes it, runs ONE
  fsync covering every batch queued since the last one (bounded by a
  max-delay / max-bytes accumulation window), and only then releases
  the covered batches' acks, sends and applies — persist-before-
  ack/send preserved by the ordered release barrier, never by timing
  (the decoupling the reference's asynchronous-storage-writes design
  permits: raft only requires persist before ack/send, not before the
  next round).

Members exchange per-round message batches. ``InProcRouter`` wires
members in one process (tests, single-host demos); the TCP fabric for
real deployments reuses the same ``deliver()`` entry point.
"""

from __future__ import annotations

import json
import logging
import os
import queue as queue_mod
import random
import struct
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..native.walog import (
    TAIL_CLEAN,
    TAIL_CORRUPT,
    TAIL_NAMES,
    Walog,
    WalogError,
    is_disk_full,
    read_all_classified as wal_read_all_classified,
    salvage as wal_salvage,
)
from ..obs.tracer import make_tracer
from ..pkg.failpoint import FailpointPanic, fp
from ..raft.confchange import ConfChangeError
from ..storage.snap import NoSnapshotError, Snapshotter
from ..raft.types import (
    ConfChangeSingle,
    ConfChangeTransition,
    ConfChangeType,
    ConfChangeV2,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
)
from .membership import GroupConfStore, decode_conf_entry
from .rawnode import BatchedRawNode, BatchedReady, RowRestore
from .state import BatchedConfig, LEADER
from .step import T_SNAP
from .telemetry import (
    TelemetryHub,
    disk_fault_failstop_counter,
    disk_fault_salvage_counter,
    disk_full_gauge,
    fenced_groups_gauge,
    joint_groups_gauge,
    learner_slots_gauge,
    round_phase_histogram,
    router_loss_counter,
    wal_fsync_histogram,
)


from ..pkg.errors import NotLeaderError  # noqa: E402 — shared error type

_log = logging.getLogger("etcd_tpu.batched.hosting")

# WAL record types (the native walog carries opaque frames; these tags
# make one log serve every group — ref: walpb's entry/state/snapshot
# record types, server/storage/wal/walpb/record.pb.go).
RT_ENTRY = 1  # group:u32 index:u64 term:u64 len:u32 data
RT_HARDSTATE = 2  # group:u32 term:u64 vote:u32 commit:u64
RT_SNAPSHOT = 3  # same layout as RT_ENTRY; data = app snapshot
# Durable watermark (protocol-aware torn-tail recovery, FAST'18): the
# per-group (last_index, last_term, commit) this member is about to
# make durable. Written FIRST in every persistence batch that appends
# entries, fsync'd with the batch — so a tail cut that destroys the
# batch's fsync'd entry records leaves their watermark behind, and
# _replay can tell "acked bytes lost" (fence the group) from "crash
# before the write" (nothing to do).
RT_WATERMARK = 4  # group:u32 last:u64 last_term:u64 commit:u64
# One record for a whole Ready's entries, numpy-serialized:
# u32 count | count * WAL_ENT_DTYPE headers | payloads back to back.
# Replaces per-entry RT_ENTRY records on the write path (RT_ENTRY still
# replays for logs written before the batch format); the watermark
# ordering contract is unchanged — a tear anywhere inside the batch
# record destroys it wholesale, and the preceding RT_WATERMARK records
# still demand every entry it carried.
RT_ENTRY_BATCH = 5
# Batched twins of RT_HARDSTATE / RT_WATERMARK, numpy-serialized:
# u32 count | count * WAL_*_DTYPE rows. A steady round writes hundreds
# of hardstate/watermark records per member; one structured-array
# tobytes replaces that many struct.pack + ctypes append calls.
RT_HS_BATCH = 6
RT_WM_BATCH = 7
# Per-group membership configs, numpy-serialized full-state rows
# (membership.GroupConfStore.pack_groups): written whenever committed
# conf-change entries flip a group's config and at inbound-snapshot
# conf restores, so _replay reconstructs config state without
# re-reading the whole log — latest record per group wins, and conf
# entries ABOVE the recorded watermark (committed but crashed before
# the record landed) re-apply from the recovered entries themselves.
RT_CONF_BATCH = 8
# File-snapshot markers (log-lifecycle plane): rows of (group, index,
# term) naming a snapshot FILE (storage/snap.Snapshotter, per-group
# dirs under member-N/snap/) this member made durable — the etcd
# architecture, where snapshot data lives in files and the WAL carries
# only the marker (ref: walpb.Snapshot records). A marker is trusted
# only once its covering fsync lands (the file itself is fsync'd
# BEFORE the marker is appended), and _replay loads the newest file
# matching a durable marker when the full RT_SNAPSHOT record has been
# rotated out of the WAL. Also re-recorded wholesale in every
# rotation checkpoint, so release never strands a group's only
# snapshot evidence in a reclaimed segment.
RT_SNAPMARK = 9

# Per-entry header inside an RT_ENTRY_BATCH record (packed, 25 bytes —
# the same fields as RT_ENTRY's "<IQQBI" header, SoA-serializable).
WAL_ENT_DTYPE = np.dtype([
    ("group", "<u4"), ("index", "<u8"), ("term", "<u8"),
    ("etype", "<u1"), ("len", "<u4"),
])
# Rows of RT_HS_BATCH / RT_WM_BATCH (field-compatible with the single
# records' "<IQIQ" / "<IQQQ" layouts).
WAL_HS_DTYPE = np.dtype([
    ("group", "<u4"), ("term", "<u8"), ("vote", "<u4"),
    ("commit", "<u8"),
])
WAL_WM_DTYPE = np.dtype([
    ("group", "<u4"), ("last", "<u8"), ("last_term", "<u8"),
    ("commit", "<u8"),
])
# Rows of RT_SNAPMARK (file-snapshot markers).
WAL_SNAPMARK_DTYPE = np.dtype([
    ("group", "<u4"), ("index", "<u8"), ("term", "<u8"),
])


def _pack_entry(group: int, index: int, term: int, data: bytes,
                etype: int = 0) -> bytes:
    return struct.pack("<IQQBI", group, index, term, etype, len(data)) + data


def _unpack_entry(b: bytes) -> Tuple[int, int, int, bytes, int]:
    g, i, t, et, ln = struct.unpack_from("<IQQBI", b)
    off = struct.calcsize("<IQQBI")
    return g, i, t, b[off:off + ln], et


def _pack_rows(dtype: np.dtype, cols: Dict[str, object]) -> bytes:
    """Count-prefixed structured rows — the one serializer behind every
    RT_*_BATCH record (the replay side is _unpack_batch)."""
    n = len(next(iter(cols.values())))
    rec = np.empty(n, dtype)
    for f, v in cols.items():
        rec[f] = v
    return struct.pack("<I", n) + rec.tobytes()


def _pack_entry_batch(eb) -> bytes:
    """Serialize an EntryBatch as one WAL record: one numpy header
    array + one payload join — no per-entry struct.pack."""
    hdr = _pack_rows(WAL_ENT_DTYPE, {
        "group": eb.rows, "index": eb.idx, "term": eb.term,
        "etype": eb.etype,
        "len": np.fromiter(map(len, eb.datas), np.uint32, len(eb.datas)),
    })
    return hdr + b"".join(eb.datas)


def _iter_entry_batch(b: bytes):
    """Yield (group, index, term, data, etype) from an RT_ENTRY_BATCH
    record (replay path)."""
    (n,) = struct.unpack_from("<I", b)
    hdr = np.frombuffer(b, WAL_ENT_DTYPE, count=n, offset=4)
    off = 4 + n * WAL_ENT_DTYPE.itemsize
    lens = hdr["len"].tolist()
    for g, i, t, et, ln in zip(hdr["group"].tolist(),
                               hdr["index"].tolist(),
                               hdr["term"].tolist(),
                               hdr["etype"].tolist(), lens):
        yield g, i, t, b[off:off + ln], et
        off += ln


def _unpack_batch(b: bytes, dtype: np.dtype) -> np.ndarray:
    """Header-counted structured rows of an RT_HS_BATCH / RT_WM_BATCH
    record."""
    (n,) = struct.unpack_from("<I", b)
    return np.frombuffer(b, dtype, count=n, offset=4)


def _pack_hs(group: int, term: int, vote: int, commit: int) -> bytes:
    return struct.pack("<IQIQ", group, term, vote, commit)


def _unpack_hs(b: bytes) -> Tuple[int, int, int, int]:
    return struct.unpack_from("<IQIQ", b)


def _pack_snap(group: int, index: int, term: int, data: bytes) -> bytes:
    # Same layout as entries (etype byte unused for snapshots).
    return _pack_entry(group, index, term, data)


_unpack_snap = _unpack_entry


def _env_wal_pipeline() -> bool:
    """ETCD_TPU_WAL_PIPELINE: default for members constructed with
    wal_pipeline=None (the hosted_bench / hosting_proc env knob)."""
    from ..pkg import env_flag

    return env_flag("ETCD_TPU_WAL_PIPELINE")


# Group-commit accumulation defaults (overridable per member and via
# env): after the first pending batch the WAL-commit worker waits up to
# max_delay for more rounds' batches to queue (one fsync then covers
# them all), cutting the wait short once max_bytes are pending. 0 delay
# means fsync as soon as the worker gets the buffer — batching then
# comes only from rounds that queue WHILE an fsync is in flight, which
# on a real disk (fsync >> round) is already most of the win.
# KNOB HAZARD: every outbound message — vote responses included —
# rides the release barrier (raft requires the vote/hardstate durable
# before the grant leaves), so a max_delay rivaling the election
# timeout (election_timeout ticks x tick_interval) delays vote acks
# past it and starves elections. Keep max_delay well under a quarter
# of the timeout.
WAL_GROUP_MAX_DELAY_S = 0.0
WAL_GROUP_MAX_BYTES = 4 << 20

# Log-lifecycle plane defaults (member args, like the pipeline knobs —
# never BatchedConfig fields: host-only, must not fork a compile).
# snap_cadence / wal_rotate_bytes default to None = OFF, preserving
# pre-lifecycle behavior for every existing caller.
SNAP_KEEP_DEFAULT = 2        # snapshot files retained per group
WAL_LIFECYCLE_TICK_S = 0.05  # commit-worker idle lifecycle cadence
SNAP_BUILD_MAX_PER_PASS = 64  # due-group snapshot builds per drain
# pass — bounds the work a single pass steals from the round loop (the
# most-overdue groups go first; the rest catch the next pass)
WAL_PINNED_SEGMENTS = 4      # sealed-but-unreleasable segments before
# the counted wal_pinned anomaly fires (a stuck group must become
# protocol-visible instead of silently pinning disk)


class _PersistGroup:
    """One submitted persistence batch riding the WAL pipeline: the
    pre-serialized records (built under _lock, so record order ==
    submission order == lock order), the Readys whose acks/sends/apply
    it gates, the per-row durable-watermark deltas to fold into the
    mirrors once the covering fsync lands, and the snapshot-install
    generations captured at submit (a MsgSnap restore racing ahead of
    this batch's fsync supersedes its mirror deltas — see
    _apply_wm_locked)."""

    __slots__ = ("records", "readys", "wm", "gens", "must_sync",
                 "nbytes", "t_submit", "on_synced", "traced")

    def __init__(self, records, readys, wm, gens, must_sync,
                 on_synced=None, traced=()):
        self.records = records
        self.readys = readys
        self.wm = wm
        self.gens = gens
        self.must_sync = must_sync
        self.nbytes = sum(len(d) for _rt, d in records)
        self.t_submit = time.monotonic()
        self.on_synced = on_synced
        self.traced = traced


def _pack_wm(group: int, last: int, last_term: int, commit: int) -> bytes:
    return struct.pack("<IQQQ", group, last, last_term, commit)


def _unpack_wm(b: bytes) -> Tuple[int, int, int, int]:
    return struct.unpack_from("<IQQQ", b)


class GroupKV:
    """The applied state machine of one group: a KV map fed committed
    payloads ``op key \\x00 value`` (ref: contrib/raftexample/kvstore.go
    gob-encoded kv pairs; here a flat length-prefixed frame)."""

    def __init__(self) -> None:
        self.data: Dict[bytes, bytes] = {}

    def apply(self, payload: bytes) -> None:
        op, rest = payload[:1], payload[1:]
        if op == b"P":
            k, v = rest.split(b"\x00", 1)
            self.data[k] = v
        elif op == b"E":
            # Expiring put (apply-plane lease form, applyplane.py:
            # u32be TTL then the P layout). The host tier stores the
            # bytes and ignores the TTL — expiry visibility is
            # leader-local (the device lessor masks lease reads, ref:
            # etcd's leader-driven lessor), so the replicated byte
            # state stays identical across members with or without
            # the plane.
            k, v = rest[4:].split(b"\x00", 1)
            self.data[k] = v
        elif op == b"D":
            self.data.pop(rest, None)

    def snapshot(self) -> bytes:
        return json.dumps(
            {k.hex(): v.hex() for k, v in self.data.items()}
        ).encode()

    def restore(self, blob: bytes) -> None:
        self.data = {
            bytes.fromhex(k): bytes.fromhex(v)
            for k, v in json.loads(blob.decode()).items()
        } if blob else {}

    @staticmethod
    def put_payload(key: bytes, value: bytes) -> bytes:
        return b"P" + key + b"\x00" + value

    @staticmethod
    def delete_payload(key: bytes) -> bytes:
        return b"D" + key


def _split_snap_blob(blob: bytes):
    """Decode a snapshot app blob in either on-disk/wire format: the
    legacy host-tier dump (a flat hex dict) or the two-tier apply-plane
    wrapper ({"host": ..., "plane": ...}). Returns (host key->value
    dict, plane image dict or None)."""
    if not blob:
        return {}, None
    d = json.loads(blob.decode())
    img = None
    if "host" in d and "plane" in d:
        img = d["plane"]
        d = d["host"]
    return {
        bytes.fromhex(k): bytes.fromhex(v) for k, v in d.items()
    }, img


class MultiRaftMember:
    """One member process: slot `member_id-1` of every group."""

    def __init__(
        self,
        member_id: int,
        num_members: int,
        num_groups: int,
        data_dir: str,
        cfg: Optional[BatchedConfig] = None,
        tick_interval: float = 0.02,
        send_fn: Optional[Callable[[int, List[Tuple[int, Message]]], None]] = None,
        pipeline: bool = True,
        mesh_devices: int = 0,
        fence: bool = True,
        trace: Optional[bool] = None,
        wal_pipeline: Optional[bool] = None,
        wal_group_max_delay: Optional[float] = None,
        wal_group_max_bytes: Optional[int] = None,
        disk_fault_hook: Optional[Callable[[str, int], None]] = None,
        snap_cadence: Optional[int] = None,
        snap_keep: int = SNAP_KEEP_DEFAULT,
        wal_rotate_bytes: Optional[int] = None,
        wal_pinned_segments: int = WAL_PINNED_SEGMENTS,
    ) -> None:
        self.id = member_id
        self.slot = member_id - 1
        self.g = num_groups
        self.cfg = cfg or BatchedConfig(
            num_groups=num_groups,
            num_replicas=num_members,
            window=64,
            max_ents_per_msg=8,
            max_props_per_round=4,
            election_timeout=10,
            heartbeat_timeout=1,
            pre_vote=True,
            check_quorum=True,
            auto_compact=True,  # floor chases applied; snapshots are
            # generated on demand at send time (apply-before-send keeps
            # host state ≥ floor)
        )
        assert self.cfg.num_groups == num_groups
        self.dir = os.path.join(data_dir, f"member-{member_id}")
        os.makedirs(self.dir, exist_ok=True)
        self.kvs = [GroupKV() for _ in range(num_groups)]
        self.applied_index = np.zeros(num_groups, np.int64)
        # Device apply plane (ISSUE 19): with cfg.apply_plane the host
        # KV above becomes the shadow/overflow BYTE tier (the device
        # stores 31-bit key/value hashes + revision/lease lanes);
        # linearizable reads route lease-first (linearizable_get) and
        # snapshot capture gathers the device tensors. _boot_plane
        # stashes per-group plane images decoded during _replay (the
        # rawnode does not exist yet there) for post-boot staging.
        self._boot_plane: Dict[int, Dict] = {}
        # Groups with a leadership transfer staged on this member:
        # lease reads refuse until the device round zeroes the lease
        # lane (MsgTimeoutNow lets the target campaign without waiting
        # an election timeout, so the tick-silence safety argument
        # does not cover the staging window).
        self._lease_block: set = set()
        self._watch_next = np.zeros(num_groups, np.int64)
        self._watches: Dict[Tuple[int, int], bytes] = {}
        self._send = send_fn  # set by the router/transport
        # Block fast path (SoA frames, see msgblock.py); routers that
        # support it set this, others get the object fallback.
        self._send_block: Optional[Callable[[int, "object"], None]] = None
        self._lock = threading.Lock()
        self._work = threading.Event()  # wakes the round loop
        # Simulated-kill flag (see crash()): once set (under _lock) the
        # WAL handle is closed and every persistence/apply path bails
        # out, so queued-but-unsaved Readys are lost like a real kill.
        self._crashed = False
        self._wal_tail_at_crash = 0  # last segment's write offset
        # gofail-style storage failpoints on the persistence path
        # (ref: etcdserver/raft.go raftBeforeSave/raftAfterSave); chaos
        # harnesses enable them per-member by these names.
        self._fp_before_save = f"hosting.m{member_id}.raftBeforeSave"
        self._fp_after_save = f"hosting.m{member_id}.raftAfterSave"
        # Pipeline-aware kill point (ISSUE 13): fires on the WAL-commit
        # worker AFTER the wave's records are written to the fd but
        # BEFORE the covering fsync/release — a crash here leaves a
        # written-but-unfsynced tail whose batches were never acked,
        # exactly the window the async pipeline introduces.
        self._fp_before_release = (
            f"hosting.m{member_id}.raftBeforeFsyncRelease")
        # Wall-seconds per phase of the member pipeline (ETCD_TPU_PROF
        # companion at the hosting layer; read via the admin 'prof' op).
        self.stats = {"rounds": 0, "round_s": 0.0, "wal_s": 0.0,
                      "apply_s": 0.0, "send_s": 0.0, "batched": 0}
        self.tick_interval = tick_interval
        # ReadIndex bookkeeping for linearizable readers: the latest
        # OPENED batch seq per group (readers bind to a batch opened
        # at-or-after their request — an earlier batch's index may
        # predate a write the reader has already observed) and the
        # latest CONFIRMED (seq, index).
        self._read_opened: Dict[int, int] = {}
        self._read_results: Dict[int, Tuple[int, int]] = {}
        self._read_cv = threading.Condition()

        # Durability fencing (protocol-aware torn-tail recovery): the
        # watermark arrays hold the highest per-group (last, last_term,
        # commit) this member ever WAL-recorded as durable; the _dur
        # arrays track what actually IS durable right now. _replay
        # fences any group whose recovered log fell below its watermark
        # — acked bytes were destroyed — and the fence lifts when the
        # durable log is back at the watermark (_maybe_lift_fences).
        self.fence_enabled = bool(fence)
        self._wm_last = np.zeros(num_groups, np.int64)
        self._wm_term = np.zeros(num_groups, np.int64)
        self._wm_commit = np.zeros(num_groups, np.int64)
        self._dur_last = np.zeros(num_groups, np.int64)
        self._dur_term = np.zeros(num_groups, np.int64)
        self._dur_commit = np.zeros(num_groups, np.int64)
        self._fenced = np.zeros(num_groups, bool)
        self._tail_state: Optional[int] = None  # walog TAIL_* at boot
        self._boot_fenced = 0
        self._g_fenced = fenced_groups_gauge().labels(str(member_id))

        # IO-error contract state (ISSUE 15). disk_fault_hook is the
        # storage fault plane's seam, threaded into the Walog handle
        # below; _disk_full flips while WAL writes refuse at that seam
        # with an ENOSPC-class error (write-back-pressure: proposals
        # refuse, nothing acks, recovery is automatic once space
        # returns); _fail_stop_cause records why a storage fault
        # crash-killed this member (health op surfaces both);
        # _salvage records an at-rest-corruption amputation at boot.
        self._disk_fault_hook = disk_fault_hook
        self._disk_full = False
        self._fail_stop_cause: Optional[str] = None
        self._salvage: Optional[Dict] = None
        self._g_disk_full = disk_full_gauge().labels(str(member_id))
        self._c_failstop = disk_fault_failstop_counter()

        # Per-group membership configs (joint-consensus control plane,
        # ISSUE 11): the replicated log drives it — committed
        # EntryConfChange/V2 entries apply here, flip the device
        # voter/learner/in_joint lanes via one bulk mask upload, and
        # WAL-record the result (RT_CONF_BATCH) so _replay reconstructs
        # config state across crashes. Guarded by _lock.
        self.conf = GroupConfStore(num_groups, self.cfg.num_replicas)
        self._g_joint = joint_groups_gauge().labels(str(member_id))
        self._g_learners = learner_slots_gauge().labels(str(member_id))
        # Auto-leave-joint re-proposal cooldowns (row -> monotonic s):
        # the leave is proposed at the joint entry's apply on the
        # leader; the sweep in run_round is the fallback for groups
        # whose leadership moved mid-joint.
        self._joint_prop: Dict[int, float] = {}
        self._next_joint_sweep = 0.0

        # Log-lifecycle plane (cadence snapshots, WAL rotation/release,
        # ring back-pressure). Both knobs default OFF; the state below
        # is initialized before _replay() because replay reconstructs
        # it from the surviving segments. All guarded by _lock except
        # where noted.
        self.snap_cadence = (
            None if snap_cadence is None else max(1, int(snap_cadence)))
        self.snap_keep = max(1, int(snap_keep))
        self.wal_rotate_bytes = (
            None if wal_rotate_bytes is None else int(wal_rotate_bytes))
        self.wal_pinned_segments = max(1, int(wal_pinned_segments))
        # Newest durable FILE snapshot per group (what cadence measures
        # against and rotation checkpoints re-record as RT_SNAPMARK).
        self._snap_file_idx = np.zeros(num_groups, np.int64)
        self._snap_file_term = np.zeros(num_groups, np.int64)
        # Release-math cover per group: the newest snapshot EVIDENCE
        # (file marker or RT_SNAPSHOT install record) at _snap_cover[g],
        # whose WAL record lives in segment _snap_seq[g]. A sealed
        # segment s is reclaimable only when, for every group with
        # entries in s (cap > 0), cover >= cap AND the evidence sits in
        # a LATER segment than s — releasing the evidence with the
        # segment would turn the snapshot into an unprovable file.
        self._snap_cover = np.zeros(num_groups, np.int64)
        self._snap_seq = np.zeros(num_groups, np.int64)
        # Sealed (cut) segments awaiting release, oldest first:
        # {"seq", "meta", "cap"} where cap[g] = g's durable last index
        # at seal time (every entry the segment holds is <= cap[g]).
        self._sealed: List[Dict] = []
        self._wal_meta = 0          # current tail segment's meta
        self._ckpt_seq = -1         # seq of the last durable checkpoint
        self._need_ckpt = False     # rotation happened / boot-with-
        # history: (re)write the full-state checkpoint into the tail
        self._last_sync_seq = 0     # tail seq at the last fsync (set
        # under _wal_io; read by the install cover fold)
        self._tail_ckpt_bytes = 0   # checkpoint bytes in the current
        # tail: the cut threshold EXCLUDES them, or at large G a
        # checkpoint bigger than wal_rotate_bytes would cut-storm
        # (every cut writes a checkpoint that immediately re-arms the
        # next cut)
        self._wal_pinned_flag = False
        self._pinned_group = -1
        self._ring_occ_hw = 0       # ring-occupancy high-water (host)
        self._snap_file_count = 0
        self._snappers: Dict[int, "Snapshotter"] = {}

        restore = self._replay()
        groups = np.arange(num_groups, dtype=np.int32)
        slots = np.full(num_groups, self.slot, np.int32)
        mesh = None
        if mesh_devices:
            # Shard this member's [G, ...] state over a device mesh on
            # the group axis — the multi-chip hosting shape: groups are
            # data-parallel, quorum reductions stay device-local, WAL/
            # transport/apply run host-side exactly as unsharded
            # (SURVEY §2.1; __graft_entry__.dryrun_multichip layout).
            import jax
            from jax.sharding import Mesh

            devs = jax.devices()[:mesh_devices]
            assert len(devs) >= mesh_devices, (
                f"need {mesh_devices} devices, have {len(jax.devices())}")
            mesh = Mesh(np.array(devs), ("groups",))
        self.rn = BatchedRawNode(
            self.cfg, groups=groups, slots=slots, restore=restore,
            mesh=mesh,
        )
        # Replayed membership configs onto the device before the first
        # round: the staged masks apply at the head of advance_round,
        # ahead of any delivery/tick — a recovered group must never run
        # one round on the boot all-voter electorate.
        conf_rows = self.conf.non_default_groups()
        if len(conf_rows):
            self.rn.set_membership_many(conf_rows,
                                        *self.conf.masks(conf_rows))
        self._update_conf_gauges()
        # Proposal-lifecycle tracer (etcd_tpu.obs, ISSUE 9): sampled
        # spans stamped at every pipeline stage. trace=None defers to
        # ETCD_TPU_TRACE (off by default); purely host-side, so the
        # device program and protocol state are identical either way.
        self.tracer = make_tracer(str(member_id), enabled=trace)
        self.rn.tracer = self.tracer
        # Telemetry plane (cfg.telemetry): the rawnode folds every
        # round's kernel frame into this hub; WAL fsync latency and
        # per-phase round timings land in the same registry. With
        # telemetry off none of this is touched — the hot path is
        # unchanged.
        self.hub: Optional[TelemetryHub] = None
        self._h_fsync = None
        self._h_phase = None
        # Fleet observatory (cfg.fleet_summary, obs/fleet.py): the
        # rawnode folds every round's device SummaryFrame into this
        # hub — etcd_tpu_fleet_* families, the bounded groups×time
        # heatmap ring (admin 'fleet' op / fleet_console read it), and
        # counted anomaly flags (commit_frozen, leader_skew).
        self.fleet = None
        if self.cfg.fleet_summary:
            from ..obs.fleet import FleetHub

            self.fleet = FleetHub(
                num_groups, self.cfg.num_replicas, num_groups,
                member=str(member_id))
            self.rn.fleet_hub = self.fleet
        if self.cfg.telemetry:
            self.hub = TelemetryHub(num_groups, member=str(member_id))
            self.rn.telemetry_hub = self.hub
            mid = str(member_id)
            self._h_fsync = wal_fsync_histogram().labels(mid)
            ph = round_phase_histogram()
            # round/wal/apply/send are member-pipeline phases; stage/
            # extract/collect split the round's host-side Python (inbox
            # staging, post-round extraction, outbound block assembly)
            # so the BENCH_NOTES phase breakdown is reproducible from
            # metrics alone (dump_metrics --admin).
            self._h_phase = {
                p: ph.labels(mid, p)
                for p in ("round", "wal", "apply", "send",
                          "stage", "extract", "collect")
            }
        # Apply-plane metric children (telemetry + plane both on):
        # gauges fold from rawnode.plane_stats on the apply path, the
        # read counter moves inline in linearizable_get.
        self._m_ap_slots = self._m_ap_leases = None
        self._m_ap_overflow = self._m_ap_watch = None
        self._m_ap_hit = self._m_ap_fb = None
        self._ap_we_prev = 0
        if self.cfg.telemetry and self.cfg.apply_plane:
            from .telemetry import (
                apply_plane_leases_gauge,
                apply_plane_overflow_gauge,
                apply_plane_reads_counter,
                apply_plane_slots_gauge,
                apply_plane_watch_events_counter,
            )

            mid = str(member_id)
            self._m_ap_slots = apply_plane_slots_gauge().labels(mid)
            self._m_ap_leases = apply_plane_leases_gauge().labels(mid)
            self._m_ap_overflow = (
                apply_plane_overflow_gauge().labels(mid))
            self._m_ap_watch = (
                apply_plane_watch_events_counter().labels(mid))
            rc = apply_plane_reads_counter()
            self._m_ap_hit = rc.labels(mid, "lease_hit")
            self._m_ap_fb = rc.labels(mid, "readindex_fallback")
        if restore:
            for row, rr in restore.items():
                self.applied_index[row] = rr.applied
                # Re-apply WAL tail beyond the app snapshot: committed
                # entries land again via the first Ready (applied mirror
                # starts at the snapshot index).
            if self.rn.plane is not None:
                # Seed the device plane rows: the stashed two-tier
                # image where the snapshot carried one (exact — its
                # applied watermark makes the tail re-dispatch
                # idempotent), else a rebuild from the host byte tier
                # (legacy blob: revisions renumbered, leases dropped —
                # the documented contract, see README).
                for row, rr in restore.items():
                    img = self._boot_plane.get(row)
                    if img is not None:
                        self._plane_restore_img(row, img)
                    elif self.kvs[row].data or rr.applied:
                        self._plane_seed_from_host(row, int(rr.applied))
        wal_dir = os.path.join(self.dir, "wal")
        fresh = not (
            os.path.isdir(wal_dir)
            and any(f.endswith(".wal") for f in os.listdir(wal_dir))
        )
        self.wal = Walog(wal_dir, create=fresh,
                         fault_hook=disk_fault_hook)

        self._stopped = threading.Event()
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)
        self._runner = threading.Thread(target=self._run_loop, daemon=True)
        # Ready pipeline: the round thread hands each BatchedReady to a
        # persist/apply/send worker so the NEXT device round overlaps
        # this round's WAL fsync + apply + TCP send (the reference's
        # overlap, ref: server/etcdserver/raft.go:218-268). Bounded:
        # a slow disk backpressures the round loop after 4 rounds, so a
        # crash loses at most the queued (unacknowledged) suffix and no
        # message ever escapes before its round's fsync (ordered queue,
        # batch fsync covers every append before any send).
        self._ready_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=4)
        self._drainer: Optional[threading.Thread] = (
            threading.Thread(target=self._drain_loop, daemon=True)
            if pipeline else None
        )
        # Async group-commit WAL pipeline (ISSUE 13). Knobs are member
        # args (NOT BatchedConfig fields: the jitted round program is
        # cached per config VALUE, and a host-only knob must never fork
        # a compile); wal_pipeline=None defers to ETCD_TPU_WAL_PIPELINE.
        # Lock hierarchy (the lock-order sentinel polices it):
        # _lock -> {_wal_io, _wal_cv}; the worker takes them one at a
        # time and never holds _wal_io or _wal_cv while acquiring _lock.
        # _wal_io serializes every native-handle touch against
        # crash()/stop() closing it mid-fsync; _wal_cv guards the open
        # double buffer (_wal_pending — producers append, the worker
        # swaps the whole list out).
        if wal_pipeline is None:
            wal_pipeline = _env_wal_pipeline()
        self._wal_max_delay = (
            WAL_GROUP_MAX_DELAY_S if wal_group_max_delay is None
            else float(wal_group_max_delay))
        self._wal_max_bytes = (
            WAL_GROUP_MAX_BYTES if wal_group_max_bytes is None
            else int(wal_group_max_bytes))
        self._wal_cv = threading.Condition()
        self._wal_pending: List[_PersistGroup] = []
        self._wal_stop = False
        self._wal_io = threading.Lock()
        self._wal_closed = False
        # Snapshot-install generation per group: deliver()'s MsgSnap
        # restore bumps it at submit, and a pipeline batch whose
        # records were built under an older generation skips its mirror
        # delta for that row at fsync completion (the snapshot's state
        # supersedes it; see _apply_wm_locked).
        self._snap_gen = np.zeros(num_groups, np.int64)
        self._wal_worker: Optional[threading.Thread] = (
            threading.Thread(target=self._wal_commit_loop, daemon=True)
            if wal_pipeline else None
        )
        self._m_wal_depth = self._m_wal_batches = None
        self._m_wal_bytes = self._m_wal_release = None
        if wal_pipeline:
            from .telemetry import (
                wal_pipeline_batches_histogram,
                wal_pipeline_bytes_histogram,
                wal_pipeline_depth_gauge,
                wal_pipeline_release_histogram,
            )

            mid = str(member_id)
            self._m_wal_depth = wal_pipeline_depth_gauge().labels(mid)
            self._m_wal_batches = (
                wal_pipeline_batches_histogram().labels(mid))
            self._m_wal_bytes = wal_pipeline_bytes_histogram().labels(mid)
            self._m_wal_release = (
                wal_pipeline_release_histogram().labels(mid))

    def start(self) -> None:
        self._ticker.start()
        self._runner.start()
        if self._drainer is not None:
            self._drainer.start()
        if self._wal_worker is not None:
            self._wal_worker.start()

    # -- boot ------------------------------------------------------------------

    def _replay(self) -> Dict[int, RowRestore]:
        wal_dir = os.path.join(self.dir, "wal")
        if not os.path.isdir(wal_dir) or not os.listdir(wal_dir):
            return {}
        rows: Dict[int, RowRestore] = defaultdict(RowRestore)
        ents: Dict[int, List[Tuple[int, int, bytes]]] = defaultdict(list)
        snaps: Dict[int, Tuple[int, int, bytes]] = {}
        wms: Dict[int, Tuple[int, int, int]] = {}
        # Lifecycle evidence gathered during the scan: per-segment
        # per-group max entry index (rebuilds the sealed-segment caps),
        # snapshot-file markers per group, and the segment each
        # group's newest in-WAL snapshot evidence lives in.
        seg_caps: Dict[int, Dict[int, int]] = defaultdict(dict)
        marks: Dict[int, List[Tuple[int, int, int]]] = defaultdict(list)
        snap_src_seq: Dict[int, int] = {}

        def _cap(seq: int, g: int, i: int) -> None:
            sc = seg_caps[seq]
            if i > sc.get(g, 0):
                sc[g] = i
        # read_all_classified snapshots the tail shape BEFORE the
        # repairing read (which truncates the mid-record evidence) —
        # the ordering protocol-aware recovery rests on, kept
        # unbreakable inside the walog helper.
        try:
            records, self._tail_state = wal_read_all_classified(wal_dir)
        except WalogError:
            # At-rest corruption (a COMPLETE record failing its CRC —
            # bit-rot, not a torn crash tail): the native reader
            # refuses by design. Salvage amputates the log at the
            # first bad record; the durable-watermark pass below then
            # fences exactly the groups whose acked bytes the cut
            # destroyed, and they heal by snapshot/probe rejoin — the
            # damage becomes protocol-visible instead of unbootable.
            info = wal_salvage(wal_dir)
            if info is None:
                raise  # not a salvageable corruption: surface it
            self._salvage = info
            disk_fault_salvage_counter().labels(str(self.id)).inc()
            _log.warning(
                "member %d: at-rest WAL corruption — salvaged: %s "
                "truncated at %d (%d bytes dropped, %d later "
                "segment(s) removed); groups below their durable "
                "watermark boot FENCED", self.id, info["segment"],
                info["truncated_at"], info["bytes_dropped"],
                len(info["removed_segments"]))
            records, _ts = wal_read_all_classified(wal_dir)
            # Keep the ORIGINAL classification: the console/health
            # must report what the boot found, not the amputated
            # aftermath.
            self._tail_state = TAIL_CORRUPT
        for rtype, data, rec_seq, _meta in records:
            if rtype == RT_HARDSTATE:
                g, term, vote, commit = _unpack_hs(data)
                rr = rows[g]
                rr.term, rr.vote, rr.commit = term, vote, commit
            elif rtype == RT_ENTRY:
                g, i, t, d, et = _unpack_entry(data)
                lst = ents[g]
                while lst and lst[-1][0] >= i:
                    lst.pop()  # WAL truncate-and-append semantics
                lst.append((i, t, d, et))
                _cap(rec_seq, g, i)
            elif rtype == RT_ENTRY_BATCH:
                for g, i, t, d, et in _iter_entry_batch(data):
                    lst = ents[g]
                    while lst and lst[-1][0] >= i:
                        lst.pop()  # truncate-and-append per entry
                    lst.append((i, t, d, et))
                    _cap(rec_seq, g, i)
            elif rtype == RT_SNAPSHOT:
                g, i, t, d, _et = _unpack_snap(data)
                snaps[g] = (i, t, d)
                snap_src_seq[g] = rec_seq
                ents[g] = [e for e in ents[g] if e[0] > i]
                _cap(rec_seq, g, i)
            elif rtype == RT_WATERMARK:
                # Latest record wins: `last` legitimately moves DOWN on
                # a conflict truncation (a new leader overwriting an
                # uncommitted suffix), so a running max would
                # false-fence a healthy member.
                g, wl, wt, wc = _unpack_wm(data)
                wms[g] = (wl, wt, wc)
            elif rtype == RT_HS_BATCH:
                hs = _unpack_batch(data, WAL_HS_DTYPE)
                for g, term, vote, commit in zip(
                        hs["group"].tolist(), hs["term"].tolist(),
                        hs["vote"].tolist(), hs["commit"].tolist()):
                    rr = rows[g]
                    rr.term, rr.vote, rr.commit = term, vote, commit
            elif rtype == RT_WM_BATCH:
                wmb = _unpack_batch(data, WAL_WM_DTYPE)
                for g, wl, wt, wc in zip(
                        wmb["group"].tolist(), wmb["last"].tolist(),
                        wmb["last_term"].tolist(),
                        wmb["commit"].tolist()):
                    wms[g] = (wl, wt, wc)
            elif rtype == RT_CONF_BATCH:
                # Full-state config rows; records replay in WAL order,
                # so the last row loaded per group is the newest.
                for g, idx, flags, slots in \
                        GroupConfStore.unpack_groups(
                            data, self.cfg.num_replicas):
                    self.conf.load_record(g, idx, flags, slots)
            elif rtype == RT_SNAPMARK:
                mk = _unpack_batch(data, WAL_SNAPMARK_DTYPE)
                for g, i, t in zip(mk["group"].tolist(),
                                   mk["index"].tolist(),
                                   mk["term"].tolist()):
                    marks[g].append((i, t, rec_seq))
        # File-backed snapshots (RT_SNAPMARK): when a group's newest
        # durable marker names an index beyond any RT_SNAPSHOT record
        # still in the WAL (the full record may live in a released
        # segment), restore from the snapshot FILE. Markers are only
        # written after the file's fsync, and load_newest_available
        # skips corrupt/partial files — a missing file falls back to
        # older evidence, and any acked state thereby lost is caught
        # by the durable-watermark fence below.
        for g, cand in marks.items():
            best = max(i for i, _t, _s in cand)
            if best <= snaps.get(g, (0, 0, b""))[0]:
                continue
            try:
                snap = self._snapper(g).load_newest_available(
                    [(i, t) for i, t, _s in cand])
            except NoSnapshotError:
                continue
            md = snap.metadata
            if md.index > snaps.get(g, (0, 0, b""))[0]:
                snaps[g] = (md.index, md.term, snap.data)
                ents[g] = [e for e in ents[g] if e[0] > md.index]
                snap_src_seq[g] = max(
                    (s for i, t, s in cand
                     if i == md.index and t == md.term), default=0)
                cs = md.conf_state
                if cs is not None:
                    # Supersedes the skipped conf entries the released
                    # segments held (no-op at/below the conf
                    # watermark, same as the install path).
                    self.conf.restore(g, md.index, cs)
        restore: Dict[int, RowRestore] = {}
        for g in set(rows) | set(ents) | set(snaps):
            rr = rows[g]
            si, st_, sd = snaps.get(g, (0, 0, b""))
            # Format-aware host restore (the RT_SNAPSHOT record holds
            # the two-tier wrapper when the plane was on); the plane
            # image is stashed for staging once the rawnode exists.
            host_data, plane_img = _split_snap_blob(sd)
            self.kvs[g].data = host_data
            if plane_img is not None:
                self._boot_plane[g] = plane_img
            rr.snap_index, rr.snap_term = si, st_
            rr.applied = si
            rr.entries = [e for e in ents.get(g, []) if e[0] > si]
            # Contiguity guard: release only ever reclaims entries a
            # snapshot covers, so a gap ABOVE the restored snapshot
            # means the newest snapshot file was unreadable and an
            # older restore point took over. Keep the contiguous
            # prefix — the watermark fence below makes the loss
            # protocol-visible and catch-up re-ships the rest.
            for j, e in enumerate(rr.entries):
                if e[0] != si + 1 + j:
                    rr.entries = rr.entries[:j]
                    break
            lim = rr.snap_index + len(rr.entries)
            rr.commit = min(rr.commit, lim) if rr.commit else rr.commit
            # BatchedRawNode._restore clamps commit up to snap_index (a
            # persisted snapshot proves its index committed) — relevant
            # here when a crash lands between the RT_SNAPSHOT record
            # and the next hardstate record.
            restore[g] = rr
            # Committed conf entries ABOVE the group's recorded conf
            # watermark (the crash landed after the entry's fsync but
            # before the RT_CONF_BATCH record / its fsync): re-apply
            # them now, in log order, so the device masks staged at
            # boot reflect every conf change the quorum may have acted
            # on. Entries above the recovered commit re-apply later
            # through the normal Ready path when they (re-)commit —
            # applying early would run a config the group never
            # committed (the apply-at-commit discipline, etcd-style).
            commit_eff = max(rr.commit, rr.snap_index)
            for ent in rr.entries:
                idx, _t, d = ent[0], ent[1], ent[2]
                et = ent[3] if len(ent) > 3 else 0
                if (et and idx <= commit_eff
                        and idx > self.conf.applied_index[g]):
                    try:
                        cc = decode_conf_entry(d or b"", et)
                    except ValueError:
                        _log.warning(
                            "member %d: undecodable conf entry "
                            "g%d i%d at replay", self.id, g, idx)
                        continue
                    self.conf.apply(g, idx, cc)
        # -- durable bookkeeping + fence decision per group ----------------
        for g, rr in restore.items():
            rec_last = rr.entries[-1][0] if rr.entries else rr.snap_index
            rec_term = rr.entries[-1][1] if rr.entries else rr.snap_term
            self._dur_last[g] = rec_last
            self._dur_term[g] = rec_term
            self._dur_commit[g] = max(rr.commit, rr.snap_index)
        for g, (wl, wt, wc) in wms.items():
            self._wm_last[g] = wl
            self._wm_term[g] = wt
            self._wm_commit[g] = wc
            if not self.fence_enabled:
                continue
            rr = restore.get(g)
            rec_last = self._dur_last[g] if rr is not None else 0
            # Acked-durable bytes lost: the recovered log no longer
            # reaches the watermark point (or holds an OLDER term
            # there — unreachable from a pure tail cut, checked
            # defensively). This replica's log/vote can no longer back
            # its pre-crash promises: boot the row FENCED and let the
            # snapshot/probe catch-up re-converge it (step.py fence
            # lane; RowRestore.fenced → BatchedRawNode._restore).
            below = rec_last < wl
            if not below and rr is not None and wl > rr.snap_index:
                terms = {i: t for i, t, *_ in rr.entries}
                below = terms.get(wl, 0) < wt
            # Term proof (mirrors _fence_lift_locked): a recovered log
            # ENDING above the watermark's term supersedes the demand —
            # the old suffix can never commit once a later-term leader
            # replaced it (reachable when a crash lands between a
            # term-rule lift and the next accurate watermark record).
            if below and self._dur_term[g] > wt:
                below = False
            if below:
                if rr is None:
                    rr = restore[g] = rows[g]
                rr.fenced = True
                self._fenced[g] = True
        self._boot_fenced = int(self._fenced.sum())
        self._g_fenced.set(self._boot_fenced)
        if self._boot_fenced or self._tail_state != TAIL_CLEAN:
            _log.warning(
                "member %d: WAL tail %s; %d group(s) below durable "
                "watermark -> fenced (campaign/vote suppressed until "
                "catch-up): %s", self.id,
                TAIL_NAMES.get(self._tail_state, self._tail_state),
                self._boot_fenced,
                np.nonzero(self._fenced)[0][:16].tolist())
        # -- log-lifecycle state from the surviving segments ----------------
        # Sealed list + caps from the on-disk segment names (all but
        # the highest seq are sealed; caps are the running per-group
        # max entry index up to and including each segment). A boot
        # with sealed segments owes the new tail a checkpoint before
        # anything can release (_ckpt_seq starts unproven).
        segs: List[Tuple[int, int]] = []
        for fname in os.listdir(wal_dir):
            if not fname.endswith(".wal") or len(fname) < 37:
                continue
            try:
                segs.append((int(fname[0:16], 16), int(fname[17:33], 16)))
            except ValueError:
                continue
        segs.sort()
        if segs:
            self._wal_meta = segs[-1][1]
            run_cap: Dict[int, int] = {}
            for sseq, smeta in segs[:-1]:
                for g, i in seg_caps.get(sseq, {}).items():
                    if i > run_cap.get(g, 0):
                        run_cap[g] = i
                cap = np.zeros(self.g, np.int64)
                for g, i in run_cap.items():
                    cap[g] = i
                self._sealed.append(
                    {"seq": sseq, "meta": smeta, "cap": cap})
            self._need_ckpt = bool(self._sealed)
        # Snapshot covers: what each group actually restored from,
        # with the segment holding its WAL evidence; file bookkeeping
        # from the newest durable marker (cadence measures its
        # applied-delta against the newest FILE, even when the restore
        # itself used a newer RT_SNAPSHOT record).
        for g, (si, st_, _sd) in snaps.items():
            if si > 0:
                self._snap_cover[g] = si
                self._snap_seq[g] = int(snap_src_seq.get(g, 0))
        for g, cand in marks.items():
            mi, mt, _ms = max(cand, key=lambda c: c[0])
            self._snap_file_idx[g] = mi
            self._snap_file_term[g] = mt
        snap_root = os.path.join(self.dir, "snap")
        if os.path.isdir(snap_root):
            total = 0
            for sub in os.listdir(snap_root):
                try:
                    total += sum(
                        1 for n in os.listdir(
                            os.path.join(snap_root, sub))
                        if n.endswith(".snap"))
                except (NotADirectoryError, OSError):
                    continue
            self._snap_file_count = total
        return restore

    # -- loops -----------------------------------------------------------------

    def _tick_loop(self) -> None:
        while not self._stopped.wait(self.tick_interval):
            self.rn.tick()
            self._work.set()

    def _run_loop(self) -> None:
        # Event-driven: staged work (proposals, inbound messages,
        # ticks) wakes the loop immediately instead of a blind sleep —
        # a put proposed mid-sleep otherwise pays up to a quarter tick
        # of dead latency PER HOP of the commit path.
        try:
            while not self._stopped.is_set():
                if not self.rn.has_work():
                    self._work.wait(self.tick_interval)
                    self._work.clear()
                    continue
                self.run_round()
        except FailpointPanic:
            # Injected crash on the synchronous (pipeline=False) path.
            # A site armed with the bare 'panic' action (not a crash()
            # callable) reaches here with the member still live — finish
            # the kill, or the member would wedge half-dead.
            _log.info("member %d: injected crash (round loop)", self.id)
            if not self._crashed:
                self.crash()

    def _drain_loop(self) -> None:
        """Persist/apply/send worker: drains Readys in round order,
        coalescing everything queued into ONE WAL fsync before any of
        their messages go out (the reference overlaps the next raft
        Ready with storage/apply the same way — raft.go:218-268 — and
        wal.Save batches; fsync-before-send holds per round because the
        queue is ordered and the sync covers every appended record).

        Guarded: any exception escaping the body (an OSError from a
        full/failed disk in _process_readys, a transport fault in the
        send path) logs and STOPS the member. Without the guard the
        thread died silently and run_round then blocked forever on the
        full _ready_q — a wedged member that still answered pings
        (the reference treats storage errors the same way: a raft
        storage fault is fatal to the member, never swallowed)."""
        try:
            while True:
                rd = self._ready_q.get()
                if rd is None:
                    return
                batch = [rd]
                while True:
                    try:
                        nxt = self._ready_q.get_nowait()
                    except queue_mod.Empty:
                        break
                    if nxt is None:
                        self._process_readys(batch)
                        return
                    batch.append(nxt)
                self._process_readys(batch)
        except FailpointPanic:
            # Injected crash (chaos harness): exit WITHOUT the orderly
            # stop() below, which would flush state a real kill would
            # have torn away. If the site was armed with the bare
            # 'panic' action (no crash() callable), the member is still
            # live here — finish the kill, else run_round spins forever
            # on the full _ready_q.
            _log.info("member %d: injected crash (drain worker)", self.id)
            if not self._crashed:
                self.crash()
        except Exception:  # noqa: BLE001 — fatal: log + stop the member
            _log.exception(
                "member %d: drain worker died; stopping member", self.id)
            self.stats["drain_dead"] = self.stats.get("drain_dead", 0) + 1
            # stop() from this thread: joins skip current_thread, and
            # run_round's queue put is deadline-based, so the round
            # thread can't be left blocked on a dead drainer.
            self.stop()

    def run_round(self) -> BatchedReady:
        """One device round; the Ready's persist/apply/send runs on the
        drain worker (pipelined with the next device round), unless the
        member runs unpipelined (pipeline=False: synchronous — kept as
        a debugging/fallback mode and covered by the test_hosting
        'sync' cluster parametrization)."""
        t0 = time.perf_counter()
        rd = self.rn.advance_round()
        self.rn.advance()
        self._joint_sweep()  # time-gated; no-op while nothing is joint
        self.stats["rounds"] += 1
        dt = time.perf_counter() - t0
        self.stats["round_s"] += dt
        if self._h_phase is not None:
            self._h_phase["round"].observe(dt)
            pl = self.rn.phase_last
            for p in ("stage", "extract", "collect"):
                self._h_phase[p].observe(pl[p])
        if self._drainer is not None:
            # Bounded: backpressure on the round — but never block
            # forever on a stopped/dead drain worker (see _drain_loop's
            # fatal-fault guard); the unpersisted Ready is dropped with
            # the member, same as a crash at this point.
            while not self._stopped.is_set():
                try:
                    self._ready_q.put(rd, timeout=0.2)
                    break
                except queue_mod.Full:
                    continue
        else:
            self._process_readys([rd])
        return rd

    def _build_persist_records(
            self, batch: List[BatchedReady],
    ) -> Tuple[bool, Dict[int, List[int]], List[Tuple[int, bytes]]]:
        """Serialize one Ready batch's persistence work (caller holds
        _lock): (must_sync, per-row durable deltas, WAL records in
        write order). Watermark records go FIRST: a tail cut destroying
        the batch's fsync'd entry records then still leaves the record
        that demanded them, so _replay detects the loss and fences."""
        must_sync = False
        records: List[Tuple[int, bytes]] = []
        # Per-group durable deltas across the whole batch:
        # row -> [last, last_term, commit, has_entries]. Entries
        # replay in order, so the final entry processed IS the new
        # last (truncate-and-append semantics included).
        wm: Dict[int, List[int]] = {}

        def _wm_row(row: int) -> List[int]:
            ent = wm.get(row)
            if ent is None:
                ent = wm[row] = [
                    int(self._dur_last[row]), int(self._dur_term[row]),
                    int(self._dur_commit[row]), 0,
                ]
            return ent

        for rd in batch:
            for row, _term, _vote, commit in rd.hardstates:
                ent = _wm_row(row)
                if commit > ent[2]:
                    ent[2] = commit
            eb = rd.entries
            if len(eb):
                # Last entry per row IS the row's new durable
                # (last, last_term): entries are row-ascending with
                # ascending indexes, so segment boundaries give the
                # per-row finals without a per-entry pass.
                rows_a = eb.rows
                ends = np.nonzero(np.diff(rows_a))[0]
                lasts = np.append(ends, len(rows_a) - 1)
                for j in lasts.tolist():
                    ent = _wm_row(int(rows_a[j]))
                    ent[0] = int(eb.idx[j])
                    ent[1] = int(eb.term[j])
                    ent[3] = 1
            must_sync |= rd.must_sync
        if self.fence_enabled:
            wm_rows: List[Tuple[int, int, int, int]] = []
            for row in sorted(wm):
                last, lterm, commit, has_ents = wm[row]
                if not has_ents:
                    continue  # commit-only: no durability promise moves
                if self._fenced[row] and last < self._wm_last[row]:
                    # Never lower the demand mid-heal: a crash
                    # during catch-up must re-fence at the original
                    # pre-loss watermark, not the partial one.
                    last = int(self._wm_last[row])
                    lterm = int(self._wm_term[row])
                if self._fenced[row]:
                    commit = max(commit, int(self._wm_commit[row]))
                wm_rows.append((row, last, lterm, commit))
            if wm_rows:
                wma = np.array(wm_rows, np.int64)
                records.append((RT_WM_BATCH, _pack_rows(
                    WAL_WM_DTYPE,
                    {"group": wma[:, 0], "last": wma[:, 1],
                     "last_term": wma[:, 2], "commit": wma[:, 3]})))
        for rd in batch:
            if rd.hardstates:
                # jitlint: waive(sync-in-loop) -- rd.hardstates is a host list (no device buffer); one pack per Ready of the drain batch, bounded by batch depth
                hsa = np.array(rd.hardstates, np.int64)
                records.append((RT_HS_BATCH, _pack_rows(
                    WAL_HS_DTYPE,
                    {"group": hsa[:, 0], "term": hsa[:, 1],
                     "vote": hsa[:, 2], "commit": hsa[:, 3]})))
            if len(rd.entries):
                records.append(
                    (RT_ENTRY_BATCH, _pack_entry_batch(rd.entries)))
        return must_sync, wm, records

    def _apply_wm_locked(self, wm: Dict[int, List[int]], synced: bool,
                         gens: Optional[Dict[int, int]] = None) -> None:
        """Fold one batch's durable deltas into the mirrors (caller
        holds _lock). Durable mirrors move only once the records are
        fsync'd (entries always set must_sync); the commit mirror rides
        along unsynced — it gates nothing in the fence protocol.
        ``gens``: snapshot-install generations captured at submit (WAL
        pipeline) — a row whose generation moved had a MsgSnap restore
        land AFTER this batch's records were built, and the snapshot's
        (already-applied, strictly-newer) mirrors must not be clobbered
        with this batch's stale delta. Skipping is safe-conservative:
        mirrors only ever claim LESS durable than reality that way, and
        the next entry-carrying batch re-converges them."""
        for row, (last, lterm, commit, has_ents) in wm.items():
            stale = (gens is not None
                     and gens.get(row, 0) != self._snap_gen[row])
            if has_ents and synced and not stale:
                self._dur_last[row] = last
                self._dur_term[row] = lterm
                if not self._fenced[row]:
                    # Track the recorded watermark for healthy rows
                    # (fenced rows keep demanding the boot-time
                    # watermark until the lift below).
                    self._wm_last[row] = last
                    self._wm_term[row] = lterm
                    self._wm_commit[row] = max(
                        self._wm_commit[row], commit)
            self._dur_commit[row] = max(self._dur_commit[row], commit)

    def _wal_submit_locked(self, records: List[Tuple[int, bytes]],
                           must_sync: bool,
                           batch: Sequence[BatchedReady] = (),
                           wm: Optional[Dict[int, List[int]]] = None,
                           on_synced: Optional[Callable[[], None]] = None,
                           ) -> None:
        """Queue one persistence batch on the WAL pipeline (caller
        holds _lock, which makes submission order == record-build
        order across the drain, conf-apply and snapshot-restore
        producers). The worker owns the native handle exclusively from
        here on."""
        gens = {row: int(self._snap_gen[row]) for row in wm} \
            if wm is not None else None
        traced = ()
        if self.tracer is not None:
            traced = [rd.traced_entries for rd in batch
                      if rd.traced_entries]
        g = _PersistGroup(records, list(batch), wm, gens, must_sync,
                          on_synced=on_synced, traced=traced)
        with self._wal_cv:
            self._wal_pending.append(g)
            depth = len(self._wal_pending)
            self._wal_cv.notify()
        if self._m_wal_depth is not None:
            self._m_wal_depth.set(depth)

    def _process_readys(self, batch: List[BatchedReady]) -> None:
        """Persist → apply → send, in round order. With the WAL
        pipeline off: one inline fsync for the whole batch before any
        of its acks/sends/applies (the pre-ISSUE-13 behavior). With it
        on: serialize the records, queue them on the WAL-commit worker
        and return — the worker's ordered release barrier runs the
        apply/send half only after the covering group-commit fsync."""
        fp(self._fp_before_save)  # crash-before-WAL-save injection site
        t0 = time.perf_counter()
        lifts: List[int] = []
        with self._lock:
            if self._crashed:
                return  # simulated kill: queued Readys are torn away
            must_sync, wm, records = self._build_persist_records(batch)
            if self._wal_worker is not None:
                self._wal_submit_locked(records, must_sync,
                                        batch=batch, wm=wm)
                self.stats["batched"] += len(batch)
                dt = time.perf_counter() - t0
                self.stats["wal_s"] += dt
                if self._h_phase is not None:
                    self._h_phase["wal"].observe(dt)
                return
            # Inline mode: snapshot the install generations under the
            # SAME lock the records were built under. The WAL write
            # below runs OUTSIDE _lock (handle serialized by _wal_io —
            # required so an ENOSPC dwell back-pressures without
            # wedging health()/crash()/stop() behind the member lock),
            # so a MsgSnap install can land between build and fsync;
            # the generation guard skips the then-stale mirror delta
            # exactly like the pipeline path does.
            gens = {row: int(self._snap_gen[row]) for row in wm}
        if not self._wal_write_sync(records, must_sync, batch):
            return  # fail-stopped / crashed / stopped mid-write:
            # nothing from the unpersisted window is released
        with self._lock:
            if self._crashed:
                return
            self._apply_wm_locked(wm, must_sync, gens)
            lifts = self._fence_lift_locked()
        dt = time.perf_counter() - t0
        self.stats["wal_s"] += dt
        if self._h_phase is not None:
            self._h_phase["wal"].observe(dt)
        self.stats["batched"] += len(batch)
        self._fence_lift_apply(lifts)
        fp(self._fp_after_save)  # crash-after-save-before-apply site
        for rd in batch:
            self._apply_and_send(rd)
        # Lifecycle work rides the drain AFTER the batch's covering
        # fsync and release (pipeline mode runs the same pass at the
        # end of each commit wave instead).
        self._lifecycle_pass()

    # -- IO-error contract (ISSUE 15) ------------------------------------------
    #
    # Three arms, applied identically to the inline drain and the
    # WAL-pipeline worker:
    #
    # * **fail-stop** — the FIRST failed fsync (any errno) kills the
    #   member crash-style: nothing gated on the failed window (acks,
    #   sends, applies) is ever released, and no code path retries an
    #   fsync whose dirty pages the kernel may already have dropped
    #   and marked clean ("Can Applications Recover from fsync
    #   Failures?", Rebello et al., ATC'19 — retry-fsync reports
    #   success without durability on ext4/xfs). Unrecoverable write
    #   errors (partial native write, injected write faults) take the
    #   same arm: the on-disk suffix is unknowable.
    # * **write-back-pressure** — an ENOSPC-class error raised AT THE
    #   FAULT SEAM (DiskFullError: provably nothing was written) puts
    #   the member in disk_full: proposals refuse, the round loop
    #   back-pressures behind the bounded ready queue, health reports
    #   it, and the SAME record retries until space returns — zero
    #   acked writes lost, no crash-loop.
    # * **fence-on-salvage** — at-rest CRC corruption found at boot is
    #   amputated (walog.salvage) and the damaged groups boot FENCED
    #   via the durable watermark (see _replay) — the ISSUE 5
    #   machinery, reused.

    def _wal_write_sync(self, records: List[Tuple[int, bytes]],
                        must_sync: bool,
                        batch: Sequence[BatchedReady]) -> bool:
        """Inline-mode persistence with the IO-error contract applied.
        Returns False when the member died (fail-stop/crash/stop)
        before the batch was durable — the caller releases nothing."""
        i = 0
        while True:
            try:
                with self._wal_io:
                    if self._wal_closed:
                        return False
                    while i < len(records):
                        rt, data = records[i]
                        self.wal.append(rt, data)
                        i += 1
            except Exception as e:  # noqa: BLE001 — classified below
                if is_disk_full(e):
                    self._enter_disk_full()
                    if self._dwell_disk_full():
                        continue  # retry the SAME record (seam
                        # guarantees it never reached the buffer)
                    return False
                self._io_fail_stop("write", e)
                return False
            break
        self._exit_disk_full()
        if not must_sync:
            return True
        if self.tracer is not None:
            # fsync_wait is stamped at fsync START (the queue/build
            # half of the old fsync hop), fsync at COMPLETION — one
            # instant pair covers every traced key the batch covers.
            tw = time.monotonic_ns()
            for rd in batch:
                self.tracer.stamp_many(rd.traced_entries, "fsync_wait",
                                       tw)
        tf = time.perf_counter()
        try:
            with self._wal_io:
                if self._wal_closed:
                    return False
                self.wal.flush(sync=True)
                # Everything serialized above is now durable in the
                # current tail segment — snapshot-install covers fold
                # with this seq as their WAL-evidence segment.
                self._last_sync_seq = int(self.wal.tail_seq())
        except Exception as e:  # noqa: BLE001 — first failed fsync
            self._io_fail_stop("fsync", e)
            return False
        dt = time.perf_counter() - tf
        self.stats["wal_fsyncs"] = self.stats.get("wal_fsyncs", 0) + 1
        self.stats["fsync_s"] = self.stats.get("fsync_s", 0.0) + dt
        if self._h_fsync is not None:
            self._h_fsync.observe(dt)
        if self.fleet is not None:
            # Gray-failure feed: the fleet hub watches sustained fsync
            # latency and raises the counted member_limping anomaly
            # the rebalancer evicts leadership on.
            self.fleet.observe_fsync(dt)
        if self.tracer is not None:
            tns = time.monotonic_ns()
            for rd in batch:
                self.tracer.stamp_many(rd.traced_entries, "fsync", tns)
        return True

    def _enter_disk_full(self) -> None:
        if self._disk_full:
            return
        self._disk_full = True
        self._g_disk_full.set(1)
        self.stats["disk_full_episodes"] = (
            self.stats.get("disk_full_episodes", 0) + 1)
        _log.warning(
            "member %d: WAL write hit ENOSPC — entering disk_full "
            "write-back-pressure (proposals refuse, nothing acks, "
            "resumes when space returns)", self.id)

    def _exit_disk_full(self) -> None:
        if not self._disk_full:
            return
        self._disk_full = False
        self._g_disk_full.set(0)
        _log.info("member %d: disk space returned — writes resumed",
                  self.id)

    def _dwell_disk_full(self) -> bool:
        """One back-pressure dwell; False once the member died (the
        batch is abandoned like any crash-torn suffix)."""
        self.stats["disk_full_waits"] = (
            self.stats.get("disk_full_waits", 0) + 1)
        time.sleep(0.05)
        return not (self._crashed or self._stopped.is_set())

    def _io_fail_stop(self, stage: str, exc: BaseException) -> None:
        """Fail-stop arm of the IO-error contract: record the cause,
        count it, and die crash-style (WAL handle torn down, NO orderly
        flush) so nothing gated on the failed window is released and
        nothing ever re-fsyncs over possibly-dropped dirty pages.
        Never called with _lock or _wal_io held (crash() takes both)."""
        if self._crashed:
            return
        self._fail_stop_cause = f"{stage}: {exc}"[:200]
        self._c_failstop.labels(str(self.id), stage).inc()
        _log.error(
            "member %d: storage %s failed (%s) — FAIL-STOP: nothing "
            "from the failed window is released", self.id, stage, exc)
        self.crash()

    # -- log-lifecycle plane (ISSUE 17) ----------------------------------------
    #
    # Bounded growth over a long life, three lanes:
    #
    # * **snapshot cadence** — when applied-minus-file-snapshot crosses
    #   snap_cadence, the group's snapshot is built OFF the apply
    #   stream (batched across due groups per drain pass): file first
    #   (fsync'd, tmp+rename), then one RT_SNAPMARK batch whose
    #   covering fsync gates the cover fold and the keep-K retention
    #   prune — the WAL pipeline's release-barrier discipline, reused.
    # * **rotation + release** — past wal_rotate_bytes the tail is cut
    #   (native cut() fdatasyncs the sealed fd: seal == durable) with
    #   cap[g] = the durable last per group, a full-state checkpoint
    #   (hardstate/watermark/conf/markers) opens the new tail, and a
    #   sealed segment releases only when every group with entries in
    #   it (cap > 0) has snapshot cover >= cap with the evidence in a
    #   LATER segment. Fenced groups never build new snapshots, so
    #   their segments stay pinned until the fence heals — a fence
    #   demand can never dangle into a released segment — and a stuck
    #   group surfaces as the counted wal_pinned anomaly instead of
    #   silently eating the disk.
    # * **ring back-pressure** — propose() refuses with a typed
    #   ring_full (counted, health-visible) at the exact occupancy
    #   where the device headroom clamp would drop the proposal, and
    #   kernels.invariant_bits trips ring_over_window if an append
    #   ever crosses the floor.

    def _snapper(self, group: int) -> Snapshotter:
        """Per-group snapshot file store (member-N/snap/gXXXXX/),
        created lazily — eager creation would mkdir G directories on
        every boot. Shares the WAL's disk-fault seam."""
        sp = self._snappers.get(group)
        if sp is None:
            sp = self._snappers[group] = Snapshotter(
                os.path.join(self.dir, "snap", f"g{group:05d}"),
                fault_hook=self._disk_fault_hook)
        return sp

    def _append_synced(
            self, records: List[Tuple[int, bytes]]) -> Optional[int]:
        """Append + fsync standalone lifecycle records (snapshot
        markers) with the IO-error contract applied. Returns the tail
        segment seq the records landed in, or None when nothing became
        durable (ENOSPC / member dead) — the caller retries on a later
        pass. Never called with _lock or _wal_io held."""
        fail: Optional[BaseException] = None
        with self._wal_io:
            if self._wal_closed:
                return None
            try:
                for rt, data in records:
                    self.wal.append(rt, data)
                self.wal.flush(sync=True)
                seq = int(self.wal.tail_seq())
                self._last_sync_seq = seq
                return seq
            except Exception as e:  # noqa: BLE001 — classified below
                fail = e
        if is_disk_full(fail):
            # Seam guarantee: the failing record never reached the
            # buffer; anything appended before it rides the next
            # covering fsync. No dwell — lifecycle work just waits.
            self._enter_disk_full()
        else:
            self._io_fail_stop("lifecycle", fail)
        return None

    def _checkpoint_records_locked(self) -> List[Tuple[int, bytes]]:
        """Full-state checkpoint for the (new) tail segment — caller
        holds _lock. Watermark + hardstate rows for every live group,
        conf rows for every non-default group, snapshot markers for
        every file-covered group: any such record a release reclaims
        from an old segment is superseded by this copy first. Fenced
        rows re-record their boot demand (the _wm arrays never lower
        it), so the fence survives rotation; term/vote from the round
        mirrors may run AHEAD of the last fsync'd record, which is the
        safe direction (persisting a vote early can never un-promise
        one). Entries are the one thing a checkpoint cannot re-record —
        the per-segment caps gate those."""
        recs: List[Tuple[int, bytes]] = []
        wmg = np.nonzero((self._wm_last > 0) | (self._wm_commit > 0))[0]
        if wmg.size:
            recs.append((RT_WM_BATCH, _pack_rows(WAL_WM_DTYPE, {
                "group": wmg, "last": self._wm_last[wmg],
                "last_term": self._wm_term[wmg],
                "commit": self._wm_commit[wmg]})))
        rn = self.rn
        live = np.nonzero((rn.m_term > 0) | (rn.m_vote > 0)
                          | (rn.m_commit > 0))[0]
        if live.size:
            recs.append((RT_HS_BATCH, _pack_rows(WAL_HS_DTYPE, {
                "group": live, "term": rn.m_term[live],
                "vote": rn.m_vote[live],
                "commit": rn.m_commit[live]})))
        conf_rows = self.conf.non_default_groups()
        if len(conf_rows):
            recs.append((RT_CONF_BATCH,
                         self.conf.pack_groups(conf_rows)))
        covered = np.nonzero(self._snap_file_idx > 0)[0]
        if covered.size:
            recs.append((RT_SNAPMARK, _pack_rows(WAL_SNAPMARK_DTYPE, {
                "group": covered,
                "index": self._snap_file_idx[covered],
                "term": self._snap_file_term[covered]})))
        return recs

    # -- device apply plane (ISSUE 19) -----------------------------------------

    def _snap_data_many(self, rows) -> List[bytes]:
        """App-state blobs for a batch of groups (caller holds _lock).
        Plane off: the host tier's JSON dump, byte-identical to the
        pre-plane wire/disk format. Plane on: the two-tier wrapper —
        host bytes at the apply watermark plus the device plane image
        captured by ONE padded gather for the whole batch (the capture
        seam: a host dict walk per group inside _lock does not survive
        growing G)."""
        rows = [int(g) for g in rows]
        if self.rn.plane is None:
            return [self.kvs[g].snapshot() for g in rows]
        imgs = self.rn.plane_capture(rows)
        return [json.dumps({
            "host": {k.hex(): v.hex()
                     for k, v in self.kvs[g].data.items()},
            "plane": img,
        }).encode() for g, img in zip(rows, imgs)]

    def _restore_data(self, row: int, blob: bytes, idx: int) -> None:
        """Install snapshot app state for one group (caller holds
        _lock): host byte tier always; with the plane on, the device
        row image is staged too — from the blob's plane section, or
        rebuilt from the host dict when a plane-off sender shipped a
        legacy blob."""
        data, img = _split_snap_blob(blob)
        self.kvs[row].data = data
        if self.rn.plane is None:
            return
        if img is not None:
            self._plane_restore_img(row, img)
        else:
            self._plane_seed_from_host(row, idx)

    def _plane_restore_img(self, row: int, img: Dict) -> None:
        self.rn.plane_restore_row(
            row, img["kv_key"], img["kv_rev"], img["kv_val"],
            img["kv_lease"], img["rev"], img["tick"],
            img["overflow"], img.get("applied", 0),
            [(bytes.fromhex(k), int(e))
             for k, e in img.get("lessor", [])])

    def _plane_seed_from_host(self, row: int, applied: int) -> None:
        """Rebuild a plane row from the host byte tier (legacy blob or
        plane-off sender): revisions renumbered 1..k in key order,
        leases dropped — the documented legacy-restore contract."""
        from .applyplane import fnv1a32

        c = self.cfg.apply_capacity
        kk, kr, kv = [0] * c, [0] * c, [0] * c
        rev = slot = 0
        over = False
        data = self.kvs[row].data
        for k in sorted(data):
            rev += 1
            if slot >= c:
                over = True
                continue
            kk[slot] = fnv1a32(k)
            kr[slot] = rev
            kv[slot] = fnv1a32(data[k])
            slot += 1
        self.rn.plane_restore_row(row, kk, kr, kv, [0] * c, rev, 0,
                                  over, applied, [])

    def _lease_masked_get(self, group: int, key: bytes):
        """Host-tier byte read masked by the lessor mirror: a key whose
        lease expired on the device plane clock reads as absent even
        though the byte tier still holds it (expiry is leader-local —
        the replicated byte state never forks)."""
        exp = self.rn.plane_lessor.get((group, bytes(key)))
        if exp is not None and exp <= int(self.rn.m_plane_tick[group]):
            return None
        return self.kvs[group].data.get(key)

    def watch(self, group: int, key: bytes) -> int:
        """Arm an exact-key watch on `group`; returns the watch slot.
        Matching runs as masked compares on the device apply stream —
        fixed-shape event frames, no host scan per commit."""
        if self.rn.plane is None:
            raise RuntimeError("apply_plane is off")
        from .applyplane import fnv1a32

        with self._lock:
            slot = int(self._watch_next[group])
            if slot >= self.cfg.apply_watch_slots:
                raise RuntimeError(
                    f"group {group}: watch slots exhausted")
            self._watch_next[group] = slot + 1
            self._watches[(int(group), slot)] = bytes(key)
        self.rn.watch_set(group, slot, fnv1a32(key))
        self._work.set()
        return slot

    def watch_events(self) -> List[Dict[str, object]]:
        """Drain pending watch events: one dict per (event, armed
        slot), the registered key bytes resolved from the slot
        bitmap."""
        out: List[Dict[str, object]] = []
        if self.rn.plane is None:
            return out
        for row, op, kh, rev, wmask in self.rn.drain_plane_events():
            for s in range(self.cfg.apply_watch_slots):
                if wmask & (1 << s):
                    out.append({
                        "group": int(row), "slot": s,
                        "op": "PUT" if op == 1 else "DELETE",
                        "key": self._watches.get(
                            (int(row), s), b"").hex(),
                        "key_hash": int(kh), "rev": int(rev),
                    })
        return out

    def _lifecycle_pass(self) -> None:
        """One bounded lifecycle step, riding the inline drain or the
        WAL-commit worker AFTER a covering fsync (never with _lock or
        _wal_io held on entry). Work per pass is capped, so the round
        loop never stalls behind snapshot building."""
        if self.snap_cadence is None and self.wal_rotate_bytes is None:
            return
        if (self._crashed or self._disk_full
                or self._fail_stop_cause is not None):
            return
        occ = int((self.rn.m_last - self.rn.m_snap).max())
        if occ > self._ring_occ_hw:
            self._ring_occ_hw = occ
        if self.snap_cadence is not None:
            self._snapshot_due_groups()
        if self.wal_rotate_bytes is not None:
            self._rotate_and_release()

    def _snapshot_due_groups(self) -> None:
        """Cadence snapshots, batched across due groups: capture
        (index, term, conf, KV blob) under _lock off the apply stream,
        write the files OUTSIDE every lock, then append ONE RT_SNAPMARK
        batch — the cover fold and the keep-K retention prune run only
        once the marker's fsync landed. Fenced groups are skipped: a
        fenced group's cover stays frozen, so release keeps every
        segment its un-healed demand may point into."""
        cad = self.snap_cadence
        builds: List[Tuple[int, int, int, bytes, object]] = []
        with self._lock:
            if self._crashed:
                return
            delta = self.applied_index - self._snap_file_idx
            # Catch-up lag: groups whose cover (or marker evidence)
            # still pins the OLDEST sealed segment build regardless of
            # cadence — without this, a group idling 1-2 applied
            # entries past its last snapshot (delta < cadence) would
            # pin that segment forever. Only groups a rebuild can
            # actually help: applied past the cover, or a fresh marker
            # needed as release evidence.
            lag = np.zeros(self.g, dtype=bool)
            if self._sealed:
                s0 = self._sealed[0]
                cap0 = s0["cap"]
                lag = (cap0 > 0) & (
                    ((self._snap_cover < cap0)
                     & (self.applied_index > self._snap_cover))
                    | ((self._snap_cover >= cap0)
                       & (self._snap_seq <= s0["seq"])))
            due = np.nonzero(((delta >= cad) | lag) & ~self._fenced
                             & (self.applied_index > 0))[0]
            if due.size == 0:
                return
            # Build cap scales with the fleet so steady-state cover
            # refresh keeps pace with rotation at large G; laggards
            # outrank merely-due groups under the cap.
            cap_n = max(SNAP_BUILD_MAX_PER_PASS, self.g // 8)
            if due.size > cap_n:
                prio = delta[due] + np.where(lag[due], 1 << 32, 0)
                order = np.argsort(-prio, kind="stable")
                due = due[order[:cap_n]]
            m_last = self.rn.m_last
            ring = self.rn.m_ring
            w = self.cfg.window
            cand: List[Tuple[int, int, int, object]] = []
            for g in due.tolist():
                idx = int(self.applied_index[g])
                last = int(m_last[g])
                # Term at idx from the host ring mirror: valid only
                # while idx is inside the mirrored window (committed
                # slots never rewrite, so mirror staleness is safe; a
                # group at the window edge catches the next pass).
                if idx <= last - w or idx > last:
                    continue
                term = int(ring[g, idx % w])
                if term <= 0:
                    continue
                cand.append((g, idx, term, self.conf.conf_state(g)))
            if cand:
                # App-state capture for the whole build batch at once:
                # with the plane on this is ONE padded device gather
                # instead of a host dict walk per group under _lock.
                blobs = self._snap_data_many([g for g, *_ in cand])
                builds = [(g, idx, term, blob, cs)
                          for (g, idx, term, cs), blob
                          in zip(cand, blobs)]
        if not builds:
            return
        built: List[Tuple[int, int, int]] = []
        for g, idx, term, data, cs in builds:
            snap = Snapshot(
                metadata=SnapshotMetadata(
                    index=idx, term=term, conf_state=cs),
                data=data)
            try:
                self._snapper(g).save_snap(snap)
            except Exception as e:  # noqa: BLE001 — classified below
                # tmp+rename is all-or-nothing: a failed build leaves
                # the previous file intact and the WAL still holds
                # everything, so skip-and-retry is loss-free (and each
                # attempt opens a FRESH tmp file — no retried-fsync
                # dirty-page hazard). ENOSPC enters back-pressure.
                self.stats["snap_build_errors"] = (
                    self.stats.get("snap_build_errors", 0) + 1)
                if is_disk_full(e):
                    self._enter_disk_full()
                    break
                continue
            built.append((g, idx, term))
        if not built:
            return
        rows = np.array(built, np.int64)
        marker = (RT_SNAPMARK, _pack_rows(WAL_SNAPMARK_DTYPE, {
            "group": rows[:, 0], "index": rows[:, 1],
            "term": rows[:, 2]}))
        seq = self._append_synced([marker])
        if seq is None:
            return  # files exist; the marker retries a later pass
        fresh = set()
        with self._lock:
            if self._crashed:
                return
            for g, idx, term in built:
                if idx > int(self._snap_file_idx[g]):
                    self._snap_file_idx[g] = idx
                    self._snap_file_term[g] = term
                    fresh.add(g)  # new file; same-idx catch-up
                    # rebuilds overwrite in place
                if idx >= int(self._snap_cover[g]):
                    self._snap_cover[g] = idx
                    self._snap_seq[g] = max(int(self._snap_seq[g]),
                                            seq)
            self.stats["snapshots_built"] = (
                self.stats.get("snapshots_built", 0) + len(built))
        for g, idx, _t in built:
            pruned = self._snapper(g).retain(self.snap_keep)
            self.stats["snap_files_pruned"] = (
                self.stats.get("snap_files_pruned", 0) + pruned)
            self._snap_file_count += (1 if g in fresh else 0) - pruned
            # Advance the device ring floor to the snapshot point
            # (staged on the rawnode, clamped to commit at the round
            # head): auto_compact's conservative floor trails applied
            # by window//2; this reclaims the rest of the headroom.
            self.rn.compact(g, idx)

    def _rotate_and_release(self) -> None:
        """Seal the tail past the byte threshold, checkpoint the new
        tail, release every sealed segment the fleet-min snapshot
        cover clears, and raise wal_pinned when the backlog of
        unreleasable segments crosses the threshold."""
        rot = self.wal_rotate_bytes
        fail: Optional[BaseException] = None
        ckpt_full = False
        release_meta: Optional[int] = None
        anomaly: Optional[Dict] = None
        with self._lock:
            if self._crashed:
                return
            with self._wal_io:
                if self._wal_closed:
                    return
                try:
                    if (self.wal.tail_offset()
                            >= rot + self._tail_ckpt_bytes):
                        seq = int(self.wal.tail_seq())
                        cap = self._dur_last.copy()
                        # cut() fdatasyncs the sealed segment's fd
                        # before switching: seal == durable, and cap
                        # (folded only after covering fsyncs) bounds
                        # every entry index the segment holds.
                        self.wal.cut(self._wal_meta + 1)
                        self._sealed.append(
                            {"seq": seq, "meta": self._wal_meta,
                             "cap": cap})
                        self._wal_meta += 1
                        self._tail_ckpt_bytes = 0
                        self.stats["wal_cuts"] = (
                            self.stats.get("wal_cuts", 0) + 1)
                        self._need_ckpt = True
                except Exception as e:  # noqa: BLE001 — a failed cut
                    # leaves the native tail state unknowable: the
                    # fail-stop arm, like any failed fsync.
                    fail = e
                if fail is None and self._need_ckpt:
                    # Checkpoint ATOMICALLY with the cut (still under
                    # _lock): no install can slip a newer hardstate
                    # into the sealed segment after our capture, so
                    # everything a release reclaims is genuinely
                    # superseded by this copy.
                    try:
                        ckpt = self._checkpoint_records_locked()
                        for rt, d in ckpt:
                            self.wal.append(rt, d)
                        self.wal.flush(sync=True)
                        self._tail_ckpt_bytes += sum(
                            len(d) + 16 for _rt, d in ckpt)
                        cseq = int(self.wal.tail_seq())
                        self._last_sync_seq = cseq
                        self._ckpt_seq = cseq
                        self._need_ckpt = False
                        cov = self._snap_file_idx > 0
                        self._snap_seq[cov] = np.maximum(
                            self._snap_seq[cov], cseq)
                    except Exception as e:  # noqa: BLE001
                        if is_disk_full(e):
                            ckpt_full = True  # retry next pass
                        else:
                            fail = e
            if fail is None and self._sealed and self._ckpt_seq >= 0:
                k = 0
                for s in self._sealed:
                    if s["seq"] >= self._ckpt_seq:
                        break  # its checkpoint lives in a later
                        # segment only once a NEWER one is written
                    need = s["cap"] > 0
                    if not bool(np.all(~need | (
                            (self._snap_cover >= s["cap"])
                            & (self._snap_seq > s["seq"])))):
                        break  # prefix-only: later segments need this
                        # one's predecessors gone first anyway
                    k += 1
                if k:
                    release_meta = (
                        self._sealed[k]["meta"]
                        if k < len(self._sealed) else self._wal_meta)
                    del self._sealed[:k]
            if fail is None:
                if len(self._sealed) > self.wal_pinned_segments:
                    if not self._wal_pinned_flag:
                        self._wal_pinned_flag = True
                        self.stats["wal_pinned_events"] = (
                            self.stats.get("wal_pinned_events", 0) + 1)
                        s = self._sealed[0]
                        lag = (s["cap"] > 0) & (
                            (self._snap_cover < s["cap"])
                            | (self._snap_seq <= s["seq"]))
                        gap = np.where(
                            lag, s["cap"] - self._snap_cover, -1)
                        self._pinned_group = (
                            int(np.argmax(gap)) if lag.any() else -1)
                        anomaly = {
                            "segments": len(self._sealed),
                            "oldest_seq": int(s["seq"]),
                            "group": self._pinned_group,
                            "gap": int(gap.max()) if lag.any() else 0,
                            "fenced": bool(
                                self._fenced[self._pinned_group])
                            if self._pinned_group >= 0 else False,
                        }
                else:
                    # Edge-triggered: re-arms after the backlog drains.
                    self._wal_pinned_flag = False
                    self._pinned_group = -1
        if fail is not None:
            self._io_fail_stop("rotate", fail)
            return
        if ckpt_full:
            self._enter_disk_full()
            return
        if release_meta is not None:
            with self._wal_io:
                if not self._wal_closed:
                    try:
                        n = self.wal.release_before(release_meta)
                    except Exception as e:  # noqa: BLE001
                        self._io_fail_stop("release", e)
                        return
                    self.stats["wal_segments_released"] = (
                        self.stats.get("wal_segments_released", 0)
                        + n)
        if anomaly is not None:
            _log.warning(
                "member %d: wal_pinned — %d sealed segment(s) "
                "unreleasable, pinned by group %s (cover gap %s%s)",
                self.id, anomaly["segments"], anomaly["group"],
                anomaly["gap"],
                ", fenced" if anomaly["fenced"] else "")
            if self.fleet is not None:
                self.fleet.raise_anomaly("wal_pinned", anomaly)

    def _ring_full(self, group: int) -> bool:
        """Host twin of the device propose-headroom clamp: occupancy
        (last minus compaction floor) has reached the window minus the
        per-round proposal quota, so a staged proposal would be
        dropped on device anyway. Refusing HERE makes the
        back-pressure typed — counted, health-visible — instead of a
        silent device-side drop."""
        occ = int(self.rn.m_last[group]) - int(self.rn.m_snap[group])
        return occ >= self.cfg.window - self.cfg.max_props_per_round

    # -- WAL-commit worker (async group-commit pipeline, ISSUE 13) -------------

    def _wal_commit_loop(self) -> None:
        """Dedicated persistence stage: swap the open double buffer,
        optionally dwell (max-delay/max-bytes group-commit window) so
        more rounds' batches coalesce, write + fsync ONCE for the whole
        wave, fold the durable mirrors, then release every covered
        batch's apply/send in submission order. Guarded like the drain
        worker: an escaping storage/transport fault is fatal to the
        member, never swallowed."""
        try:
            while True:
                idle = False
                with self._wal_cv:
                    while not self._wal_pending and not self._wal_stop:
                        if (self.snap_cadence is not None
                                or self.wal_rotate_bytes is not None):
                            # Lifecycle on: bounded wait so cadence
                            # builds, cuts and releases keep making
                            # progress through idle gaps — without the
                            # tick, a quiet pipeline would freeze the
                            # lifecycle plane until the next write.
                            self._wal_cv.wait(WAL_LIFECYCLE_TICK_S)
                            if (not self._wal_pending
                                    and not self._wal_stop):
                                idle = True
                                break
                        else:
                            self._wal_cv.wait()
                    wave = self._wal_pending
                    self._wal_pending = []
                    stopping = self._wal_stop
                if idle and not wave and not stopping:
                    # Idle lifecycle tick: still THIS thread, so every
                    # cut/checkpoint stays serialized with wave appends.
                    self._lifecycle_pass()
                    continue
                if not wave:
                    return  # stop() with nothing pending
                nbytes = sum(g.nbytes for g in wave)
                if self._wal_max_delay > 0 and not stopping:
                    deadline = time.monotonic() + self._wal_max_delay
                    while nbytes < self._wal_max_bytes:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            break
                        with self._wal_cv:
                            if (not self._wal_pending
                                    and not self._wal_stop):
                                self._wal_cv.wait(rem)
                            more = self._wal_pending
                            self._wal_pending = []
                            stopping = self._wal_stop
                        wave.extend(more)
                        nbytes += sum(g.nbytes for g in more)
                        if stopping:
                            break
                if self._m_wal_depth is not None:
                    self._m_wal_depth.set(0)
                self._commit_wave(wave, nbytes)
                if stopping:
                    with self._wal_cv:
                        if not self._wal_pending:
                            return
        except FailpointPanic:
            # Injected crash (chaos harness) at the pipeline kill
            # point; finish the kill if the site was armed with the
            # bare 'panic' action (see _drain_loop).
            _log.info("member %d: injected crash (WAL-commit worker)",
                      self.id)
            if not self._crashed:
                self.crash()
        except Exception:  # noqa: BLE001 — fatal: log + stop the member
            _log.exception(
                "member %d: WAL-commit worker died; stopping member",
                self.id)
            self.stats["walpipe_dead"] = (
                self.stats.get("walpipe_dead", 0) + 1)
            self.stop()

    def _commit_wave(self, wave: List[_PersistGroup],
                     nbytes: int) -> None:
        """Write + group-commit one wave, then run the ordered release
        barrier. Never called with member locks held; takes _wal_io
        around every handle touch (crash()/stop() close under it) and
        _lock only for the mirror fold."""
        must_sync = any(g.must_sync for g in wave)
        recs = [rec for g in wave for rec in g.records]
        i = 0
        while True:
            try:
                with self._wal_io:
                    if self._wal_closed:
                        return  # crashed: wave torn away like a kill
                    while i < len(recs):
                        rt, data = recs[i]
                        self.wal.append(rt, data)
                        i += 1
                    # bytes to the fd; NOT yet durable
                    self.wal.flush(sync=False)
            except Exception as e:  # noqa: BLE001 — IO-error contract
                if is_disk_full(e):
                    # ENOSPC at the fault seam (nothing written):
                    # back-pressure OUTSIDE _wal_io so crash()/stop()
                    # can still take the handle lock, then retry the
                    # SAME record. The wave's acks stay withheld the
                    # whole time — the release barrier below never ran.
                    self._enter_disk_full()
                    if self._dwell_disk_full():
                        continue
                    return
                self._io_fail_stop("write", e)
                return
            break
        self._exit_disk_full()
        # The pipeline's chaos window: records written, fsync pending,
        # nothing released/acked. Outside _wal_io so a crash() action
        # at the site can take _lock -> _wal_io itself.
        fp(self._fp_before_release)
        tw_ns = time.monotonic_ns()  # fsync start (fsync_wait stamp)
        tf = time.perf_counter()
        if must_sync:
            try:
                with self._wal_io:
                    if self._wal_closed:
                        return
                    self.wal.flush(sync=True)
                    # Wave durable in the current tail (cuts happen
                    # only on THIS worker, so every record appended
                    # above landed in it): snapshot-install covers
                    # fold with this seq as their evidence segment.
                    self._last_sync_seq = int(self.wal.tail_seq())
            except Exception as e:  # noqa: BLE001 — first failed fsync
                # Fail-stop releasing NOTHING covered by the failed
                # window: every batch queued behind this group-commit
                # keeps its acks/sends/applies withheld forever
                # (ATC'19: a retried fsync can report success over
                # already-dropped dirty pages).
                self._io_fail_stop("fsync", e)
                return
        dt_sync = time.perf_counter() - tf
        td_ns = time.monotonic_ns()  # fsync completion (fsync stamp)
        lifts: List[int] = []
        with self._lock:
            if self._crashed:
                return
            for g in wave:
                if g.wm is not None:
                    self._apply_wm_locked(g.wm, must_sync, g.gens)
                if g.on_synced is not None:
                    g.on_synced()
            lifts = self._fence_lift_locked()
        self._fence_lift_apply(lifts)
        if must_sync:
            self.stats["wal_fsyncs"] = self.stats.get("wal_fsyncs", 0) + 1
            self.stats["fsync_s"] = (
                self.stats.get("fsync_s", 0.0) + dt_sync)
            if self._h_fsync is not None:
                self._h_fsync.observe(dt_sync)
            if self.fleet is not None:
                # Gray-failure feed (see _wal_write_sync): sustained
                # slow group-commits raise member_limping.
                self.fleet.observe_fsync(dt_sync)
            # Amortization accounting rides the fsyncs only: an idle
            # no-sync wave covering empty rounds must not inflate the
            # rounds-per-fsync ratio the pipeline is judged by.
            rounds = sum(len(g.readys) for g in wave)
            self.stats["wal_fsync_rounds"] = (
                self.stats.get("wal_fsync_rounds", 0) + rounds)
            self.stats["wal_fsync_bytes"] = (
                self.stats.get("wal_fsync_bytes", 0) + nbytes)
            if self._m_wal_batches is not None:
                # Round-Ready batches only: readys-less submissions
                # (conf records, snapshot installs) must not inflate
                # the coverage metric, and the histogram must agree
                # with the health op's rounds_per_fsync ratio.
                if rounds:
                    self._m_wal_batches.observe(rounds)
                self._m_wal_bytes.observe(nbytes)
        if self.tracer is not None:
            # The covering group-commit's instants, for every traced
            # key in the wave: fsync_wait at fsync start (queue half),
            # fsync at completion — the satellite contract that keeps
            # the SLO hop table telescoping with the pipeline on.
            for g in wave:
                for keys in g.traced:
                    self.tracer.stamp_many(keys, "fsync_wait", tw_ns)
                    self.tracer.stamp_many(keys, "fsync", td_ns)
        fp(self._fp_after_save)  # fsync'd-but-unreleased kill window
        # Ordered release barrier: acks, sends and applies of a batch
        # leave ONLY here, after its covering fsync — persist-before-
        # send/ack by construction, not by timing.
        now = time.monotonic()
        for g in wave:
            if self._m_wal_release is not None and g.readys:
                self._m_wal_release.observe(now - g.t_submit)
            for rd in g.readys:
                self._apply_and_send(rd)
        # Lifecycle work rides the commit worker after the wave's
        # release — same thread as every cut/checkpoint, so segment
        # rotation never races the wave appends above.
        self._lifecycle_pass()

    def _apply_and_send(self, rd: BatchedReady) -> None:
        if self._crashed:
            return  # dead members neither apply nor send
        t0 = time.perf_counter()
        conf_changed: List[int] = []
        auto_leave_rows: List[int] = []
        io_fail: Optional[Tuple[str, BaseException]] = None
        with self._lock:
            if self._crashed:
                return  # re-check under _lock: crash() closed the WAL
            # 2. apply committed payloads (persist already happened in
            #    _process_readys; the batch fsync precedes every send).
            #    Conf-change entries apply to the membership control
            #    plane instead of the KV state machine: the new config
            #    flips the device voter/learner/in_joint lanes via one
            #    bulk mask upload after the loop (ref: raft.go:896
            #    applyConfChange; SURVEY §2.1 host-side control plane).
            for row, items in rd.committed:
                for i, _t, d, et in items:
                    if et == 0:
                        if d:
                            self.kvs[row].apply(d)
                    else:
                        self._apply_conf_entry(
                            row, i, d or b"", et, conf_changed,
                            auto_leave_rows)
                    self.applied_index[row] = i
            if conf_changed:
                # WAL-record the new configs before anything downstream
                # of them can be acknowledged; the next batch fsync
                # covers the record, and a crash before it re-derives
                # the state from the (already fsync'd) entries at
                # _replay.
                conf_changed = sorted(set(conf_changed))
                rows = np.asarray(conf_changed)
                packed = self.conf.pack_groups(rows)
                if self._wal_worker is not None:
                    # Pipeline mode: the worker owns the handle, so the
                    # record rides the open buffer — same durability
                    # contract (the next group-commit fsync covers it,
                    # and a crash before that re-derives the config
                    # from the already-fsync'd entries at _replay).
                    self._wal_submit_locked([(RT_CONF_BATCH, packed)],
                                            must_sync=False)
                else:
                    try:
                        with self._wal_io:
                            if not self._wal_closed:
                                self.wal.append(RT_CONF_BATCH, packed)
                    except Exception as e:  # noqa: BLE001 — IO contract
                        if is_disk_full(e):
                            # Can't dwell under _lock: SKIP the record.
                            # Safe by the same argument as a crash
                            # before it lands — the config re-derives
                            # from the (already-fsync'd) conf entries
                            # at _replay; the next conf change or
                            # snapshot re-records full state.
                            self._enter_disk_full()
                            self.stats["conf_rec_skipped"] = (
                                self.stats.get("conf_rec_skipped", 0)
                                + 1)
                        else:
                            # Unrecoverable write fault: defer the
                            # fail-stop to after the lock release
                            # (crash() takes _lock itself).
                            io_fail = ("write", e)
                # Stage the device masks UNDER the same lock as the
                # conf mutation (member._lock -> rn._lock nesting is
                # established — install_snapshot_state does the same):
                # reading or staging after release races deliver()'s
                # snapshot conf restore — torn mask planes, or a stale
                # older config overwriting a newer staging for the
                # same row (rn._pending_conf is last-writer-wins).
                self.rn.set_membership_many(rows,
                                            *self.conf.masks(rows))
                self._update_conf_gauges()
            # 3a. build outbound batch (MsgSnap carries app state at the
            #     host's applied watermark, ≥ the device floor after
            #     step 2; the floor metadata rides in m.index/log_term)
            out: List[Tuple[int, Message]] = []
            w = self.cfg.window
            for row, m in rd.messages:
                if int(m.type) == T_SNAP:
                    idx = int(self.applied_index[row])
                    # Term at the applied watermark, from THIS round's
                    # ring row (captured in the Ready): the drain
                    # worker may run rounds behind the device, and the
                    # live ring slot could have wrapped to a different
                    # entry by now. Below/at the floor, the floor term
                    # rides in the message (m.log_term) — the receiver
                    # persists it and restores its ring floor from it.
                    ring_row = rd.snap_rings.get(row)
                    t = (
                        int(ring_row[idx % w])
                        if idx > m.index and ring_row is not None
                        else m.log_term
                    )
                    m.snapshot = Snapshot(
                        # The config at the snapshot point rides the
                        # metadata (raft.proto ConfState): conf entries
                        # in the skipped log never reach the receiver,
                        # so the snapshot must carry membership or a
                        # rejoining member restores data without its
                        # config (ref: confchange/restore.go).
                        metadata=SnapshotMetadata(
                            index=idx, term=t,
                            conf_state=self.conf.conf_state(row)),
                        # One-row capture on the rare catch-up path
                        # (two-tier blob when the plane is on).
                        data=self._snap_data_many([row])[0],
                    )
                out.append((row, m))
        if io_fail is not None:
            self._io_fail_stop(*io_fail)
            return
        if conf_changed:
            self._post_conf_apply(conf_changed, auto_leave_rows)
        # Apply instant captured here, stamped at the END of this
        # function: "apply" retires a span, and a same-round
        # append+commit (solo group) must take its "send" stamp first.
        tr_apply_ns = (
            time.monotonic_ns()
            if self.tracer is not None and rd.traced_commit else 0
        )
        # 2b. surface ReadIndex progress to waiting readers (after
        #     apply: applied_index moved under the same round).
        if rd.read_opened or rd.read_states or rd.committed:
            with self._read_cv:
                for row, seq in rd.read_opened:
                    self._read_opened[row] = seq
                for row, seq, idx in rd.read_states:
                    self._read_results[row] = (seq, idx)
                self._read_cv.notify_all()
        t1 = time.perf_counter()
        self.stats["apply_s"] += t1 - t0
        if self._h_phase is not None:
            self._h_phase["apply"].observe(t1 - t0)
        if self._m_ap_slots is not None and rd.committed:
            ps = self.rn.plane_stats
            self._m_ap_slots.set(ps["slots_hw"])
            self._m_ap_leases.set(ps["active_leases"])
            self._m_ap_overflow.set(ps["overflow_rows"])
            we = int(ps["watch_events"])
            if we > self._ap_we_prev:
                self._m_ap_watch.inc(we - self._ap_we_prev)
                self._ap_we_prev = we
        # 3b. send OUTSIDE the lock: delivery takes the receiver's lock,
        #     and two members sending to each other must not deadlock.
        # "send" = the instant this round's outbound batch is handed to
        # the transport — captured BEFORE the hand-off (the wire/peer
        # clock starts here, not after local serialization returned),
        # stamped only if something actually left (a round that
        # persisted a traced entry but transmitted nothing — transport
        # detached, nothing outbound — must not fabricate a send hop).
        tr_send_ns = time.monotonic_ns() if self.tracer is not None else 0
        sent_any = False
        if out and self._send is not None:
            self._send(self.id, out)
            sent_any = True
        blk = rd.msg_block
        if blk is not None and len(blk):
            if self._send_block is not None:
                self._send_block(self.id, blk)
                sent_any = True
            elif self._send is not None:
                from .msgblock import block_messages

                self._send(self.id, block_messages(blk))
                sent_any = True
        if self.tracer is not None:
            if rd.traced_entries and sent_any:
                # On the leader the batch carries the entry's MsgApp;
                # on a follower the same round's block carries its
                # MsgAppResp — either way, the ack/replication clock
                # starts here.
                self.tracer.stamp_many(rd.traced_entries, "send",
                                       tr_send_ns)
            if rd.traced_commit:
                # Terminal stamp (retires the span) at the instant the
                # apply loop finished above.
                self.tracer.stamp_many(rd.traced_commit, "apply",
                                       tr_apply_ns)
        dt = time.perf_counter() - t1
        self.stats["send_s"] += dt
        if self._h_phase is not None:
            self._h_phase["send"].observe(dt)

    # -- membership (joint-consensus conf changes, ISSUE 11) -------------------

    def _apply_conf_entry(self, row: int, index: int, data: bytes,
                          etype: int, changed: List[int],
                          auto_rows: List[int]) -> None:
        """Apply one committed conf-change entry to the control plane
        (caller holds _lock). Undecodable bytes and deterministic
        refusals are logged and skipped — every member sees the same
        bytes at the same index, so every member skips identically."""
        try:
            cc = decode_conf_entry(data, etype)
        except ValueError:
            _log.warning("member %d: undecodable conf entry g%d i%d",
                         self.id, row, index)
            return
        err = self.conf.apply(row, index, cc)
        if err is not None:
            if err != "stale":
                _log.info("member %d: conf change g%d i%d refused: %s",
                          self.id, row, index, err)
            return
        changed.append(row)
        if self.conf.in_joint[row] and self.conf.auto_leave[row]:
            auto_rows.append(row)

    def _post_conf_apply(self, changed: List[int],
                         auto_rows: List[int]) -> None:
        """Follow-on actions a leader owes a freshly applied config
        (the masks themselves were staged under _lock by the caller):
        an immediate append/probe to changed membership
        (switchToConfig → maybeSendAppend) and the auto-leave proposal
        for implicit joint entries (raft.go advance() proposing the
        zero ConfChangeV2)."""
        for row in changed:
            if self.rn.is_leader(row):
                # Newly admitted members must be contacted now, not at
                # the next heartbeat timeout.
                self.rn.poke_append(row)
        for row in sorted(set(auto_rows)):
            if self.rn.is_leader(row):
                self._propose_leave_joint(row)
        self._work.set()

    def _propose_leave_joint(self, row: int) -> None:
        """Propose the empty ConfChangeV2 that exits an auto-leave
        joint config, at most once per row per cooldown window (a
        duplicate leave landing after the exit refuses idempotently at
        apply)."""
        now = time.monotonic()
        if now - self._joint_prop.get(row, 0.0) < 1.0:
            return
        self._joint_prop[row] = now
        self.rn.propose(row, ConfChangeV2().marshal(),
                        etype=int(EntryType.EntryConfChangeV2))
        self._work.set()

    def _joint_sweep(self) -> None:
        """Fallback auto-leave driver (run_round, time-gated): the
        leave is normally proposed at the joint entry's apply on the
        leader, but leadership can move mid-joint — the NEW leader must
        exit the joint config or the group is stuck needing both
        quorums forever (the classic place multi-raft breaks; the
        check_config_safety 'joint always exited' clause watches it)."""
        now = time.monotonic()
        if now < self._next_joint_sweep:
            return
        self._next_joint_sweep = now + 0.25
        with self._lock:
            rows = np.nonzero(self.conf.in_joint
                              & self.conf.auto_leave)[0]
        for row in rows.tolist():
            if self.rn.is_leader(row):
                self._propose_leave_joint(row)

    def _update_conf_gauges(self) -> None:
        self._g_joint.set(int(self.conf.in_joint.sum()))
        self._g_learners.set(int(self.conf.learner.sum()))

    def propose_conf(self, group: int, cc) -> bool:
        """Propose a membership change through `group`'s log (leaders
        only — returns False otherwise so callers redirect like
        clients). Accepts ConfChange or ConfChangeV2; always marshals
        as an EntryConfChangeV2 record. A new change while the group is
        mid-joint is refused loudly (ConfChangeError) — one config
        transition in flight per group, the reference's
        pendingConfIndex discipline — except the empty leave-joint."""
        cc2 = cc.as_v2()
        if not self.rn.is_leader(group):
            return False
        with self._lock:
            if self.conf.in_joint[group] and not cc2.leave_joint():
                raise ConfChangeError(
                    f"group {group} is mid-joint; only the leave-joint "
                    "change may be proposed")
        self.rn.propose(group, cc2.marshal(),
                        etype=int(EntryType.EntryConfChangeV2))
        self._work.set()
        return True

    # Learner promotable once its match covers this share of the
    # leader's (ref: server.go:1473 readyPercent).
    LEARNER_READY_PERCENT = 0.9

    def reconfig(self, action: str, target_member: int, groups,
                 joint: bool = False) -> Dict[int, str]:
        """Batched membership admin over the groups this member leads:
        ``add-learner`` / ``promote`` (catch-up-gated) / ``remove``.
        Returns a per-group result string: "ok" (proposed), or why not
        ("not-leader", "not-learner", "not-ready:<match>/<last>",
        "self", "refused:<reason>"). ``joint=True`` proposes the change
        with an implicit joint transition (enter-joint at apply,
        auto-leave once the joint config commits) — the batched
        joint-consensus path."""
        t = int(target_member)
        if not 1 <= t <= self.cfg.num_replicas:
            raise ValueError(
                f"member {t} outside replica capacity "
                f"R={self.cfg.num_replicas}")
        kind = {
            "add-learner": ConfChangeType.ConfChangeAddLearnerNode,
            "promote": ConfChangeType.ConfChangeAddNode,
            "remove": ConfChangeType.ConfChangeRemoveNode,
        }.get(action)
        if kind is None:
            raise ValueError(f"unknown reconfig action {action!r}")
        match = self.rn.peer_match() if action == "promote" else None
        results: Dict[int, str] = {}
        for g in groups:
            g = int(g)
            if not self.rn.is_leader(g):
                results[g] = "not-leader"
                continue
            if action == "promote":
                with self._lock:
                    is_learner = bool(self.conf.learner[g, t - 1])
                if not is_learner:
                    results[g] = "not-learner"
                    continue
                # Catch-up gate (the PR 1 promote_member gate, read
                # from the leader's device progress view): the learner
                # must cover >= LEARNER_READY_PERCENT of the leader's
                # own log before its vote starts counting.
                lead_last = int(self.rn.m_last[g])
                lm = int(match[g, t - 1])
                if lead_last > 0 and (
                        lm < lead_last * self.LEARNER_READY_PERCENT):
                    results[g] = f"not-ready:{lm}/{lead_last}"
                    continue
            if action == "remove" and t == self.id:
                # Removing the leader through itself wedges the group's
                # proposals mid-flight; transfer leadership away first.
                results[g] = "self"
                continue
            cc = ConfChangeV2(changes=[ConfChangeSingle(kind, t)])
            if joint:
                cc.transition = (
                    ConfChangeTransition.ConfChangeTransitionJointImplicit)
            try:
                results[g] = ("ok" if self.propose_conf(g, cc)
                              else "not-leader")
            except ConfChangeError as e:
                results[g] = f"refused:{e}"
        return results

    def wait_transfers(self, groups, to_member: int,
                       timeout: float = 5.0) -> Tuple[List[int],
                                                      List[int]]:
        """Bounded wait for staged leadership transfers: a group is
        done once this member no longer leads it (the transferee's
        TimeoutNow campaign displaced us) or it already names the
        target as leader. Returns (done, pending-at-timeout)."""
        pending = {int(g) for g in groups}
        done: List[int] = []
        deadline = time.monotonic() + timeout
        while pending and time.monotonic() < deadline:
            for g in list(pending):
                if (not self.rn.is_leader(g)
                        or self.rn.lead(g) == to_member):
                    pending.discard(g)
                    done.append(g)
            if pending:
                time.sleep(0.01)
        return sorted(done), sorted(pending)

    def conf_snapshot(self) -> Dict[str, object]:
        """Membership rollup for checkers/admin (checker duck-type:
        functional.checker.check_config_safety)."""
        with self._lock:
            c = self.conf
            return {
                "voters": [tuple((np.nonzero(c.voter[g])[0]
                                  + 1).tolist())
                           for g in range(self.g)],
                "voters_out": [tuple((np.nonzero(c.voter_out[g])[0]
                                      + 1).tolist())
                               for g in range(self.g)],
                "learners": [tuple((np.nonzero(c.learner[g])[0]
                                    + 1).tolist())
                             for g in range(self.g)],
                "in_joint": c.in_joint.copy(),
                "applied_index": c.applied_index.copy(),
                "epoch": c.epoch.copy(),
                "refused": int(c.refused),
            }

    def conf_history(self, group: int) -> List[Dict]:
        with self._lock:
            return self.conf.history(group)

    # -- durability fence ------------------------------------------------------

    def _fence_lift_locked(self) -> List[int]:
        """Collect fenced groups that re-proved durability (caller
        holds _lock); flips the host mirror, leaves the device edit to
        _fence_lift_apply (outside the lock). Two sufficient proofs:

        * **index**: the durable log reaches the watermark point again
          (``dur_last >= wm_last``) — every pre-crash promise is backed
          by fsync'd bytes once more;
        * **term**: the durable log ENDS in a term above the
          watermark's (``dur_term > wm_term``). A later-term leader was
          elected by a quorum of non-fenced members (this member
          granted nothing while fenced), so by Leader Completeness its
          log carries every entry committed at terms <= wm_term; the
          prefix-matched append that landed the later-term entry
          therefore proves the un-recovered old suffix could never
          have been committed. Without this rule a FALSE fence — a
          kill mid-write persisting a batch's watermark but not its
          (never-acked) entries — wedges an idle group forever: the
          new leader's log is legitimately shorter than the
          overshooting watermark, so the index proof alone never
          arrives.
        """
        if not self.fence_enabled or not self._fenced.any():
            return []
        lifts: List[int] = []
        for row in np.nonzero(self._fenced)[0]:
            if (self._dur_last[row] >= self._wm_last[row]
                    or self._dur_term[row] > self._wm_term[row]):
                self._fenced[row] = False
                lifts.append(int(row))
        return lifts

    def _fence_lift_apply(self, lifts: List[int]) -> None:
        """Stage the device-side fence drop for healed groups (the
        rawnode applies it at the head of the next round) and move the
        gauge. The durable log re-reaching the watermark point means
        every pre-crash promise is backed by fsync'd bytes again —
        terms at a given index never regress across leaders, so the
        comparison needs no term recheck."""
        if not lifts:
            return
        for row in lifts:
            self.rn.set_fence(row, False)
        remaining = int(self._fenced.sum())
        self._g_fenced.set(remaining)
        _log.info(
            "member %d: durability fence lifted for group(s) %s "
            "(%d still fenced)", self.id, lifts[:16], remaining)
        self._work.set()

    def health(self) -> Dict[str, object]:
        """Fence/catch-up visibility (admin 'health' op): per-group
        fenced state, index gap to the durable watermark, and the boot
        WAL-tail classification (walog tail_state)."""
        with self._lock:
            fenced = np.nonzero(self._fenced)[0]
            gaps = {
                int(g): int(self._wm_last[g] - self._dur_last[g])
                for g in fenced
            }
            joint_groups = int(self.conf.in_joint.sum())
            learner_slots = int(self.conf.learner.sum())
            conf_applied = int(self.conf.epoch.sum())
            conf_refused = int(self.conf.refused)
        with self._wal_cv:
            wal_depth = len(self._wal_pending)
        fsyncs = int(self.stats.get("wal_fsyncs", 0))
        rounds_covered = int(self.stats.get("wal_fsync_rounds", 0))
        wal_pipe = {
            # Async group-commit pipeline visibility (ISSUE 13): live
            # queue depth, fsync count, and the amortization ratio the
            # pipeline exists for (device rounds whose persistence one
            # fsync covered) — fleet_console's wal-pipe column reads
            # this.
            "enabled": self._wal_worker is not None,
            "queue_depth": wal_depth,
            "fsyncs": fsyncs,
            "rounds_per_fsync": (
                round(rounds_covered / fsyncs, 2) if fsyncs else 0.0),
            "bytes_per_fsync": (
                int(self.stats.get("wal_fsync_bytes", 0) // fsyncs)
                if fsyncs else 0),
            "max_delay_s": self._wal_max_delay,
            "max_bytes": self._wal_max_bytes,
        }
        # Log-lifecycle visibility (ISSUE 17): segments + bytes on
        # disk, the oldest still-pinned sealed segment and the group
        # pinning it, snapshot-file census, and the ring back-pressure
        # high-water — fleet_console's lifecycle columns read this.
        wal_dir = os.path.join(self.dir, "wal")
        wal_segments = 0
        wal_bytes = 0
        try:
            for fname in os.listdir(wal_dir):
                if fname.endswith(".wal"):
                    wal_segments += 1
                    try:
                        wal_bytes += os.path.getsize(
                            os.path.join(wal_dir, fname))
                    except OSError:
                        pass
        except OSError:
            pass
        with self._lock:
            sealed = len(self._sealed)
            oldest = (int(self._sealed[0]["seq"])
                      if self._sealed else -1)
            pinned_group = self._pinned_group
            wal_pinned = self._wal_pinned_flag
        lifecycle = {
            "enabled": (self.snap_cadence is not None
                        or self.wal_rotate_bytes is not None),
            "snap_cadence": self.snap_cadence,
            "snap_keep": self.snap_keep,
            "wal_rotate_bytes": self.wal_rotate_bytes,
            "wal_segments": wal_segments,
            "wal_bytes": wal_bytes,
            "sealed_segments": sealed,
            "oldest_pinned_seq": oldest,
            "pinned_group": int(pinned_group),
            "wal_pinned": bool(wal_pinned),
            "wal_cuts": int(self.stats.get("wal_cuts", 0)),
            "segments_released": int(
                self.stats.get("wal_segments_released", 0)),
            "snapshots_built": int(
                self.stats.get("snapshots_built", 0)),
            "snap_files": int(self._snap_file_count),
            "snap_files_pruned": int(
                self.stats.get("snap_files_pruned", 0)),
            "snap_build_errors": int(
                self.stats.get("snap_build_errors", 0)),
        }
        occ_now = int((self.rn.m_last - self.rn.m_snap).max())
        if occ_now > self._ring_occ_hw:
            self._ring_occ_hw = occ_now
        ring = {
            # Ring back-pressure: occupancy high-water vs the window,
            # and how many proposals the typed ring_full refusal
            # turned away before the device would have dropped them.
            "window": int(self.cfg.window),
            "occ_now": occ_now,
            "occ_high_water": int(self._ring_occ_hw),
            "full_refusals": int(
                self.stats.get("ring_full_refusals", 0)),
        }
        # Device apply plane visibility (ISSUE 19): slot occupancy
        # high-water vs capacity, live lease/watch census, and the
        # lease-read hit ratio — fleet_console's plane columns read
        # this.
        ap: Dict[str, object] = {"enabled": False}
        if self.rn.plane is not None:
            ps = dict(self.rn.plane_stats)
            hits = int(self.stats.get("lease_read_hits", 0))
            falls = int(self.stats.get("lease_read_fallbacks", 0))
            ap = {
                "enabled": True,
                "capacity": int(self.cfg.apply_capacity),
                "watch_slots": int(self.cfg.apply_watch_slots),
                "slots_high_water": int(ps["slots_hw"]),
                "overflow_rows": int(ps["overflow_rows"]),
                "active_leases": int(ps["active_leases"]),
                "dispatches": int(ps["dispatches"]),
                "puts": int(ps["puts"]),
                "dels": int(ps["dels"]),
                "expired": int(ps["expired"]),
                "watch_events": int(ps["watch_events"]),
                "watch_armed": len(self._watches),
                "lease_holders": int(
                    (self.rn.m_lease_ticks > 0).sum()),
                "lease_read_hits": hits,
                "lease_read_fallbacks": falls,
                "lease_hit_ratio": (
                    round(hits / (hits + falls), 4)
                    if hits + falls else 0.0),
            }
        return {
            "wal_pipeline": wal_pipe,
            "lifecycle": lifecycle,
            "ring": ring,
            "apply_plane": ap,
            "fence_enabled": self.fence_enabled,
            # IO-error contract visibility (ISSUE 15): live ENOSPC
            # back-pressure, the fail-stop cause when a storage fault
            # killed this member, and the boot-time salvage record for
            # at-rest corruption amputations.
            "disk_full": self._disk_full,
            "disk_full_waits": int(self.stats.get("disk_full_waits", 0)),
            "fail_stop": self._fail_stop_cause,
            "salvage": self._salvage,
            "wal_tail": (TAIL_NAMES.get(self._tail_state, "unknown")
                         if self._tail_state is not None else "fresh"),
            "fenced_groups": [int(g) for g in fenced],
            "catchup_gap": gaps,
            "boot_fenced": self._boot_fenced,
            # Membership control plane (ISSUE 11): live joint/learner
            # census + applied/refused conf-change totals — the
            # fleet_console joint/learner columns read these.
            "joint_groups": joint_groups,
            "learner_slots": learner_slots,
            "conf_applied": conf_applied,
            "conf_refused": conf_refused,
            "crashed": self._crashed,
            "stopped": self._stopped.is_set(),
        }

    # -- wire ------------------------------------------------------------------

    def deliver(self, group: int, m: Message) -> None:
        """Entry point for the router/transport."""
        if self._stopped.is_set():
            return
        if int(m.type) == int(MessageType.MsgSnap):
            # Restore app state before the device sees the install — all
            # under _lock so run_round's apply step can't interleave
            # stale entries into the freshly restored state.
            idx = m.snapshot.metadata.index
            lifts: List[int] = []
            fail: Optional[Tuple[str, BaseException]] = None
            with self._lock:
                if self._stopped.is_set():
                    # Re-check under _lock: a crash() that won the lock
                    # first has closed the WAL handle this path appends
                    # to (the unlocked check above is advisory only).
                    return
                if idx > self.applied_index[group]:
                    if self._disk_full:
                        # Write-back-pressured: drop the install BEFORE
                        # any state mutates — an install that cannot be
                        # WAL-recorded is a replay hole, and raft
                        # re-sends snapshots (lossy-net semantics; the
                        # dwell cannot run here, it would sit on _lock).
                        self.stats["snap_dropped_disk_full"] = (
                            self.stats.get("snap_dropped_disk_full", 0)
                            + 1)
                        return
                    snap_term = m.snapshot.metadata.term
                    self._restore_data(group, m.snapshot.data, idx)
                    self.applied_index[group] = idx
                    self.rn.install_snapshot_state(group, idx)
                    # WAL-record the snapshot before any post-restore
                    # state can be acknowledged.
                    records: List[Tuple[int, bytes]] = [(
                        RT_SNAPSHOT,
                        _pack_snap(group, idx, snap_term,
                                   m.snapshot.data),
                    )]
                    # Membership rides the snapshot metadata: conf
                    # entries in the skipped log never arrive, so the
                    # carried ConfState supersedes whatever this member
                    # last applied (raft.restore → confchange.Restore).
                    cs = m.snapshot.metadata.conf_state
                    if cs is not None and cs.voters:
                        if self.conf.restore(group, idx, cs):
                            rows = np.asarray([group])
                            records.append((
                                RT_CONF_BATCH,
                                self.conf.pack_groups(rows)))
                            # Stage under the SAME lock as the conf
                            # mutation (see the conf-apply path): a
                            # post-release staging can lose the
                            # last-writer-wins race against a
                            # concurrent apply and leave the device
                            # on the older config.
                            self.rn.set_membership_many(
                                rows, *self.conf.masks(rows))
                            self._update_conf_gauges()
                    wl = wt = None
                    if self.fence_enabled:
                        wl = max(idx, int(self._wm_last[group]))
                        wt = (snap_term if wl == idx
                              else int(self._wm_term[group]))
                        records.append((
                            RT_WATERMARK,
                            _pack_wm(group, wl, wt,
                                     max(idx,
                                         int(self._wm_commit[group])))))

                    def _snap_mirrors(group=group, idx=idx,
                                      snap_term=snap_term,
                                      wl=wl, wt=wt) -> None:
                        # Snapshot-driven heal: the install makes (idx,
                        # snap_term) durable and committed, so the
                        # durable mirrors jump with it and a fence
                        # demanding anything at-or-below idx lifts —
                        # protocol-aware re-convergence needs no log
                        # replay when the quorum ships state directly.
                        # Runs ONLY once the records above are fsync'd
                        # (inline below, or the pipeline's on_synced
                        # callback under _lock).
                        if idx > self._dur_last[group]:
                            self._dur_last[group] = idx
                            self._dur_term[group] = snap_term
                        self._dur_commit[group] = max(
                            self._dur_commit[group], idx)
                        if wl is not None and not self._fenced[group]:
                            self._wm_last[group] = wl
                            self._wm_term[group] = wt
                            self._wm_commit[group] = max(
                                self._wm_commit[group], idx)
                        # Install = durable snapshot cover too (the
                        # full RT_SNAPSHOT record just fsync'd): WAL
                        # segments below idx stop being needed for
                        # this group. Evidence segment = the covering
                        # fsync's tail (file bookkeeping untouched —
                        # there is no FILE, and cadence measures
                        # against the newest file, so a freshly
                        # installed group builds one promptly).
                        if idx >= int(self._snap_cover[group]):
                            self._snap_cover[group] = idx
                            self._snap_seq[group] = max(
                                int(self._snap_seq[group]),
                                int(self._last_sync_seq))

                    if self._wal_worker is not None:
                        # Pipeline mode: the records ride the open
                        # buffer IN ORDER with every pending round
                        # batch; the generation bump makes any
                        # already-submitted (older) batch skip its
                        # now-stale mirror delta for this group, and
                        # the mirror jump itself waits for the covering
                        # fsync via on_synced.
                        self._snap_gen[group] += 1
                        self._wal_submit_locked(
                            records, must_sync=True,
                            on_synced=_snap_mirrors)
                    else:
                        try:
                            # _wal_io nested under _lock (the documented
                            # order): the inline drain writes under
                            # _wal_io WITHOUT _lock now, so the handle
                            # needs its own serialization here too.
                            with self._wal_io:
                                if self._wal_closed:
                                    return
                                for rt, d in records:
                                    self.wal.append(rt, d)
                                self.wal.flush(sync=True)
                                self._last_sync_seq = int(
                                    self.wal.tail_seq())
                        except Exception as e:  # noqa: BLE001
                            # Storage fault mid-install (state already
                            # mutated): fail-stop — the install is
                            # all-or-nothing, and a disk-full dwell
                            # here would sit on _lock. Deferred below:
                            # crash() takes _lock itself.
                            fail = ("snap_install", e)
                        else:
                            # Inline installs bump the generation too:
                            # the drain's mirror fold now runs outside
                            # _lock and guards on it (see
                            # _process_readys).
                            self._snap_gen[group] += 1
                            _snap_mirrors()
                            lifts = self._fence_lift_locked()
            if fail is not None:
                self._io_fail_stop(*fail)
                return
            self._fence_lift_apply(lifts)
        self.rn.step(group, m)
        self._work.set()

    def deliver_block(self, blk) -> None:
        """Batch entry point: payload-free messages as one SoA block
        (no snapshots ever ride a block)."""
        if self._stopped.is_set():
            return
        self.rn.step_block(blk)
        self._work.set()

    # -- API -------------------------------------------------------------------

    def propose(self, group: int, payload: bytes) -> bool:
        """Propose on this member; returns False if this member isn't
        the group's leader (the caller redirects, like etcd clients
        following leader hints) — or while the member sits in ENOSPC
        write-back-pressure (disk_full: accepting a proposal that can
        never persist would just strand the client)."""
        if self._disk_full:
            return False
        if not self.rn.is_leader(group):
            return False
        if self._ring_full(group):
            # Typed ring back-pressure (the disk_full twin): the log
            # ring has no headroom for another proposal this round —
            # the device clamp would silently drop it. Refuse so the
            # caller retries after compaction frees slots.
            self.stats["ring_full_refusals"] = (
                self.stats.get("ring_full_refusals", 0) + 1)
            return False
        self.rn.propose(group, payload)
        self._work.set()
        return True

    def leader_of(self, group: int) -> int:
        """Member id this member believes leads `group` (0 unknown)."""
        return self.rn.lead(group)

    def is_leader(self, group: int) -> bool:
        return self.rn.is_leader(group)

    def campaign(self, groups) -> None:
        self.rn.campaign(np.asarray(groups))
        self._work.set()

    def transfer_leader(self, group: int, target_member: int) -> bool:
        """Hand leadership of `group` to `target_member` (slot+1) —
        the admin rebalancing primitive; campaigns cannot displace a
        healthy leader under pre-vote/check-quorum, transfers can
        (ref: raft.go:1339 MsgTransferLeader, campaignTransfer)."""
        if not self.rn.is_leader(group):
            return False
        if self.rn.plane is not None:
            # Block lease reads for the group BEFORE the transfer
            # stages: the device zeroes the lease lane in the same
            # round the transfer applies, but a read racing the
            # staging window would still see the stale mirror —
            # MsgTimeoutNow bypasses the election-timeout silence the
            # lease safety argument rests on. The block lifts once
            # the mirror reads 0 (linearizable_get).
            with self._lock:
                self._lease_block.add(int(group))
        self.rn.transfer_leader(group, target_member - 1)
        self._work.set()
        return True

    def get(self, group: int, key: bytes) -> Optional[bytes]:
        """Serializable read from local applied state."""
        return self.kvs[group].data.get(key)

    def linearizable_get(self, group: int, key: bytes,
                         timeout: float = 5.0) -> Optional[bytes]:
        """Linearizable read: open a device ReadIndex batch, wait for
        its heartbeat-ack quorum, wait until the local apply watermark
        covers the confirmed index, then read (ref: v3_server.go
        linearizableReadLoop over Ready.ReadStates — here the batch
        runs in the device kernel). Raises on a non-leader member so
        callers redirect like clients following leader hints.

        Lease fast path (cfg.apply_plane): when this member's lease
        lane shows quorum evidence within the last election-timeout
        ticks (minus lease_read_margin for tick skew), no other leader
        can exist — a peer needs a full election timeout of leader
        silence to win, counted in the same tick currency — so the
        local applied state IS linearizable and the read is one host
        lookup with ZERO per-read quorum rounds (ref: raft §6.4 /
        etcd ReadOnlyLeaseBased). Every acknowledged write on this
        group was acknowledged at-or-below the local apply watermark
        (writes ack on this member after apply), and prior ReadIndex
        reads waited for apply too, so serving the applied host tier
        preserves real-time order. Transfers break the silence
        argument (MsgTimeoutNow campaigns immediately): _lease_block
        refuses lease reads from transfer staging until the device
        round zeroes the lane."""
        if not self.rn.is_leader(group):
            raise NotLeaderError(f"group {group}: not leader here")
        if self.rn.plane is not None:
            with self._lock:
                lt = int(self.rn.m_lease_ticks[group])
                if group in self._lease_block:
                    if lt == 0:
                        # Device processed the transfer staging; from
                        # here the mirror is truth again (it stays 0
                        # until quorum evidence re-arms it with no
                        # transfer in flight).
                        self._lease_block.discard(group)
                    lt = 0
                hit = lt >= self.cfg.lease_read_margin
                if hit:
                    self.stats["lease_read_hits"] = (
                        self.stats.get("lease_read_hits", 0) + 1)
                    if self._m_ap_hit is not None:
                        self._m_ap_hit.inc()
                else:
                    self.stats["lease_read_fallbacks"] = (
                        self.stats.get("lease_read_fallbacks", 0) + 1)
                    if self._m_ap_fb is not None:
                        self._m_ap_fb.inc()
            if hit:
                return self._lease_masked_get(group, key)
        # Any batch already opened captured its commit index BEFORE
        # this request; the serving batch must open at-or-after it
        # (the device latches requests arriving mid-batch, so waiting
        # for confirmed seq > the pre-request opened seq is exact).
        # The seq guard alone is not enough: a batch can open in the
        # same device round that commits a write the caller already
        # observed applied (solo groups confirm instantly), so the
        # confirmed index must also cover the apply watermark at
        # request time — every write this caller could have observed
        # locally is at-or-below it.
        with self._read_cv:
            base_open = self._read_opened.get(group, 0)
        base_applied = int(self.applied_index[group])
        self.rn.read_index(group)
        deadline = time.monotonic() + timeout

        def confirmed():
            got = self._read_results.get(group)
            ok = (
                got is not None
                and got[0] > base_open
                and got[1] >= base_applied
            )
            return got if ok else None

        with self._read_cv:
            while True:
                got = confirmed()
                if got is not None:
                    break
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(
                        f"group {group}: ReadIndex quorum not confirmed")
                self._read_cv.wait(rem)
            idx = got[1]
            while self.applied_index[group] < idx:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(
                        f"group {group}: apply lagging read index {idx}")
                self._read_cv.wait(rem)
        return self.kvs[group].data.get(key)

    def crash(self) -> None:
        """Simulated ``kill -9`` for chaos testing: mark the member dead
        and close the WAL handle WITHOUT draining queued Readys — every
        Ready still sitting in ``_ready_q`` (persist not yet run) is
        torn away, exactly the suffix a real crash at this point loses.
        The handle close releases the WAL dir flock so a restarted
        member (a fresh ``MultiRaftMember`` on the same data_dir, booting
        through ``_replay``) can take it in the same process. Closing an
        idle handle flushes at most already-appended-unsynced bytes,
        which only ever makes the survivor MORE durable — never less —
        so no invariant can be violated by the simulation shortcut."""
        with self._lock:
            if self._stopped.is_set():
                return
            self._crashed = True
            self._stopped.set()
            # _wal_io nested under _lock (the documented order): the
            # WAL-commit worker holds _wal_io for the duration of any
            # in-flight write/fsync and NEVER takes _lock while holding
            # it, so this close waits out at most one fsync and can
            # never race the native handle (a close under a live
            # fdatasync is C-level use-after-free).
            with self._wal_io:
                self._wal_closed = True
                try:
                    self._wal_tail_at_crash = self.wal.tail_offset()
                    self.wal.close()
                except WalogError:
                    pass
        # Unpark the WAL-commit worker; pending waves are torn away by
        # its _wal_closed/_crashed gates — exactly the unfsynced,
        # never-acked suffix a real kill at this point loses.
        if self._wal_worker is not None:
            with self._wal_cv:
                self._wal_stop = True
                self._wal_cv.notify_all()
        self._work.set()
        with self._read_cv:
            self._read_cv.notify_all()
        # Unpark the drain worker; queued Readys ahead of the sentinel
        # are discarded by the _crashed gate. The put must be RELIABLE:
        # a put_nowait swallowed by a full queue (crash mid-backpressure
        # is the likeliest crash) parks the worker on get() forever once
        # it drains the gated batches — and stop() after a crash returns
        # at its _stopped check without ever enqueueing a sentinel. A
        # crash FROM the drain worker itself (failpoint action) needs no
        # sentinel: it is unwinding via FailpointPanic.
        if (self._drainer is not None
                and self._drainer is not threading.current_thread()):
            while self._drainer.is_alive():
                try:
                    self._ready_q.put(None, timeout=0.2)
                    break
                except queue_mod.Full:
                    continue

    def stop(self) -> None:
        # Atomic claim: concurrent stop() calls must not both proceed to
        # the WAL close (Event.is_set/set is a check-then-act race).
        with self._lock:
            if self._stopped.is_set():
                return
            self._stopped.set()
        for t in (self._ticker, self._runner):
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=5)
        drainer_done = True
        if self._drainer is not None and self._drainer.is_alive():
            if self._drainer is threading.current_thread():
                # Fatal-fault stop FROM the drain worker (_drain_loop
                # guard): it is exiting anyway; a put(None) here could
                # deadlock on a full queue. Leave the WAL open (the
                # comment below) — process exit closes it.
                drainer_done = False
            else:
                # Timed put, re-checking liveness: a drainer that hit
                # its fatal-fault guard is alive-but-exiting and will
                # never drain a full queue — an untimed put(None) here
                # would hang shutdown (and the WAL flush after it).
                while self._drainer.is_alive():
                    try:
                        self._ready_q.put(None, timeout=0.2)
                        break  # drainer drains all queued, then exits
                    except queue_mod.Full:
                        continue
                self._drainer.join(timeout=60)
                drainer_done = not self._drainer.is_alive()
        # Drain the WAL pipeline DETERMINISTICALLY: the drainer above
        # already submitted every queued Ready, so signaling stop and
        # joining the worker flushes + releases every pending wave —
        # stop() returns with nothing in flight and nothing lost (the
        # stop-during-pending-fsync regression). A stop() issued FROM
        # the worker (its fatal-fault guard) skips the join; the
        # worker is exiting anyway and the close below stays guarded.
        walworker_done = True
        if self._wal_worker is not None and self._wal_worker.is_alive():
            if self._wal_worker is threading.current_thread():
                walworker_done = False
            else:
                with self._wal_cv:
                    self._wal_stop = True
                    self._wal_cv.notify_all()
                self._wal_worker.join(timeout=60)
                walworker_done = not self._wal_worker.is_alive()
        with self._lock:
            with self._wal_io:
                if self._wal_closed:
                    return  # crash() already tore the handle down
                try:
                    self.wal.flush(sync=True)
                except (WalogError, OSError):
                    # Storage fault at shutdown: skip the close-flush.
                    # The unflushed suffix was never released/acked, so
                    # losing it is the crash contract, not data loss —
                    # and retrying an fsync here is exactly what the
                    # IO-error contract forbids.
                    _log.exception(
                        "member %d: final WAL flush failed at stop",
                        self.id)
                if drainer_done and walworker_done:
                    # Never close the WAL under a live drain/WAL-commit
                    # worker — its next append would hit a closed file
                    # and silently drop the queued rounds' persistence.
                    # Leaving it open on a wedged worker is safe:
                    # process exit closes the fd and the CRC chain ends
                    # at the last completed record.
                    self.wal.close()
                    self._wal_closed = True


class InProcRouter:
    """Wires MultiRaftMembers in one process; per-destination worker
    queues preserve per-peer ordering (rafthttp's stream semantics)
    without blocking the sender's round loop."""

    kind = "inproc"

    def __init__(self) -> None:
        self.members: Dict[int, MultiRaftMember] = {}
        self._isolated: set = set()
        self._lock = threading.Lock()
        # Loss counters live on the shared pkg.metrics registry — ONE
        # source of truth for drop classes across routers, fabrics and
        # the telemetry plane (ISSUE 4 satellite). This router keeps
        # per-(member, class) label children plus the child's value at
        # first touch, so stats() still reports per-instance counts
        # while /metrics exposes the process-wide monotone totals.
        self._loss = router_loss_counter()
        self._children: Dict[Tuple[int, str], Tuple[object, float]] = {}

    def _count(self, member_id: int, key: str, n: int = 1) -> None:
        with self._lock:
            ent = self._children.get((member_id, key))
            if ent is None:
                child = self._loss.labels("inproc", str(member_id), key)
                ent = (child, child.value())
                self._children[(member_id, key)] = ent
        ent[0].inc(n)

    def stats(self) -> Dict[int, Dict[str, int]]:
        """Per-member counters: isolated_drop (suppressed by
        isolate()), no_route (target not attached), deliver_error
        (exception swallowed on the deliver path). Values are read back
        from the shared registry (etcd_tpu_router_loss_total), scoped
        to this router instance."""
        with self._lock:
            items = list(self._children.items())
        out: Dict[int, Dict[str, int]] = {}
        for (mid, key), (child, base) in items:
            out.setdefault(mid, {})[key] = int(child.value() - base)
        return out

    def attach(self, m: MultiRaftMember) -> None:
        self.members[m.id] = m
        m._send = self.send
        m._send_block = self.send_block

    def send(self, from_id: int, batch: List[Tuple[int, Message]]) -> None:
        with self._lock:
            if from_id in self._isolated:
                sender_isolated = True
                targets = {}
            else:
                sender_isolated = False
                targets = {
                    to: mem for to, mem in self.members.items()
                    if to not in self._isolated
                }
        if sender_isolated:
            self._count(from_id, "isolated_drop", len(batch))
            return
        for group, msg in batch:
            mem = targets.get(msg.to)
            if mem is None:
                self._count(
                    from_id,
                    "isolated_drop" if msg.to in self.members
                    else "no_route",
                )
                continue
            try:
                mem.deliver(group, msg)
            except Exception:  # noqa: BLE001 — drop, like a lossy net
                self._count(from_id, "deliver_error")

    def send_block(self, from_id: int, blk) -> None:
        with self._lock:
            if from_id in self._isolated:
                sender_isolated = True
                targets = {}
            else:
                sender_isolated = False
                targets = {
                    to: mem for to, mem in self.members.items()
                    if to not in self._isolated
                }
        if sender_isolated:
            self._count(from_id, "isolated_drop", len(blk))
            return
        for to, sub in blk.split_by_target().items():
            mem = targets.get(to)
            if mem is None:
                self._count(
                    from_id,
                    "isolated_drop" if to in self.members else "no_route",
                    len(sub),
                )
                continue
            try:
                mem.deliver_block(sub)
            except Exception:  # noqa: BLE001 — drop, like a lossy net
                self._count(from_id, "deliver_error", len(sub))

    def isolate(self, member_id: int) -> None:
        with self._lock:
            self._isolated.add(member_id)

    def heal(self, member_id: int) -> None:
        with self._lock:
            self._isolated.discard(member_id)


class TCPRouter:
    """Real-network fabric for MultiRaftMembers: one listener per
    member, one ordered stream per peer, frames carrying
    ``u32 len | u32 group | message-codec bytes`` (the rafthttp
    "message" codec with a group prefix — SURVEY §7.5's host-side
    per-shard message routing). Reuses ``MultiRaftMember.deliver()``
    exactly like InProcRouter; senders drop-don't-block (ref:
    etcdserver/raft.go:108-111)."""

    kind = "tcp"
    MAX_PENDING = 16384
    BLOCK_SENTINEL = 0xFFFFFFFF  # group-id marker for SoA block frames
    # Sender redial policy: bounded exponential backoff with ±50%
    # jitter (ref: rafthttp's probing/backoff discipline — a dead peer
    # must not be hammered at full rate, a recovered one must be found
    # within ~a second), capped per frame by REDIAL_BUDGET so a long
    # outage degrades to drop-don't-block instead of queue collapse.
    # Backoff sleeps use _stopped.wait, so stop() never waits on one.
    BACKOFF_BASE = 0.05
    BACKOFF_CAP = 1.0
    REDIAL_BUDGET = 3.0
    # Per-peer sender lanes (PriorityQueue; FIFO within a lane via the
    # monotone sequence number). Liveness traffic — the SoA block
    # frames carrying heartbeats/acks/votes — outranks bulk MsgApp
    # streams so queue pressure never churns leadership; stop outranks
    # everything so shutdown can't wedge behind a full bulk backlog.
    PRIO_STOP, PRIO_LIVE, PRIO_BULK = 0, 1, 2

    def __init__(self, member: MultiRaftMember,
                 bind: Tuple[str, int] = ("127.0.0.1", 0)) -> None:
        import itertools
        import socket

        from ..transport.codec import MAX_FRAME, decode_message, \
            encode_message

        self._socket = socket
        self._seq = itertools.count()  # FIFO tiebreak within a lane
        self._enc, self._dec = encode_message, decode_message
        self._max_frame = MAX_FRAME
        self.member = member
        member._send = self.send
        member._send_block = self.send_block
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        # Fabric loss/error counters (never silently pass): queue-full
        # drops, oversize drops, dial failures, per-frame redial-budget
        # drops, send errors, corrupt inbound frames, deliver errors.
        # Counted on the shared registry (etcd_tpu_router_loss_total,
        # transport="tcp") — same source of truth as InProcRouter;
        # stats() reports this instance's deltas.
        self._loss = router_loss_counter()
        self._children: Dict[str, Tuple[object, float]] = {}
        self._stats_lock = threading.Lock()
        # peer id -> (queue, sender thread); established lazily.
        self._peers: Dict[int, "object"] = {}
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._conns: List["object"] = []  # accepted sockets, for stop()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(16)
        self.addr: Tuple[str, int] = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def add_peer(self, peer_id: int, addr: Tuple[str, int]) -> None:
        with self._lock:
            self._addrs[peer_id] = addr

    @staticmethod
    def _frame(group_or_sentinel: int, body: bytes) -> bytes:
        """The wire frame: u4 total (group word + body) | u4 group or
        BLOCK_SENTINEL | body. The one place the header layout is
        packed — the shm fabric reuses the body layout (group word +
        payload) without the length prefix."""
        return struct.pack(
            "<II", len(body) + 4, group_or_sentinel) + body

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            ent = self._children.get(key)
            if ent is None:
                child = self._loss.labels(
                    "tcp", str(self.member.id), key)
                ent = (child, child.value())
                self._children[key] = ent
        ent[0].inc(n)

    def stats(self) -> Dict[str, int]:
        """Loss/error counters for this member's fabric (the TCP analog
        of InProcRouter.stats); chaos tests assert these move, operators
        read them through the admin 'stats' op. Values read back from
        the shared registry, scoped to this router instance."""
        with self._stats_lock:
            items = list(self._children.items())
        return {k: int(child.value() - base) for k, (child, base) in items}

    # -- outbound --------------------------------------------------------------

    def send(self, _from_id: int,
             batch: List[Tuple[int, Message]]) -> None:
        import queue as _q  # stdlib; alias avoids shadowing below

        # Resolve/create destination queues once per batch under one
        # lock acquisition (send runs on every member round).
        targets = {m.to for _g, m in batch}
        queues: Dict[int, "_q.Queue"] = {}
        with self._lock:
            if self._stopped.is_set():
                return
            for to in targets:
                ent = self._ensure_peer_locked(to)
                if ent is not None:
                    queues[to] = ent[0]
        for group, m in batch:
            q2 = queues.get(m.to)
            if q2 is None:
                self._count("no_route")
                continue
            try:
                q2.put_nowait((self.PRIO_BULK, next(self._seq),
                               (group, m)))
            except _q.Full:  # drop, never block the round loop
                self._count("queue_full_drop")

    def send_block(self, _from_id: int, blk) -> None:
        """Ship a SoA block: pre-encoded frames per target member (vs
        one frame per message on the object path). Each target's block
        is split into a LIVENESS half (payload-free records:
        heartbeats/acks/votes, PRIO_LIVE) and a BULK half (MsgApp with
        entries, PRIO_BULK) — the rafthttp two-channel discipline
        (ref: server/etcdserver/api/rafthttp/peer.go:337-349): a queue
        full of append payloads must never starve or drop the liveness
        traffic, or followers churn leadership under load. Bulk frames
        exceeding the codec frame cap are chunked (an oversized frame
        would kill the receiver's stream every round, forever)."""
        import queue as _q

        rec = blk.rec
        tos = np.unique(rec["to"]).tolist()
        queues: Dict[int, "_q.Queue"] = {}
        with self._lock:
            if self._stopped.is_set():
                return
            for to in tos:
                ent = self._ensure_peer_locked(int(to))
                if ent is not None:
                    queues[int(to)] = ent[0]

        def enqueue(q2, sub, prio) -> None:
            body = sub.to_bytes()
            if len(body) + 8 > self._max_frame and len(sub) > 1:
                # Contiguous record halves keep the entry arena as
                # pure slices (no gather on the chunking path).
                half = len(sub) // 2
                enqueue(q2, sub.take(slice(0, half)), prio)
                enqueue(q2, sub.take(slice(half, None)), prio)
                return
            if len(body) + 8 > self._max_frame:
                # single unsendable record: drop (raft retries)
                self._count("oversize_drop")
                return
            frame = self._frame(self.BLOCK_SENTINEL, body)
            try:
                q2.put_nowait((prio, next(self._seq), frame))
            except _q.Full:  # drop, never block the round loop
                self._count("queue_full_drop", len(sub))

        # One gather per shipped half, straight off the round block:
        # target and liveness/bulk masks combine BEFORE take(), so the
        # per-target sub-block is never materialized twice.
        has_ents = rec["n_ents"] > 0
        any_ents = bool(has_ents.any())
        for to in tos:
            to = int(to)
            tmask = rec["to"] == to
            q2 = queues.get(to)
            if q2 is None:
                self._count("no_route", int(tmask.sum()))
                continue
            if any_ents and (tmask & has_ents).any():
                live = blk.take(tmask & ~has_ents)
                bulk = blk.take(tmask & has_ents)
                if len(live):
                    enqueue(q2, live, self.PRIO_LIVE)
                enqueue(q2, bulk, self.PRIO_BULK)
            elif len(tos) == 1:
                enqueue(q2, blk, self.PRIO_LIVE)
            else:
                enqueue(q2, blk.take(tmask), self.PRIO_LIVE)

    def _ensure_peer_locked(self, to: int):
        """Resolve or lazily create the (queue, sender) for a peer.
        Caller holds _lock."""
        import queue as _q

        ent = self._peers.get(to)
        if ent is None:
            addr = self._addrs.get(to)
            if addr is None:
                return None
            q: "_q.Queue" = _q.PriorityQueue(maxsize=self.MAX_PENDING)
            t = threading.Thread(
                target=self._sender, args=(to, addr, q), daemon=True)
            self._peers[to] = (q, t)
            t.start()
            ent = self._peers[to]
        return ent

    def _sender(self, peer_id: int, addr: Tuple[str, int], q) -> None:
        """Per-peer sender lane. A down peer is redialed with bounded
        exponential backoff + jitter (state carries across frames so a
        long outage settles at BACKOFF_CAP instead of hammering), each
        frame charged at most REDIAL_BUDGET of redial time before it is
        dropped (drop-don't-block, ref: etcdserver/raft.go:108-111).
        Backoff sleeps are _stopped.wait()s: stop() interrupts them, so
        shutdown never serves out a backoff."""
        rng = random.Random()  # jitter decorrelates peers; not seeded
        sock = None
        backoff = self.BACKOFF_BASE
        while not self._stopped.is_set():
            _prio, _seq, item = q.get()
            if item is None:
                break
            if isinstance(item, bytes):  # pre-encoded block frame
                frame = item
            else:
                group, m = item
                # encode_message returns a length-prefixed frame; strip
                # its prefix — this framing carries its own total +
                # group id.
                payload = self._enc(m)[4:]
                if len(payload) + 4 > self._max_frame:
                    # The receiver would kill the stream on an
                    # oversized frame and the resend would churn it
                    # forever; drop it here instead (the raft layer
                    # retries via snapshots).
                    self._count("oversize_drop")
                    continue
                frame = self._frame(group, payload)
            deadline = time.monotonic() + self.REDIAL_BUDGET
            while not self._stopped.is_set():
                if sock is None:
                    try:
                        sock = self._socket.create_connection(
                            addr, timeout=2.0)
                        if (sock.getsockname()
                                == sock.getpeername()):
                            # TCP simultaneous-open self-connect:
                            # while the peer's listener is down, the
                            # kernel can hand the dial OUR ephemeral
                            # source port == the target port,
                            # connecting the socket to itself. Writes
                            # then "succeed" into our own receive
                            # buffer and deliver nothing — a silently
                            # dead lane (found by the chaos harness:
                            # a follower wedged one entry behind with
                            # zero errors counted).
                            self._count("self_connect")
                            try:
                                sock.close()
                            except OSError:
                                pass
                            sock = None
                            raise OSError("tcp self-connect")
                        sock.setsockopt(
                            self._socket.IPPROTO_TCP,
                            self._socket.TCP_NODELAY, 1)
                    except OSError:
                        sock = None
                        self._count("dial_fail")
                        delay = backoff * (0.5 + rng.random())
                        backoff = min(backoff * 2, self.BACKOFF_CAP)
                        if time.monotonic() + delay > deadline:
                            # Budget exhausted: drop THIS frame but keep
                            # the backoff state — the next frame resumes
                            # the slow probe instead of re-hammering.
                            self._count("redial_drop")
                            break
                        if self._stopped.wait(delay):
                            break
                        continue
                try:
                    sock.sendall(frame)
                    # Only a delivered frame proves the peer healthy:
                    # resetting on dial success would let a peer that
                    # accepts connections but RSTs every write erase
                    # the backoff each cycle — a full-speed
                    # dial/send/reset spin.
                    backoff = self.BACKOFF_BASE
                    break
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                    self._count("send_error")
                    delay = backoff * (0.5 + rng.random())
                    backoff = min(backoff * 2, self.BACKOFF_CAP)
                    if time.monotonic() + delay > deadline:
                        # A peer that accepts dials but resets every
                        # send must not pin this lane to one frame.
                        self._count("redial_drop")
                        break
                    if self._stopped.wait(delay):
                        break
                    continue  # redial under the same frame budget
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- inbound ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                if self._stopped.is_set():
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.append(conn)
            threading.Thread(
                target=self._recv_loop, args=(conn,), daemon=True
            ).start()

    def _recv_loop(self, conn) -> None:
        # Frames are read straight into one preallocated buffer with
        # recv_into (grown on demand up to the frame cap) — a frame
        # costs ONE owned copy-out at the end instead of O(chunks)
        # bytes concatenations per frame. The copy-out is not
        # removable: deliver_block defers the block to the next round,
        # so handing it a view into a reused buffer would corrupt it
        # under the queue.
        buf = bytearray(64 * 1024)

        def read_exact(n: int) -> Optional[memoryview]:
            nonlocal buf
            if n > len(buf):
                buf = bytearray(n)
            mv = memoryview(buf)
            got = 0
            while got < n:
                try:
                    k = conn.recv_into(mv[got:n])
                except OSError:
                    return None
                if not k:
                    return None
                got += k
            return mv[:n]

        while not self._stopped.is_set():
            hdr = read_exact(4)
            if hdr is None:
                break
            (total,) = struct.unpack("<I", hdr)
            if not 4 <= total <= self._max_frame:
                self._count("recv_corrupt")
                break
            body = read_exact(total)
            if body is None:
                break
            (group,) = struct.unpack_from("<I", body)
            if group == self.BLOCK_SENTINEL:
                from .msgblock import MsgBlock

                try:
                    blk = MsgBlock.from_bytes(bytes(body[4:]))
                except ValueError:  # corrupt frame: drop conn
                    self._count("recv_corrupt")
                    break
                try:
                    self.member.deliver_block(blk)
                except Exception:  # noqa: BLE001 — lossy-net semantics
                    self._count("deliver_error")
                continue
            try:
                m = self._dec(bytes(body[4:]))
            except Exception:  # noqa: BLE001 — corrupt frame: drop conn
                self._count("recv_corrupt")
                break
            try:
                self.member.deliver(group, m)
            except Exception:  # noqa: BLE001 — lossy-net semantics
                self._count("deliver_error")
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:  # after _stopped: send() cannot add peers now
            peers = list(self._peers.values())
            self._peers.clear()
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:  # unblock recv threads parked in recv()
            try:
                conn.close()
            except OSError:
                pass
        for q, t in peers:
            try:
                q.put_nowait((self.PRIO_STOP, next(self._seq), None))
            except Exception:  # noqa: BLE001
                pass
        for _q2, t in peers:
            t.join(timeout=2)


def wait_group_leaders(members_fn, num_groups: int,
                       timeout: float = 60.0,
                       nudge_interval: float = 5.0) -> np.ndarray:
    """Block until every group has an elected leader among the members
    ``members_fn()`` returns; returns the per-group leader member id.
    Under heavy host load device rounds can lag the tick clock, so
    leaderless groups are periodically nudged with an explicit campaign
    on every member (any single member's replica may be unelectable —
    shorter log after a restart; pre-vote keeps the extra campaigns
    from disrupting groups that elect meanwhile). Shared by
    MultiRaftCluster and the chaos harness so their convergence
    behavior can't drift apart."""
    deadline = time.monotonic() + timeout
    next_nudge = time.monotonic() + nudge_interval
    while time.monotonic() < deadline:
        leads = np.zeros(num_groups, np.int64)
        for m in members_fn():
            _term, role, _lead = m.rn.m_view
            leads[role == LEADER] = m.id
        if (leads > 0).all():
            return leads
        if time.monotonic() >= next_nudge:
            stuck = np.nonzero(leads == 0)[0]
            for m in members_fn():
                m.campaign(stuck)
            next_nudge = time.monotonic() + nudge_interval
        time.sleep(0.05)
    raise TimeoutError("groups without leader")


class MultiRaftCluster:
    """Convenience harness: R members × G groups in one process."""

    def __init__(self, data_dir: str, num_members: int = 3,
                 num_groups: int = 16,
                 cfg: Optional[BatchedConfig] = None,
                 pipeline: bool = True,
                 mesh_devices: int = 0,
                 fence: bool = True,
                 trace: Optional[bool] = None,
                 wal_pipeline: Optional[bool] = None,
                 wal_group_max_delay: Optional[float] = None,
                 wal_group_max_bytes: Optional[int] = None,
                 disk_fault_hook_fn: Optional[
                     Callable[[int], Optional[Callable[[str, int],
                                                       None]]]] = None,
                 snap_cadence: Optional[int] = None,
                 snap_keep: int = SNAP_KEEP_DEFAULT,
                 wal_rotate_bytes: Optional[int] = None,
                 wal_pinned_segments: int = WAL_PINNED_SEGMENTS,
                 ) -> None:
        self.router = InProcRouter()
        self.members: Dict[int, MultiRaftMember] = {}
        for mid in range(1, num_members + 1):
            m = MultiRaftMember(
                mid, num_members, num_groups, data_dir, cfg=cfg,
                pipeline=pipeline, mesh_devices=mesh_devices,
                fence=fence, trace=trace, wal_pipeline=wal_pipeline,
                wal_group_max_delay=wal_group_max_delay,
                wal_group_max_bytes=wal_group_max_bytes,
                # Log-lifecycle plane knobs (ISSUE 17).
                snap_cadence=snap_cadence, snap_keep=snap_keep,
                wal_rotate_bytes=wal_rotate_bytes,
                wal_pinned_segments=wal_pinned_segments,
                # Storage fault plane seam (ISSUE 15): a per-member
                # hook factory, e.g. DiskFaultPlan.hook_for.
                disk_fault_hook=(disk_fault_hook_fn(mid)
                                 if disk_fault_hook_fn is not None
                                 else None),
            )
            self.router.attach(m)
            self.members[mid] = m
        for m in self.members.values():
            m.start()

    def wait_leaders(self, timeout: float = 60.0) -> np.ndarray:
        """Block until every group has an elected leader; returns the
        per-group leader member id (the hosting analog of etcd clients
        retrying against a leaderless cluster)."""
        g = next(iter(self.members.values())).g
        return wait_group_leaders(
            self.members.values, g, timeout=timeout)

    def put(self, group: int, key: bytes, value: bytes,
            timeout: float = 10.0, lease_ttl: int = 0) -> None:
        """Client write: find the leader, propose, wait for local apply
        (read-your-write via the leader's applied state). lease_ttl>0
        attaches a plane lease (ticks): the replicated bytes are
        identical everywhere, expiry visibility is leader-local."""
        if lease_ttl:
            from .applyplane import put_payload

            payload = put_payload(key, value, lease_ttl)
        else:
            payload = GroupKV.put_payload(key, value)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for m in self.members.values():
                if not m.propose(group, payload):
                    continue
                # Wait briefly for local apply; a stale (partitioned)
                # leader accepts but never commits — fall through and
                # retry on another member (retries are idempotent:
                # the orphaned entry is truncated by the new leader's
                # conflicting append).
                sub = min(deadline, time.monotonic() + 2.0)
                while time.monotonic() < sub:
                    if m.get(group, key) == value:
                        return
                    time.sleep(0.005)
            time.sleep(0.02)
        raise TimeoutError(f"put for group {group} did not commit")

    def stop(self) -> None:
        for m in self.members.values():
            m.stop()
