"""Device-resident apply plane: tensorized MVCC, watch matching, and
lease TTL expiry (ROADMAP item 5; PAPER.md layer map L2 — ``mvcc.KV``/
``WatchableKV``/``lease.Lessor`` as device tensors riding the round).

The plane is a SEPARATE jitted program from the round step: the round
decides *what committed*; this program folds those commits into a
fixed-capacity per-group KV/revision store without the per-entry host
Python loop (``hosting.py`` ``kvs[row].apply``). One dispatch applies up
to ``A = cfg.apply_records`` committed entries per group row — a round
that commits more redispatches the SAME compiled program, so the shape
set stays static (its own ``apply_plane`` compile-key kind; the
round-step budget in tests/batched/conftest.py never moves).

Byte honesty (SURVEY §7: payload bytes don't belong on the TPU): the
device store holds 31-bit FNV-1a key/value *hashes* and i32 revision /
lease-expiry lanes — the MVCC metadata. Byte truth stays in the host
``GroupKV`` tier, which keeps applying every payload (shadow/overflow
tier): lease-hit reads serve bytes from the host tier after the device
lane authorizes them, and rows whose live keys exceed ``C =
cfg.apply_capacity`` set a sticky overflow flag routing that row's
reads/snapshot-capture back to the host tier.

Semantics of one dispatch (the oracle below replays them exactly):

1. ``tick += tick_add`` (the member's staged round-tick count — the
   plane clock is per-member host time, like the lease lane).
2. Expiry pass: every slot with ``0 < kv_lease <= tick`` is cleared;
   the group revision advances by the number of expired slots.
3. Apply scan over the A record lanes in order. put: exact-hash match
   updates the slot, else first-free-slot insert, else sticky
   ``overflow``; revision always advances. delete: clears the matching
   slot and advances the revision only if the key existed (a delete of
   a missing key is a no-op, matching the host tier's ``pop``).
   Each applied record's watch bitmap is the OR of exact-key matches
   against the armed watch slots (``WS <= 32`` packs into one i32).

Client lease TTLs ride a third payload form (``E`` = expiring put; the
host tier stores the bytes and ignores the TTL — expiry is leader-local
visibility, faithful to etcd's leader-driven lessor, and keeping the
host bytes untouched keeps the cross-member KV-hash parity checker
meaningful).
"""

from __future__ import annotations

import functools
import struct
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sentinels import note_compile_key
from .state import BatchedConfig, I32

# Record opcodes (the host-built apply stream).
OP_NONE, OP_PUT, OP_DEL = 0, 1, 2


# -----------------------------------------------------------------------------
# Host-side hashing + payload forms
# -----------------------------------------------------------------------------


def fnv1a32(data: bytes) -> int:
    """31-bit nonzero FNV-1a — the plane's key/value identity. Masked
    to 31 bits so it stays positive in i32 lanes; 0 is reserved for
    'empty slot', so a zero digest maps to 1."""
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    h &= 0x7FFFFFFF
    return h or 1


def put_payload(key: bytes, value: bytes, lease_ttl: int = 0) -> bytes:
    """Proposal payload for a put; ``lease_ttl`` > 0 (plane ticks)
    makes it an expiring put (payload form ``E``). The non-lease forms
    are byte-identical to GroupKV's (``P``/``D``) — every pre-plane
    WAL/snapshot stays replayable."""
    if lease_ttl > 0:
        return b"E" + struct.pack(">I", lease_ttl) + key + b"\x00" + value
    return b"P" + key + b"\x00" + value


def delete_payload(key: bytes) -> bytes:
    return b"D" + key


def parse_payload(d: bytes) -> Optional[Tuple[int, bytes, bytes, int]]:
    """(op, key, value, lease_ttl) of a KV payload; None for payloads
    the KV tier ignores (conf entries never reach here — the rawnode
    splits on etype first)."""
    if not d:
        return None
    tag = d[:1]
    if tag == b"P":
        k, _, v = d[1:].partition(b"\x00")
        return (OP_PUT, k, v, 0)
    if tag == b"E":
        if len(d) < 5:
            return None
        (ttl,) = struct.unpack(">I", d[1:5])
        k, _, v = d[5:].partition(b"\x00")
        return (OP_PUT, k, v, int(ttl))
    if tag == b"D":
        return (OP_DEL, d[1:], b"", 0)
    return None


# -----------------------------------------------------------------------------
# Device state + frames
# -----------------------------------------------------------------------------


class PlaneState(NamedTuple):
    """Per-row (row = group on the hosting path) MVCC tensors."""

    kv_key: jnp.ndarray  # [n, C] i32 key hash; 0 = empty slot
    kv_rev: jnp.ndarray  # [n, C] i32 mod-revision of the slot
    kv_val: jnp.ndarray  # [n, C] i32 value hash
    kv_lease: jnp.ndarray  # [n, C] i32 expiry tick; 0 = no lease
    watch_key: jnp.ndarray  # [n, WS] i32 armed exact-key watches; 0 = off
    rev: jnp.ndarray  # [n] i32 group revision counter
    tick: jnp.ndarray  # [n] i32 plane clock (staged round ticks)
    overflow: jnp.ndarray  # [n] bool sticky capacity overflow
    slots_hw: jnp.ndarray  # [n] i32 used-slot high-water


class PlaneFrame(NamedTuple):
    """Fixed-shape per-dispatch output the host drains: the watch event
    lanes (the SummaryFrame pattern generalized to the apply stream)
    plus per-row counters."""

    ev_op: jnp.ndarray  # [n, A] i32 applied opcode (0 = empty lane)
    ev_key: jnp.ndarray  # [n, A] i32 key hash of the applied record
    ev_rev: jnp.ndarray  # [n, A] i32 revision assigned (0 = none)
    ev_wmask: jnp.ndarray  # [n, A] i32 watch-slot match bitmap
    puts: jnp.ndarray  # [n] i32
    dels: jnp.ndarray  # [n] i32
    expired: jnp.ndarray  # [n] i32 lease expiries this dispatch
    slots_used: jnp.ndarray  # [n] i32 live slots after the dispatch
    leases: jnp.ndarray  # [n] i32 slots holding an unexpired lease
    overflow: jnp.ndarray  # [n] bool (post-dispatch sticky flag)


def init_plane(cfg: BatchedConfig, n: int) -> PlaneState:
    c, ws = cfg.apply_capacity, cfg.apply_watch_slots
    return PlaneState(
        kv_key=jnp.zeros((n, c), I32),
        kv_rev=jnp.zeros((n, c), I32),
        kv_val=jnp.zeros((n, c), I32),
        kv_lease=jnp.zeros((n, c), I32),
        watch_key=jnp.zeros((n, ws), I32),
        rev=jnp.zeros((n,), I32),
        tick=jnp.zeros((n,), I32),
        overflow=jnp.zeros((n,), bool),
        slots_hw=jnp.zeros((n,), I32),
    )


@functools.lru_cache(maxsize=None)
def _dispatch_jit(c: int, ws: int, a: int, n: int):
    """One compiled apply program per (capacity, watch slots, records,
    rows) — its own compile-key kind, so the round-step shape budget is
    structurally untouched."""
    note_compile_key("apply_plane", f"C={c}|WS={ws}|A={a}|n={n}")

    def per_row(kv_key, kv_rev, kv_val, kv_lease, watch_key, rev, tick,
                overflow, ops, keys, vals, ttls, tick_add):
        tick = tick + tick_add
        # --- expiry pass (before new records: a put in this dispatch
        # re-arms its key AFTER the old lease's deadline fires) --------
        dead = (kv_lease > 0) & (kv_lease <= tick)
        n_dead = jnp.sum(dead.astype(I32))
        kv_key = jnp.where(dead, 0, kv_key)
        kv_rev = jnp.where(dead, 0, kv_rev)
        kv_val = jnp.where(dead, 0, kv_val)
        kv_lease = jnp.where(dead, 0, kv_lease)
        rev = rev + n_dead

        # --- apply scan over the A record lanes in order --------------
        def apply_one(carry, rec):
            kv_key, kv_rev, kv_val, kv_lease, rev, overflow = carry
            op, key, val, ttl = rec
            hit = kv_key == key
            exists = jnp.any(hit)
            free = kv_key == 0
            # First free slot: argmax over bool finds the first True.
            ins = jnp.argmax(free)
            has_free = jnp.any(free)
            slot = jnp.where(exists, jnp.argmax(hit), ins)
            slot_ok = exists | has_free
            is_put = op == OP_PUT
            is_del = op == OP_DEL
            # put: revision always advances (the store of record even
            # when the row overflows — the host tier holds the bytes);
            # del: only if the key existed.
            bump = is_put | (is_del & exists)
            new_rev = rev + jnp.where(bump, 1, 0)
            onehot = (jnp.arange(c, dtype=I32) == slot) & slot_ok
            wr_put = is_put & slot_ok
            kv_key = jnp.where(wr_put & onehot, key, kv_key)
            kv_val = jnp.where(wr_put & onehot, val, kv_val)
            kv_rev = jnp.where(wr_put & onehot, new_rev, kv_rev)
            kv_lease = jnp.where(
                wr_put & onehot,
                jnp.where(ttl > 0, tick + ttl, 0), kv_lease)
            wr_del = is_del & exists
            kv_key = jnp.where(wr_del & onehot, 0, kv_key)
            kv_val = jnp.where(wr_del & onehot, 0, kv_val)
            kv_rev = jnp.where(wr_del & onehot, 0, kv_rev)
            kv_lease = jnp.where(wr_del & onehot, 0, kv_lease)
            overflow = overflow | (is_put & ~slot_ok)
            wmask = jnp.sum(
                jnp.where(
                    (watch_key == key) & (key != 0),
                    jnp.left_shift(
                        jnp.ones((ws,), I32), jnp.arange(ws, dtype=I32)),
                    0))
            ev = (op, key, jnp.where(bump, new_rev, 0),
                  jnp.where(op != OP_NONE, wmask, 0))
            return (kv_key, kv_rev, kv_val, kv_lease, new_rev,
                    overflow), ev

        (kv_key, kv_rev, kv_val, kv_lease, rev, overflow), evs = (
            jax.lax.scan(
                apply_one,
                (kv_key, kv_rev, kv_val, kv_lease, rev, overflow),
                (ops, keys, vals, ttls)))
        used = jnp.sum((kv_key != 0).astype(I32))
        return (
            (kv_key, kv_rev, kv_val, kv_lease, rev, tick, overflow,
             used),
            evs,
            (jnp.sum((ops == OP_PUT).astype(I32)),
             jnp.sum((ops == OP_DEL).astype(I32)), n_dead, used,
             jnp.sum((kv_lease > 0).astype(I32))),
        )

    def dispatch(plane: PlaneState, ops, keys, vals, ttls, tick_add):
        rows, evs, counts = jax.vmap(
            per_row, in_axes=(0,) * 8 + (0, 0, 0, 0, 0),
        )(plane.kv_key, plane.kv_rev, plane.kv_val, plane.kv_lease,
          plane.watch_key, plane.rev, plane.tick, plane.overflow,
          ops, keys, vals, ttls, tick_add)
        (kv_key, kv_rev, kv_val, kv_lease, rev, tick, overflow,
         used) = rows
        plane2 = PlaneState(
            kv_key=kv_key, kv_rev=kv_rev, kv_val=kv_val,
            kv_lease=kv_lease, watch_key=plane.watch_key, rev=rev,
            tick=tick, overflow=overflow,
            slots_hw=jnp.maximum(plane.slots_hw, used))
        frame = PlaneFrame(
            ev_op=evs[0], ev_key=evs[1], ev_rev=evs[2],
            ev_wmask=evs[3], puts=counts[0], dels=counts[1],
            expired=counts[2], slots_used=counts[3], leases=counts[4],
            overflow=overflow)
        return plane2, frame

    # Donate the plane carry: its buffers are always jax-native (built
    # by init_plane / the previous dispatch), never host-aliased like
    # the round's staged inbox, so XLA reuses the SoA KV buffers
    # in place between dispatches.
    return jax.jit(dispatch, donate_argnums=(0,))


def make_dispatch(cfg: BatchedConfig, n: int):
    """dispatch(plane, ops, keys, vals, ttls, tick_add) ->
    (plane', PlaneFrame); all [n, A] i32 record lanes + [n] tick_add."""
    return _dispatch_jit(
        cfg.apply_capacity, cfg.apply_watch_slots, cfg.apply_records, n)


@functools.lru_cache(maxsize=None)
def _gather_jit(m: int):
    """Sliced snapshot-capture gather (satellite: _build_snapshots must
    not walk host dicts per group): ONE device gather per build batch,
    rows padded host-side to the member's fixed build cap so the shape
    set stays static."""
    note_compile_key("apply_plane", f"gather|m={m}")

    def gather(plane: PlaneState, rows):
        take = lambda x: jnp.take(x, rows, axis=0)  # noqa: E731
        return (take(plane.kv_key), take(plane.kv_rev),
                take(plane.kv_val), take(plane.kv_lease),
                take(plane.rev), take(plane.tick), take(plane.overflow))

    return jax.jit(gather)


def gather_rows(plane: PlaneState, rows: np.ndarray):
    """Device-side batched row gather for snapshot capture; ``rows`` is
    a fixed-width padded i32 vector (pad with row 0; the host slices)."""
    return _gather_jit(int(rows.shape[0]))(plane, jnp.asarray(rows, I32))


# -----------------------------------------------------------------------------
# Host-side shadow oracle (tests + smoke reconcile against this, and
# this against the device — exact, not statistical)
# -----------------------------------------------------------------------------


class PlaneOracle:
    """Pure-Python replay of one row's dispatch semantics. Feeding it
    the exact (records, tick_add) stream a member staged must reproduce
    the device tensors bit-for-bit (tests/batched/test_applyplane.py)."""

    def __init__(self, cfg: BatchedConfig):
        self.c = cfg.apply_capacity
        self.ws = cfg.apply_watch_slots
        self.kv_key = [0] * self.c
        self.kv_rev = [0] * self.c
        self.kv_val = [0] * self.c
        self.kv_lease = [0] * self.c
        self.watch_key = [0] * self.ws
        self.rev = 0
        self.tick = 0
        self.overflow = False
        self.slots_hw = 0
        self.events: List[Tuple[int, int, int, int]] = []
        self.expired = 0

    def dispatch(self, records: List[Tuple[int, int, int, int]],
                 tick_add: int) -> None:
        """records: [(op, key_hash, val_hash, ttl)] (<= A per call the
        way the rawnode chunks them, but the oracle takes any length —
        chunking cannot change the fold)."""
        self.tick += tick_add
        for s in range(self.c):
            if 0 < self.kv_lease[s] <= self.tick:
                self.kv_key[s] = self.kv_rev[s] = 0
                self.kv_val[s] = self.kv_lease[s] = 0
                self.rev += 1
                self.expired += 1
        for op, key, val, ttl in records:
            if op == OP_NONE:
                continue
            slot = next(
                (s for s in range(self.c) if self.kv_key[s] == key),
                None)
            if op == OP_PUT:
                self.rev += 1
                if slot is None:
                    slot = next(
                        (s for s in range(self.c)
                         if self.kv_key[s] == 0), None)
                if slot is None:
                    self.overflow = True
                else:
                    self.kv_key[slot] = key
                    self.kv_val[slot] = val
                    self.kv_rev[slot] = self.rev
                    self.kv_lease[slot] = (
                        self.tick + ttl if ttl > 0 else 0)
                ev_rev = self.rev
            else:  # OP_DEL
                if slot is not None:
                    self.rev += 1
                    self.kv_key[slot] = self.kv_rev[slot] = 0
                    self.kv_val[slot] = self.kv_lease[slot] = 0
                    ev_rev = self.rev
                else:
                    ev_rev = 0
            wmask = 0
            if key != 0:
                for w in range(self.ws):
                    if self.watch_key[w] == key:
                        wmask |= 1 << w
            self.events.append((op, key, ev_rev, wmask))
        self.slots_hw = max(
            self.slots_hw, sum(1 for k in self.kv_key if k != 0))

    def state(self) -> Dict[str, object]:
        return {
            "kv_key": list(self.kv_key), "kv_rev": list(self.kv_rev),
            "kv_val": list(self.kv_val),
            "kv_lease": list(self.kv_lease),
            "rev": self.rev, "tick": self.tick,
            "overflow": self.overflow, "slots_hw": self.slots_hw,
        }
