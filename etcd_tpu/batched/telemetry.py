"""Device-to-host telemetry plane for the batched multi-raft engine.

The jitted round is a black box by construction — every observable
worth having (who voted, who probed, who stalled) lives in device
arrays the host never looks at on the hot path. This module is the
observability spine the SURVEY maps as etcd's Status/metrics plane
("device -> host gather"), in the Dapper spirit of always-on,
low-overhead tracing:

* **Kernel counters** — behind ``BatchedConfig.telemetry`` (default
  off), ``step.py`` emits one extra SoA block per round
  (``TelemetryFrame``): per-instance event counters (messages emitted
  by lane/type, append accepts/rejects, progress-state transitions,
  elections started/won, commit delta, ReadIndex confirmations,
  proposals dropped) plus an **invariant bitmap** computed on-device
  (``kernels.invariant_bits``). The frame is a pure function of round
  inputs/outputs: with telemetry off the compiled program is
  unchanged; with it on, protocol state stays bit-identical.

* **Host hub** — ``TelemetryHub`` folds round frames into monotonic
  counters on the shared ``pkg.metrics`` registry (labeled by member /
  group-shard) and keeps a bounded **flight recorder**: a ring of the
  last K rounds of per-group deltas plus inbox/outbox lane summaries,
  dumped to ``artifacts/flightrec_*.json`` on demand, on invariant
  trip, or on chaos-checker failure.

This module is import-light on purpose (numpy + pkg.metrics, no jax):
``step.py`` imports the counter indices from here; the hub side never
touches device code.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..pkg import metrics as pmet

# -----------------------------------------------------------------------------
# Counter layout (column order of TelemetryFrame.counters; step.py
# builds the frame in exactly this order — keep the two in sync).
# -----------------------------------------------------------------------------

TM_NAMES = (
    "sent_vote_req",       # vote / pre-vote requests emitted
    "sent_append",         # MsgApp emitted (probes included)
    "sent_snapshot",       # MsgSnap emitted
    "sent_heartbeat",      # MsgHeartbeat emitted
    "sent_timeout_now",    # MsgTimeoutNow emitted (leader transfer)
    "sent_vote_resp",      # vote / pre-vote responses emitted
    "sent_append_resp",    # MsgAppResp emitted (accepts + rejects)
    "sent_heartbeat_resp",  # MsgHeartbeatResp emitted
    "recv_messages",       # inbox slots delivered (post-isolation)
    "append_accepted",     # inbound appends acked (reject=false)
    "append_rejected",     # inbound appends rejected (hint probing)
    "probe_to_replicate",  # peer transitions PROBE -> REPLICATE
    "to_snapshot",         # peer transitions into SNAPSHOT
    "to_probe",            # peer transitions into PROBE
    "elections_started",   # campaigns entered (candidate/pre-candidate)
    "elections_won",       # transitions into LEADER
    "commit_delta",        # commit-index advance this round
    "reads_confirmed",     # ReadIndex batches quorum-confirmed
    "proposals_dropped",   # staged proposals the device did not append
    "fenced_rounds",       # rounds spent durability-fenced (PAR rejoin)
    # Membership-mask applications staged onto the device this round
    # (entry-driven conf-change applies, snapshot conf restores, manual
    # uploads). The device column is zero — entry types never reach the
    # kernel — and the rawnode adds the count at the staging seam
    # (advance_round's pending-conf application), so the flight
    # recorder still shows per-group conf flips round by round.
    "conf_changes_applied",
)
NUM_COUNTERS = len(TM_NAMES)
TM_INDEX = {n: i for i, n in enumerate(TM_NAMES)}

# Invariant bitmap layout (kernels.invariant_bits builds bits in this
# order). Every bit is impossible under the raft model: a trip means a
# kernel bug or a violated environment assumption (torn WAL tail).
INV_NAMES = (
    "next_le_match",        # progress next <= match on a tracked peer
    "commit_gt_last",       # commit beyond the last log index
    "snap_gt_commit",       # compaction floor above commit
    "leader_lead_mismatch",  # leader whose lead pointer names another
    "probe_wedge",          # paused probe with next <= match (the
    # restarted-member wedge signature — see CHANGES.md PR 4)
    "snapshot_stuck",       # SNAPSHOT state with pending <= match
    "read_ready_no_batch",  # confirmed read with no batch open
    "fenced_leader",        # durability-fenced instance became leader
    "voter_out_no_joint",   # outgoing-voter mask residue while the
    # row is not in a joint config (conf-apply lane inconsistency)
    "ring_over_window",     # log-ring occupancy (last - snap_index)
    # beyond the ring width W: an append crossed the compaction floor
    # (wrap = silent log corruption; the ring_full back-pressure lane
    # exists to make this unreachable)
    "lease_on_nonleader",   # leader-lease tick residue on a
    # non-leader: a stale quorum-free read authorization (ISSUE 19 —
    # every step-down path must zero the lane in the same round)
)


def decode_invariants(bits: int) -> List[str]:
    return [n for i, n in enumerate(INV_NAMES) if bits & (1 << i)]


# -----------------------------------------------------------------------------
# Registry metric families (registered lazily, shared process-wide;
# label children distinguish members/shards).
# -----------------------------------------------------------------------------


def counter_family(name: str,
                   registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        f"etcd_tpu_batched_{name}_total",
        f"batched kernel telemetry: {name} events",
        ("member", "shard"),
    ))


def invariant_family(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_batched_invariant_trips_total",
        "on-device invariant bitmap trips (any set bit is a bug or a "
        "violated durability assumption)",
        ("member", "invariant"),
    ))


def wal_fsync_histogram(
        registry: Optional[pmet.Registry] = None) -> pmet.Histogram:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        "etcd_tpu_hosting_wal_fsync_seconds",
        "WAL append+fsync latency per persistence batch",
        ("member",),
    ))


def wal_pipeline_depth_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    """Persistence batches sitting in the async WAL pipeline's open
    buffer (ISSUE 13) — sampled at submit and at every worker swap.
    A depth pinned high means the disk can't keep up with the round
    cadence even amortized."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_wal_pipeline_queue_depth",
        "persistence batches queued on the WAL-commit worker",
        ("member",),
    ))


def wal_pipeline_batches_histogram(
        registry: Optional[pmet.Registry] = None) -> pmet.Histogram:
    """Device rounds whose persistence one group-commit fsync covered —
    the amortization the pipeline exists for (1 == no better than the
    inline path)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        "etcd_tpu_wal_pipeline_batches_per_fsync",
        "round persistence batches covered by one group-commit fsync",
        ("member",),
        buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64),
    ))


def wal_pipeline_bytes_histogram(
        registry: Optional[pmet.Registry] = None) -> pmet.Histogram:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        "etcd_tpu_wal_pipeline_bytes_per_fsync",
        "WAL bytes covered by one group-commit fsync",
        ("member",),
        buckets=(1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10,
                 1 << 20, 4 << 20, 16 << 20),
    ))


def wal_pipeline_release_histogram(
        registry: Optional[pmet.Registry] = None) -> pmet.Histogram:
    """Submit→release latency of a persistence batch on the pipeline:
    the time its acks/sends/applies waited on the covering group-commit
    fsync (the ack-release barrier's cost, paid OFF the round thread)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        "etcd_tpu_wal_pipeline_ack_release_seconds",
        "WAL-pipeline batch submit-to-release (ack barrier) latency",
        ("member",),
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 2.5),
    ))


def round_phase_histogram(
        registry: Optional[pmet.Registry] = None) -> pmet.Histogram:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Histogram(
        "etcd_tpu_hosting_round_phase_seconds",
        "member pipeline phase wall time per round "
        "(phase: round/wal/apply/send)",
        ("member", "phase"),
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0),
    ))


def fenced_groups_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    """Per-member count of groups currently durability-fenced (torn
    acked bytes detected at _replay; drops back to 0 as the snapshot/
    probe catch-up lifts each fence). Set by the hosting layer at boot
    and on every lift — no per-round cost."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_batched_fenced_groups",
        "groups currently fenced out of elections after durable-loss "
        "detection (protocol-aware torn-tail recovery)",
        ("member",),
    ))


def joint_groups_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    """Per-member count of groups currently inside a joint membership
    config (between the enter-joint entry's apply and the leave-joint
    commit). Set by the hosting layer's conf-apply path — a value stuck
    above zero means auto-leave never fired (the condition
    check_config_safety's 'joint always exited' clause asserts away)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_batched_joint_groups",
        "groups currently in a joint (two-quorum) membership config",
        ("member",),
    ))


def learner_slots_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    """Per-member count of (group, slot) learner entries in the live
    config — the catch-up population the promote gate watches."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_batched_learner_slots",
        "live (group, slot) learner entries across this member's "
        "group configs",
        ("member",),
    ))


def disk_fault_failstop_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """Storage fail-stop events by stage (the ISSUE 15 IO-error
    contract: the FIRST failed fsync — or any unrecoverable write —
    kills the member crash-style, releasing nothing gated on the
    failed window; never retry-fsync over possibly-dropped dirty
    pages, per Rebello et al., ATC'19)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_disk_fault_failstop_total",
        "member fail-stops forced by storage faults, by stage "
        "(write | fsync | snap_install)",
        ("member", "stage"),
    ))


def disk_full_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    """1 while the member sits in ENOSPC write-back-pressure (WAL
    appends refused at the fault seam before any byte was written):
    proposals refuse, acks/sends stall behind the unwritten batch, and
    the member resumes — zero acked writes lost — once space returns.
    The health op's ``disk_full`` field mirrors it."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_disk_fault_disk_full",
        "member currently in ENOSPC write-back-pressure (0/1)",
        ("member",),
    ))


def disk_fault_injected_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """Injected disk-fault decisions at the Walog/Snapshotter file-op
    seam (batched/faults.DiskFaultPlan) — the fault plane must PROVE
    it injected, same discipline as the message-fault counters."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_disk_fault_injected_total",
        "injected disk faults at the storage seam, by op and kind "
        "(kind: fsync_error | write_error | enospc | delay)",
        ("member", "op", "kind"),
    ))


def disk_fault_salvage_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """At-rest WAL corruption amputations performed at boot (walog
    salvage: truncate at the first CRC-bad complete record, drop later
    segments; the damaged groups boot FENCED via the durable
    watermark)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_disk_fault_salvage_total",
        "at-rest WAL corruption salvage amputations at member boot",
        ("member",),
    ))


def trace_span_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """Spans opened by the proposal-lifecycle tracer (etcd_tpu.obs) —
    the sampled 1-in-N population size, so rates can be scaled back to
    absolute proposal counts."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_trace_spans_total",
        "proposal-lifecycle trace spans opened (sampled)",
        ("member",),
    ))


def trace_drop_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """Tracer shedding classes (open_evict: span evicted before apply;
    ring_evict: retired span pushed out of the bounded ring). The
    tracer never sheds silently — a hot run that overflows its rings
    shows up here, not as a mystery gap in the merged timeline."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_trace_span_drops_total",
        "proposal-lifecycle trace spans dropped/evicted, by class",
        ("member", "cls"),
    ))


def router_loss_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    """One source of truth for transport drop classes (InProcRouter,
    TCPRouter and ShmFabric all count here; their stats() ops read
    back from it)."""
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_router_loss_total",
        "messages lost or errored by the member fabric, by drop class",
        ("transport", "member", "cls"),
    ))


# Shared-memory ring fabric families (ISSUE 16, batched/shmfabric.py):
# per outbound lane (member -> peer, live|bulk ring). Losses count on
# router_loss_counter (transport="shm") like every fabric; these
# families carry the ring-occupancy/throughput shape the fleet
# console's transport column and capacity tuning read.


def shm_ring_depth_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_shm_ring_bytes",
        "shm fabric ring occupancy (unread bytes) per outbound lane",
        ("member", "peer", "ring"),
    ))


def shm_ring_high_water_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_shm_ring_high_water_bytes",
        "shm fabric ring occupancy high-water mark per outbound lane",
        ("member", "peer", "ring"),
    ))


def shm_frames_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_shm_frames_total",
        "frames written into shm fabric rings per outbound lane",
        ("member", "peer", "ring"),
    ))


def shm_copy_bytes_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_shm_copy_bytes_total",
        "frame body bytes copied into shm fabric rings per outbound "
        "lane (the transport's entire copy cost)",
        ("member", "peer", "ring"),
    ))


def shm_ring_full_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_shm_ring_full_total",
        "shm ring-full events per outbound lane (each drops one frame "
        "drop-don't-block; records counted on "
        "etcd_tpu_router_loss_total cls=ring_full_drop)",
        ("member", "peer", "ring"),
    ))


# Device apply-plane families (ISSUE 19, batched/applyplane.py): the
# hosting layer folds rawnode.plane_stats + its own lease-read
# counters into these after each health/metrics pass — fleet_console's
# plane columns and the read-mix SLO row read them back.


def apply_plane_slots_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_apply_plane_slots_high_water",
        "device KV slot occupancy high-water across a member's rows "
        "(vs cfg.apply_capacity; overflow rows spill to the host tier)",
        ("member",),
    ))


def apply_plane_leases_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_apply_plane_active_leases",
        "live (unexpired) key leases on the device plane, member-wide",
        ("member",),
    ))


def apply_plane_overflow_gauge(
        registry: Optional[pmet.Registry] = None) -> pmet.Gauge:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Gauge(
        "etcd_tpu_apply_plane_overflow_rows",
        "rows whose device KV store overflowed capacity (sticky; "
        "reads for spilled keys stay host-tier correct)",
        ("member",),
    ))


def apply_plane_watch_events_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_apply_plane_watch_events_total",
        "watch events emitted by device apply-stream matching",
        ("member",),
    ))


def apply_plane_reads_counter(
        registry: Optional[pmet.Registry] = None) -> pmet.Counter:
    reg = registry or pmet.DEFAULT
    return reg.register(pmet.Counter(
        "etcd_tpu_apply_plane_reads_total",
        "linearizable reads by serving path: kind=lease_hit (zero "
        "quorum rounds) vs kind=readindex_fallback",
        ("member", "kind"),
    ))


# -----------------------------------------------------------------------------
# The hub
# -----------------------------------------------------------------------------


class TelemetryHub:
    """Folds per-round telemetry frames into the metrics registry and
    keeps a bounded flight recorder.

    ``n_rows``: instance rows of the attached engine/rawnode (groups
    for a hosting member). Counters are exposed summed per group-shard
    (``shards`` label children per member — per-group label children
    would explode at G=65536). The flight recorder keeps per-row
    detail: full per-row deltas when ``n_rows`` is small, else totals
    plus the rows whose invariants tripped.
    """

    # Keep full per-row counter deltas in the ring below this many rows.
    FULL_DETAIL_ROWS = 256

    def __init__(self, n_rows: int, member: str = "0",
                 registry: Optional[pmet.Registry] = None,
                 ring: int = 64, shards: int = 8,
                 dump_dir: Optional[str] = None,
                 dump_on_trip: bool = True) -> None:
        self.n_rows = int(n_rows)
        self.member = str(member)
        self.registry = registry or pmet.DEFAULT
        self.shards = max(1, min(int(shards), self.n_rows))
        self._shard_of = (
            np.arange(self.n_rows) * self.shards // max(self.n_rows, 1)
        )
        self.dump_dir = dump_dir or os.environ.get(
            "ETCD_TPU_FLIGHTREC_DIR", "artifacts")
        self.dump_on_trip = dump_on_trip
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring))
        self._round = 0
        self._trips = 0
        self._dumped_on_trip = False
        self._last_totals: Optional[np.ndarray] = None
        self._last_inv: Optional[np.ndarray] = None
        self._counters = [
            [counter_family(n, self.registry).labels(self.member, str(s))
             for s in range(self.shards)]
            for n in TM_NAMES
        ]
        self._inv_counter = invariant_family(self.registry)
        self.last_dump: Optional[str] = None

    # -- ingest ---------------------------------------------------------------

    def ingest_round(self, counters: np.ndarray, invariants: np.ndarray,
                     extra: Optional[Dict] = None) -> None:
        """Fold one round's frame: ``counters`` [n_rows, NUM_COUNTERS]
        per-round deltas, ``invariants`` [n_rows] bitmaps."""
        counters = np.asarray(counters)
        invariants = np.asarray(invariants)
        # Registry fold: per counter, per shard.
        for ci in range(NUM_COUNTERS):
            col = counters[:, ci]
            if not col.any():
                continue
            if self.shards == 1:
                self._counters[ci][0].inc(float(col.sum()))
            else:
                sums = np.bincount(self._shard_of, weights=col,
                                   minlength=self.shards)
                for s in np.nonzero(sums)[0]:
                    self._counters[ci][int(s)].inc(float(sums[s]))
        tripped = np.nonzero(invariants)[0]
        for row in tripped:
            for name in decode_invariants(int(invariants[row])):
                self._inv_counter.labels(self.member, name).inc()
        with self._lock:
            self._round += 1
            self._ring.append(self._record(counters, invariants,
                                           tripped, extra))
            self._trips += len(tripped)
            want_dump = (
                len(tripped) > 0 and self.dump_on_trip
                and not self._dumped_on_trip
            )
            if want_dump:
                self._dumped_on_trip = True
        if want_dump:
            try:
                self.dump(reason="invariant-trip")
            except OSError:
                # The dump is evidence, not control flow: an unwritable
                # dump dir must not take down the member round thread
                # that ingested the frame.
                pass

    def ingest_totals(self, counters: np.ndarray, invariants: np.ndarray,
                      extra: Optional[Dict] = None) -> None:
        """Fold MONOTONE totals (the engine's in-device accumulator):
        the delta against the previously ingested totals is fed through
        ``ingest_round``. The invariant bitmap is OR-folded on device,
        so only bits NEWLY set since the last drain count — draining
        every chunk must not re-count one trip per drain. Used by
        closed-loop callers that only sync at chunk boundaries."""
        counters = np.asarray(counters, np.int64)
        invariants = np.asarray(invariants, np.int64)
        with self._lock:
            prev = self._last_totals
            prev_inv = self._last_inv
            self._last_totals = counters.copy()
            self._last_inv = invariants.copy()
        delta = counters if prev is None else counters - prev
        new_inv = (invariants if prev_inv is None
                   else invariants & ~prev_inv)
        self.ingest_round(np.maximum(delta, 0), new_inv, extra)

    def _record(self, counters: np.ndarray, invariants: np.ndarray,
                tripped: np.ndarray, extra: Optional[Dict]) -> Dict:
        rec: Dict = {
            "round": self._round,
            "t": time.time(),
            "totals": {
                n: int(counters[:, i].sum())
                for i, n in enumerate(TM_NAMES) if counters[:, i].any()
            },
        }
        if self.n_rows <= self.FULL_DETAIL_ROWS:
            nz_rows = np.nonzero(counters.any(axis=1))[0]
            rec["rows"] = {
                int(r): {
                    n: int(counters[r, i])
                    for i, n in enumerate(TM_NAMES) if counters[r, i]
                }
                for r in nz_rows
            }
        if len(tripped):
            rec["invariants"] = {
                int(r): decode_invariants(int(invariants[r]))
                for r in tripped
            }
        if extra:
            rec["extra"] = extra
        return rec

    # -- flight recorder ------------------------------------------------------

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def trips(self) -> int:
        with self._lock:
            return self._trips

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the flight-recorder ring (+ a registry snapshot of this
        member's counters) as JSON; returns the path."""
        with self._lock:
            recs = list(self._ring)
            rnd = self._round
            trips = self._trips
        if path is None:
            # Shared collision-free artifact naming (obs.artifacts):
            # simultaneous multi-member dumps on a checker failure must
            # never overwrite each other. Lazy import: obs must stay
            # out of this module's import graph (tracer imports the
            # registry families from here).
            from ..obs.artifacts import KIND_FLIGHTREC, dump_path

            path = dump_path(KIND_FLIGHTREC, self.member, reason,
                             self.dump_dir)
        payload = {
            "member": self.member,
            "reason": reason,
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "rounds_ingested": rnd,
            "invariant_trips": trips,
            "counter_names": list(TM_NAMES),
            "invariant_names": list(INV_NAMES),
            "ring": recs,
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        with self._lock:
            self.last_dump = path
        return path


def lane_summary(valid: np.ndarray) -> List[int]:
    """Per-lane message counts from a [n, R, K] validity mask — the
    decoded inbox/outbox summary the flight recorder rides."""
    return np.asarray(valid).sum(axis=(0, 1)).astype(int).tolist()
