"""Anomaly-driven leadership rebalancer for the batched hosting path.

Closes the loop the fleet observatory (obs/fleet.py, ISSUE 10) opened:
its device-side SummaryFrames already surface **when** leadership is
skewed (the ``leader_skew`` anomaly / the per-slot leader census behind
``etcd_tpu_fleet_leader_groups``), **who** is overloaded (the census
again — each hosting member's frame counts the groups its own rows
lead), and **which** groups are hurting (``commit_frozen`` plus the
top-K worst-backlogged rows, WITH group identities). This module turns
those signals into action:

* **when** — a pass triggers when the observed leader balance exceeds
  ``skew_ratio`` × the fair share (the same quantity the fleet hub's
  ``leader_skew`` flag edge-triggers on), or when a member's rollup
  carries a fresh ``leader_skew`` anomaly;
* **donors/receivers** — the member leading the most groups donates to
  the members below fair share, emptiest first;
* **priority** — donor-led groups that the observatory flagged
  (``commit_frozen`` log entries, merged top-K laggard ids) move FIRST:
  a lagging group on an overloaded leader is the one whose tail
  latency the move actually fixes;
* **actuation** — ``MsgTransferLeader`` per group (the admin
  ``transfer`` op / ``MultiRaftMember.transfer_leader``), each move
  awaited with a bounded timeout and retried at most ``max_retries``
  times. For full **migration** (move the replica, not just the
  lease), drive the membership ops instead: add-as-learner →
  snapshot-rejoin (an inbound snapshot ≥ watermark lifts fences,
  hosting.deliver) → promote → remove old voter (``reconfig``).

Flap-proofing is structural, not probabilistic: a group is never moved
twice within ``cooldown_s`` (whatever the signals claim), a pass moves
at most ``max_moves_per_pass`` groups, and a transfer that will not
complete is abandoned after ``max_retries`` bounded waits — so a noisy
or adversarial signal stream degrades to "no action", never to
leadership churn (proven by the flap-injection test in
tests/batched/test_rebalance.py).

Two actuators speak the same duck-typed surface: ``InProcActuator``
(tests, single-process clusters) and ``AdminActuator`` (the
``tools/rebalancerd.py`` daemon over the hosting admin API). No
bespoke probes: every decision input is a fleet rollup, every action an
existing admin op.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger("etcd_tpu.batched.rebalance")


@dataclass(frozen=True)
class RebalanceConfig:
    """Policy knobs. ``skew_ratio`` doubles as trigger and convergence
    bar: a pass fires above it and reports converged at-or-below it
    (matching the fleet hub's leader_skew flag semantics)."""

    skew_ratio: float = 1.5
    cooldown_s: float = 10.0  # per-group re-move quarantine
    max_moves_per_pass: int = 64
    max_retries: int = 3  # bounded transfer attempts per move
    transfer_wait_s: float = 5.0
    min_groups: int = 8  # tiny clusters are never "skewed"


@dataclass
class Move:
    group: int
    frm: int
    to: int
    attempts: int = 0
    ok: bool = False
    reason: str = ""  # why this group was picked (laggard/frozen/fill)


class InProcActuator:
    """Actuator over in-process MultiRaftMembers (tests, smokes)."""

    def __init__(self, members: Dict[int, object]) -> None:
        self._members = members

    def members(self) -> List[int]:
        return sorted(self._members)

    def rollup(self, mid: int) -> Optional[Dict]:
        m = self._members.get(mid)
        fleet = getattr(m, "fleet", None)
        return fleet.snapshot() if fleet is not None else None

    def led_groups(self, mid: int) -> List[int]:
        return self._members[mid].rn.leader_rows().tolist()

    def transfer(self, mid: int, groups: List[int], to: int,
                 wait_s: float) -> Tuple[List[int], List[int]]:
        m = self._members[mid]
        staged = [g for g in groups if m.transfer_leader(g, to)]
        missed = [g for g in groups if g not in staged]
        done, pending = m.wait_transfers(staged, to, timeout=wait_s)
        return done, pending + missed


class AdminActuator:
    """Actuator over the hosting admin API (rebalancerd's transport):
    ``fleet`` rollups in, ``leaders``/``transfer`` ops out."""

    def __init__(self, addrs: Dict[int, Tuple[str, int]],
                 timeout: float = 30.0) -> None:
        from .hosting_proc import ProcClient

        self._clients = {mid: ProcClient(a, timeout=timeout)
                         for mid, a in addrs.items()}

    def members(self) -> List[int]:
        return sorted(self._clients)

    def _call(self, mid: int, **req) -> Optional[Dict]:
        try:
            r = self._clients[mid].call(**req)
        except (OSError, ConnectionError, ValueError):
            return None
        return r if r.get("ok") else None

    def rollup(self, mid: int) -> Optional[Dict]:
        r = self._call(mid, op="fleet")
        return r.get("rollup") if r else None

    def led_groups(self, mid: int) -> List[int]:
        r = self._call(mid, op="leaders")
        if not r:
            return []
        return [g for g, lead in enumerate(r.get("leads", []))
                if lead == mid]

    def transfer(self, mid: int, groups: List[int], to: int,
                 wait_s: float) -> Tuple[List[int], List[int]]:
        r = self._call(mid, op="transfer", to=to, groups=groups,
                       wait_s=wait_s)
        if not r:
            return [], list(groups)
        return list(r.get("done", [])), list(r.get("pending", []))

    def close(self) -> None:
        for c in self._clients.values():
            c.close()


class Rebalancer:
    """One rebalancing control loop over an actuator. ``run_once`` is
    the whole contract: observe → decide → (maybe) move → re-observe;
    ``rebalancerd --once --json`` prints its report verbatim."""

    def __init__(self, actuator, cfg: Optional[RebalanceConfig] = None,
                 clock=time.monotonic) -> None:
        self.act = actuator
        self.cfg = cfg or RebalanceConfig()
        self._clock = clock
        self._last_move: Dict[int, float] = {}  # group -> move instant
        self._seen_skew: Dict[int, int] = {}  # edge-detect anomaly counts
        self._seen_limp: Dict[int, int] = {}  # edge-detect member_limping

    # -- observe ---------------------------------------------------------------

    def observe(self) -> Dict:
        """Scrape every member's fleet rollup into one decision view:
        leader balance, skew ratio, fresh leader_skew anomalies, and
        the flagged groups (commit_frozen + merged top-K laggards)."""
        balance: Dict[int, int] = {}
        flagged: List[Tuple[int, str]] = []  # (group, reason), ordered
        fresh_skew = False
        fresh_limp = False
        limping: List[int] = []  # members CURRENTLY limping (level)
        groups = 0
        for mid in self.act.members():
            roll = self.act.rollup(mid)
            if roll is None:
                continue
            balance[mid] = int(roll.get("leaders_total", 0))
            groups = max(groups, int(roll.get("groups", 0) or 0))
            counts = roll.get("anomalies", {})
            skew_n = int(counts.get("leader_skew", 0))
            if skew_n > self._seen_skew.get(mid, 0):
                fresh_skew = True
            self._seen_skew[mid] = skew_n
            # Gray-failure eviction input (ISSUE 15): the LEVEL signal
            # (member still limping now) targets the drain; the counted
            # edge triggers a pass even when the level flag flickers.
            limp_n = int(counts.get("member_limping", 0))
            if limp_n > self._seen_limp.get(mid, 0):
                fresh_limp = True
            self._seen_limp[mid] = limp_n
            if (roll.get("limp") or {}).get("limping"):
                limping.append(mid)
            for a in roll.get("anomaly_log", []):
                if a.get("kind") == "commit_frozen" and "group" in a:
                    flagged.append((int(a["group"]), "commit_frozen"))
            for e in roll.get("top", []):
                flagged.append((int(e["group"]), "laggard"))
        total = sum(balance.values())
        fair = total / max(len(balance), 1)
        ratio = (max(balance.values()) / fair
                 if balance and fair > 0 else 0.0)
        # Balance among the HEALTHY members only: after an eviction the
        # healthy survivors legitimately carry fair×R/(R-1) each — the
        # convergence bar for a fleet with limping members is judged on
        # this ratio, or a completed drain would read as fresh skew.
        healthy = {m: b for m, b in balance.items() if m not in limping}
        fair_h = sum(healthy.values()) / max(len(healthy), 1)
        healthy_ratio = (max(healthy.values()) / fair_h
                         if healthy and fair_h > 0 else 0.0)
        return {
            "members_seen": len(balance),
            "balance": balance,
            "groups": groups,
            "fair": fair,
            "ratio": ratio,
            "healthy_ratio": healthy_ratio,
            "fresh_skew": fresh_skew,
            "fresh_limp": fresh_limp,
            "limping": limping,
            "flagged": flagged,
        }

    # -- decide ----------------------------------------------------------------

    def plan(self, view: Dict) -> Tuple[List[Move], int]:
        """Moves for one pass (may be empty), plus how many candidate
        groups the per-group cooldown vetoed. Two modes:

        * **skew** (the ISSUE 11 loop): shave the most-loaded member
          down to fair share, receivers filled to fair share.
        * **evict** (gray-failure, ISSUE 15): a LIMPING member that
          still leads anything is drained to ZERO — a limping leader
          sits on every commit's critical path; as a follower the
          quorum forms from the healthy members. Healthy receivers
          split the drained load (limping members never receive —
          without that exclusion, the next skew pass would refill the
          slowest member in the fleet). Cooldown/caps apply unchanged:
          a flapping limp signal degrades to a bounded drain, never
          to churn.
        """
        cfg = self.cfg
        balance = dict(view["balance"])
        if len(balance) < 2 or view["fair"] <= 0:
            return [], 0
        limping = [m for m in view.get("limping", ())
                   if balance.get(m, 0) > 0]
        evict = bool(limping)
        if not evict:
            if (view["groups"] < cfg.min_groups
                    or not (view["ratio"] > cfg.skew_ratio
                            or view["fresh_skew"])):
                return [], 0
            donor = max(balance, key=lambda m: balance[m])
            excess = balance[donor] - int(view["fair"] + 0.5)
        else:
            donor = max(limping, key=lambda m: balance[m])
            excess = balance[donor]  # drain to zero
        if excess <= 0:
            return [], 0
        led = self.act.led_groups(donor)
        led_set = set(led)
        reason_of: Dict[int, str] = {}
        ordered: List[int] = []
        for g, why in view["flagged"]:
            if g in led_set and g not in reason_of:
                reason_of[g] = why
                ordered.append(g)
        ordered += [g for g in led if g not in reason_of]
        now = self._clock()
        cooled: List[int] = []
        vetoed = 0
        for g in ordered:
            if now - self._last_move.get(g, -1e9) < cfg.cooldown_s:
                vetoed += 1
            else:
                cooled.append(g)
        n = min(excess, cfg.max_moves_per_pass, len(cooled))
        # Receivers by deficit, emptiest first — limping members are
        # never receivers in EITHER mode. Skew mode fills each to fair
        # share so one pass cannot overshoot into a new skew; evict
        # mode splits the whole drain across the healthy members.
        moves: List[Move] = []
        receivers = sorted(
            (m for m in balance
             if m != donor and m not in view.get("limping", ())),
            key=lambda m: balance[m])
        if not receivers:
            return [], vetoed  # whole fleet limping: nowhere to move
        evict_room = -(-n // len(receivers))  # ceil split
        gi = 0
        for to in receivers:
            room = (evict_room if evict
                    else max(int(view["fair"] + 0.5) - balance[to], 0))
            while room > 0 and gi < n:
                g = cooled[gi]
                moves.append(Move(
                    group=g, frm=donor, to=to,
                    reason=("limp_evict" if evict
                            else reason_of.get(g, "fill"))))
                gi += 1
                room -= 1
                balance[to] += 1
        return moves, vetoed

    # -- act -------------------------------------------------------------------

    def run_once(self) -> Dict:
        cfg = self.cfg
        view = self.observe()
        moves, vetoed = self.plan(view)
        t0 = time.monotonic()
        # One actuator call per (donor, receiver) pair and retry round:
        # the transfer op takes a group list, and a 1024-group pass
        # must not serialize into a thousand waited round trips.
        by_pair: Dict[Tuple[int, int], List[Move]] = {}
        for mv in moves:
            by_pair.setdefault((mv.frm, mv.to), []).append(mv)
        for (frm, to), batch in by_pair.items():
            for _ in range(cfg.max_retries):
                todo = [mv for mv in batch if not mv.ok]
                if not todo:
                    break
                for mv in todo:
                    mv.attempts += 1
                done, _pending = self.act.transfer(
                    frm, [mv.group for mv in todo], to,
                    cfg.transfer_wait_s)
                done_set = set(done)
                for mv in todo:
                    mv.ok = mv.group in done_set
        for mv in moves:
            # Cooldown stamps even failed attempts: a group that will
            # not transfer must not be hammered pass after pass.
            self._last_move[mv.group] = self._clock()
        after = view
        if moves:
            # A completed transfer means the donor STOPPED leading; the
            # transferee's TimeoutNow election lands a few rounds
            # later. Let the census recover its pre-move leader total
            # before judging convergence, or the ratio is computed
            # over mid-election holes.
            deadline = time.monotonic() + max(cfg.transfer_wait_s, 1.0)
            total = sum(view["balance"].values())
            while True:
                after = self.observe()
                if (sum(after["balance"].values()) >= total
                        or time.monotonic() > deadline):
                    break
                time.sleep(0.2)
        # Gray-failure convergence: a limping member that still LEADS
        # anything is unfinished business, whatever the ratio says.
        undrained = [m for m in after.get("limping", ())
                     if after["balance"].get(m, 0) > 0]
        report = {
            "triggered": bool(moves) or view["ratio"] > cfg.skew_ratio
            or view["fresh_skew"] or bool(view.get("limping"))
            or view.get("fresh_limp", False),
            "ratio_before": round(view["ratio"], 3),
            "ratio_after": round(after["ratio"], 3),
            "balance_before": view["balance"],
            "balance_after": after["balance"],
            "limping": view.get("limping", []),
            "limping_after": after.get("limping", []),
            "moves": [vars(mv) for mv in moves],
            "moved": sum(1 for mv in moves if mv.ok),
            "failed": sum(1 for mv in moves if not mv.ok),
            "cooldown_vetoed": vetoed,
            "move_wall_s": round(time.monotonic() - t0, 3),
            "members_seen": after["members_seen"],
            # Zero reachable rollups is an observability outage, not a
            # balanced cluster — never report it as convergence. With
            # limping members present, balance is judged among the
            # healthy survivors (they legitimately carry the drained
            # load).
            "converged": (after["members_seen"] > 0
                          and (after["healthy_ratio"]
                               if after.get("limping")
                               else after["ratio"]) <= cfg.skew_ratio
                          and not undrained),
        }
        if moves:
            _log.info(
                "rebalance pass: %d/%d moves ok, ratio %.2f -> %.2f",
                report["moved"], len(moves), view["ratio"],
                after["ratio"])
        return report

    def run_forever(self, interval: float = 5.0,
                    on_report=None) -> None:
        while True:
            rep = self.run_once()
            if on_report is not None:
                on_report(rep)
            time.sleep(interval)
