"""Persistent XLA compilation cache wiring.

The batched round programs are the most expensive artifacts this repo
builds: a G=65536 closed-loop scan costs ~500s of XLA compile over the
remote-compile TPU tunnel (BENCH_NOTES r05), and every bench config,
layout probe, and frontier-sweep point used to pay it again from
scratch. JAX ships a persistent on-disk compilation cache keyed by the
(program, backend, flags) fingerprint; pointing every entry point at
one shared directory makes the second compile of an identical config a
disk hit instead of a recompile.

Wired through ``MultiRaftEngine``/``BatchedRawNode`` (idempotent,
env-overridable) and explicitly by ``bench.py``, ``tools/tpu_batch.py``
and ``tools/frontier_sweep.py`` (which log the dir and warm/cold
compile times).

Environment:

* ``ETCD_TPU_COMPILE_CACHE=<dir>`` — cache directory (default
  ``~/.cache/etcd_tpu/xla``).
* ``ETCD_TPU_COMPILE_CACHE=off`` (or ``0``/``none``) — disable.

Layout: one ``jit_<name>-<fingerprint>-cache`` blob per compiled
program plus an ``-atime`` sidecar (JAX's own format; safe to delete
wholesale — the next run recompiles and repopulates).
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "etcd_tpu", "xla"
)
_configured: Optional[str] = None


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Idempotently point JAX's persistent compilation cache at
    ``cache_dir`` (explicit arg > already-configured dir >
    ``ETCD_TPU_COMPILE_CACHE`` env > default; the env ``off`` switch
    applies only to no-arg calls). Returns the active directory, or
    None when disabled.

    Every program is cached regardless of size/compile time: the round
    kernels compile in seconds on CPU and minutes over the TPU tunnel,
    and both are worth the disk hit (frontier sweeps re-enter identical
    configs constantly).
    """
    global _configured
    env = os.environ.get("ETCD_TPU_COMPILE_CACHE", "")
    if cache_dir is None and env.lower() in ("0", "off", "none"):
        return None
    # A previously configured dir wins over env/default so the no-arg
    # calls every engine constructor makes don't silently repoint a
    # cache someone configured explicitly.
    cache_dir = cache_dir or _configured or env or _DEFAULT_DIR
    if _configured == cache_dir:
        return cache_dir

    import jax

    if cache_dir == _DEFAULT_DIR and _configured is None:
        # Latch a cache dir the EMBEDDING process already configured
        # (jax.config / JAX_COMPILATION_CACHE_DIR) instead of silently
        # repointing the process-wide cache at our default — an app
        # hosting this engine keeps its own cache.
        ext = getattr(jax.config, "jax_compilation_cache_dir", None)
        if ext:
            _configured = ext
            return ext
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _configured = cache_dir
    return cache_dir
