"""Replica-axis and log-ring kernels for the batched engine.

These are the array forms of the scalar oracles in ``etcd_tpu.raft``:
  - quorum_committed   ↔ quorum.MajorityConfig.committed_index
                         (ref: raft/quorum/majority.go:126-172)
  - vote_result        ↔ quorum.MajorityConfig.vote_result
                         (ref: raft/quorum/majority.go:178-210)
  - term_at            ↔ raftLog.term (ref: raft/log.go:268-288)
  - find_conflict_by_term ↔ raftLog.findConflictByTerm
                         (ref: raft/log.go:150-171) — exploits that log
                         terms are nondecreasing in the index, so the
                         backward scan becomes a masked count.

All functions are written per-instance (scalars + [R]/[W] vectors) and
are used under vmap over the instance axis.
"""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32
MAX_I32 = 2**31 - 1  # plain int: keep module import free of backend init

VOTE_PENDING, VOTE_LOST, VOTE_WON = 1, 2, 3


def quorum_committed(match: jnp.ndarray, voter: jnp.ndarray) -> jnp.ndarray:
    """Largest index acked by a quorum of voters.

    Go picks srt[n-(n/2+1)] of the ascending sort of n acked indexes
    (missing voters count 0). Masking non-voters to 0 prepends (R-n)
    zeros to the sort, shifting the pick to position R - n//2 - 1.
    """
    r = match.shape[-1]
    n = jnp.sum(voter.astype(I32))
    masked = jnp.where(voter, match, 0)
    srt = jnp.sort(masked)  # ascending
    pos = jnp.clip(r - n // 2 - 1, 0, r - 1)
    # Empty config commits "everything" (joint-quorum convention).
    return jnp.where(n == 0, MAX_I32, srt[pos])


def vote_result(votes: jnp.ndarray, voter: jnp.ndarray) -> jnp.ndarray:
    """VOTE_WON / VOTE_LOST / VOTE_PENDING from a [R] vote vector
    (-1 missing / 0 rejected / 1 granted) and a voter mask."""
    n = jnp.sum(voter.astype(I32))
    yes = jnp.sum((voter & (votes == 1)).astype(I32))
    no = jnp.sum((voter & (votes == 0)).astype(I32))
    missing = n - yes - no
    q = n // 2 + 1
    won = (yes >= q) | (n == 0)
    pending = yes + missing >= q
    return jnp.where(won, VOTE_WON, jnp.where(pending, VOTE_PENDING, VOTE_LOST))


def joint_committed(
    match: jnp.ndarray,
    voter: jnp.ndarray,
    voter_out: jnp.ndarray,
    in_joint: jnp.ndarray,
) -> jnp.ndarray:
    """Joint-config commit index = min over both halves
    (ref: raft/quorum/joint.go:49-56)."""
    main = quorum_committed(match, voter)
    return jnp.where(
        in_joint,
        jnp.minimum(main, quorum_committed(match, voter_out)),
        main,
    )


def joint_vote_result(
    votes: jnp.ndarray,
    voter: jnp.ndarray,
    voter_out: jnp.ndarray,
    in_joint: jnp.ndarray,
) -> jnp.ndarray:
    """Joint vote result (ref: raft/quorum/joint.go:61-75): lost if
    either half lost, pending if either half pending, else won."""
    a = vote_result(votes, voter)
    b = jnp.where(in_joint, vote_result(votes, voter_out), VOTE_WON)
    lost = (a == VOTE_LOST) | (b == VOTE_LOST)
    pending = (a == VOTE_PENDING) | (b == VOTE_PENDING)
    return jnp.where(lost, VOTE_LOST,
                     jnp.where(pending, VOTE_PENDING, VOTE_WON))


def term_at(
    log_term: jnp.ndarray,
    snap_index: jnp.ndarray,
    snap_term: jnp.ndarray,
    last: jnp.ndarray,
    i: jnp.ndarray,
) -> jnp.ndarray:
    """Term of entry i; 0 outside [snap_index, last] (the reference's
    "zero term on compacted/unavailable" behavior)."""
    w = log_term.shape[-1]
    in_ring = (i > snap_index) & (i <= last)
    ring_val = log_term[jnp.clip(i, 0, None) % w]
    return jnp.where(
        i == snap_index, snap_term, jnp.where(in_ring, ring_val, 0)
    )


def find_conflict_by_term(
    log_term: jnp.ndarray,
    snap_index: jnp.ndarray,
    snap_term: jnp.ndarray,
    last: jnp.ndarray,
    index: jnp.ndarray,
    term: jnp.ndarray,
) -> jnp.ndarray:
    """Largest idx <= index with term_at(idx) <= term.

    Log terms never decrease with index, so the answer is
    snap_index + |{ j in (snap_index, min(index,last)] : term(j) <= term }|.
    Degenerates to snap_index (the dummy index) when nothing matches,
    like the reference's backward scan hitting ErrCompacted.
    """
    w = log_term.shape[-1]
    hi = jnp.minimum(index, last)
    j = jnp.arange(w, dtype=I32)
    idx = snap_index + 1 + j
    valid = idx <= hi
    terms = log_term[idx % w]
    cnt = jnp.sum((valid & (terms <= term)).astype(I32))
    # When nothing in the window matches, the reference's backward walk
    # stops at the dummy index (term = snap_term) or, if even that term
    # is too large, one below it (term() reports 0 below the dummy —
    # ref: log.go:268-274).
    floor = jnp.where(snap_term <= term, snap_index, snap_index - 1)
    return jnp.where(cnt > 0, snap_index + cnt, floor)


def ring_write(
    log_term: jnp.ndarray, start_index: jnp.ndarray, terms: jnp.ndarray,
    count: jnp.ndarray,
) -> jnp.ndarray:
    """Write `count` terms at log positions start_index..start_index+count-1
    into the [W] ring."""
    w = log_term.shape[-1]
    k = terms.shape[-1]
    j = jnp.arange(k, dtype=I32)
    pos = (start_index + j) % w
    mask = j < count
    cur = log_term[pos]
    return log_term.at[pos].set(jnp.where(mask, terms, cur))
