"""Replica-axis and log-ring kernels for the batched engine.

These are the array forms of the scalar oracles in ``etcd_tpu.raft``:
  - quorum_committed   ↔ quorum.MajorityConfig.committed_index
                         (ref: raft/quorum/majority.go:126-172)
  - vote_result        ↔ quorum.MajorityConfig.vote_result
                         (ref: raft/quorum/majority.go:178-210)
  - term_at            ↔ raftLog.term (ref: raft/log.go:268-288)
  - find_conflict_by_term ↔ raftLog.findConflictByTerm
                         (ref: raft/log.go:150-171) — exploits that log
                         terms are nondecreasing in the index, so the
                         backward scan becomes a masked count.

All functions are written per-instance (scalars + [R]/[W] vectors) and
are used under vmap over the instance axis.
"""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32
MAX_I32 = 2**31 - 1  # plain int: keep module import free of backend init

VOTE_PENDING, VOTE_LOST, VOTE_WON = 1, 2, 3


def quorum_committed(match: jnp.ndarray, voter: jnp.ndarray) -> jnp.ndarray:
    """Largest index acked by a quorum of voters.

    Go picks srt[n-(n/2+1)] of the ascending sort of n acked indexes
    (missing voters count 0). Masking non-voters to 0 prepends (R-n)
    zeros to the sort, shifting the pick to position R - n//2 - 1.
    """
    r = match.shape[-1]
    n = jnp.sum(voter.astype(I32))
    masked = jnp.where(voter, match, 0)
    srt = jnp.sort(masked)  # ascending
    pos = jnp.clip(r - n // 2 - 1, 0, r - 1)
    # One-hot pick instead of srt[pos]: traced-index gathers serialize
    # on TPU; a compare+reduce over R stays on the VPU.
    pick = jnp.sum(jnp.where(jnp.arange(r, dtype=I32) == pos, srt, 0), -1)
    # Empty config commits "everything" (joint-quorum convention).
    return jnp.where(n == 0, MAX_I32, pick)


def vote_result(votes: jnp.ndarray, voter: jnp.ndarray) -> jnp.ndarray:
    """VOTE_WON / VOTE_LOST / VOTE_PENDING from a [R] vote vector
    (-1 missing / 0 rejected / 1 granted) and a voter mask."""
    n = jnp.sum(voter.astype(I32))
    yes = jnp.sum((voter & (votes == 1)).astype(I32))
    no = jnp.sum((voter & (votes == 0)).astype(I32))
    missing = n - yes - no
    q = n // 2 + 1
    won = (yes >= q) | (n == 0)
    pending = yes + missing >= q
    return jnp.where(won, VOTE_WON, jnp.where(pending, VOTE_PENDING, VOTE_LOST))


def joint_committed(
    match: jnp.ndarray,
    voter: jnp.ndarray,
    voter_out: jnp.ndarray,
    in_joint: jnp.ndarray,
) -> jnp.ndarray:
    """Joint-config commit index = min over both halves
    (ref: raft/quorum/joint.go:49-56)."""
    main = quorum_committed(match, voter)
    return jnp.where(
        in_joint,
        jnp.minimum(main, quorum_committed(match, voter_out)),
        main,
    )


def joint_vote_result(
    votes: jnp.ndarray,
    voter: jnp.ndarray,
    voter_out: jnp.ndarray,
    in_joint: jnp.ndarray,
) -> jnp.ndarray:
    """Joint vote result (ref: raft/quorum/joint.go:61-75): lost if
    either half lost, pending if either half pending, else won."""
    a = vote_result(votes, voter)
    b = jnp.where(in_joint, vote_result(votes, voter_out), VOTE_WON)
    lost = (a == VOTE_LOST) | (b == VOTE_LOST)
    pending = (a == VOTE_PENDING) | (b == VOTE_PENDING)
    return jnp.where(lost, VOTE_LOST,
                     jnp.where(pending, VOTE_PENDING, VOTE_WON))


def term_at(
    log_term: jnp.ndarray,
    snap_index: jnp.ndarray,
    snap_term: jnp.ndarray,
    last: jnp.ndarray,
    i: jnp.ndarray,
) -> jnp.ndarray:
    """Term of entry i; 0 outside [snap_index, last] (the reference's
    "zero term on compacted/unavailable" behavior).

    `i` may be a scalar or an [..., K] batch of indexes; the ring read
    is a one-hot compare+reduce over W (TPU-friendly: no gathers)."""
    w = log_term.shape[-1]
    in_ring = (i > snap_index) & (i <= last)
    p = jnp.arange(w, dtype=I32)
    im = jnp.mod(jnp.clip(i, 0, None), w)
    hit = jnp.expand_dims(im, -1) == p  # [..., W]
    ring_val = jnp.sum(jnp.where(hit, log_term, 0), axis=-1)
    return jnp.where(
        i == snap_index, snap_term, jnp.where(in_ring, ring_val, 0)
    )


def find_conflict_by_term(
    log_term: jnp.ndarray,
    snap_index: jnp.ndarray,
    snap_term: jnp.ndarray,
    last: jnp.ndarray,
    index: jnp.ndarray,
    term: jnp.ndarray,
) -> jnp.ndarray:
    """Largest idx <= index with term_at(idx) <= term.

    Log terms never decrease with index, so the answer is
    snap_index + |{ j in (snap_index, min(index,last)] : term(j) <= term }|.
    Degenerates to snap_index (the dummy index) when nothing matches,
    like the reference's backward scan hitting ErrCompacted.
    """
    w = log_term.shape[-1]
    hi = jnp.minimum(index, last)
    # Iterate ring POSITIONS instead of indexes: ring slot p holds the
    # unique index i_p in (snap_index, snap_index+W] with i_p % W == p,
    # so the rotation-gather becomes a pure compare+reduce.
    p = jnp.arange(w, dtype=I32)
    idx = snap_index + 1 + jnp.mod(p - snap_index - 1, w)
    valid = idx <= hi
    cnt = jnp.sum((valid & (log_term <= term)).astype(I32))
    # When nothing in the window matches, the reference's backward walk
    # stops at the dummy index (term = snap_term) or, if even that term
    # is too large, one below it (term() reports 0 below the dummy —
    # ref: log.go:268-274).
    floor = jnp.where(snap_term <= term, snap_index, snap_index - 1)
    return jnp.where(cnt > 0, snap_index + cnt, floor)


def invariant_bits(st, slot) -> jnp.ndarray:
    """Per-instance illegal-state bitmap (bit layout:
    telemetry.INV_NAMES), computed on end-of-round state.

    Everything here is impossible under the raft model — a set bit
    means either a kernel bug or a violated environment assumption
    (e.g. a torn WAL tail faking back acked state). Leader-side
    progress conditions are masked to tracked peers other than self.
    """
    # Local constants mirror state.py (state imports nothing from this
    # module, but keeping kernels import-free of state preserves the
    # existing layering for its scalar-oracle consumers).
    leader, probe, snapshot = 2, 0, 2
    r = st.match.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    is_leader = st.role == leader
    tracked = (st.voter | st.voter_out | st.learner) & (peers != slot)
    bad = [
        # next <= match on a tracked peer: next must stay >= match+1.
        is_leader & jnp.any(tracked & (st.next <= st.match)),
        # commit beyond the last log index.
        st.commit > st.last,
        # compaction floor above the commit watermark.
        st.snap_index > st.commit,
        # a leader whose own lead pointer names someone else.
        is_leader & (st.lead != slot + 1),
        # the progress wedge signature: paused probe that can never
        # make progress (probe_sent pinned while next <= match).
        is_leader & jnp.any(
            tracked & (st.pr_state == probe) & st.probe_sent
            & (st.next <= st.match)),
        # snapshot state whose pending index the peer already covers:
        # the accept path can never lift the pause.
        is_leader & jnp.any(
            tracked & (st.pr_state == snapshot)
            & (st.pending_snapshot <= st.match)),
        # a confirmed read batch with no batch open.
        st.read_ready & (st.read_index < 0),
        # a durability-fenced instance holding leadership: the fence
        # suppresses campaigning (and boot roles are follower), so a
        # fenced leader means the fence lane failed to gate an
        # election path — the exact hazard the fence exists to close.
        st.fenced & is_leader,
        # outgoing-voter residue outside a joint config: voter_out only
        # means anything while in_joint (quorum/commit read it through
        # the joint gates), so a nonzero row with in_joint false is a
        # conf-apply that flipped the lanes inconsistently — stale
        # outgoing voters would silently rejoin the electorate the
        # moment a later change re-enters joint.
        ~st.in_joint & jnp.any(st.voter_out),
        # ring occupancy past the window: an append crossed the
        # compaction floor and overwrote a live slot. The propose
        # headroom clamp + the host-side ring_full refusal make this
        # unreachable; a trip means log-lifecycle pressure accounting
        # broke (wrap = silent log corruption, the worst failure the
        # ring representation admits).
        (st.last - st.snap_index) > st.log_term.shape[-1],
        # leader-lease residue on a non-leader: the lease lane
        # authorizes quorum-free linearizable reads, so every
        # step-down path must zero it in the same round (step.py's
        # post-emit re-arm does exactly that) — a trip here is a
        # stale read authorization, the one failure mode the lease
        # fast path admits.
        (st.lease_ticks > 0) & ~is_leader,
    ]
    bits = jnp.zeros((), I32)
    for i, b in enumerate(bad):
        bits = bits | (b.astype(I32) << i)
    return bits


def log_bucket_index(v: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Log2 bucket of each non-negative value: bucket 0 holds v == 0,
    bucket b (1..num_buckets-2) holds v in [2^(b-1), 2^b), the last
    bucket is open-ended — the fleet-summary histogram discipline
    (obs/fleet.BUCKET_BOUNDS mirrors this host-side).

    Branch- and gather-free: the bucket index is the count of powers of
    two at-or-below v (a [.., B-1] compare + reduce keeps the VPU full
    instead of a serialized floor-log)."""
    thr = jnp.asarray([1 << b for b in range(num_buckets - 1)], I32)
    return jnp.sum((v[..., None] >= thr).astype(I32), axis=-1)


def log_bucket_counts_masked(v: jnp.ndarray, num_buckets: int,
                             mask: jnp.ndarray) -> jnp.ndarray:
    """[B] histogram of `v` (any leading shape) over log2 buckets,
    restricted to `mask` (same shape as v; masked-out elements count
    toward no bucket). One-hot compare + reduce — no scatters, so it
    vectorizes on TPU like the ring/quorum kernels above. The ONE
    bucketing implementation: the unmasked variant wraps it, so the
    bucket discipline cannot diverge between the two."""
    b = log_bucket_index(v, num_buckets)
    hit = (b[..., None] == jnp.arange(num_buckets, dtype=I32))
    hit = hit & mask[..., None]
    axes = tuple(range(hit.ndim - 1))
    return jnp.sum(hit.astype(I32), axis=axes)


def log_bucket_counts(v: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Unmasked log_bucket_counts_masked (an all-true mask fuses to a
    no-op; a shared Optional-mask branch would trip the jitlint
    tracer-branch rule)."""
    return log_bucket_counts_masked(
        v, num_buckets, jnp.ones(jnp.shape(v), bool))


def ring_write(
    log_term: jnp.ndarray, start_index: jnp.ndarray, terms: jnp.ndarray,
    count: jnp.ndarray,
) -> jnp.ndarray:
    """Write `count` terms at log positions start_index..start_index+count-1
    into the [W] ring."""
    j = jnp.arange(terms.shape[-1], dtype=I32)
    return ring_write_masked(log_term, start_index, terms, j < count)


def ring_write_masked(
    log_term: jnp.ndarray, start_index: jnp.ndarray, terms: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Write terms[j] at log position start_index+j for each masked j.

    Scatter-free: a [W, K] outer compare selects which ring slot each
    masked entry lands in (positions are distinct since K <= W and the
    indexes are consecutive), then a reduce over K folds them in."""
    w = log_term.shape[-1]
    k = terms.shape[-1]
    # K > W would alias ring positions and SUM colliding terms; shapes
    # are static, so this check costs nothing at runtime.
    assert k <= w, f"ring write batch {k} exceeds window {w}"
    p = jnp.arange(w, dtype=I32)
    jj = jnp.arange(k, dtype=I32)
    pos_j = jnp.mod(start_index + jj, w)  # [K]
    hit = (p[:, None] == pos_j[None, :]) & mask[None, :]  # [W, K]
    val = jnp.sum(jnp.where(hit, terms[None, :], 0), axis=-1)
    return jnp.where(jnp.any(hit, axis=-1), val, log_term)
