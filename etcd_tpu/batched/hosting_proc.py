"""Multi-raft hosting members as real OS processes.

One process = one ``MultiRaftMember`` (slot of every group) wired to its
peers by ``TCPRouter`` over real sockets — the deployment shape of the
reference, where each peer is a separate process reached via rafthttp
(ref: server/etcdserver/api/rafthttp/transport.go:97-132, Procfile).

The process exposes a small line-delimited JSON admin API on a local
TCP port so harnesses (tests/e2e, tools/multiraft_proc_demo) can drive
puts/reads, trigger campaigns, run a hosted-path benchmark, and stop it.
Run as::

    python -m etcd_tpu.batched.hosting_proc --id 1 --members 3 \
        --groups 1024 --data-dir /tmp/mr --bind 127.0.0.1:7001 \
        --admin 127.0.0.1:8001 --peer 2=127.0.0.1:7002 --peer 3=...
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

# NB: jax import happens inside MultiRaftMember; keep module import
# cheap so the spawning harness can import the client half freely.


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode())


# -- server side ---------------------------------------------------------------


class AdminServer:
    """Line-delimited JSON admin endpoint for one member process."""

    def __init__(self, member, router, bind: Tuple[str, int]) -> None:
        self.member = member
        self.router = router
        self._stopping = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(bind)
        self._srv.listen(8)
        self.addr = self._srv.getsockname()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        f = conn.makefile("rwb")
        try:
            for line in f:
                req: Dict = {}
                try:
                    req = json.loads(line)
                    resp = self._handle(req)
                except Exception as e:  # noqa: BLE001 — report to caller
                    resp = {"err": f"{type(e).__name__}: {e}"}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
                if req.get("op") == "stop":
                    break
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, req: Dict) -> Dict:
        m = self.member
        op = req["op"]
        if op == "ping":
            # Liveness probes are exactly what gray failures slip past
            # (HotOS'17): a fail-stopped or disk-full member still
            # answers this socket, so the ping carries the IO-error
            # contract's state — orchestration can see "up but dead"
            # and "up but write-stalled" without the full health op.
            return {"ok": True, "id": m.id,
                    "fail_stop": m._fail_stop_cause,
                    "disk_full": m._disk_full}
        if op == "campaign":
            m.campaign(req["groups"])
            return {"ok": True}
        if op == "leaders":
            import numpy as np

            from .state import LEADER

            mask = np.asarray(m.rn.m_role == LEADER)
            leads = [int(m.rn.lead(g)) for g in req.get(
                "groups", range(m.g))]
            return {"ok": True, "leads": leads,
                    "own": int(mask.sum())}
        if op == "put":
            g = req["g"]
            from .hosting import GroupKV

            payload = GroupKV.put_payload(_unb64(req["k"]), _unb64(req["v"]))
            if not m.propose(g, payload):
                return {"ok": False, "redirect": m.leader_of(g)}
            return {"ok": True}
        if op == "get":
            v = m.get(req["g"], _unb64(req["k"]))
            return {"ok": True, "v": _b64(v) if v is not None else None}
        if op == "lget":
            try:
                v = m.linearizable_get(req["g"], _unb64(req["k"]),
                                       timeout=req.get("timeout", 10.0))
            except Exception as e:  # noqa: BLE001 — NotLeader/Timeout
                return {"ok": False, "err": type(e).__name__}
            return {"ok": True, "v": _b64(v) if v is not None else None}
        if op == "applied":
            g = req["g"]
            return {"ok": True, "applied": int(m.applied_index[g])}
        if op == "transfer":
            to = req["to"]
            if not isinstance(to, int) or not 1 <= to <= m.cfg.num_replicas:
                return {"err": f"transfer target must be a member id "
                               f"1..{m.cfg.num_replicas}, got {to!r}"}
            moved = [g for g in req["groups"] if m.transfer_leader(g, to)]
            # Bounded wait-for-completion (default on; wait_s=0 keeps
            # the old fire-and-forget): a transfer is DONE once this
            # member no longer leads the group (the transferee's
            # TimeoutNow campaign displaced it) — callers like
            # rebalancerd need completion, not staging, and an
            # unbounded wait would wedge the admin lane on a wedged
            # transferee.
            wait_s = float(req.get("wait_s", 5.0))
            done, pending = (m.wait_transfers(moved, to, timeout=wait_s)
                             if wait_s > 0 and moved else (moved, []))
            return {"ok": True, "moved": len(moved), "done": done,
                    "pending": pending}
        if op == "reconfig":
            # Batched membership admin (ISSUE 11): add-learner /
            # promote (catch-up-gated) / remove, proposed through the
            # log on groups this member leads; per-group results tell
            # the driver exactly what to retry where ("not-leader" →
            # redirect, "not-ready" → wait for catch-up, "refused" →
            # illegal against the current config).
            action = req["action"]
            target = req["member"]
            if (not isinstance(target, int)
                    or not 1 <= target <= m.cfg.num_replicas):
                return {"err": f"reconfig member must be a member id "
                               f"1..{m.cfg.num_replicas}, got {target!r}"}
            try:
                res = m.reconfig(action, target, req["groups"],
                                 joint=bool(req.get("joint", False)))
            except ValueError as e:
                return {"err": str(e)}
            ok_n = sum(1 for v in res.values() if v == "ok")
            return {"ok": True, "proposed": ok_n,
                    "results": {str(g): v for g, v in res.items()}}
        if op == "conf":
            # Membership rollup: per-group voters/learners/joint state
            # plus applied/refused totals (check_config_safety's admin
            # face; fleet_console reads the cheaper health census).
            snap = m.conf_snapshot()
            return {"ok": True,
                    "voters": [list(v) for v in snap["voters"]],
                    "learners": [list(v) for v in snap["learners"]],
                    "voters_out": [list(v) for v in snap["voters_out"]],
                    "in_joint": [int(x) for x in snap["in_joint"]],
                    "applied_index":
                        [int(x) for x in snap["applied_index"]],
                    "refused": snap["refused"]}
        if op == "prof_reset":
            for k in list(m.stats):
                m.stats[k] = 0 if isinstance(m.stats[k], int) else 0.0
            if m.rn.prof:
                for k in list(m.rn.prof):
                    m.rn.prof[k] = 0
            return {"ok": True}
        if op == "prof":
            st = dict(m.stats)
            if m.rn.prof:
                st.update({f"rn_{k}": v for k, v in m.rn.prof.items()})
            return {"ok": True, "stats": st}
        if op == "stats":
            # Loss/error observability (ISSUE 2 satellite): member
            # pipeline stats + the fabric's drop counters — queue-full
            # drops, dial failures, redial-budget drops, send errors —
            # so operators see loss instead of silence. (The counters
            # live on the shared metrics registry; see op 'metrics'
            # for the full Prometheus-text dump.)
            rstats = {}
            rs = getattr(self.router, "stats", None)
            if callable(rs):
                rstats = rs()
            # Fabric identity + per-lane ring occupancy (shm only):
            # fleet_console's transport column reads this.
            fabric = {"kind": getattr(self.router, "kind", "tcp")}
            ls = getattr(self.router, "lane_stats", None)
            if callable(ls):
                fabric["lanes"] = ls()
            return {"ok": True, "member": dict(m.stats),
                    "router": rstats, "fabric": fabric}
        if op == "health":
            # Durability-fence visibility (protocol-aware torn-tail
            # recovery): per-group fenced state, the index gap still to
            # close to the durable watermark, and the boot WAL-tail
            # classification (clean boundary vs mid-record break) —
            # plus, since ISSUE 15, the IO-error contract's state:
            # disk_full back-pressure, the fail-stop cause, and the
            # boot-time salvage record for at-rest corruption.
            return {"ok": True, **m.health()}
        if op == "metrics":
            # Prometheus text exposition of the process registry —
            # kernel telemetry counters, invariant trips, WAL fsync /
            # round-phase histograms, router loss classes. Scrape with
            # tools/dump_metrics.py --admin host:port.
            from ..pkg import metrics as pmet

            return {"ok": True, "text": pmet.DEFAULT.expose()}
        if op == "trace":
            # Proposal-lifecycle trace ring (etcd_tpu.obs): inline
            # payload by default (tools/trace_merge.py joins the
            # members' payloads), or a JSON dump next to the flight
            # recorders with {"dump": true}.
            if m.tracer is None:
                return {"err": "tracing disabled (start the member "
                               "with --trace / ETCD_TPU_TRACE=1)"}
            if req.get("dump"):
                path = m.tracer.dump(reason=req.get("reason", "admin"))
                return {"ok": True, "path": path,
                        "spans": m.tracer.span_count()}
            return {"ok": True, "payload": m.tracer.to_payload()}
        if op == "fleet":
            # Fleet observatory (obs/fleet.py): inline rollup of the
            # latest device SummaryFrame — leader balance, top-K
            # laggards with group ids, fenced/role/progress censuses,
            # anomaly flags — or a groups×time heatmap ring dump with
            # {"dump": true}. tools/fleet_console.py renders the
            # rollups of every member as a live cluster view.
            if m.fleet is None:
                return {"err": "fleet summary disabled (start the "
                               "member with --fleet)"}
            if req.get("dump"):
                path = m.fleet.dump(reason=req.get("reason", "admin"))
                return {"ok": True, "path": path,
                        "frames": m.fleet.frames()}
            return {"ok": True, "rollup": m.fleet.snapshot(),
                    "invariant_trips": (m.hub.trips()
                                        if m.hub is not None else None)}
        if op == "flightrec":
            # Dump the member's flight recorder (last K rounds of
            # per-group telemetry deltas) to a JSON file on demand.
            if m.hub is None:
                return {"err": "telemetry disabled "
                               "(BatchedConfig.telemetry=False)"}
            path = m.hub.dump(reason=req.get("reason", "admin"))
            return {"ok": True, "path": path,
                    "trips": m.hub.trips()}
        if op == "bench":
            return self._bench(int(req["n"]),
                               int(req.get("value_size", 64)),
                               int(req.get("inflight", 4)),
                               float(req.get("read_mix", 0.0)))
        if op == "stop":
            threading.Thread(target=self._shutdown, daemon=True).start()
            return {"ok": True}
        return {"err": f"unknown op {op}"}

    def _bench(self, n: int, value_size: int,
               inflight: int = 4, read_mix: float = 0.0) -> Dict:
        """Hosted-path benchmark: propose n entries across the groups
        this member leads, confirm each applied locally (read-your-
        write at the leader), report throughput + commit p50/p99 —
        the service-rate number next to bench.py's kernel rate.

        read_mix in (0, 1] converts that fraction of the n ops into
        linearizable reads interleaved with the put stream (the first
        non-put hosted workload): each read is a synchronous
        linearizable_get on a bench key of a led group — lease-held
        leaders serve it locally with zero quorum rounds, cold leaders
        fall back to ReadIndex; the hit/fallback split rides the
        result so hosted_bench's SLO table reports the read hop."""
        import numpy as np

        from ..pkg.errors import NotLeaderError
        from .hosting import GroupKV
        from .state import LEADER

        m = self.member
        own = [g for g in range(m.g) if m.is_leader(g)]
        if not own:
            return {"err": "no groups led by this member"}
        val = b"v" * value_size
        n_reads = max(0, min(n, int(round(n * read_mix))))
        n = n - n_reads
        rd_lat: List[float] = []
        rd_lost = 0
        rd_issued = 0
        hits0 = int(m.stats.get("lease_read_hits", 0))
        falls0 = int(m.stats.get("lease_read_fallbacks", 0))

        def do_reads(owed: int) -> None:
            nonlocal rd_issued, rd_lost
            for _ in range(owed):
                g = own[rd_issued % len(own)]
                k = b"bench-%d" % (rd_issued % max(n, 1))
                t0 = time.perf_counter()
                try:
                    m.linearizable_get(g, k, timeout=5.0)
                    rd_lat.append(time.perf_counter() - t0)
                except (NotLeaderError, TimeoutError):
                    rd_lost += 1
                rd_issued += 1

        t_start = time.perf_counter()
        # Pipeline: propose in waves to bound the per-group inflight
        # (the engine caps proposals staged per round). A proposal
        # queued on a row that loses leadership before a round consumes
        # it is stranded (leader-only propose, no cross-member
        # forwarding at this layer), so stuck keys are re-proposed
        # while we still lead and counted lost otherwise — the etcd
        # benchmark tool's client-side retry, collapsed into the
        # worker (ref: tools/benchmark/cmd/put.go retry-on-error).
        lat: List[float] = []
        # Completion detection is watermark-driven: one numpy compare
        # of applied_index per poll, then key checks ONLY for groups
        # whose watermark moved — a flat poll over every outstanding
        # key burned most of the core and displaced the round loop it
        # was measuring.
        from collections import deque as _dq

        pend: Dict[int, "_dq"] = {g: _dq() for g in own}
        outstanding = 0
        lost = 0
        i = 0
        deadline = time.perf_counter() + max(60.0, n / 50.0)
        last_applied = m.applied_index.copy()
        last_sweep = time.perf_counter()
        while i < n or outstanding:
            while i < n and outstanding < inflight * len(own):
                g = own[i % len(own)]
                k = b"bench-%d" % i
                now = time.perf_counter()
                if m.propose(g, GroupKV.put_payload(k, val)):
                    pend[g].append([k, now, now])
                    outstanding += 1
                else:
                    lost += 1
                i += 1
            arr = m.applied_index.copy()
            now = time.perf_counter()
            changed = np.nonzero(arr != last_applied)[0]
            last_applied = arr
            sweep = now - last_sweep > 1.0
            groups = pend.keys() if sweep else changed
            if sweep:
                last_sweep = now
            for g in groups:
                q = pend.get(g)
                if not q:
                    continue
                while q and m.get(g, q[0][0]) is not None:
                    _k, t0, _tp = q.popleft()
                    outstanding -= 1
                    lat.append(now - t0)
                if sweep:
                    for rec in q:
                        if now - rec[2] > 2.0:
                            if m.propose(g, GroupKV.put_payload(
                                    rec[0], val)):
                                rec[2] = now
                            else:
                                rec[2] = float("inf")  # stranded
                    while q and q[0][2] == float("inf"):
                        q.popleft()
                        outstanding -= 1
                        lost += 1
            # Interleave owed reads with the put stream (same clock,
            # same thread — the mix is a schedule, not a second
            # phase, so the A/B stays same-day AND same-second).
            if n_reads and n:
                do_reads(min(i * n_reads // n, n_reads) - rd_issued)
            if now > deadline:
                lost += outstanding
                outstanding = 0
                break
            if outstanding:
                time.sleep(0.005)
        if n_reads:
            do_reads(n_reads - rd_issued)  # pure-read mixes land here
        dt = time.perf_counter() - t_start
        if not lat and not rd_lat:
            return {"err": "no ops completed", "lost": lost + rd_lost}
        lat_ms = sorted(x * 1000 for x in lat) or [0.0]
        out = {
            "ok": True,
            "n": n,
            "completed": len(lat),
            "lost": lost,
            "groups": len(own),
            "puts_per_sec": round(len(lat) / dt, 1) if lat else 0.0,
            "p50_ms": round(lat_ms[len(lat_ms) // 2], 3),
            "p99_ms": round(lat_ms[int(len(lat_ms) * 0.99) - 1], 3),
            # Raw samples so a multi-member harness can compute true
            # percentiles of the MERGED distribution (a mean of p50s is
            # not a percentile of anything).
            "lat_ms_samples": [round(x, 2) for x in lat_ms],
        }
        if n_reads:
            rms = sorted(x * 1000 for x in rd_lat) or [0.0]
            out.update({
                "reads": n_reads,
                "reads_completed": len(rd_lat),
                "reads_lost": rd_lost,
                "reads_per_sec": (
                    round(len(rd_lat) / dt, 1) if rd_lat else 0.0),
                "read_p50_ms": round(rms[len(rms) // 2], 3),
                "read_p99_ms": round(rms[int(len(rms) * 0.99) - 1], 3),
                "read_lat_ms_samples": [round(x, 2) for x in rms],
                # Serving-path split over THIS bench window (stats
                # deltas): lease_hit reads took zero quorum rounds.
                "lease_hits": int(
                    m.stats.get("lease_read_hits", 0)) - hits0,
                "lease_fallbacks": int(
                    m.stats.get("lease_read_fallbacks", 0)) - falls0,
            })
        return out

    def close(self) -> None:
        """Close the listening socket WITHOUT exiting the process —
        the in-process embedding path (tools/fleet_smoke.py hosts
        AdminServers around in-proc members); the worker-process path
        keeps using the 'stop' op → _shutdown → os._exit contract."""
        self._stopping.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _shutdown(self) -> None:
        self._stopping.set()
        try:
            self.member.stop()
        finally:
            self.router.stop()
            try:
                self._srv.close()
            except OSError:
                pass
            # Hard-exit: daemon threads (jax runtime included) must not
            # keep the worker alive after an orderly stop.
            os._exit(0)


def serve(member_id: int, num_members: int, num_groups: int,
          data_dir: str, bind: Tuple[str, int],
          admin: Tuple[str, int],
          peers: Dict[int, Tuple[str, int]],
          window: int = 32,
          tick_interval: float = 0.1,
          telemetry: bool = False,
          fleet: bool = False,
          trace: Optional[bool] = None,
          wal_pipeline: Optional[bool] = None,
          fabric: str = "tcp",
          shm_dir: Optional[str] = None,
          pin_core: Optional[int] = None,
          snap_cadence: Optional[int] = None,
          snap_keep: int = 2,
          wal_rotate_bytes: Optional[int] = None,
          apply_plane: bool = False) -> None:
    from .hosting import MultiRaftMember
    from .state import BatchedConfig

    if fabric == "inproc":
        raise SystemExit(
            "--fabric=inproc is the single-process harness fabric "
            "(MultiRaftCluster / tools/fleet_smoke.py); a hosting_proc "
            "worker is its own OS process — use tcp or shm")
    if fabric == "shm" and not shm_dir:
        raise SystemExit("--fabric=shm requires --shm-dir (one "
                         "directory SHARED by all member processes)")
    if pin_core is not None:
        # One pinned core per member process (true multi-core runs):
        # the shm fabric's whole point is that co-hosted members stop
        # time-slicing one socket loop.
        try:
            os.sched_setaffinity(0, {pin_core})
        except (AttributeError, OSError) as e:
            print(f"member {member_id}: pin to core {pin_core} "
                  f"failed: {e}", flush=True)

    cfg = BatchedConfig(
        num_groups=num_groups,
        num_replicas=num_members,
        window=window,
        max_ents_per_msg=4,
        max_props_per_round=4,
        election_timeout=10,
        heartbeat_timeout=1,
        pre_vote=True,
        check_quorum=True,
        auto_compact=True,
        # --telemetry: kernel counters + invariant sweep + flight
        # recorder, served through the admin 'metrics'/'flightrec' ops.
        telemetry=telemetry,
        # --fleet: device-side fleet SummaryFrame + FleetHub, served
        # through the admin 'fleet' op (tools/fleet_console.py).
        fleet_summary=fleet,
        # --apply-plane (ISSUE 19): device-resident KV/watch/lease
        # tensors + leader-lease local reads; the bench op's read_mix
        # serving-path split and the admin 'health' apply_plane block
        # light up with it.
        apply_plane=apply_plane,
    )
    member = MultiRaftMember(
        member_id, num_members, num_groups, data_dir, cfg=cfg,
        tick_interval=tick_interval, trace=trace,
        # --wal-pipeline / ETCD_TPU_WAL_PIPELINE (ISSUE 13): async
        # group-commit WAL pipeline — persistence decoupled from the
        # round cadence, acks released on fsync completion.
        wal_pipeline=wal_pipeline,
        # --snap-cadence / --wal-rotate-bytes (ISSUE 17): log-lifecycle
        # plane — cadence file snapshots, WAL segment rotation and
        # fleet-min-gated release; admin 'health' reports the
        # lifecycle/ring blocks, fleet_console renders them.
        snap_cadence=snap_cadence,
        snap_keep=snap_keep,
        wal_rotate_bytes=wal_rotate_bytes,
    )
    if fabric == "shm":
        from .shmfabric import ShmFabric

        router = ShmFabric(member, shm_dir)
        for pid in peers:
            router.add_peer(pid)
        raft_ep = f"shm:{shm_dir}"
    else:
        from .hosting import TCPRouter

        router = TCPRouter(member, bind=bind)
        for pid, addr in peers.items():
            router.add_peer(pid, addr)
        raft_ep = router.addr
    srv = AdminServer(member, router, admin)
    member.start()
    print(f"member {member_id} serving: raft={raft_ep} "
          f"admin={srv.addr} groups={num_groups}", flush=True)
    threading.Event().wait()  # park; admin 'stop' hard-exits


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--id", type=int, required=True)
    p.add_argument("--members", type=int, required=True)
    p.add_argument("--groups", type=int, required=True)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--bind", required=True)
    p.add_argument("--admin", required=True)
    p.add_argument("--peer", action="append", default=[],
                   help="peerid=host:port (repeatable)")
    p.add_argument("--window", type=int, default=32)
    p.add_argument("--tick-interval", type=float, default=0.1)
    p.add_argument("--telemetry", action="store_true",
                   help="enable the kernel telemetry plane (metrics + "
                        "flight recorder via the admin API)")
    p.add_argument("--fleet", action="store_true",
                   help="enable the fleet observatory (device-side "
                        "group-state summary frames; admin 'fleet' op "
                        "+ etcd_tpu_fleet_* metrics + heatmap ring — "
                        "see tools/fleet_console.py)")
    p.add_argument("--trace", action="store_true",
                   help="enable proposal-lifecycle tracing (sampled "
                        "span stamps; admin 'trace' op serves the "
                        "ring — see ETCD_TPU_TRACE_SAMPLE/_SEED)")
    p.add_argument("--wal-pipeline", action="store_true",
                   help="run persistence as an async group-commit "
                        "pipeline: WAL append+fsync on a dedicated "
                        "worker overlapped with device rounds, one "
                        "fsync covering every round queued since the "
                        "last, acks released at fsync completion "
                        "(ETCD_TPU_WAL_PIPELINE=1 is the env form; "
                        "admin 'health' reports rounds_per_fsync)")
    p.add_argument("--fabric", choices=("tcp", "shm", "inproc"),
                   default="tcp",
                   help="peer transport: tcp (TCPRouter sockets, "
                        "default), shm (mmap'd SPSC ring fabric for "
                        "co-hosted members — requires --shm-dir), "
                        "inproc (single-process harness only; a "
                        "worker process rejects it with a pointer)")
    p.add_argument("--shm-dir", default=None,
                   help="directory for the shm fabric's lane ring "
                        "files; must be the SAME directory for every "
                        "member process of the cluster")
    p.add_argument("--pin-core", type=int, default=None,
                   help="pin this member process to one CPU core "
                        "(sched_setaffinity) — one core per member "
                        "is the multi-core hosted-bench shape")
    p.add_argument("--snap-cadence", type=int, default=None,
                   help="build a file snapshot for a group every N "
                        "applied entries (log-lifecycle plane; off by "
                        "default — the WAL then grows unboundedly)")
    p.add_argument("--snap-keep", type=int, default=2,
                   help="snapshot files retained per group after each "
                        "successful build (keep-K pruning)")
    p.add_argument("--wal-rotate-bytes", type=int, default=None,
                   help="cut the WAL tail segment past this many "
                        "bytes and release sealed segments once every "
                        "group's snapshot covers them (off by "
                        "default)")
    p.add_argument("--apply-plane", action="store_true",
                   help="enable the device-resident apply plane "
                        "(tensorized KV/watch/lease state + leader-"
                        "lease local reads; protocol state stays "
                        "bit-identical — see README 'Device apply "
                        "plane')")
    a = p.parse_args(argv)

    def hp(s: str) -> Tuple[str, int]:
        h, _, pt = s.rpartition(":")
        return h, int(pt)

    peers = {}
    for spec in a.peer:
        pid, _, addr = spec.partition("=")
        peers[int(pid)] = hp(addr)
    serve(a.id, a.members, a.groups, a.data_dir, hp(a.bind),
          hp(a.admin), peers, window=a.window,
          tick_interval=a.tick_interval, telemetry=a.telemetry,
          fleet=a.fleet, trace=a.trace or None,
          wal_pipeline=a.wal_pipeline or None,
          fabric=a.fabric, shm_dir=a.shm_dir, pin_core=a.pin_core,
          snap_cadence=a.snap_cadence, snap_keep=a.snap_keep,
          wal_rotate_bytes=a.wal_rotate_bytes,
          apply_plane=a.apply_plane)


# -- client side ---------------------------------------------------------------


class ProcClient:
    """Admin-API client for one member process."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 60.0):
        self.addr = addr
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._f = None
        self._lock = threading.Lock()

    def _ensure(self) -> None:
        if self._sock is None:
            self._sock = socket.create_connection(
                self.addr, timeout=self.timeout)
            self._f = self._sock.makefile("rwb")

    def call(self, **req) -> Dict:
        with self._lock:
            self._ensure()
            try:
                self._f.write(json.dumps(req).encode() + b"\n")
                self._f.flush()
                line = self._f.readline()
            except OSError:
                self.close()
                raise
            if not line:
                self.close()
                raise ConnectionError("admin connection closed")
            return json.loads(line)

    def put(self, g: int, k: bytes, v: bytes) -> Dict:
        return self.call(op="put", g=g, k=_b64(k), v=_b64(v))

    def get(self, g: int, k: bytes) -> Optional[bytes]:
        r = self.call(op="get", g=g, k=_b64(k))
        return _unb64(r["v"]) if r.get("v") else None

    def lget(self, g: int, k: bytes, timeout: float = 10.0) -> Dict:
        return self.call(op="lget", g=g, k=_b64(k), timeout=timeout)

    def close(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._f = None


def wait_admin(addr: Tuple[str, int], timeout: float = 120.0) -> ProcClient:
    """Wait for a member process's admin endpoint to come up (device
    program compile happens at process start and can take a while)."""
    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            c = ProcClient(addr)
            r = c.call(op="ping")
            if r.get("ok"):
                return c
        except (OSError, ConnectionError, ValueError) as e:
            last = e
        time.sleep(0.25)
    raise TimeoutError(f"admin {addr} not up: {last}")


if __name__ == "__main__":
    main()
