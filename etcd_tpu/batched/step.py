"""The batched step kernel: branch-free message handlers vmapped over the
instance axis, with an all-device message router.

Semantics mirror the single-group oracle (etcd_tpu.raft.raft, ref:
raft/raft.go Step/stepLeader/stepCandidate/stepFollower): appends,
append responses with reject-hint probing, heartbeats, elections (vote +
optional pre-vote), commit-index advancement, snapshot fallback for
lagging followers, proposals — and, since round 2, the former cold
paths as well: ReadIndex (heartbeat-ack quorum, see the readindex
handling around the heartbeat-response lane below), joint-config
membership changes (per-instance voter/learner masks with joint
commit/vote kernels), learners, and leader transfer all execute on
device. The host uploads mask/config rows (set_membership) but does not
step the protocol for any of these — see SURVEY.md §2.1.

Network model: per round each replica sends at most one message of each
KIND to each peer, so an inbox is a dense ``[N, R, K]`` slot array and
routing between instances of the same group is a single transpose over
the (sender, target) axes — no scatters, no host round-trips. A round
is one jitted program:

    deliver (shape-configured: lane scans, merged scans, or the
    scan-free vectorized fold) → tick → control → propose → emit → route

Determinism: randomized election timeouts use a per-instance hash of
(instance id, reset count), reproducible by the host oracle for
differential testing (ref: raft.go:1718-1720 resetRandomizedElectionTimeout).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import (
    VOTE_LOST,
    VOTE_WON,
    find_conflict_by_term,
    invariant_bits,
    joint_committed,
    joint_vote_result,
    log_bucket_counts,
    log_bucket_counts_masked,
    ring_write,
    ring_write_masked,
    term_at,
)
from ..analysis.sentinels import note_compile_key
from ..obs.fleet import FLEET_BUCKETS, FleetLayout
from .telemetry import NUM_COUNTERS
from .state import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    PRECANDIDATE,
    PROBE,
    REPLICATE,
    SNAPSHOT,
    BatchedConfig,
    BatchedState,
    I32,
    narrow_state,
    widen_state,
)

# Message kinds = inbox slot layout (capacity classes, not semantics: a
# slot of a response kind may carry a stale-term MsgAppResp; handlers
# dispatch on the type field).
#
# THE INBOX LANE-ORDER CONTRACT (one constant, three consumers): the
# first NUM_REQ_KINDS lanes carry requests, and the response to a
# kind-k request routes back in lane ``k + NUM_REQ_KINDS`` — lane 3
# carries vote responses, lane 4 append responses, lane 5 heartbeat
# responses. Everything that splits or scatters lanes derives from
# NUM_REQ_KINDS: the deliver shapes' request/response split, the
# round's response scatter (``out[:, NUM_REQ_KINDS:]`` in
# _step_round_jit), and route()'s no-op on lane indexes (responses are
# already placed in their response lane BEFORE the transpose). The
# msgblock↔step differential test pins the contract
# (tests/batched/test_msgblock.py), so a drifted call site fails a
# test instead of silently crossing lanes.
KIND_VOTE, KIND_APP, KIND_HB, KIND_VOTE_RESP, KIND_APP_RESP, KIND_HB_RESP = range(6)
NUM_KINDS = 6
NUM_REQ_KINDS = 3
assert (KIND_VOTE_RESP, KIND_APP_RESP, KIND_HB_RESP) == tuple(
    k + NUM_REQ_KINDS for k in (KIND_VOTE, KIND_APP, KIND_HB)
), "response lanes must sit exactly NUM_REQ_KINDS above their requests"

# Wire types (values match etcd_tpu.raft.types.MessageType).
T_APP, T_APP_RESP = 3, 4
T_VOTE, T_VOTE_RESP = 5, 6
T_SNAP = 7
T_HB, T_HB_RESP = 8, 9
T_TIMEOUT_NOW = 14
T_PREVOTE, T_PREVOTE_RESP = 17, 18

# Wire type -> inbox lane, as a lookup table usable both host-side
# (msgblock codec validation) and on device (pack_outbox); -1 marks
# unroutable types (mirrors rawnode._LANE).
NUM_WIRE_TYPES = 32
LANE_OF = np.full(NUM_WIRE_TYPES, -1, np.int8)
for _t, _lane in (
    (T_VOTE, KIND_VOTE), (T_PREVOTE, KIND_VOTE),
    (T_APP, KIND_APP), (T_SNAP, KIND_APP),
    (T_HB, KIND_HB), (T_TIMEOUT_NOW, KIND_HB),
    (T_VOTE_RESP, KIND_VOTE_RESP), (T_PREVOTE_RESP, KIND_VOTE_RESP),
    (T_APP_RESP, KIND_APP_RESP),
    (T_HB_RESP, KIND_HB_RESP),
):
    LANE_OF[_t] = _lane


class MsgSlots(NamedTuple):
    """SoA message batch; every field has the same leading shape, plus
    ent_terms with a trailing [E]."""

    valid: jnp.ndarray  # bool
    type: jnp.ndarray  # i32
    term: jnp.ndarray  # i32
    log_term: jnp.ndarray  # i32
    index: jnp.ndarray  # i32
    commit: jnp.ndarray  # i32
    reject: jnp.ndarray  # bool
    reject_hint: jnp.ndarray  # i32
    n_ents: jnp.ndarray  # i32
    # Context word (the reference's Message.Context bytes, reduced to
    # what rides it: campaign-transfer flag on votes, read_seq on
    # heartbeats/acks — ref: raft.go campaignTransfer, read_only.go ctx).
    ctx: jnp.ndarray  # i32
    ent_terms: jnp.ndarray  # i32 [..., E]


# Narrow storage dtype per bounded message lane (cfg.narrow_lanes),
# the MsgSlots twin of state.NARROW_DTYPES: wire types are < 32 (int8),
# per-message entry counts are <= MAX_WIRE_ENTS = 255 (int16; int8 is
# signed and would wrap at 128). valid/reject are already bool. The
# unbounded protocol words (term/index/commit/log_term/reject_hint/
# ent_terms, plus ctx which carries read_seq) stay int32 — narrowing a
# watermark would change wrap semantics. Narrow lanes live ONLY in the
# between-rounds carry (the routed inbox / emitted outbox); the round
# kernel widens at deliver entry and narrows at emit exit, so handler
# math is bit-identical to the wide layout (the jitlint narrow-lane
# contract, mirroring state.widen_state/narrow_state).
NARROW_MSG_DTYPES = {
    "type": jnp.int8,
    "n_ents": jnp.int16,
}


def narrow_msgs(m: MsgSlots) -> MsgSlots:
    """Cast the bounded message lanes to their narrow storage dtypes."""
    return m._replace(**{
        f: getattr(m, f).astype(dt) for f, dt in NARROW_MSG_DTYPES.items()
    })


def widen_msgs(m: MsgSlots) -> MsgSlots:
    """Cast narrow message lanes back to i32 for the round kernel."""
    return m._replace(**{
        f: getattr(m, f).astype(I32) for f in NARROW_MSG_DTYPES
    })


def empty_msgs(shape: Tuple[int, ...], num_ents: int,
               narrow: bool = False) -> MsgSlots:
    # One fresh buffer per field (no aliasing): the round loop donates
    # its inbox, and a buffer appearing under two leaves of a donated
    # pytree is a runtime error ("attempt to donate the same buffer
    # twice"). Inside a trace these are constants either way.
    z = lambda: jnp.zeros(shape, I32)  # noqa: E731
    m = MsgSlots(
        valid=jnp.zeros(shape, bool),
        type=z(),
        term=z(),
        log_term=z(),
        index=z(),
        commit=z(),
        reject=jnp.zeros(shape, bool),
        reject_hint=z(),
        n_ents=z(),
        ctx=z(),
        ent_terms=jnp.zeros(shape + (num_ents,), I32),
    )
    return narrow_msgs(m) if narrow else m


def _sel(cond, a, b):
    """Tree-select: where(cond, a, b) leafwise (cond is scalar here)."""
    return jax.tree.map(lambda x, y: jnp.where(cond, x, y), a, b)


def _pick(vec, at):
    """vec[s] for a traced s, as compare+reduce (at = peers == s):
    traced-index gathers serialize on TPU, one-hot reads don't."""
    return jnp.sum(jnp.where(at, vec, 0), axis=-1)


def _pick_b(vec, at):
    """Bool variant of _pick."""
    return jnp.any(vec & at, axis=-1)


# -----------------------------------------------------------------------------
# Per-instance primitive transitions (scalars + [R]/[W] vectors; used
# under vmap). Each returns a full BatchedState slice.
# -----------------------------------------------------------------------------


def _rand_timeout(cfg: BatchedConfig, iid, reset_count):
    """Deterministic stand-in for lockedRand: [et, 2et-1], reproducible
    by the host oracle."""
    h = ((iid + 1) * 7919 + reset_count * 104729) % cfg.election_timeout
    return cfg.election_timeout + h


def _reset(cfg: BatchedConfig, st: BatchedState, iid, slot, term) -> BatchedState:
    """ref: raft.go:590-619 reset()."""
    r = st.match.shape[-1]
    changed = st.term != term
    rc = st.reset_count + 1
    peers = jnp.arange(r, dtype=I32)
    return st._replace(
        term=term,
        vote=jnp.where(changed, 0, st.vote),
        lead=jnp.zeros_like(st.lead),
        election_elapsed=jnp.zeros_like(st.election_elapsed),
        heartbeat_elapsed=jnp.zeros_like(st.heartbeat_elapsed),
        reset_count=rc,
        randomized_timeout=_rand_timeout(cfg, iid, rc),
        votes=jnp.full((r,), -1, I32),
        match=jnp.where(peers == slot, st.last, 0),
        next=jnp.full((r,), 1, I32) * (st.last + 1),
        pr_state=jnp.full((r,), PROBE, I32),
        probe_sent=jnp.zeros((r,), bool),
        pending_snapshot=jnp.zeros((r,), I32),
        recent_active=jnp.zeros((r,), bool),
        inflight=jnp.zeros((r,), I32),
        # abortLeaderTransfer + read state dies with the term/role
        # (ref: raft.go:590-619 reset).
        transferee=jnp.zeros_like(st.transferee),
        transfer_sent=jnp.zeros_like(st.transfer_sent),
        read_index=jnp.full_like(st.read_index, -1),
        read_acks=jnp.zeros((r,), bool),
        read_ready=jnp.zeros_like(st.read_ready),
        read_req_latch=jnp.zeros_like(st.read_req_latch),
    )


def _become_follower(cfg, st, iid, slot, term, lead) -> BatchedState:
    st = _reset(cfg, st, iid, slot, term)
    return st._replace(role=jnp.full_like(st.role, FOLLOWER), lead=lead)


def _append_own(cfg: BatchedConfig, st: BatchedState, slot, n) -> BatchedState:
    """Leader appends n entries of its own term (ref: raft.go:621-642
    appendEntry): ring write, self progress, maybe_commit."""
    p = cfg.max_props_per_round
    terms = jnp.full((p,), 1, I32) * st.term
    log = ring_write(st.log_term, st.last + 1, terms, n)
    last = st.last + n
    r = st.match.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    match = jnp.where(peers == slot, jnp.maximum(st.match, last), st.match)
    nxt = jnp.where(peers == slot, jnp.maximum(st.next, last + 1), st.next)
    st = st._replace(log_term=log, last=last, match=match, next=nxt)
    return _maybe_commit(st)


def _maybe_commit(st: BatchedState) -> BatchedState:
    """Quorum commit-index advancement — THE replica-axis reduction
    (ref: raft.go:585-588 + quorum/majority.go:126, joint.go:49-56)."""
    mci = joint_committed(st.match, st.voter, st.voter_out, st.in_joint)
    ok = (mci > st.commit) & (
        term_at(st.log_term, st.snap_index, st.snap_term, st.last, mci) == st.term
    )
    return st._replace(commit=jnp.where(ok, mci, st.commit))


def _repl_targets(st: BatchedState) -> jnp.ndarray:
    """[R] replication set: every tracked progress — voters of both
    configs plus learners (ref: tracker.go Visit over the full
    progress map)."""
    return st.voter | st.voter_out | st.learner


def _vote_targets(st: BatchedState) -> jnp.ndarray:
    """[R] electorate: voters of both halves, never learners."""
    return st.voter | st.voter_out


def _become_leader(cfg, st, iid, slot) -> BatchedState:
    """ref: raft.go:724-758 (reset, self replicate, append empty entry)."""
    st = _reset(cfg, st, iid, slot, st.term)
    r = st.match.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    st = st._replace(
        role=jnp.full_like(st.role, LEADER),
        lead=slot + 1,
        pr_state=jnp.where(peers == slot, REPLICATE, st.pr_state),
    )
    return _append_own(cfg, st, slot, jnp.asarray(1, I32))


def _record_vote_and_tally(st: BatchedState, from_slot, granted):
    """ref: tracker.go RecordVote (setdefault) + TallyVotes."""
    r = st.votes.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    new_vote = jnp.where(granted, 1, 0)
    votes = jnp.where(
        (peers == from_slot) & (st.votes == -1), new_vote, st.votes
    )
    st = st._replace(votes=votes)
    return st, joint_vote_result(votes, st.voter, st.voter_out, st.in_joint)


def _campaign(cfg: BatchedConfig, st: BatchedState, iid, slot, pre: bool,
              transfer: bool = False) -> BatchedState:
    """ref: raft.go:785-835; `pre`/`transfer` are static bools
    (config.pre_vote; campaignTransfer skips pre-vote and marks its
    vote requests to pierce leader leases)."""
    if pre:
        # becomePreCandidate: no term bump, no vote change.
        st1 = st._replace(
            role=jnp.full_like(st.role, PRECANDIDATE),
            lead=jnp.zeros_like(st.lead),
            votes=jnp.full_like(st.votes, -1),
        )
    else:
        st1 = _reset(cfg, st, iid, slot, st.term + 1)
        st1 = st1._replace(
            role=jnp.full_like(st.role, CANDIDATE), vote=slot + 1
        )
    st1, res = _record_vote_and_tally(st1, slot, jnp.asarray(True))
    won = res == VOTE_WON
    if pre:
        # Single-voter group: pre-vote win chains into the real election.
        st_won = _campaign(cfg, st1, iid, slot, False)
    else:
        st_won = _become_leader(cfg, st1, iid, slot)
    st_lost = st1._replace(
        send_vote_req=jnp.ones_like(st.send_vote_req),
        vote_req_is_pre=jnp.full_like(st.vote_req_is_pre, pre),
        vote_req_transfer=jnp.full_like(st.vote_req_transfer, transfer),
    )
    return _sel(won, st_won, st_lost)


def _paused(cfg: BatchedConfig, st: BatchedState):
    """[R] bool — ref: tracker/progress.go:201-212 IsPaused."""
    return jnp.where(
        st.pr_state == PROBE,
        st.probe_sent,
        jnp.where(
            st.pr_state == REPLICATE,
            st.inflight >= cfg.max_inflight,
            True,
        ),
    )


# -----------------------------------------------------------------------------
# Per-message delivery (one inbox slot for one instance)
# -----------------------------------------------------------------------------


def _term_gate(cfg: BatchedConfig, iid, slot, st: BatchedState, m: MsgSlots,
               from_slot):
    """raft.Step's term handling (ref: raft.go:849-920), shared by every
    lane handler. Returns (st1, dead, lower, stale_resp_needed) where
    st1 is post-become-follower state, `dead` kills the message
    entirely, `lower` routes to the stale path."""
    higher = m.term > st.term
    lower = m.term < st.term

    from_leader_type = (m.type == T_APP) | (m.type == T_HB) | (m.type == T_SNAP)
    is_vote_req = (m.type == T_VOTE) | (m.type == T_PREVOTE)

    in_lease = (
        jnp.asarray(cfg.check_quorum)
        & (st.lead != 0)
        & (st.election_elapsed < cfg.election_timeout)
    )
    # Transfer-campaign votes pierce the lease (ref: raft.go:870-880
    # force = Context == campaignTransfer).
    ignore_lease = higher & is_vote_req & in_lease & ~(m.ctx == 1)

    keep_term = (m.type == T_PREVOTE) | ((m.type == T_PREVOTE_RESP) & ~m.reject)
    do_become = higher & ~keep_term & ~ignore_lease
    st_b = _become_follower(
        cfg, st, iid, slot, m.term,
        jnp.where(from_leader_type, from_slot + 1, 0),
    )
    st1 = _sel(do_become, st_b, st)
    dead = ~m.valid | ignore_lease
    return st1, dead, lower


# -- lane handlers: each processes ONE inbox lane's message for one
# instance, implementing only the types that can land in that lane
# (lanes are capacity classes — the specialization is what keeps the
# per-slot cost low; ref: raft.go:991-1473 step* dispatch).


def _lane_vote(cfg: BatchedConfig, iid, slot, st: BatchedState, m: MsgSlots,
               from_slot):
    """Lane KIND_VOTE: T_VOTE / T_PREVOTE requests (ref: raft.go:930-978)."""
    no_resp = empty_msgs((), cfg.max_ents_per_msg)
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)

    last_term = term_at(
        st1.log_term, st1.snap_index, st1.snap_term, st1.last, st1.last
    )
    can_vote = (
        (st1.vote == from_slot + 1)
        | ((st1.vote == 0) & (st1.lead == 0))
        | ((m.type == T_PREVOTE) & (m.term > st1.term))
    )
    up_to_date = (m.log_term > last_term) | (
        (m.log_term == last_term) & (m.index >= st1.last)
    )
    # Durability-fenced instances grant nothing (vote or pre-vote): a
    # fence means this replica verifiably lost fsync'd-acked state at
    # its last crash, so neither its log comparison nor its persisted
    # vote can back the election-safety promises a grant makes
    # (protocol-aware recovery, FAST'18).
    grant = can_vote & up_to_date & ~st1.fenced
    resp_type = jnp.where(m.type == T_VOTE, T_VOTE_RESP, T_PREVOTE_RESP)
    vote_resp = no_resp._replace(
        valid=True,
        type=resp_type,
        term=jnp.where(grant, m.term, st1.term),
        reject=~grant,
    )
    record_real = grant & (m.type == T_VOTE)
    st_vote = st1._replace(
        election_elapsed=jnp.where(record_real, 0, st1.election_elapsed),
        vote=jnp.where(record_real, from_slot + 1, st1.vote),
    )

    # Stale pre-vote: reject with our term (deposes the sender).
    stale_prevote = lower & (m.type == T_PREVOTE)
    resp_stale = no_resp._replace(
        valid=stale_prevote,
        type=jnp.asarray(T_PREVOTE_RESP, I32),
        term=st.term,
        reject=True,
    )
    st_out = _sel(dead | lower, st, st_vote)
    resp = _sel(dead, no_resp, _sel(lower, resp_stale, vote_resp))
    return st_out, resp


def _leader_traffic_prelude(cfg, iid, slot, st1, m, from_slot):
    """Candidate step-down + follower bookkeeping shared by the APP and
    HB lanes (ref: raft.go:1390-1398, 1433-1444)."""
    is_cand = (st1.role == CANDIDATE) | (st1.role == PRECANDIDATE)
    st_f = _sel(
        is_cand,
        _become_follower(cfg, st1, iid, slot, m.term, from_slot + 1),
        st1,
    )
    return st_f._replace(
        election_elapsed=jnp.zeros_like(st1.election_elapsed),
        lead=from_slot + 1,
    )


def _lane_app(cfg: BatchedConfig, iid, slot, st: BatchedState, m: MsgSlots,
              from_slot):
    """Lane KIND_APP: T_APP / T_SNAP (ref: raft.go:1475-1614)."""
    no_resp = empty_msgs((), cfg.max_ents_per_msg)
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)

    fol = _leader_traffic_prelude(cfg, iid, slot, st1, m, from_slot)
    st_app, app_resp = _handle_append(cfg, fol, m)
    st_snap, snap_resp = _handle_snapshot(cfg, fol, m)
    is_snap = m.type == T_SNAP
    leader_traffic_ok = st1.role != LEADER
    st_live = _sel(is_snap, st_snap, st_app)
    resp_live = _sel(is_snap, snap_resp, app_resp)
    st_live = _sel(leader_traffic_ok, st_live, st1)
    resp_live = _sel(leader_traffic_ok, resp_live, no_resp)

    # Stale leader: nudge with an empty MsgAppResp carrying our term
    # (ref: raft.go:885-905).
    stale = lower & jnp.asarray(cfg.check_quorum or cfg.pre_vote)
    resp_stale = no_resp._replace(
        valid=stale, type=jnp.asarray(T_APP_RESP, I32), term=st.term
    )
    st_out = _sel(dead | lower, st, st_live)
    resp = _sel(dead, no_resp, _sel(lower, resp_stale, resp_live))
    return st_out, resp


def _lane_hb(cfg: BatchedConfig, iid, slot, st: BatchedState, m: MsgSlots,
             from_slot):
    """Lane KIND_HB: T_HB + T_TIMEOUT_NOW (ref: raft.go:1513;
    :1465-1472 MsgTimeoutNow → immediate transfer campaign)."""
    no_resp = empty_msgs((), cfg.max_ents_per_msg)
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)

    fol = _leader_traffic_prelude(cfg, iid, slot, st1, m, from_slot)
    st_hb = fol._replace(
        commit=jnp.maximum(fol.commit, jnp.minimum(m.commit, fol.last))
    )
    hb_resp = no_resp._replace(
        valid=True, type=jnp.asarray(T_HB_RESP, I32), term=fol.term,
        ctx=m.ctx,  # ReadIndex ack context echo (read_only.go recvAck)
    )
    leader_traffic_ok = st1.role != LEADER

    # MsgTimeoutNow: campaign at once regardless of timers; only
    # promotable instances honor it (raft.go:1465-1472 + hup gating) —
    # and never a durability-fenced one (the fence exists to keep this
    # replica out of elections until its durable log is whole again).
    is_ton = m.type == T_TIMEOUT_NOW
    r = st1.match.shape[-1]
    promotable = _pick_b(_vote_targets(st1), jnp.arange(r, dtype=I32) == slot)
    st_ton = _campaign(cfg, st1, iid, slot, False, transfer=True)

    st_live = _sel(leader_traffic_ok,
                   _sel(is_ton & promotable & ~st1.fenced, st_ton, st_hb),
                   st1)
    resp_live = _sel(leader_traffic_ok & ~is_ton, hb_resp, no_resp)

    stale = lower & jnp.asarray(cfg.check_quorum or cfg.pre_vote) & ~is_ton
    resp_stale = no_resp._replace(
        valid=stale, type=jnp.asarray(T_APP_RESP, I32), term=st.term
    )
    st_out = _sel(dead | lower, st, st_live)
    resp = _sel(dead, no_resp, _sel(lower, resp_stale, resp_live))
    return st_out, resp


def _lane_vote_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                    m: MsgSlots, from_slot):
    """Lane KIND_VOTE_RESP: T_VOTE_RESP / T_PREVOTE_RESP
    (ref: raft.go:1399-1414)."""
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)
    is_cand = (st1.role == CANDIDATE) | (st1.role == PRECANDIDATE)
    my_resp_type = jnp.where(
        st1.role == PRECANDIDATE, T_PREVOTE_RESP, T_VOTE_RESP
    )
    st_vr = _candidate_vote_resp(cfg, iid, slot, st1, m, from_slot)
    st_live = _sel(is_cand & (m.type == my_resp_type), st_vr, st1)
    return _sel(dead | lower, st, st_live)


def _lane_app_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                   m: MsgSlots, from_slot):
    """Lane KIND_APP_RESP: T_APP_RESP (ref: raft.go:1106-1283)."""
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)
    is_leader = st1.role == LEADER
    st_ar = _leader_app_resp(cfg, st1, m, from_slot)
    st_live = _sel(is_leader & (m.type == T_APP_RESP), st_ar, st1)
    return _sel(dead | lower, st, st_live)


def _lane_hb_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                  m: MsgSlots, from_slot):
    """Lane KIND_HB_RESP: T_HB_RESP, plus T_APP_RESP stale-leader nudges
    that route back in this lane (ref: raft.go:1284-1309)."""
    st1, dead, lower = _term_gate(cfg, iid, slot, st, m, from_slot)
    is_leader = st1.role == LEADER
    st_hr = _leader_hb_resp(cfg, st1, m, from_slot)
    st_ar = _leader_app_resp(cfg, st1, m, from_slot)
    st_live = st1
    st_live = _sel(is_leader & (m.type == T_HB_RESP), st_hr, st_live)
    st_live = _sel(is_leader & (m.type == T_APP_RESP), st_ar, st_live)
    return _sel(dead | lower, st, st_live)


def _handle_append(cfg: BatchedConfig, st: BatchedState, m: MsgSlots):
    """Follower append handling (ref: raft.go:1475-1511 +
    log.go maybeAppend/findConflict)."""
    e = cfg.max_ents_per_msg
    no_resp = empty_msgs((), e)
    prev = m.index

    # Fast path: stale append below commit acks the commit index.
    below_commit = prev < st.commit
    resp_below = no_resp._replace(
        valid=True, type=jnp.asarray(T_APP_RESP, I32), index=st.commit,
        term=st.term,
    )

    ta = lambda i: term_at(st.log_term, st.snap_index, st.snap_term, st.last, i)
    match_ok = ta(prev) == m.log_term

    j = jnp.arange(e, dtype=I32)
    idx = prev + 1 + j
    have = j < m.n_ents
    existing = ta(idx)
    conflict = have & ((idx > st.last) | (existing != m.ent_terms))
    any_conflict = jnp.any(conflict)
    ci = jnp.argmax(conflict)  # first conflicting offset

    write_mask = have & (j >= ci) & any_conflict
    log = ring_write_masked(st.log_term, prev + 1, m.ent_terms, write_mask)
    last = jnp.where(any_conflict, prev + m.n_ents, st.last)
    lastnewi = prev + m.n_ents
    commit = jnp.maximum(st.commit, jnp.minimum(m.commit, lastnewi))
    st_ok = st._replace(log_term=log, last=last, commit=commit)
    resp_ok = no_resp._replace(
        valid=True, type=jnp.asarray(T_APP_RESP, I32), index=lastnewi,
        term=st.term,
    )

    # Reject with a term-skipping hint (ref: raft.go:1487-1509).
    hint0 = jnp.minimum(prev, st.last)
    hint = find_conflict_by_term(
        st.log_term, st.snap_index, st.snap_term, st.last, hint0, m.log_term
    )
    resp_rej = no_resp._replace(
        valid=True,
        type=jnp.asarray(T_APP_RESP, I32),
        index=prev,
        reject=True,
        reject_hint=hint,
        log_term=ta(hint),
        term=st.term,
    )

    st_out = _sel(below_commit, st, _sel(match_ok, st_ok, st))
    resp = _sel(below_commit, resp_below, _sel(match_ok, resp_ok, resp_rej))
    return st_out, resp


def _handle_snapshot(cfg: BatchedConfig, st: BatchedState, m: MsgSlots):
    """Follower snapshot install (ref: raft.go:1518-1614 restore). The
    conf state rides host-side; on device membership masks are already
    current. m.index/m.log_term carry the snapshot (index, term)."""
    no_resp = empty_msgs((), cfg.max_ents_per_msg)
    ignore = m.index <= st.commit
    ta = lambda i: term_at(st.log_term, st.snap_index, st.snap_term, st.last, i)
    fast_forward = ta(m.index) == m.log_term

    st_ff = st._replace(commit=jnp.maximum(st.commit, m.index))
    st_restore = st._replace(
        log_term=jnp.zeros_like(st.log_term),
        snap_index=m.index,
        snap_term=m.log_term,
        last=m.index,
        commit=m.index,
    )
    restored = ~ignore & ~fast_forward
    st_out = _sel(ignore, st, _sel(fast_forward, st_ff, st_restore))
    resp = no_resp._replace(
        valid=True,
        type=jnp.asarray(T_APP_RESP, I32),
        index=jnp.where(restored, m.index, st_out.commit),
        term=st.term,
    )
    return st_out, resp


def _leader_app_resp(cfg: BatchedConfig, st: BatchedState, m: MsgSlots, s):
    """Leader MsgAppResp handling (ref: raft.go:1106-1283)."""
    r = st.match.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    at_s = peers == s
    prog_ok = _pick_b(_repl_targets(st), at_s)  # progress exists for
    # voters+learners

    st = st._replace(recent_active=jnp.where(at_s, True, st.recent_active))

    # --- rejected: move next back using the hint (ref: raft.go:1130-1236) ---
    hint = jnp.where(
        m.log_term > 0,
        find_conflict_by_term(
            st.log_term, st.snap_index, st.snap_term, st.last, m.reject_hint,
            m.log_term,
        ),
        m.reject_hint,
    )
    match_s, next_s = _pick(st.match, at_s), _pick(st.next, at_s)
    in_repl = _pick(st.pr_state, at_s) == REPLICATE
    stale_rej = jnp.where(
        in_repl, m.index <= match_s, next_s - 1 != m.index
    )
    dec_next = jnp.where(
        in_repl,
        match_s + 1,
        jnp.maximum(jnp.minimum(m.index, hint + 1), 1),
    )
    # On a genuine rejection a replicating peer drops to probing
    # (becomeProbe: next=match+1, reset probe bookkeeping).
    #
    # Stale-high match repair: a follower that rejects the probe at
    # next-1 with a hint BELOW our recorded match has verifiably lost
    # entries it once acked — reachable only when durability was
    # violated under it (torn WAL tail). The reference keeps match
    # untouched (its Next >= Match+1 invariant makes this state
    # unreachable in-model), but keeping it here pins next <= match and
    # the accept path then drops every re-ack at-or-below match
    # (`updated` false) — the restarted-member progress wedge: next
    # frozen, the missing suffix never re-sent. Lowering match is
    # always safe (commit is monotone and never re-derived), so take
    # the follower's own evidence and let normal probing re-heal.
    match_repair = at_s & (dec_next <= match_s)
    st_rej = st._replace(
        next=jnp.where(at_s, dec_next, st.next),
        match=jnp.where(match_repair, dec_next - 1, st.match),
        probe_sent=jnp.where(at_s, False, st.probe_sent),
        pr_state=jnp.where(at_s & in_repl, PROBE, st.pr_state),
        pending_snapshot=jnp.where(at_s & in_repl, 0, st.pending_snapshot),
        inflight=jnp.where(at_s & in_repl, 0, st.inflight),
        send_append=st.send_append | (at_s & ~stale_rej),
    )
    st_rej = _sel(stale_rej, st, st_rej)

    # --- accepted: MaybeUpdate + state transitions + commit ---
    old_paused = _pick_b(_paused(cfg, st), at_s)
    updated = match_s < m.index
    match = jnp.where(at_s, jnp.maximum(st.match, m.index), st.match)
    nxt = jnp.where(at_s, jnp.maximum(st.next, m.index + 1), st.next)
    st_acc = st._replace(
        match=match,
        next=nxt,
        probe_sent=jnp.where(at_s & updated, False, st.probe_sent),
    )

    pr_state_s = _pick(st.pr_state, at_s)
    new_match_s = jnp.maximum(match_s, m.index)
    was_probe = pr_state_s == PROBE
    was_snap = (pr_state_s == SNAPSHOT) & (
        new_match_s >= _pick(st.pending_snapshot, at_s)
    )
    to_replicate = updated & (was_probe | was_snap)
    st_acc = st_acc._replace(
        pr_state=jnp.where(at_s & to_replicate, REPLICATE, st_acc.pr_state),
        pending_snapshot=jnp.where(
            at_s & to_replicate, 0, st_acc.pending_snapshot
        ),
        inflight=jnp.where(
            at_s & updated, 0, st_acc.inflight
        ),  # count+watermark degeneration of FreeLE
        next=jnp.where(
            at_s & to_replicate, new_match_s + 1, nxt
        ),
    )
    committed_before = st_acc.commit
    st_acc = _maybe_commit(st_acc)
    advanced = st_acc.commit > committed_before
    # bcastAppend on commit advance; resend to a previously-paused peer;
    # keep draining while entries remain (ref: raft.go:1259-1276).
    more = st_acc.last >= _pick(st_acc.next, at_s)
    st_acc = st_acc._replace(
        send_append=jnp.where(
            advanced,
            st_acc.send_append | _repl_targets(st_acc),
            st_acc.send_append | (at_s & (old_paused | more)),
        )
    )
    st_acc = _sel(updated, st_acc, st)

    out = _sel(m.reject, st_rej, st_acc)
    return _sel(prog_ok, out, st)


def _leader_hb_resp(cfg: BatchedConfig, st: BatchedState, m: MsgSlots, s):
    """ref: raft.go:1284-1309, incl. the ReadIndex ack path
    (read_only.go:68 recvAck + :81 advance, on-device)."""
    r = st.match.shape[-1]
    peers = jnp.arange(r, dtype=I32)
    at_s = peers == s
    full = st.inflight >= cfg.max_inflight
    st2 = st._replace(
        recent_active=jnp.where(at_s, True, st.recent_active),
        probe_sent=jnp.where(at_s, False, st.probe_sent),
        inflight=jnp.where(
            at_s & (st.pr_state == REPLICATE) & full,
            jnp.maximum(st.inflight - 1, 0),
            st.inflight,
        ),
        send_append=st.send_append | (at_s & (st.match < st.last)),
    )
    # ReadIndex ack: a heartbeat response echoing the pending read's
    # ctx counts toward its quorum; quorum → read_ready.
    pending = (st2.read_index >= 0) & ~st2.read_ready
    ack = pending & (m.ctx == st2.read_seq) & (m.ctx > 0)
    acks = st2.read_acks | (at_s & ack)
    votes = jnp.where(acks, 1, -1)
    confirmed = joint_vote_result(
        votes, st2.voter, st2.voter_out, st2.in_joint
    ) == VOTE_WON
    st2 = st2._replace(
        read_acks=acks,
        read_ready=st2.read_ready | (pending & confirmed),
    )
    return _sel(_pick_b(_repl_targets(st), at_s), st2, st)


def _candidate_vote_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                         m: MsgSlots, s):
    """ref: raft.go:1399-1414."""
    st2, res = _record_vote_and_tally(st, s, ~m.reject)
    won, lost = res == VOTE_WON, res == VOTE_LOST
    if cfg.pre_vote:
        st_won_pre = _campaign(cfg, st2, iid, slot, False)
    else:
        st_won_pre = st2
    st_won_real = _become_leader(cfg, st2, iid, slot)
    peers_mask = _repl_targets(st_won_real) & (
        jnp.arange(st.match.shape[-1], dtype=I32) != slot
    )
    st_won_real = st_won_real._replace(
        send_append=st_won_real.send_append | peers_mask
    )
    is_pre = st.role == PRECANDIDATE
    st_won = _sel(is_pre, st_won_pre, st_won_real)
    st_lost = _become_follower(cfg, st2, iid, slot, st2.term, 0)
    return _sel(won, st_won, _sel(lost, st_lost, st2))


# -----------------------------------------------------------------------------
# Phases: deliver (scan) / tick / propose / emit
# -----------------------------------------------------------------------------


_LANE_HANDLERS = (
    _lane_vote, _lane_app, _lane_hb,
    _lane_vote_resp, _lane_app_resp, _lane_hb_resp,
)


def _deliver_all(cfg: BatchedConfig, iid, slot, st: BatchedState,
                 inbox: MsgSlots, lane_any=None):
    """Deliver this instance's inbox; the shape is configured
    (cfg.deliver_shape — see state.BatchedConfig for the catalog):

    * ``"lanes"``: six length-R scans, one per kind lane, senders
      ascending within a lane (kind-major order). Small bodies.
    * ``"merged"``: two length-R scans — request half (kinds
      0..NUM_REQ_KINDS-1) then response half — each body chaining the
      three kind handlers for one sender (sender-major order within a
      half). Same 18 handler applications, 3x bigger fused bodies, a
      third of the loop-carry round trips; the r5 on-TPU winner.
    * ``"vectorized"``: NO sender scan (see _deliver_vectorized) —
      response lanes fold as masked reductions, request lanes resolve
      one winner per lane, and the full BatchedState stops round-
      tripping through a loop carry 6R (or 2R) times per round.

    Every shape collects responses for the request lanes and routes
    them back in lanes ``k + NUM_REQ_KINDS``, and the shadow oracle
    replicates the exact delivery order of the configured shape.

    ``lane_any`` ([K] bool, optional) is the vectorized shape's
    batch-level lane-occupancy vector: the CALLER computes
    ``jnp.any(inbox.valid, axis=(0, 1))`` OUTSIDE the instance vmap so
    each lane's fold sits under a lax.cond with an UNMAPPED predicate
    — a lane nobody used this round (votes in steady state, heartbeat
    lanes off-cadence) costs nothing instead of a full masked no-op.
    An all-invalid lane is an exact identity, so the skip is
    bit-equivalent; None falls back to per-instance occupancy (the
    cond degrades to a select under a mapped predicate — correct,
    just unskipped)."""
    if cfg.deliver_shape == "vectorized":
        return _deliver_vectorized(cfg, iid, slot, st, inbox, lane_any)
    if cfg.deliver_shape == "merged":
        return _deliver_merged(cfg, iid, slot, st, inbox)
    if cfg.deliver_shape == "lanes":
        return _deliver_lanes(cfg, iid, slot, st, inbox)
    raise ValueError(
        f"unresolved deliver_shape {cfg.deliver_shape!r}: call "
        "cfg.resolved() before building a round program")


def _deliver_lanes(cfg: BatchedConfig, iid, slot, st: BatchedState,
                   inbox: MsgSlots):
    r = cfg.num_replicas
    senders = jnp.arange(r, dtype=I32)

    req_resps = []
    for k, handler in enumerate(_LANE_HANDLERS):
        msgs_k = jax.tree.map(lambda x, _k=k: x[:, _k], inbox)  # [R, ...]
        if k < NUM_REQ_KINDS:
            def body(carry, xs, _h=handler):
                m, s = xs
                st2, resp = _h(cfg, iid, slot, carry, m, s)
                return st2, resp

            st, resps_k = jax.lax.scan(body, st, (msgs_k, senders))
            req_resps.append(resps_k)
        else:
            def body(carry, xs, _h=handler):
                m, s = xs
                return _h(cfg, iid, slot, carry, m, s), 0

            st, _ = jax.lax.scan(body, st, (msgs_k, senders))

    # [R] per request lane → [R, 3].
    req = jax.tree.map(
        lambda a, b, c: jnp.stack((a, b, c), axis=1), *req_resps
    )
    return st, req


def _deliver_merged(cfg: BatchedConfig, iid, slot, st: BatchedState,
                    inbox: MsgSlots):
    r = cfg.num_replicas
    senders = jnp.arange(r, dtype=I32)

    req_inbox = jax.tree.map(
        lambda x: x[:, :NUM_REQ_KINDS], inbox)  # [R, 3, ...]

    def req_body(carry, xs):
        msgs, s = xs  # msgs leaves: [3, ...]
        resps = []
        for k, handler in enumerate(_LANE_HANDLERS[:NUM_REQ_KINDS]):
            m = jax.tree.map(lambda x, _k=k: x[_k], msgs)
            carry, resp = handler(cfg, iid, slot, carry, m, s)
            resps.append(resp)
        return carry, tuple(resps)

    st, (r0, r1, r2) = jax.lax.scan(req_body, st, (req_inbox, senders))

    resp_inbox = jax.tree.map(
        lambda x: x[:, NUM_REQ_KINDS:], inbox)  # [R, 3, ...]

    def resp_body(carry, xs):
        msgs, s = xs
        for k, handler in enumerate(_LANE_HANDLERS[NUM_REQ_KINDS:]):
            m = jax.tree.map(lambda x, _k=k: x[_k], msgs)
            carry = handler(cfg, iid, slot, carry, m, s)
        return carry, 0

    st, _ = jax.lax.scan(resp_body, st, (resp_inbox, senders))

    # [R] per request lane → [R, 3].
    req = jax.tree.map(
        lambda a, b, c: jnp.stack((a, b, c), axis=1), r0, r1, r2
    )
    return st, req


# -----------------------------------------------------------------------------
# Vectorized deliver (cfg.deliver_shape == "vectorized"): no sender
# scan. The protocol structure this exploits: per round each sender
# contributes at most ONE message per lane, response-lane handlers are
# order-invariant reductions over distinct progress columns (sender s
# only ever touches column s; commit/read-quorum are single global
# recomputes), and request lanes admit at most one effective winner
# after term gating (one leader per term; votes record at most one
# grant). Where the sequential scans' sender order DID matter — a
# higher-term message deposing the receiver mid-lane — the vectorized
# shape fixes its own order contract, mirrored exactly by the shadow
# oracle (shadow.ShadowCluster deliver_shape="vectorized"):
#
#   * lanes still process in kind order 0..5;
#   * request lanes: the winner (highest term, lowest sender) delivers
#     first through the full handler; losers then answer against the
#     post-winner state (stale nudges; equal-term losers cannot exist
#     in-protocol — the shadow raises on them);
#   * the vote lane orders T_VOTE (term desc, sender asc) before every
#     T_PREVOTE (prevotes never change state, so they all evaluate
#     against the post-vote state);
#   * response lanes: same-term effects first (commutative), then the
#     single highest-term depose, re-gated against the post-effect
#     term.
# -----------------------------------------------------------------------------


def _argfirst(mask):
    """Index of the first set bit of a [R] bool mask (0 if none)."""
    return jnp.argmax(mask).astype(I32)


def _gather_msg(msgs: MsgSlots, at) -> MsgSlots:
    """msgs[w] for a traced winner index, as one-hot compare+reduce per
    field (at = senders == w): traced-index gathers serialize on TPU,
    one-hot reads don't (the _pick discipline, tree-wide)."""
    def pick(x):
        sel = at if x.ndim == 1 else at[:, None]
        if x.dtype == jnp.bool_:
            return jnp.any(x & sel, axis=0)
        return jnp.sum(jnp.where(sel, x, 0), axis=0)

    return jax.tree.map(pick, msgs)


def _vec_lane_request(cfg: BatchedConfig, iid, slot, st: BatchedState,
                      m: MsgSlots, handler, hb_lane: bool):
    """One request lane (KIND_APP / KIND_HB), vectorized: at most one
    in-protocol message can take effect per (instance, lane) per round
    (there is one leader per term, and only the highest term survives
    the gate), so the winner — highest term, lowest sender — runs the
    full per-message handler once, and every loser is answered with
    the stale-leader nudge it would have received anyway, computed
    against the post-winner state (ref: raft.go:885-905)."""
    r = cfg.num_replicas
    senders = jnp.arange(r, dtype=I32)
    t_max = jnp.max(jnp.where(m.valid, m.term, -1))
    at_w = senders == _argfirst(m.valid & (m.term == t_max))
    mw = _gather_msg(m, at_w)
    st2, wresp = handler(cfg, iid, slot, st, mw, _pick(senders, at_w))

    nudge = (
        m.valid & ~at_w & (m.term < st2.term)
        & jnp.asarray(cfg.check_quorum or cfg.pre_vote)
    )
    if hb_lane:
        # A losing MsgTimeoutNow never draws a response
        # (ref: raft.go:885-905 applies to leader traffic only).
        nudge = nudge & (m.type != T_TIMEOUT_NOW)
    resp = empty_msgs((r,), cfg.max_ents_per_msg)
    resp = resp._replace(
        valid=jnp.where(at_w, wresp.valid, nudge),
        type=jnp.where(at_w, wresp.type, T_APP_RESP),
        term=jnp.where(at_w, wresp.term, st2.term),
        log_term=jnp.where(at_w, wresp.log_term, 0),
        index=jnp.where(at_w, wresp.index, 0),
        commit=jnp.where(at_w, wresp.commit, 0),
        reject=at_w & wresp.reject,
        reject_hint=jnp.where(at_w, wresp.reject_hint, 0),
        n_ents=jnp.where(at_w, wresp.n_ents, 0),
        ctx=jnp.where(at_w, wresp.ctx, 0),
        ent_terms=jnp.where(at_w[:, None], wresp.ent_terms[None, :], 0),
    )
    return st2, resp


def _vec_lane_vote(cfg: BatchedConfig, iid, slot, st: BatchedState,
                   m: MsgSlots):
    """Lane KIND_VOTE, vectorized. State effects come only from T_VOTE
    at the highest surviving term: one depose (become_follower) and at
    most one recorded grant — if the vote is already cast only its
    holder can re-grant; if it is free the first up-to-date sender
    takes it (sender-ascending, exactly the sequential setdefault).
    Prevotes never mutate state, so all prevote responses evaluate
    against the post-vote state in one masked shot."""
    r = cfg.num_replicas
    senders = jnp.arange(r, dtype=I32)
    is_vote = m.type == T_VOTE
    is_pre = m.type == T_PREVOTE

    # Leases block higher-term requests unless transfer-flagged
    # (ref: raft.go:870-880); evaluated against lane-entry state for
    # T_VOTE (the winner is the first message delivered).
    def lease_block(stx):
        in_lease = (
            jnp.asarray(cfg.check_quorum)
            & (stx.lead != 0)
            & (stx.election_elapsed < cfg.election_timeout)
        )
        return (m.term > stx.term) & in_lease & ~(m.ctx == 1)

    vmask = m.valid & is_vote & ~lease_block(st)
    t_hi = jnp.max(jnp.where(vmask, m.term, -1))
    st1 = _sel(
        t_hi > st.term,
        _become_follower(cfg, st, iid, slot, jnp.maximum(t_hi, st.term),
                         jnp.zeros_like(st.lead)),
        st,
    )

    eq = vmask & (m.term == st1.term)
    last_term = term_at(
        st1.log_term, st1.snap_index, st1.snap_term, st1.last, st1.last
    )
    up_to_date = (m.log_term > last_term) | (
        (m.log_term == last_term) & (m.index >= st1.last)
    )
    can_vote = (st1.vote == senders + 1) | (
        (st1.vote == 0) & (st1.lead == 0)
    )
    grantable = eq & can_vote & up_to_date & ~st1.fenced
    has_grant = jnp.any(grantable)
    granted = grantable & (senders == _argfirst(grantable))
    st2 = st1._replace(
        vote=jnp.where(has_grant, _argfirst(grantable) + 1, st1.vote),
        election_elapsed=jnp.where(has_grant, 0, st1.election_elapsed),
    )

    # Prevote responses against the post-vote state (no state change:
    # grants never record, ref: raft.go:960-972 m.Type == MsgPreVote).
    pv = m.valid & is_pre & ~lease_block(st2)
    lower_p = m.term < st2.term
    # can_vote above read st1.vote; a grant recorded this lane changes
    # it, so prevotes re-derive against st2.
    can_pre = (st2.vote == senders + 1) | (
        (st2.vote == 0) & (st2.lead == 0)
    ) | (m.term > st2.term)
    grant_p = pv & ~lower_p & can_pre & up_to_date & ~st2.fenced

    resp = empty_msgs((r,), cfg.max_ents_per_msg)
    resp = resp._replace(
        valid=eq | pv,
        type=jnp.where(is_vote, T_VOTE_RESP, T_PREVOTE_RESP),
        term=jnp.where(grant_p, m.term,
                       jnp.broadcast_to(st2.term, (r,))),
        reject=jnp.where(is_vote, ~granted, ~grant_p),
    )
    return st2, resp


def _vec_app_resp_effects(cfg: BatchedConfig, st: BatchedState,
                          m: MsgSlots, eq):
    """Columnwise _leader_app_resp for every same-term MsgAppResp at
    once — sender s's message only ever touches progress column s, so
    the R sequential handler applications collapse to masked column
    updates plus ONE commit recompute and one bcast/resend fold. The
    PR 4 wedge-repair semantics (stale-high match lowered to the
    follower's own evidence) ride the same masks bit-for-bit.
    `eq` gates to valid same-term T_APP_RESP on a leader."""
    prog = _repl_targets(st)
    ok = eq & prog
    # recent_active is recorded for every handled message, progress row
    # or not (the sequential handler sets it before the prog_ok gate).
    st_in = st._replace(recent_active=st.recent_active | eq)

    # --- rejected: move next back using the hint (raft.go:1130-1236) ---
    hint = jax.vmap(
        lambda idx, t: find_conflict_by_term(
            st.log_term, st.snap_index, st.snap_term, st.last, idx, t)
    )(m.reject_hint, m.log_term)
    hint = jnp.where(m.log_term > 0, hint, m.reject_hint)
    in_repl = st.pr_state == REPLICATE
    stale_rej = jnp.where(
        in_repl, m.index <= st.match, st.next - 1 != m.index
    )
    dec_next = jnp.where(
        in_repl,
        st.match + 1,
        jnp.maximum(jnp.minimum(m.index, hint + 1), 1),
    )
    rej = ok & m.reject & ~stale_rej
    # Stale-high match repair (the restarted-member progress wedge —
    # see _leader_app_resp): lowering match is always safe.
    match_repair = rej & (dec_next <= st.match)

    # --- accepted: MaybeUpdate + state transitions ---
    old_paused = _paused(cfg, st)
    updated = st.match < m.index
    accu = ok & ~m.reject & updated
    new_match = jnp.maximum(st.match, m.index)
    was_probe = st.pr_state == PROBE
    was_snap = (st.pr_state == SNAPSHOT) & (
        new_match >= st.pending_snapshot
    )
    to_repl = accu & (was_probe | was_snap)

    match1 = jnp.where(match_repair, dec_next - 1, st.match)
    match1 = jnp.where(accu, new_match, match1)
    next1 = jnp.where(rej, dec_next, st.next)
    next1 = jnp.where(accu, jnp.maximum(st.next, m.index + 1), next1)
    next1 = jnp.where(to_repl, new_match + 1, next1)
    pr1 = jnp.where(rej & in_repl, PROBE, st.pr_state)
    pr1 = jnp.where(to_repl, REPLICATE, pr1)
    st2 = st_in._replace(
        match=match1,
        next=next1,
        pr_state=pr1,
        probe_sent=st.probe_sent & ~rej & ~accu,
        pending_snapshot=jnp.where(
            (rej & in_repl) | to_repl, 0, st.pending_snapshot),
        inflight=jnp.where((rej & in_repl) | accu, 0, st.inflight),
        send_append=st.send_append | rej,
    )
    # ONE commit recompute: commit is monotone in match and the
    # per-message recomputes' fixpoint equals the recompute on the
    # final match plane (leader log terms above an own-term entry stay
    # own-term, so the term gate cannot flip between prefix and final).
    commit0 = st.commit
    st2 = _maybe_commit(st2)
    advanced = st2.commit > commit0
    # bcastAppend on commit advance; per-column resend to previously
    # paused peers / peers with entries remaining (raft.go:1259-1276).
    resend = accu & (old_paused | (st2.last >= next1))
    st2 = st2._replace(
        send_append=jnp.where(
            advanced,
            st2.send_append | _repl_targets(st2),
            st2.send_append | resend,
        )
    )
    return _sel(jnp.any(eq), st2, st)


def _vec_depose(cfg: BatchedConfig, iid, slot, st: BatchedState,
                m: MsgSlots):
    """The response-lane depose tail: become follower at the highest
    term carried by any deposing message, re-gated against the
    post-effect state (a candidacy won this lane may have raised the
    term past the depose)."""
    keep = (m.type == T_PREVOTE_RESP) & ~m.reject
    deposing = m.valid & (m.term > st.term) & ~keep
    dep_t = jnp.max(jnp.where(deposing, m.term, -1))
    return _sel(
        dep_t > st.term,
        _become_follower(cfg, st, iid, slot, jnp.maximum(dep_t, st.term),
                         jnp.zeros_like(st.lead)),
        st,
    )


def _vec_lane_vote_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                        m: MsgSlots):
    """Lane KIND_VOTE_RESP, vectorized: record every same-term tally
    vote at once (distinct senders → distinct slots; the sequential
    early-exit on a decisive prefix equals the full tally, since
    grants can only keep a won verdict and rejections a lost one),
    then resolve won/lost once, then the depose tail."""
    keep = (m.type == T_PREVOTE_RESP) & ~m.reject
    is_cand = (st.role == CANDIDATE) | (st.role == PRECANDIDATE)
    my_resp_type = jnp.where(
        st.role == PRECANDIDATE, T_PREVOTE_RESP, T_VOTE_RESP
    )
    tally = (
        m.valid
        & ~(m.term < st.term)
        & ~((m.term > st.term) & ~keep)
        & (m.type == my_resp_type)
        & is_cand
    )
    votes = jnp.where(
        tally & (st.votes == -1), jnp.where(m.reject, 0, 1), st.votes
    )
    st_t = st._replace(votes=votes)
    res = joint_vote_result(votes, st.voter, st.voter_out, st.in_joint)
    won, lost = res == VOTE_WON, res == VOTE_LOST
    if cfg.pre_vote:
        st_won_pre = _campaign(cfg, st_t, iid, slot, False)
    else:
        st_won_pre = st_t
    st_won_real = _become_leader(cfg, st_t, iid, slot)
    peers_mask = _repl_targets(st_won_real) & (
        jnp.arange(st.match.shape[-1], dtype=I32) != slot
    )
    st_won_real = st_won_real._replace(
        send_append=st_won_real.send_append | peers_mask
    )
    st_won = _sel(st.role == PRECANDIDATE, st_won_pre, st_won_real)
    st_lost = _become_follower(cfg, st_t, iid, slot, st_t.term,
                               jnp.zeros_like(st.lead))
    st_dec = _sel(won, st_won, _sel(lost, st_lost, st_t))
    st1 = _sel(jnp.any(tally), st_dec, st)
    return _vec_depose(cfg, iid, slot, st1, m)


def _vec_lane_app_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                       m: MsgSlots):
    """Lane KIND_APP_RESP, vectorized: the masked column fold above,
    then the depose tail (a stale-leader nudge carrying a higher term
    lands here — raft.go:885-905)."""
    eq = (
        m.valid & (m.term == st.term) & (m.type == T_APP_RESP)
        & (st.role == LEADER)
    )
    st1 = _vec_app_resp_effects(cfg, st, m, eq)
    return _vec_depose(cfg, iid, slot, st1, m)


def _vec_lane_hb_resp(cfg: BatchedConfig, iid, slot, st: BatchedState,
                      m: MsgSlots):
    """Lane KIND_HB_RESP, vectorized: heartbeat acks are a masked OR
    into probe_sent/inflight/recent_active plus ONE ReadIndex quorum
    recompute (acks are monotone; quorum on the full set equals the
    sequential per-ack checks); T_APP_RESP stale-leader probes that
    route back in this lane reuse the column fold; then the depose
    tail."""
    is_leader = st.role == LEADER
    eqterm = m.valid & (m.term == st.term) & is_leader
    prog = _repl_targets(st)
    okh = eqterm & (m.type == T_HB_RESP) & prog
    apr = eqterm & (m.type == T_APP_RESP)

    full = st.inflight >= cfg.max_inflight
    st_h = st._replace(
        recent_active=st.recent_active | okh,
        probe_sent=st.probe_sent & ~okh,
        inflight=jnp.where(
            okh & (st.pr_state == REPLICATE) & full,
            jnp.maximum(st.inflight - 1, 0),
            st.inflight,
        ),
        send_append=st.send_append | (okh & (st.match < st.last)),
    )
    # ReadIndex acks (read_only.go recvAck/advance). The sequential
    # scans stop RECORDING once an ack confirms quorum mid-lane
    # (pending drops with read_ready), so for bit-parity the fold
    # records only the sender-ascending prefix up to and including the
    # quorum-confirming ack: conf_at[s] = "quorum with acks from
    # senders <= s folded in" is monotone in s, so the first set bit
    # is where the sequential scan stopped. Bits past it are dead
    # state either way (cleared at the next batch open) — this keeps
    # the three shapes comparable field-for-field, not just
    # protocol-equivalent.
    senders = jnp.arange(st.match.shape[-1], dtype=I32)
    pending = (st_h.read_index >= 0) & ~st_h.read_ready
    inc = okh & pending & (m.ctx == st_h.read_seq) & (m.ctx > 0)
    prefix = st_h.read_acks[None, :] | (
        inc[None, :] & (senders[None, :] <= senders[:, None])
    )  # [R prefixes, R]
    conf_at = jax.vmap(
        lambda a: joint_vote_result(
            jnp.where(a, 1, -1), st_h.voter, st_h.voter_out,
            st_h.in_joint) == VOTE_WON
    )(prefix)
    confirmed = jnp.any(conf_at)  # == quorum over the full fold
    rec = inc & (~confirmed | (senders <= _argfirst(conf_at)))
    st_h = st_h._replace(
        read_acks=st_h.read_acks | rec,
        read_ready=st_h.read_ready
        | (pending & confirmed & jnp.any(okh)),
    )
    st_a = _vec_app_resp_effects(cfg, st_h, m, apr)
    return _vec_depose(cfg, iid, slot, st_a, m)


def _deliver_vectorized(cfg: BatchedConfig, iid, slot, st: BatchedState,
                        inbox: MsgSlots, lane_any=None):
    """Scan-free deliver: lanes in kind order, each lane one vectorized
    fold over the sender axis (see the order contract in the section
    comment above). With no lax.scan barrier left anywhere in the
    round, deliver→tick→control→propose→emit trace into ONE
    straight-line fused region — the full-state loop-carry round trips
    of the scanned shapes disappear, and the named_scope annotations
    (ROUND_PHASE_SCOPES) survive purely as attribution labels inside
    the fused program. Each lane runs under lax.cond on its occupancy
    (see _deliver_all on ``lane_any``), so idle lanes are skipped for
    the whole batch."""
    lane = lambda k: jax.tree.map(lambda x, _k=k: x[:, _k], inbox)  # noqa: E731
    no_resp = empty_msgs((cfg.num_replicas,), cfg.max_ents_per_msg)

    def occupied(k, m):
        if lane_any is None:
            return jnp.any(m.valid)
        return lane_any[k]

    def with_resp(k, fn, stx):
        m = lane(k)
        return jax.lax.cond(
            occupied(k, m),
            lambda sty, mx: fn(sty, mx),
            lambda sty, mx: (sty, no_resp),
            stx, m,
        )

    def state_only(k, fn, stx):
        m = lane(k)
        return jax.lax.cond(
            occupied(k, m),
            lambda sty, mx: fn(sty, mx),
            lambda sty, mx: sty,
            stx, m,
        )

    st, r0 = with_resp(
        KIND_VOTE, lambda s, m: _vec_lane_vote(cfg, iid, slot, s, m),
        st)
    st, r1 = with_resp(
        KIND_APP,
        lambda s, m: _vec_lane_request(
            cfg, iid, slot, s, m, _lane_app, hb_lane=False), st)
    st, r2 = with_resp(
        KIND_HB,
        lambda s, m: _vec_lane_request(
            cfg, iid, slot, s, m, _lane_hb, hb_lane=True), st)
    st = state_only(
        KIND_VOTE_RESP,
        lambda s, m: _vec_lane_vote_resp(cfg, iid, slot, s, m), st)
    st = state_only(
        KIND_APP_RESP,
        lambda s, m: _vec_lane_app_resp(cfg, iid, slot, s, m), st)
    st = state_only(
        KIND_HB_RESP,
        lambda s, m: _vec_lane_hb_resp(cfg, iid, slot, s, m), st)
    # [R] per request lane → [R, 3].
    req = jax.tree.map(
        lambda a, b, c: jnp.stack((a, b, c), axis=1), r0, r1, r2
    )
    return st, req


def _tick(cfg: BatchedConfig, iid, slot, st: BatchedState, do_tick,
          do_campaign):
    """ref: raft.go:645-684 tickElection/tickHeartbeat."""
    r = cfg.num_replicas
    peers = jnp.arange(r, dtype=I32)
    is_leader = st.role == LEADER

    ee = st.election_elapsed + jnp.where(do_tick, 1, 0)
    he = st.heartbeat_elapsed + jnp.where(do_tick & is_leader, 1, 0)

    # Leader heartbeat firing.
    hb_fire = is_leader & (he >= cfg.heartbeat_timeout)
    cq_fire = is_leader & (ee >= cfg.election_timeout)
    st1 = st._replace(
        election_elapsed=jnp.where(cq_fire, 0, ee),
        heartbeat_elapsed=jnp.where(hb_fire, 0, he),
        send_heartbeat=st.send_heartbeat
        | (hb_fire & _repl_targets(st) & (peers != slot)),
        # A transfer that outlives one election timeout is aborted
        # (ref: raft.go:670-678 tickHeartbeat abortLeaderTransfer).
        transferee=jnp.where(cq_fire, 0, st.transferee),
        transfer_sent=jnp.where(cq_fire, False, st.transfer_sent),
        # Leader lease decays in the same tick currency the electorate
        # measures leader silence in (see BatchedState.lease_ticks for
        # the safety argument); quorum evidence re-arms it below and in
        # the post-emit freshness check.
        lease_ticks=jnp.maximum(
            st.lease_ticks - jnp.where(do_tick & is_leader, 1, 0), 0),
    )
    if cfg.check_quorum:
        # Leader self-check every election timeout: step down when a
        # quorum hasn't been heard from, then re-arm the activity bits
        # (ref: raft.go:997-1018 MsgCheckQuorum).
        active = jnp.where(peers == slot, True, st1.recent_active)
        votes = jnp.where(active, 1, 0)
        alive = joint_vote_result(
            votes, st1.voter, st1.voter_out, st1.in_joint
        ) == VOTE_WON
        st_down = _become_follower(cfg, st1, iid, slot, st1.term, 0)
        st1 = _sel(cq_fire & ~alive, st_down, st1)
        st1 = st1._replace(
            recent_active=jnp.where(
                cq_fire, peers == slot, st1.recent_active
            ),
            # A passed quorum self-check is exactly the evidence the
            # lease leans on: a quorum heard from us within the last
            # election_timeout, so no rival can assemble a quorum for
            # at least that long again.
            lease_ticks=jnp.where(
                cq_fire & alive & (st1.transferee == 0),
                cfg.election_timeout, st1.lease_ticks),
        )

    # Follower/candidate election firing (hup gated on promotability —
    # learners never campaign, ref: raft.go:760-784). Durability-fenced
    # instances never fire: campaigning on a log that verifiably lost
    # acked entries is how a torn member forces a survivor to overwrite
    # a committed entry (the out-of-contract divergence the fence
    # closes); the fence also swallows host-staged campaign nudges.
    promotable = _pick_b(_vote_targets(st), peers == slot)
    fire = (
        (~is_leader & (ee >= st.randomized_timeout)) | do_campaign
    ) & promotable & (st.role != LEADER) & ~st.fenced
    st1 = st1._replace(
        election_elapsed=jnp.where(fire & ~is_leader, 0, st1.election_elapsed)
    )
    st_camp = _campaign(cfg, st1, iid, slot, cfg.pre_vote)
    return _sel(fire, st_camp, st1)


def _control(cfg: BatchedConfig, slot, st: BatchedState, transfer_to,
             read_req):
    """Host control plane: leader-transfer requests and ReadIndex
    rounds (ref: raft.go:1339-1372 stepLeader MsgTransferLeader;
    raft.go:1078-1096 MsgReadIndex + read_only.go addRequest).

    `transfer_to` is slot+1 (0 = none); `read_req` asks the leader to
    open a read batch at its current commit index. Both are no-ops on
    non-leaders (the host routes requests to the leader instance)."""
    r = cfg.num_replicas
    peers = jnp.arange(r, dtype=I32)
    is_leader = st.role == LEADER

    # --- leader transfer -----------------------------------------------------
    target = transfer_to - 1
    valid_target = (
        is_leader
        & (transfer_to > 0)
        & (transfer_to != slot + 1)          # self-transfer is a no-op
        & (transfer_to != st.transferee)     # dup request ignored
        & _pick_b(_vote_targets(st), peers == target)  # learners can't lead
    )
    st_tr = st._replace(
        transferee=transfer_to,
        transfer_sent=jnp.zeros_like(st.transfer_sent),
        election_elapsed=jnp.zeros_like(st.election_elapsed),
        # Last-chance catch-up append (raft.go:1367-1371 sendAppend).
        send_append=st.send_append
        | ((peers == target) & (st.match < st.last)),
        # A transferring leader stops serving lease reads NOW: the
        # target may campaign (TimeoutNow pierces leases) before our
        # lease would have decayed.
        lease_ticks=jnp.zeros_like(st.lease_ticks),
    )
    st = _sel(valid_target, st_tr, st)

    # --- ReadIndex -----------------------------------------------------------
    # Leader must have committed in its own term before serving reads
    # (ref: raft.go:1813-1825 pending queue until first commit), and a
    # batch in flight must not be clobbered (its in-flight acks would
    # be orphaned). Unserviceable requests latch and open the next
    # batch when the blocker clears — read_only.go's pending queue.
    committed_in_term = (
        term_at(st.log_term, st.snap_index, st.snap_term, st.last, st.commit)
        == st.term
    )
    batch_pending = (st.read_index >= 0) & ~st.read_ready
    want = read_req | st.read_req_latch
    accept = is_leader & want & committed_in_term & ~batch_pending
    acks0 = peers == slot
    votes0 = jnp.where(acks0, 1, -1)
    solo = joint_vote_result(
        votes0, st.voter, st.voter_out, st.in_joint
    ) == VOTE_WON
    st_rd = st._replace(
        read_seq=st.read_seq + 1,
        read_index=st.commit,
        read_acks=acks0,
        read_ready=solo,  # single-voter group confirms instantly
        # Confirmation heartbeats to the electorate (bcastHeartbeat-
        # WithCtx, raft.go:1827-1843); emit stamps ctx = read_seq.
        send_heartbeat=st.send_heartbeat
        | (_repl_targets(st) & (peers != slot)),
    )
    st = _sel(accept, st_rd, st)
    return st._replace(read_req_latch=want & ~accept)


def _propose(cfg: BatchedConfig, slot, st: BatchedState, n_new):
    """Append n_new proposals on leader instances; payload bytes stay in
    the host arena keyed by (group, index) (ref: v3_server.go Propose →
    appendEntry → bcastAppend)."""
    r = cfg.num_replicas
    peers = jnp.arange(r, dtype=I32)
    # Proposals are dropped while a leadership transfer is in flight
    # (ref: raft.go:1048-1053 ErrProposalDropped on leadTransferee) and
    # on a leader that has been removed from the config — no progress
    # for self means no proposals (ref: raft.go:1043-1046
    # "not currently a member of the range").
    self_tracked = _pick_b(_repl_targets(st), peers == slot)
    is_leader = (st.role == LEADER) & (st.transferee == 0) & self_tracked
    headroom = jnp.maximum(
        cfg.window - (st.last - st.snap_index) - cfg.max_props_per_round, 0
    )
    n = jnp.clip(jnp.where(is_leader, n_new, 0), 0, cfg.max_props_per_round)
    n = jnp.minimum(n, headroom)
    st2 = _append_own(cfg, st, slot, n)
    st2 = st2._replace(
        send_append=st2.send_append
        | ((n > 0) & _repl_targets(st2) & (peers != slot))
    )
    return _sel(n > 0, st2, st)


def _emit(cfg: BatchedConfig, slot, st: BatchedState):
    """Materialize pending sends into an outbox [R, K] and clear flags;
    auto-apply committed entries (device applies immediately; the host
    drains (group, index) ranges for real payload apply)."""
    e = cfg.max_ents_per_msg
    r = cfg.num_replicas
    peers = jnp.arange(r, dtype=I32)
    out = empty_msgs((r, NUM_KINDS), e)

    # Device-side apply + compaction first: committed == applied on
    # device (payload apply is the host's job, driven from the commit
    # watermark), and with auto_compact the snapshot floor chases the
    # applied watermark so the ring never fills. Stale ring slots below
    # the floor need no clearing — term_at bounds exclude them.
    st = st._replace(applied=jnp.maximum(st.applied, st.commit))
    if cfg.auto_compact:
        ta0 = lambda i: term_at(
            st.log_term, st.snap_index, st.snap_term, st.last, i
        )
        keep = cfg.window // 2
        new_snap = jnp.maximum(
            st.snap_index, jnp.minimum(st.applied, st.last - keep)
        )
        st = st._replace(snap_term=ta0(new_snap), snap_index=new_snap)

    ta = lambda i: term_at(st.log_term, st.snap_index, st.snap_term, st.last, i)

    not_self = peers != slot
    vote_peer = _vote_targets(st) & not_self
    repl_peer = _repl_targets(st) & not_self
    is_leader = st.role == LEADER

    # --- vote requests (ref: raft.go:822-834) ---
    vr = st.send_vote_req & vote_peer
    vtype = jnp.where(st.vote_req_is_pre, T_PREVOTE, T_VOTE)
    vterm = jnp.where(st.vote_req_is_pre, st.term + 1, st.term)
    out = out._replace(
        valid=out.valid.at[:, KIND_VOTE].set(vr),
        type=out.type.at[:, KIND_VOTE].set(vtype),
        term=out.term.at[:, KIND_VOTE].set(vterm),
        index=out.index.at[:, KIND_VOTE].set(st.last),
        log_term=out.log_term.at[:, KIND_VOTE].set(ta(st.last)),
        ctx=out.ctx.at[:, KIND_VOTE].set(
            jnp.where(st.vote_req_transfer, 1, 0)
        ),
    )

    # --- heartbeats + TimeoutNow (ref: raft.go:495-511; :1367-1372) ---
    # The pending read's seq rides every confirmation heartbeat
    # (bcastHeartbeatWithCtx); TimeoutNow to a caught-up transferee
    # shares the lane (a transfer supersedes that peer's heartbeat).
    hb = st.send_heartbeat & repl_peer & is_leader
    pending_read = (st.read_index >= 0) & ~st.read_ready
    hb_ctx = jnp.where(pending_read, st.read_seq, 0)
    tr = st.transferee - 1  # valid only when transferee > 0
    ton = (
        is_leader
        & (st.transferee > 0)
        & ~st.transfer_sent
        & (st.match >= st.last)  # masked to the transferee's slot below
        & (peers == tr)
    )
    out = out._replace(
        valid=out.valid.at[:, KIND_HB].set(hb | ton),
        type=out.type.at[:, KIND_HB].set(
            jnp.where(ton, T_TIMEOUT_NOW, T_HB)
        ),
        term=out.term.at[:, KIND_HB].set(st.term),
        commit=out.commit.at[:, KIND_HB].set(
            jnp.minimum(st.match, st.commit)
        ),
        ctx=out.ctx.at[:, KIND_HB].set(jnp.where(ton, 0, hb_ctx)),
    )
    st = st._replace(transfer_sent=st.transfer_sent | jnp.any(ton))

    # --- appends / snapshots (ref: raft.go:432-492 maybeSendAppend) ---
    want = st.send_append & repl_peer & is_leader & ~_paused(cfg, st)
    prev = st.next - 1
    snap_needed = prev < st.snap_index
    n_send = jnp.clip(st.last - prev, 0, e)  # [R]
    j = jnp.arange(e, dtype=I32)
    ent_idx = prev[:, None] + 1 + j[None, :]  # [R, E]
    ent_terms = ta(ent_idx)
    ent_mask = j[None, :] < n_send[:, None]
    app = want & ~snap_needed
    snp = want & snap_needed

    out = out._replace(
        valid=out.valid.at[:, KIND_APP].set(app | snp),
        type=out.type.at[:, KIND_APP].set(jnp.where(snp, T_SNAP, T_APP)),
        term=out.term.at[:, KIND_APP].set(st.term),
        index=out.index.at[:, KIND_APP].set(
            jnp.where(snp, st.snap_index, prev)
        ),
        log_term=out.log_term.at[:, KIND_APP].set(
            jnp.where(snp, st.snap_term, ta(prev))
        ),
        commit=out.commit.at[:, KIND_APP].set(st.commit),
        n_ents=out.n_ents.at[:, KIND_APP].set(jnp.where(app, n_send, 0)),
        ent_terms=out.ent_terms.at[:, KIND_APP].set(
            jnp.where(ent_mask & app[:, None], ent_terms, 0)
        ),
    )

    # Progress effects of the sends.
    sent_ents = app & (n_send > 0)
    st = st._replace(
        probe_sent=st.probe_sent | (sent_ents & (st.pr_state == PROBE)),
        next=jnp.where(
            sent_ents & (st.pr_state == REPLICATE), st.next + n_send, st.next
        ),
        inflight=jnp.where(
            sent_ents & (st.pr_state == REPLICATE),
            st.inflight + 1,
            st.inflight,
        ),
        pr_state=jnp.where(snp, SNAPSHOT, st.pr_state),
        pending_snapshot=jnp.where(snp, st.snap_index, st.pending_snapshot),
        send_append=jnp.zeros_like(st.send_append),
        send_heartbeat=jnp.zeros_like(st.send_heartbeat),
        send_vote_req=jnp.zeros_like(st.send_vote_req),
        vote_req_transfer=jnp.zeros_like(st.vote_req_transfer),
    )
    return st, out


# Annotation registry for tools/phaseprobe.py and trace tooling: the
# named_scope segments of one round, in execution order. Labels match
# the jax.named_scope strings below exactly, so xprof captures, the
# phaseprobe artifact, and the SURVEY/ROADMAP prose all name the same
# segments.
ROUND_PHASE_SCOPES = (
    ("deliver", "raft_deliver"),
    ("tick", "raft_tick"),
    ("control", "raft_control"),
    ("propose", "raft_propose"),
    ("emit", "raft_emit"),
    ("route", "raft_route"),
)

# -----------------------------------------------------------------------------
# Round assembly + router
# -----------------------------------------------------------------------------


def route(cfg: BatchedConfig, outbox: MsgSlots) -> MsgSlots:
    """All-device network: outbox[i, target_slot, k] → inbox[t, sender_slot, k]
    where i=(g, s) and t=(g, r). With the dense instance layout this is
    one transpose per field — the ICI-friendly formulation of rafthttp's
    peer streams (ref: SURVEY.md §5 "Distributed communication backend")."""
    g, r = cfg.num_groups, cfg.num_replicas

    def tr(x):
        # [G*R_sender, R_target, K, ...] → [G, R_target, R_sender, K, ...]
        y = x.reshape((g, r) + x.shape[1:])
        y = jnp.swapaxes(y, 1, 2)
        return y.reshape((g * r,) + x.shape[1:])

    with jax.named_scope("raft_route"):
        inbox = jax.tree.map(tr, outbox)
    # Lane indexes pass through untouched: by the inbox lane-order
    # contract (NUM_REQ_KINDS, top of module), emit writes requests
    # into lanes 0..NUM_REQ_KINDS-1 and the round's response scatter
    # has ALREADY placed each response in lane k + NUM_REQ_KINDS of the
    # responder's outbox row for the requester (see _step_round_jit),
    # so the transpose alone lands everything in its inbox lane.
    return inbox


class TelemetryFrame(NamedTuple):
    """Per-round kernel telemetry (cfg.telemetry): event counters in
    telemetry.TM_NAMES column order plus the on-device invariant
    bitmap (kernels.invariant_bits / telemetry.INV_NAMES)."""

    counters: jnp.ndarray  # [N, NUM_COUNTERS] i32 (per-instance [C])
    invariants: jnp.ndarray  # [N] i32 bitmap (per-instance scalar)


def _telemetry_frame(cfg: BatchedConfig, slot, pre: BatchedState,
                     post: BatchedState, inbox_i: MsgSlots,
                     out: MsgSlots, last_tick, n_new) -> TelemetryFrame:
    """Counters for one instance's round — a pure READ of the round's
    inputs/outputs (column order = telemetry.TM_NAMES). Never touches
    protocol state, so telemetry=True stays bit-identical."""
    cnt = lambda m: jnp.sum(m.astype(I32))  # noqa: E731
    v, t = out.valid, out.type
    ar_v = v[:, KIND_APP_RESP] & (t[:, KIND_APP_RESP] == T_APP_RESP)
    appended = post.last - last_tick
    cand = lambda role: (role == CANDIDATE) | (role == PRECANDIDATE)  # noqa: E731
    won = (post.role == LEADER) & (pre.role != LEADER)
    started = (cand(post.role) & ~cand(pre.role)) | (won & ~cand(pre.role))
    cols = (
        cnt(v[:, KIND_VOTE]),
        cnt(v[:, KIND_APP] & (t[:, KIND_APP] == T_APP)),
        cnt(v[:, KIND_APP] & (t[:, KIND_APP] == T_SNAP)),
        cnt(v[:, KIND_HB] & (t[:, KIND_HB] == T_HB)),
        cnt(v[:, KIND_HB] & (t[:, KIND_HB] == T_TIMEOUT_NOW)),
        cnt(v[:, KIND_VOTE_RESP]),
        cnt(v[:, KIND_APP_RESP]),
        cnt(v[:, KIND_HB_RESP]),
        cnt(inbox_i.valid),
        cnt(ar_v & ~out.reject[:, KIND_APP_RESP]),
        cnt(ar_v & out.reject[:, KIND_APP_RESP]),
        cnt((pre.pr_state == PROBE) & (post.pr_state == REPLICATE)),
        cnt((pre.pr_state != SNAPSHOT) & (post.pr_state == SNAPSHOT)),
        cnt((pre.pr_state != PROBE) & (post.pr_state == PROBE)),
        started.astype(I32),
        won.astype(I32),
        post.commit - pre.commit,
        (post.read_ready & ~pre.read_ready).astype(I32),
        jnp.maximum(jnp.maximum(n_new, 0) - appended, 0),
        post.fenced.astype(I32),
        # conf_changes_applied: always zero on device — entry types
        # live in the host arena, so the rawnode adds the count where
        # the masks are actually staged (advance_round's pending-conf
        # application), keeping the column's per-round per-group shape.
        jnp.zeros((), I32),
    )
    counters = jnp.stack([jnp.asarray(c, I32) for c in cols])
    assert counters.shape == (NUM_COUNTERS,)
    return TelemetryFrame(counters, invariant_bits(post, slot))


def _fleet_frame(cfg: BatchedConfig, pre: BatchedState,
                 post: BatchedState, iids, slots) -> jnp.ndarray:
    """The fleet SummaryFrame (cfg.fleet_summary): one flat [L] i32
    vector in obs/fleet.FleetLayout field order, computed OUTSIDE the
    per-instance vmap — every field is a cross-row reduction
    (histograms, censuses, heat bins, top-k), aggregated at the source
    so fleet visibility costs O(L), never O(G), host-side. A pure READ
    of the round's pre/post state: protocol state stays bit-identical
    and with fleet_summary=False none of this is ever traced."""
    n = post.term.shape[0]
    r = cfg.num_replicas
    layout = FleetLayout(n, r, cfg.num_groups)
    peers = jnp.arange(r, dtype=I32)

    delta = post.commit - pre.commit          # [N] commit progress
    backlog = post.last - post.commit         # [N] uncommitted tail
    is_leader = post.role == LEADER
    # Leader-side tracked peers (voters of both halves + learners,
    # self excluded) — the progress rows the pr/inflight censuses read.
    tracked = (
        (post.voter | post.voter_out | post.learner)
        & (peers[None, :] != slots[:, None])
    )
    lmask = is_leader[:, None] & tracked

    group = iids // r                         # [N] group id of each row
    hb = layout.heat_bins
    gbin = group * hb // cfg.num_groups       # [N] heat column
    heat_hit = gbin[:, None] == jnp.arange(hb, dtype=I32)[None, :]

    k = layout.top_k
    # lax.top_k makes laggards IDENTIFIABLE: the k worst-backlogged
    # rows with their full identity. The k-element gathers below are
    # negligible next to the top_k sort itself (k is 8, not G).
    top_lag, top_idx = jax.lax.top_k(backlog, k)

    # Ring-pressure lane (log-lifecycle plane): occupancy is the live
    # span of the device log ring — last minus the compaction floor.
    # The histogram shows the fleet-wide distribution (how close rows
    # run to the window W); the max is the member's high-water mark the
    # console surfaces next to the ring_full refusal counter.
    ring_occ = post.last - post.snap_index

    parts = {
        "hist_commit_delta": log_bucket_counts(delta, FLEET_BUCKETS),
        "hist_backlog": log_bucket_counts(backlog, FLEET_BUCKETS),
        "hist_inflight": log_bucket_counts_masked(
            post.inflight, FLEET_BUCKETS, lmask),
        "hist_ring_occupancy": log_bucket_counts(
            ring_occ, FLEET_BUCKETS),
        "ring_occ_max": jnp.max(ring_occ)[None],
        "leader_slot": jnp.sum(
            ((slots[:, None] == peers[None, :]) & is_leader[:, None])
            .astype(I32), axis=0),
        "role_census": jnp.sum(
            (post.role[:, None] == jnp.arange(4, dtype=I32)[None, :])
            .astype(I32), axis=0),
        "pr_census": jnp.stack([
            jnp.sum((lmask & (post.pr_state == s)).astype(I32))
            for s in (PROBE, REPLICATE, SNAPSHOT)]),
        "fenced": jnp.sum(post.fenced.astype(I32))[None],
        "term_min": jnp.min(post.term)[None],
        "term_max": jnp.max(post.term)[None],
        "term_sum": jnp.sum(post.term)[None],
        "heat_commit": jnp.sum(
            heat_hit.astype(I32) * delta[:, None], axis=0),
        "heat_backlog": jnp.sum(
            heat_hit.astype(I32) * backlog[:, None], axis=0),
        "top_group": group[top_idx],
        "top_lag": top_lag,
        "top_commit": post.commit[top_idx],
        "top_applied": post.applied[top_idx],
        "top_term": post.term[top_idx],
        "top_role": post.role[top_idx],
        "top_lead": post.lead[top_idx],
    }
    pieces = []
    for name, length, _acc in layout.fields:
        p = jnp.ravel(jnp.asarray(parts[name], I32))
        assert p.shape == (length,), (
            f"fleet frame field {name}: {p.shape} != ({length},)")
        pieces.append(p)
    return jnp.concatenate(pieces)


class StepAux(NamedTuple):
    """Per-instance mid-round snapshots the host needs.

    last_tick: log watermark after the tick phase (just before
    proposals append) — the host assigns its queued proposal payloads
    to indexes (last_tick, last], keeping payload bytes off the device
    (ref: SURVEY.md §7).

    read_*: the ReadIndex state right after delivery — a batch can
    confirm in the deliver phase and be replaced by a latched reopen in
    _control within the same round; this snapshot is how that
    confirmation still reaches Ready.ReadStates."""

    last_tick: jnp.ndarray  # [N] last log index pre-propose
    read_seq: jnp.ndarray  # [N]
    read_index: jnp.ndarray  # [N]
    read_ready: jnp.ndarray  # [N]


@functools.lru_cache(maxsize=None)
def _step_round_jit(cfg: BatchedConfig, with_aux: bool,
                    lane_skip: bool = True):
    """One jitted round program per config — shared by every engine/
    node with the same config, whatever rows it hosts (iids/slots are
    runtime arguments, so three hosting processes' nodes reuse one
    compilation per shape).

    ``lane_skip`` enables the vectorized shape's batch-level lane-
    occupancy conds. It MUST be off for mesh-sharded callers: the
    occupancy reduce (any over the sharded instance axis) would be the
    round's first cross-device collective — the sharded layout's whole
    point is that NO collective rides the hot path (row-local quorums,
    ROADMAP item 3), and concurrent per-member sharded programs
    deadlock in the AllReduce rendezvous. Without it the conds take
    per-instance predicates and batch away into selects — correct,
    merely unskipped."""
    # Recompile sentinel: one key per distinct round-step program this
    # session (the lru_cache means this runs once per config). The
    # tier-1 shape budget in tests/batched/conftest.py audits this set.
    note_compile_key(
        "round_step",
        f"{cfg}|aux={int(with_aux)}|laneskip={int(lane_skip)}")

    def step_round(st: BatchedState, inbox: MsgSlots, tick_mask, campaign_mask,
                   propose_n, isolate, transfer_to, read_req, iids, slots):
        if cfg.narrow_lanes:
            # Narrow lanes live int8/int16 BETWEEN rounds (the donated
            # state carry AND the routed inbox); the protocol math runs
            # on i32 exactly as in the wide layout, so parity is by
            # construction.
            st = widen_state(st)
            inbox = widen_msgs(inbox)

        # Batch-level lane occupancy for the vectorized shape's
        # lax.cond lane skips: computed OUTSIDE the vmap and passed
        # unmapped (in_axes=None), so the conds stay real branches
        # instead of degrading to selects under a mapped predicate.
        # None when lane_skip is off (sharded callers — see docstring).
        lane_any = (
            jnp.any(inbox.valid, axis=(0, 1)) if lane_skip else None
        )  # [K]

        def per_instance(iid, slot, sti, inbox_i, do_tick, do_camp, n_new,
                         iso, tr_to, rd_req, lane_any):
            # Partitioned instances neither receive nor send this round
            # (fault injection; ref: tests/framework bridge & pkg/proxy).
            # Phases carry jax.named_scope annotations so xprof/JAX
            # profiler traces attribute device time per phase (SURVEY
            # §5 tracing: profiler hooks around the step kernel).
            pre = sti  # round-entry state (telemetry deltas)
            inbox_i = inbox_i._replace(valid=inbox_i.valid & ~iso)
            with jax.named_scope("raft_deliver"):
                sti, req_resps = _deliver_all(cfg, iid, slot, sti, inbox_i,
                                              lane_any)
            with jax.named_scope("raft_tick"):
                sti = _tick(cfg, iid, slot, sti, do_tick, do_camp)
            read_snap = (sti.read_seq, sti.read_index, sti.read_ready)
            with jax.named_scope("raft_control"):
                sti = _control(cfg, slot, sti, tr_to, rd_req)
            last_tick = sti.last
            with jax.named_scope("raft_propose"):
                sti = _propose(cfg, slot, sti, n_new)
            with jax.named_scope("raft_emit"):
                sti, out = _emit(cfg, slot, sti)
            # Responses to requests from sender s (request kinds) land
            # in out[s, k + NUM_REQ_KINDS]; they route back by the same
            # transpose (the inbox lane-order contract, top of module).
            out = jax.tree.map(
                lambda o, rr: o.at[:, NUM_REQ_KINDS:].set(rr),
                out, req_resps,
            )
            out = out._replace(valid=out.valid & ~iso)
            with jax.named_scope("raft_lease"):
                # Quorum-evidence lease re-arm (BatchedState.lease_ticks):
                # commit progress this round means a quorum just acked
                # our log; a ReadIndex batch confirming means a quorum
                # just answered our heartbeat ctx. Either way no rival
                # can win for >= election_timeout of our ticks. Leaders
                # mid-transfer never re-arm; non-leaders hold zero (the
                # one step-down path, so every become_follower variant
                # is covered without touching it).
                # read_snap, not sti.read_ready: a batch can confirm in
                # deliver and be replaced by a latched reopen within
                # this same round — the confirmation still happened.
                fresh = (
                    (sti.role == LEADER) & (sti.transferee == 0)
                    & ((sti.commit > pre.commit)
                       | (read_snap[2] & ~pre.read_ready))
                )
                lease = jnp.where(
                    fresh, cfg.election_timeout, sti.lease_ticks)
                sti = sti._replace(lease_ticks=jnp.where(
                    sti.role == LEADER, lease, 0))
            ret = (sti, out, StepAux(last_tick, *read_snap))
            if cfg.telemetry:
                with jax.named_scope("raft_telemetry"):
                    ret += (_telemetry_frame(
                        cfg, slot, pre, sti, inbox_i, out, last_tick,
                        n_new),)
            return ret

        if cfg.lanes_minor:
            # Instance axis minor inside the kernel: every elementwise
            # op fills the TPU vector lanes with N, not with R/K/W.
            to_minor = lambda x: (
                jnp.moveaxis(x, 0, -1) if x.ndim > 1 else x
            )
            to_major = lambda x: (
                jnp.moveaxis(x, -1, 0) if x.ndim > 1 else x
            )
            args = jax.tree.map(
                to_minor,
                (iids, slots, st, inbox, tick_mask, campaign_mask,
                 propose_n, isolate, transfer_to, read_req),
            )
            outs = jax.vmap(
                per_instance,
                in_axes=(-1,) * len(args) + (None,), out_axes=-1,
            )(*args, lane_any)
            outs = jax.tree.map(to_major, outs)
        else:
            outs = jax.vmap(
                per_instance, in_axes=(0,) * 10 + (None,),
            )(
                iids, slots, st, inbox, tick_mask, campaign_mask,
                propose_n, isolate, transfer_to, read_req, lane_any,
            )
        sti, out, aux = outs[:3]
        fleet = None
        if cfg.fleet_summary:
            # Cross-row reductions, so this lives OUTSIDE the vmap on
            # the full [N, ...] pre/post state (`st` is the widened
            # round-entry state; `sti` the widened post state).
            with jax.named_scope("raft_fleet"):
                fleet = _fleet_frame(cfg, st, sti, iids, slots)
        if cfg.narrow_lanes:
            sti = narrow_state(sti)
            # Telemetry/fleet frames above read the WIDE outbox; the
            # narrowed one is what rides the route()→inbox carry.
            out = narrow_msgs(out)
        # Output order: (state, outbox[, aux][, telemetry][, fleet]) —
        # callers index via the cfg flags (engine/rawnode compute the
        # positions once at build time).
        ret = (sti, out) + ((aux,) if with_aux else ())
        if cfg.telemetry:
            ret += (outs[3],)
        if cfg.fleet_summary:
            ret += (fleet,)
        return ret

    # NOT donated: hosting callers (BatchedRawNode) build the inbox by
    # zero-copy wrapping host numpy staging buffers (jnp.asarray on CPU
    # aliases the host memory), and donating an aliased buffer lets XLA
    # write outputs into memory the host still views — observed as
    # garbage outbox fields on the hosted restart path. Buffer-donation
    # round pipelining lives in the engine's closed_loop jit
    # (engine.py), whose state/inbox are always jax-native buffers.
    return jax.jit(step_round)


def make_step_round(cfg: BatchedConfig, iids=None, slots=None,
                    with_aux: bool = False, lane_skip: bool = True):
    """Build the round function:

        state, outbox[, aux] = step_round(state, inbox, tick_mask,
                                          campaign_mask, propose_n, isolate)

    All arrays stay on device; chain with route() for a closed-loop
    multi-raft simulation (the dense all-replica layout), or pass
    explicit `iids`/`slots` for a hosting process that owns one replica
    slot of each group (iid = group*R + slot keeps the deterministic
    randomized-timeout hash identical across topologies)."""
    # Resolve deliver_shape="auto" BEFORE the per-config jit cache so
    # "auto" and its concrete platform resolution share one program.
    # ``lane_skip=False`` is for mesh-sharded callers — see
    # _step_round_jit on why the occupancy reduce must not cross
    # shards.
    cfg = cfg.resolved()
    # Apply-plane knobs never enter the round-step program (the plane
    # is a separate jitted program, applyplane.py): strip them to
    # defaults before the per-config jit cache so apply_plane on/off
    # share ONE compiled round — the static-plane contract enforced
    # structurally, and the conftest compile-shape budget stays put.
    cfg = cfg.apply_plane_key()
    if iids is None:
        iids = jnp.arange(cfg.num_instances, dtype=I32)
    else:
        iids = jnp.asarray(iids, I32)
    if slots is None:
        slots = iids % cfg.num_replicas
    else:
        slots = jnp.asarray(slots, I32)
    inner = _step_round_jit(cfg, with_aux, lane_skip)
    n = iids.shape[0]
    zero_i = jnp.zeros((n,), I32)
    zero_b = jnp.zeros((n,), bool)

    def step(st, inbox, tick_mask, campaign_mask, propose_n, isolate,
             transfer_to=None, read_req=None):
        return inner(st, inbox, tick_mask, campaign_mask, propose_n,
                     isolate,
                     zero_i if transfer_to is None else transfer_to,
                     zero_b if read_req is None else read_req,
                     iids, slots)

    return step


# -----------------------------------------------------------------------------
# On-device outbox packing (the hosted collect fast path)
# -----------------------------------------------------------------------------

# Words per wire record: the device emits outbox messages pre-packed at
# wire widths — [M, REC_WORDS] i32 rows whose little-endian bytes ARE
# msgblock.REC_DTYPE records. The host then materializes the round's
# outbound block with one np.asarray + view-cast + boolean take instead
# of 14 fancy-indexed gathers over [n, R, K] fields (msgblock
# compact_records).
REC_WORDS = 9


@functools.lru_cache(maxsize=None)
def _pack_outbox_jit():
    # Unroutable types pack lane 0; they are never valid so the host
    # compress drops them (a -1 lane would smear into the type byte).
    lane_tab = jnp.asarray(np.maximum(LANE_OF, 0).astype(np.int32))

    def pack(valid, typ, reject, n_ents, term, log_term, index, commit,
             reject_hint, ctx, slots):
        # The outbox may arrive in narrow storage dtypes
        # (cfg.narrow_lanes → NARROW_MSG_DTYPES); the shift/or packing
        # below needs i32 words (an int8 `typ << 24` would wrap).
        typ = typ.astype(I32)
        n_ents = n_ents.astype(I32)
        n, r, _k = typ.shape
        shape = typ.shape
        rows = jnp.broadcast_to(
            jnp.arange(n, dtype=I32)[:, None, None], shape)
        to = jnp.broadcast_to(
            jnp.arange(1, r + 1, dtype=I32)[None, :, None], shape)
        frm = jnp.broadcast_to(
            (slots.astype(I32) + 1)[:, None, None], shape)
        lane = lane_tab[jnp.clip(typ, 0, NUM_WIRE_TYPES - 1)]
        # Little-endian byte lanes of REC_DTYPE's packed u1 fields.
        w_addr = to | (frm << 8) | (lane << 16) | (typ << 24)
        ne = jnp.where(typ == T_APP, n_ents, 0)
        w_flags = reject.astype(I32) | (ne << 8)
        words = jnp.stack(
            (rows, w_addr, w_flags, term, log_term, index, commit,
             reject_hint, ctx), axis=-1)
        simple = (valid & (typ != T_SNAP)).reshape(-1)
        cplx = (valid & (typ == T_SNAP)).reshape(-1)
        return words.reshape(-1, REC_WORDS), simple, cplx

    return jax.jit(pack)


def pack_outbox(out: MsgSlots, slots):
    """Pack a device outbox into wire-record words on device.

    Returns (words [M, REC_WORDS] i32, simple [M] bool, complex [M]
    bool) with M = n*R*K flat slots: `simple` marks block-eligible
    messages (everything but MsgSnap), `complex` the MsgSnap slots that
    keep the per-message object path. The words' bytes are exactly
    msgblock.REC_DTYPE, so the host-side collect is a view-cast."""
    return _pack_outbox_jit()(
        out.valid, out.type, out.reject, out.n_ents, out.term,
        out.log_term, out.index, out.commit, out.reject_hint, out.ctx,
        slots,
    )
