"""Host-facing wrapper around the batched step kernel.

``MultiRaftEngine`` is the BatchedRawNode of the north star: it keeps
the full multi-group SoA state on device, exposes the same logical
contract as ``raft.RawNode`` (tick / campaign / propose / step / ready
watermarks / advance) but batched over every group at once, and runs
closed-loop rounds entirely on device (deliver → tick → propose → emit →
route). Entry payloads never touch the device: the host keeps them in
an arena keyed by (group, index), and the commit watermarks streaming
back from the device drive payload application — mirroring how the
reference applies committed entries after the Ready loop (ref:
server/etcdserver/raft.go:158-315).
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sentinels import note_compile_key, warm_guard
from .compile_cache import enable_compile_cache

# Never-reused engine identity for transfer-guard warm keys (itertools
# .count is atomic under the GIL).
_ENGINE_SERIAL = itertools.count()
from .state import BatchedConfig, BatchedState, init_state, LEADER, I32
from .step import MsgSlots, NUM_KINDS, empty_msgs, make_step_round, route


class MultiRaftEngine:
    def __init__(self, cfg: BatchedConfig, start_index: int = 0):
        # deliver_shape="auto" resolves to the platform default here
        # (state.default_deliver_shape), so self.cfg always names the
        # concrete shape the compiled round actually runs.
        self.cfg = cfg = cfg.validate().resolved()
        # Round programs are expensive to build (minutes over the
        # remote-compile tunnel); cache compilations across processes
        # unless ETCD_TPU_COMPILE_CACHE=off.
        enable_compile_cache()
        self.state = init_state(cfg, start_index)
        self.inbox = empty_msgs(
            (cfg.num_instances, cfg.num_replicas, NUM_KINDS),
            cfg.max_ents_per_msg,
            narrow=cfg.narrow_lanes,
        )
        self._step = make_step_round(cfg)
        n = cfg.num_instances
        self._zeros_b = jnp.zeros((n,), bool)
        self._zeros_i = jnp.zeros((n,), I32)
        # In-device telemetry accumulator (cfg.telemetry): per-instance
        # counter totals + OR-folded invariant bitmaps, accumulated
        # inside the closed-loop scan with no per-round host sync.
        if cfg.telemetry:
            from .telemetry import NUM_COUNTERS

            self._tel_counters = jnp.zeros((n, NUM_COUNTERS), I32)
            self._tel_invariants = jnp.zeros((n,), I32)
        self.telemetry_hub = None
        # Step output positions past (state, outbox): aux is absent on
        # the engine's step (with_aux=False), then telemetry, then the
        # fleet summary vector — indexed here once instead of fragile
        # out[-1] reads that break when a second plane is on.
        self._tel_pos = 2
        self._fleet_pos = 2 + (1 if cfg.telemetry else 0)
        # In-device fleet-summary accumulator (cfg.fleet_summary): one
        # flat [L] i32 frame; delta fields (sum_mask) add across
        # rounds, snapshot fields keep the latest round's value — both
        # inside the scan carry, zero per-round host sync.
        if cfg.fleet_summary:
            from ..obs.fleet import FleetLayout

            self._fleet_layout = FleetLayout(
                n, cfg.num_replicas, cfg.num_groups)
            self._fleet_vec = jnp.zeros((self._fleet_layout.size,), I32)
            self._fleet_summask = jnp.asarray(
                self._fleet_layout.sum_mask())
            # The device carry is i32 and its ACC_SUM fields aggregate
            # ALL rows into a few buckets (hist_commit_delta gains N
            # counts per round), so an undrained closed loop would
            # wrap after ~2^31/N rounds at large G — silently, and
            # ingest_totals' delta clamp would then eat every later
            # frame. drain_fleet() folds the device sums into this
            # i64 host base and RESETS them, so the public totals are
            # unbounded while the on-device window stays small; any
            # consumer that reads the histograms drains periodically
            # (the hosted path ingests per round and never uses this).
            self._fleet_base = np.zeros(self._fleet_layout.size,
                                        np.int64)
            self._fleet_sum_np = self._fleet_layout.sum_mask()
        self.fleet_hub = None

        def closed_loop(st, inbox, ticks, props, tel, flt, rounds):
            def body(carry, _):
                st, inbox, tel, flt = carry
                out = self._step(
                    st, inbox, ticks, self._zeros_b, props, self._zeros_b
                )
                st, outbox = out[:2]
                if cfg.telemetry:
                    fr = out[self._tel_pos]
                    tel = (tel[0] + fr.counters, tel[1] | fr.invariants)
                if cfg.fleet_summary:
                    fv = out[self._fleet_pos]
                    flt = jnp.where(self._fleet_summask, flt + fv, fv)
                return (st, route(cfg, outbox), tel, flt), None

            (st, inbox, tel, flt), _ = jax.lax.scan(
                body, (st, inbox, tel, flt), None, length=rounds
            )
            # The scalar fence is a SEPARATE output buffer: pipelined
            # callers block on it to bound queue depth without holding
            # (and thereby breaking) a donated state buffer.
            return st, inbox, tel, flt, st.commit[0]

        # State and inbox are donated: run_rounds/run_rounds_pipelined
        # reassign both from the return value, so XLA writes round k+1
        # into round k-1's freed SoA buffers instead of allocating.
        # (The telemetry accumulator rides the carry undonated — it is
        # tiny next to the SoA state and donation would complicate the
        # telemetry-off path, which must stay byte-identical.)
        self._closed_loop = jax.jit(
            closed_loop, static_argnames=("rounds",), donate_argnums=(0, 1)
        )
        note_compile_key("closed_loop", f"{cfg}")
        # Transfer-guard warm keys (analysis.sentinels): the guard wraps
        # dispatch only AFTER a (program, statics) pair has compiled
        # once — compilation legitimately transfers host constants. The
        # round program is shared per config (step._step_round_jit), so
        # its warmth is keyed by config, not engine identity; the
        # per-engine closed-loop wrapper is keyed by a monotonic serial
        # (NOT id(self): CPython reuses freed addresses, and a stale
        # warm key would put a new engine's compile inside the guard).
        self._wkey_step = f"round_step/{hash((cfg, False, n))}"
        self._serial = next(_ENGINE_SERIAL)

    # -- driving --------------------------------------------------------------

    def step_round(
        self,
        tick: bool = False,
        campaign_mask: Optional[jnp.ndarray] = None,
        propose_n: Optional[jnp.ndarray] = None,
        isolate: Optional[jnp.ndarray] = None,
        transfer_to: Optional[jnp.ndarray] = None,
        read_req: Optional[jnp.ndarray] = None,
    ) -> None:
        """One round: deliver pending messages, optionally tick every
        instance, run host control ops (leader transfer, ReadIndex),
        append proposals on leaders, route the outbox. `isolate` cuts
        instances off the network for this round."""
        ticks = (
            jnp.ones_like(self._zeros_b) if tick else self._zeros_b
        )
        camp = campaign_mask if campaign_mask is not None else self._zeros_b
        props = propose_n if propose_n is not None else self._zeros_i
        iso = isolate if isolate is not None else self._zeros_b
        # Inside the guard the dispatch must be all-device: any implicit
        # transfer (an eager scalar op, a stray host array) is a hard
        # error when ETCD_TPU_TRANSFER_GUARD=disallow (tests, benches).
        with warm_guard(self._wkey_step):
            out = self._step(
                self.state, self.inbox, ticks, camp, props, iso,
                transfer_to, read_req,
            )
            self.state, outbox = out[:2]
            if self.cfg.telemetry:
                fr = out[self._tel_pos]
                self._tel_counters = self._tel_counters + fr.counters
                self._tel_invariants = self._tel_invariants | fr.invariants
            if self.cfg.fleet_summary:
                fv = out[self._fleet_pos]
                self._fleet_vec = jnp.where(
                    self._fleet_summask, self._fleet_vec + fv, fv)
            self.inbox = route(self.cfg, outbox)

    def _tel(self):
        """Telemetry carry for the closed loop (empty pytree when off)."""
        if self.cfg.telemetry:
            return (self._tel_counters, self._tel_invariants)
        return ()

    def _set_tel(self, tel) -> None:
        if self.cfg.telemetry:
            self._tel_counters, self._tel_invariants = tel

    def _flt(self):
        """Fleet-summary carry for the closed loop (empty when off)."""
        if self.cfg.fleet_summary:
            return self._fleet_vec
        return ()

    def _set_flt(self, flt) -> None:
        if self.cfg.fleet_summary:
            self._fleet_vec = flt

    def run_rounds(self, rounds: int, tick: bool = True,
                   propose_n: Optional[jnp.ndarray] = None) -> None:
        """Closed-loop simulation of `rounds` rounds without leaving the
        device (one fused lax.scan program)."""
        ticks = jnp.ones_like(self._zeros_b) if tick else self._zeros_b
        props = propose_n if propose_n is not None else self._zeros_i
        # `rounds` is a static arg: each new value compiles a new scan
        # program, so warmth (and thus the transfer guard) is per value.
        with warm_guard(f"closed_loop/{self._serial}/{rounds}"):
            self.state, self.inbox, tel, flt, _ = self._closed_loop(
                self.state, self.inbox, ticks, props, self._tel(),
                self._flt(), rounds
            )
        self._set_tel(tel)
        self._set_flt(flt)

    def run_rounds_pipelined(self, rounds: int, chunk: int = 16,
                             depth: int = 2, tick: bool = True,
                             propose_n: Optional[jnp.ndarray] = None) -> None:
        """Double-buffered round pipelining: split `rounds` into scan
        chunks and keep up to `depth` chunks in flight — chunk k+1 is
        enqueued while chunk k's scan executes, and because the state
        carry is donated, XLA writes chunk k+1's output into chunk
        k-1's freed buffers. Dispatch gaps between scans vanish without
        device memory growing with `rounds`.

        Blocking is on the per-chunk scalar fence (an independent
        output), never on donated state; the final chunk is left in
        flight — callers that need completion block on
        ``self.state.commit`` as usual."""
        if rounds <= 0:
            return
        if chunk <= 0:
            # A non-positive chunk would dispatch zero-round scans
            # forever (done never advances) — a silent host hang.
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        ticks = jnp.ones_like(self._zeros_b) if tick else self._zeros_b
        props = propose_n if propose_n is not None else self._zeros_i
        fences: deque = deque()
        done = 0
        while done < rounds:
            n = min(chunk, rounds - done)
            with warm_guard(f"closed_loop/{self._serial}/{n}"):
                self.state, self.inbox, tel, flt, fence = self._closed_loop(
                    self.state, self.inbox, ticks, props, self._tel(),
                    self._flt(), n
                )
            self._set_tel(tel)
            self._set_flt(flt)
            done += n
            fences.append(fence)
            while len(fences) > depth:
                # jitlint: waive(sync-in-loop) -- the sync IS the pipelining contract: block on the per-chunk scalar fence to bound queue depth at `depth` without holding a donated buffer
                jax.block_until_ready(fences.popleft())

    def campaign(self, instance_ids) -> None:
        mask = self._zeros_b.at[jnp.asarray(instance_ids)].set(True)
        self.step_round(campaign_mask=mask)

    def transfer_leader(self, leader_instance: int, target_slot: int) -> None:
        """Ask the leader instance to hand leadership to target_slot
        (ref: raft.go:1339 MsgTransferLeader on the leader)."""
        tr = self._zeros_i.at[leader_instance].set(target_slot + 1)
        self.step_round(transfer_to=tr)

    def read_index(self, instance_ids) -> None:
        """Open a ReadIndex batch on the given leader instances
        (ref: v3_server.go sendReadIndex → MsgReadIndex)."""
        req = self._zeros_b.at[jnp.asarray(instance_ids)].set(True)
        self.step_round(read_req=req)

    def read_states(self) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
        """(seq, index, ready) per instance — the ReadState watermarks
        the host read loop waits on (ref: read_only.go advance →
        Ready.ReadStates)."""
        return (
            np.asarray(self.state.read_seq),
            np.asarray(self.state.read_index),
            np.asarray(self.state.read_ready),
        )

    def set_membership(self, group: int, voters, voters_out=(),
                       learners=(), joint: bool = False) -> None:
        """Upload new membership masks for every replica row of `group`
        — the confchange apply point (ref: confchange/confchange.go
        EnterJoint/LeaveJoint/Simple; the host Changer computes the
        slot sets, the device only sees masks)."""
        r = self.cfg.num_replicas
        rows = jnp.arange(group * r, (group + 1) * r)

        def mask(slots) -> jnp.ndarray:
            slots = list(slots)  # materialize once: iterators welcome
            m = jnp.zeros((r,), bool)
            return m.at[jnp.asarray(slots, I32)].set(True) if slots else m

        vin, vout, lrn = mask(voters), mask(voters_out), mask(learners)
        st = self.state
        self.state = st._replace(
            voter=st.voter.at[rows].set(vin),
            voter_out=st.voter_out.at[rows].set(vout),
            learner=st.learner.at[rows].set(lrn),
            in_joint=st.in_joint.at[rows].set(bool(joint)),
        )

    # -- telemetry (device → host gather; cfg.telemetry only) -----------------

    def telemetry(self) -> "tuple[np.ndarray, np.ndarray]":
        """(counters [N, NUM_COUNTERS], invariants [N]) — monotone
        per-instance totals accumulated in-device since the last reset
        (column order: telemetry.TM_NAMES). One host gather; no
        per-round sync ever happened."""
        assert self.cfg.telemetry, "engine built with telemetry=False"
        return (np.asarray(self._tel_counters),
                np.asarray(self._tel_invariants))

    def drain_telemetry(self, hub=None) -> "tuple[np.ndarray, np.ndarray]":
        """Fold the accumulated totals into `hub` (or the attached
        ``telemetry_hub``) via its monotone-totals path; returns the
        fetched (counters, invariants)."""
        counters, inv = self.telemetry()
        hub = hub or self.telemetry_hub
        if hub is not None:
            hub.ingest_totals(counters, inv)
        return counters, inv

    # -- fleet summary (device → host gather; cfg.fleet_summary only) ---------

    def fleet_frame(self) -> np.ndarray:
        """The accumulated [L] SummaryFrame (obs/fleet.FleetLayout
        order, int64): delta fields are monotone sums across rounds
        (device window + drained i64 base — see __init__), snapshot
        fields hold the LAST round's census/top-k. One host gather; no
        per-round sync ever happened."""
        assert self.cfg.fleet_summary, (
            "engine built with fleet_summary=False")
        vec = np.asarray(self._fleet_vec).astype(np.int64)
        return np.where(self._fleet_sum_np, self._fleet_base + vec, vec)

    def drain_fleet(self, hub=None) -> np.ndarray:
        """Fold the accumulated frame into `hub` (or the attached
        ``fleet_hub``) via its monotone-totals path, then bank the
        device window's sums into the i64 base and reset them on
        device (bounds the i32 carry far below wrap); returns the
        fetched monotone vector."""
        dev = np.asarray(self._fleet_vec).astype(np.int64)
        vec = np.where(self._fleet_sum_np, self._fleet_base + dev, dev)
        hub = hub or self.fleet_hub
        if hub is not None:
            hub.ingest_totals(vec)
        self._fleet_base += np.where(self._fleet_sum_np, dev, 0)
        self._fleet_vec = jnp.where(
            self._fleet_summask, 0, self._fleet_vec)
        return vec

    # -- observation (device → host gathers, debug/Ready watermarks) ----------

    def leaders(self) -> np.ndarray:
        """Per group: leader replica slot, or -1."""
        role = np.asarray(self.state.role).reshape(
            self.cfg.num_groups, self.cfg.num_replicas
        )
        is_lead = role == LEADER
        return np.where(is_lead.any(axis=1), is_lead.argmax(axis=1), -1)

    def commits(self) -> np.ndarray:
        """Per-instance commit watermarks [G, R] — the host applies
        payloads from its arena up to these."""
        return np.asarray(self.state.commit).reshape(
            self.cfg.num_groups, self.cfg.num_replicas
        )

    def terms(self) -> np.ndarray:
        return np.asarray(self.state.term).reshape(
            self.cfg.num_groups, self.cfg.num_replicas
        )
