"""Host shadow cluster: the single-group oracle driven under the batched
engine's round/slot network semantics, for lockstep differential testing.

The batched engine's network delivers at most one message of each KIND
per (sender, target) pair per round and processes inbox slots in a fixed
(sender, kind) order. This adapter runs R reference-semantics RawNodes
(etcd_tpu.raft) under exactly those rules so that, for schedules within
the common feature envelope (explicit campaigns, leader-side proposals,
heartbeat ticks, full-instance partitions; no timer elections), the
device state must match the oracle state field-for-field after every
round. Schedules that would overflow a slot (two same-kind messages to
one target in one round) raise, keeping the comparison honest.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..raft import Config, MemoryStorage, RawNode
from ..raft.errors import RaftError
from ..raft.types import ConfState, Message, MessageType
from .step import (
    KIND_APP,
    KIND_APP_RESP,
    KIND_HB,
    KIND_HB_RESP,
    KIND_VOTE,
    KIND_VOTE_RESP,
    NUM_KINDS,
)

# Kind lanes, matching step.py's inbox layout.
_TYPE_TO_KIND = {
    MessageType.MsgVote: KIND_VOTE,
    MessageType.MsgPreVote: KIND_VOTE,
    MessageType.MsgApp: KIND_APP,
    MessageType.MsgSnap: KIND_APP,
    MessageType.MsgHeartbeat: KIND_HB,
    MessageType.MsgTimeoutNow: KIND_HB,
    MessageType.MsgVoteResp: KIND_VOTE_RESP,
    MessageType.MsgPreVoteResp: KIND_VOTE_RESP,
    MessageType.MsgAppResp: KIND_APP_RESP,
    MessageType.MsgHeartbeatResp: KIND_HB_RESP,
}


class ShadowCluster:
    def __init__(
        self,
        num_replicas: int,
        election_timeout: int = 1 << 20,
        heartbeat_timeout: int = 1,
        max_inflight: int = 1 << 20,
        pre_vote: bool = False,
        learners: Sequence[int] = (),
    ):
        self.r = num_replicas
        self.nodes: List[RawNode] = []
        lrn = {s + 1 for s in learners}
        for slot in range(num_replicas):
            storage = MemoryStorage()
            # Bootstrap the full-voter config the way the batched engine
            # does: membership is initial state, not replayed conf changes.
            storage._snapshot.metadata.conf_state = ConfState(
                voters=[i for i in range(1, num_replicas + 1)
                        if i not in lrn],
                learners=sorted(lrn),
            )
            cfg = Config(
                id=slot + 1,
                election_tick=election_timeout,
                heartbeat_tick=heartbeat_timeout,
                storage=storage,
                max_size_per_msg=1 << 62,
                max_inflight_msgs=max_inflight,
                pre_vote=pre_vote,
            )
            self.nodes.append(RawNode(cfg))
        # inbox[target][sender][kind]
        self.inbox: List[List[List[Optional[Message]]]] = self._empty_inbox()

    def _empty_inbox(self):
        return [
            [[None] * NUM_KINDS for _ in range(self.r)] for _ in range(self.r)
        ]

    def round(
        self,
        campaigns: Sequence[int] = (),
        proposals: Optional[Dict[int, int]] = None,
        tick: bool = False,
        isolate: Iterable[int] = (),
        transfers: Optional[Dict[int, int]] = None,
    ) -> None:
        """One round with the device's phase order:
        deliver → tick/campaign → control → propose → emit.
        `transfers` maps leader slot → target slot."""
        iso = set(isolate)
        proposals = proposals or {}
        transfers = transfers or {}

        # Phase 1: deliver, fixed (kind, sender) order per target — the
        # device processes lane-by-lane with senders ascending within a
        # lane (step.py _deliver_all).
        inbox, self.inbox = self.inbox, self._empty_inbox()
        for target in range(self.r):
            if target in iso:
                continue
            for kind in range(NUM_KINDS):
                for sender in range(self.r):
                    m = inbox[target][sender][kind]
                    if m is None:
                        continue
                    try:
                        self.nodes[target].step(m)
                    except RaftError:
                        pass

        # Phase 2: tick / explicit campaigns.
        if tick:
            for node in self.nodes:
                node.tick()
        for slot in campaigns:
            self.nodes[slot].campaign()

        # Phase 2b: host control ops, same slot order as the device's
        # _control phase (after tick, before propose).
        for slot, target in transfers.items():
            try:
                self.nodes[slot].transfer_leader(target + 1)
            except RaftError:
                pass

        # Phase 3: proposals (empty payloads; the batched engine carries
        # payloads in the host arena, so terms are the shared content).
        # All n entries ride one MsgProp — the batched engine appends
        # its per-round proposals as one batch with one broadcast.
        from ..raft.types import Entry

        for slot, n in proposals.items():
            if n <= 0:
                continue
            node = self.nodes[slot]
            try:
                node.raft.step(
                    Message(
                        type=MessageType.MsgProp,
                        from_=node.raft.id,
                        entries=[Entry(data=b"") for _ in range(n)],
                    )
                )
            except RaftError:
                pass

        # Phase 4: emit — run the Ready loop, bucket outbound messages.
        for slot, node in enumerate(self.nodes):
            if not node.has_ready():
                continue
            rd = node.ready()
            storage = node.raft.raft_log.storage
            if rd.hard_state.term or rd.hard_state.vote or rd.hard_state.commit:
                storage.set_hard_state(rd.hard_state)
            storage.append(rd.entries)
            for m in rd.messages:
                if slot in iso:
                    continue
                kind = _TYPE_TO_KIND.get(m.type)
                if kind is None:
                    raise AssertionError(f"unroutable message type {m.type}")
                target = m.to - 1
                if self.inbox[target][slot][kind] is not None:
                    raise AssertionError(
                        f"slot collision: {m.type} from {slot} to {target}; "
                        "schedule outside the differential envelope"
                    )
                self.inbox[target][slot][kind] = m
            node.advance(rd)

    # -- state vector for comparison ------------------------------------------

    def snapshot_state(self) -> List[Tuple[int, ...]]:
        """(term, role, lead, commit, last) per replica — the fields the
        batched engine must reproduce exactly."""
        out = []
        for node in self.nodes:
            r = node.raft
            out.append(
                (
                    r.term,
                    int(r.state),
                    r.lead,
                    r.raft_log.committed,
                    r.raft_log.last_index(),
                )
            )
        return out

    def log_terms(self, slot: int) -> List[Tuple[int, int]]:
        r = self.nodes[slot].raft
        lo = r.raft_log.first_index()
        hi = r.raft_log.last_index()
        return [(i, r.raft_log.term(i)) for i in range(lo, hi + 1)]
