"""Host shadow cluster: the single-group oracle driven under the batched
engine's round/slot network semantics, for lockstep differential testing.

The batched engine's network delivers at most one message of each KIND
per (sender, target) pair per round and processes inbox slots in a fixed
(sender, kind) order. This adapter runs R reference-semantics RawNodes
(etcd_tpu.raft) under exactly those rules so that, for schedules within
the common feature envelope (explicit campaigns, leader-side proposals,
heartbeat ticks, full-instance partitions; no timer elections), the
device state must match the oracle state field-for-field after every
round. Schedules that would overflow a slot (two same-kind messages to
one target in one round) raise, keeping the comparison honest.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..raft import Config, MemoryStorage, RawNode
from ..raft.errors import RaftError
from ..raft.types import ConfState, Message, MessageType
from .step import (
    KIND_APP,
    KIND_APP_RESP,
    KIND_HB,
    KIND_HB_RESP,
    KIND_VOTE,
    KIND_VOTE_RESP,
    NUM_KINDS,
)

# Kind lanes, matching step.py's inbox layout.
_TYPE_TO_KIND = {
    MessageType.MsgVote: KIND_VOTE,
    MessageType.MsgPreVote: KIND_VOTE,
    MessageType.MsgApp: KIND_APP,
    MessageType.MsgSnap: KIND_APP,
    MessageType.MsgHeartbeat: KIND_HB,
    MessageType.MsgTimeoutNow: KIND_HB,
    MessageType.MsgVoteResp: KIND_VOTE_RESP,
    MessageType.MsgPreVoteResp: KIND_VOTE_RESP,
    MessageType.MsgAppResp: KIND_APP_RESP,
    MessageType.MsgHeartbeatResp: KIND_HB_RESP,
}


def _same_message(a: Message, b: Message) -> bool:
    return (
        a.type == b.type and a.term == b.term and a.log_term == b.log_term
        and a.index == b.index and a.commit == b.commit
        and a.reject == b.reject and a.reject_hint == b.reject_hint
        and [(e.index, e.term) for e in a.entries]
        == [(e.index, e.term) for e in b.entries]
    )


def _merge_apps(a: Message, b: Message) -> Optional[Message]:
    """Coalesce two same-round MsgApps to one target the way the
    device's single send flag does (one append per peer per round
    carrying the union): contiguous, same-term appends merge; anything
    else is a real envelope violation (returns None).

    The oracle legitimately emits two — commit-advance bcastAppend plus
    the proposal bcastAppend in the same Ready (raft.go maybeCommit →
    bcastAppend; appendEntry → bcastAppend)."""
    if a.type != MessageType.MsgApp or b.type != MessageType.MsgApp:
        return None
    if a.term != b.term:
        return None
    first, second = (a, b) if a.index <= b.index else (b, a)
    end1 = first.index + len(first.entries)
    end2 = second.index + len(second.entries)
    if end1 < second.index:
        return None  # gap — not one logical send
    if end1 >= end2:
        # first covers second entirely (re-materialized sends overlap)
        return Message(
            type=MessageType.MsgApp, to=first.to, from_=first.from_,
            term=first.term, log_term=first.log_term, index=first.index,
            entries=list(first.entries), commit=max(a.commit, b.commit))
    take = end1 - second.index  # overlap length to skip in second
    return Message(
        type=MessageType.MsgApp, to=a.to, from_=a.from_, term=a.term,
        log_term=first.log_term, index=first.index,
        entries=list(first.entries) + list(second.entries[take:]),
        commit=max(a.commit, b.commit),
    )


class DeviceHashRand:
    """Replays the device's deterministic randomized-timeout hash
    (step.py _rand_timeout) through the host Config's ``rand`` seam:
    call n (0-based; init's randomize is call 0, matching device
    reset_count 0) returns ((iid+1)*7919 + n*104729) % et. With this,
    timer-driven elections fire on identical rounds in both engines —
    the risky masked path VERDICT r1 flagged as never differentially
    checked."""

    def __init__(self, iid: int):
        self.iid = iid
        self.n = 0

    def randrange(self, et: int) -> int:
        out = ((self.iid + 1) * 7919 + self.n * 104729) % et
        self.n += 1
        return out


class ShadowCluster:
    def __init__(
        self,
        num_replicas: int,
        election_timeout: int = 1 << 20,
        heartbeat_timeout: int = 1,
        max_inflight: int = 1 << 20,
        pre_vote: bool = False,
        learners: Sequence[int] = (),
        group: int = 0,
        deterministic_timeouts: bool = False,
        auto_compact_window: int = 0,
        max_ents: Optional[int] = None,
        deliver_shape: str = "auto",
    ):
        # Mirrors BatchedConfig.deliver_shape: the device's delivery
        # order is kind-major (six lane scans, "lanes"), sender-major
        # within request/response halves ("merged"), or the vectorized
        # order contract ("vectorized" — see _deliver_vectorized_target
        # below). "auto" resolves to the same platform default the
        # engine resolves, so default-config engine↔shadow pairs always
        # agree on the order.
        if deliver_shape == "auto":
            from .state import default_deliver_shape

            deliver_shape = default_deliver_shape()
        self.deliver_shape = deliver_shape
        self.r = num_replicas
        self.nodes: List[RawNode] = []
        lrn = {s + 1 for s in learners}
        for slot in range(num_replicas):
            storage = MemoryStorage()
            # Bootstrap the full-voter config the way the batched engine
            # does: membership is initial state, not replayed conf changes.
            storage._snapshot.metadata.conf_state = ConfState(
                voters=[i for i in range(1, num_replicas + 1)
                        if i not in lrn],
                learners=sorted(lrn),
            )
            cfg = Config(
                id=slot + 1,
                election_tick=election_timeout,
                heartbeat_tick=heartbeat_timeout,
                storage=storage,
                max_size_per_msg=1 << 62,
                max_inflight_msgs=max_inflight,
                pre_vote=pre_vote,
                rand=(DeviceHashRand(group * num_replicas + slot)
                      if deterministic_timeouts else None),
            )
            self.nodes.append(RawNode(cfg))
        self.auto_compact_window = auto_compact_window
        # Device per-message entry cap: an append exceeding it cannot
        # fit the device's one send per round, so it is an envelope
        # error, never a silent truncation.
        self.max_ents = max_ents
        # inbox[target][sender][kind]
        self.inbox: List[List[List[Optional[Message]]]] = self._empty_inbox()

    def _empty_inbox(self):
        return [
            [[None] * NUM_KINDS for _ in range(self.r)] for _ in range(self.r)
        ]

    def round(
        self,
        campaigns: Sequence[int] = (),
        proposals: Optional[Dict[int, int]] = None,
        tick: bool = False,
        isolate: Iterable[int] = (),
        transfers: Optional[Dict[int, int]] = None,
        drop_pairs: Iterable[Tuple[int, int]] = (),
    ) -> None:
        """One round with the device's phase order:
        deliver → tick/campaign → control → propose → emit.
        `transfers` maps leader slot → target slot; `drop_pairs` drops
        (sender, target) directed edges at emit — partial partitions."""
        iso = set(isolate)
        proposals = proposals or {}
        transfers = transfers or {}
        drops = set(drop_pairs)

        # Phase 1: deliver in the exact order of the device's
        # configured deliver shape (step.py _deliver_all): kind-major
        # for the six lane scans ("lanes"), request/response halves
        # sender-major for the two merged scans ("merged"), or the
        # vectorized order contract ("vectorized").
        if self.deliver_shape == "merged":
            order = [
                (sender, kind)
                for kinds in (range(0, 3), range(3, NUM_KINDS))
                for sender in range(self.r)
                for kind in kinds
            ]
        else:  # "lanes" (the vectorized path orders per target below)
            order = [
                (sender, kind)
                for kind in range(NUM_KINDS)
                for sender in range(self.r)
            ]
        inbox, self.inbox = self.inbox, self._empty_inbox()
        for target in range(self.r):
            if target in iso:
                continue
            if self.deliver_shape == "vectorized":
                self._deliver_vectorized_target(target, inbox[target])
                continue
            for sender, kind in order:
                m = inbox[target][sender][kind]
                if m is None:
                    continue
                try:
                    self.nodes[target].step(m)
                except RaftError:
                    pass

        # Phase 2: tick / explicit campaigns.
        if tick:
            for node in self.nodes:
                node.tick()
        for slot in campaigns:
            self.nodes[slot].campaign()

        # Phase 2b: host control ops, same slot order as the device's
        # _control phase (after tick, before propose).
        for slot, target in transfers.items():
            try:
                self.nodes[slot].transfer_leader(target + 1)
            except RaftError:
                pass

        # Phase 3: proposals (empty payloads; the batched engine carries
        # payloads in the host arena, so terms are the shared content).
        # All n entries ride one MsgProp — the batched engine appends
        # its per-round proposals as one batch with one broadcast.
        from ..raft.types import Entry

        for slot, n in proposals.items():
            if n <= 0:
                continue
            node = self.nodes[slot]
            try:
                node.raft.step(
                    Message(
                        type=MessageType.MsgProp,
                        from_=node.raft.id,
                        entries=[Entry(data=b"") for _ in range(n)],
                    )
                )
            except RaftError:
                pass

        # Phase 4a: persist — take every node's Ready and store
        # hardstate/snapshot/entries FIRST, so the compaction and the
        # send materialization below see this round's log.
        readys: List[Tuple[int, object]] = []
        for slot, node in enumerate(self.nodes):
            if not node.has_ready():
                continue
            rd = node.ready()
            storage = node.raft.raft_log.storage
            if rd.hard_state.term or rd.hard_state.vote or rd.hard_state.commit:
                storage.set_hard_state(rd.hard_state)
            if rd.snapshot.metadata.index > 0:
                # Installed snapshot persists before entries
                # (the production drain order, etcdserver/raft.go).
                storage.apply_snapshot(rd.snapshot)
            storage.append(rd.entries)
            readys.append((slot, rd))

        # Phase 4b: auto-compaction emulation — the device compacts at
        # the top of _emit with this round's commit and log, and its
        # append-vs-snapshot decision sees the new floor (step.py
        # _emit auto_compact then snap_needed).
        if self.auto_compact_window:
            keep = self.auto_compact_window // 2
            for node in self.nodes:
                r = node.raft
                st = r.raft_log.storage
                target = min(
                    r.raft_log.committed, st.last_index() - keep
                )
                if target > st.first_index() - 1:
                    st.create_snapshot(target, None, b"")
                    st.compact(target)

        # Phase 4c: emit — bucket outbound messages, device-coalesced.
        for slot, rd in readys:
            node = self.nodes[slot]
            for m in rd.messages:
                if slot in iso:
                    continue
                m = self._rematerialize(node, m)
                kind = _TYPE_TO_KIND.get(m.type)
                if kind is None:
                    raise AssertionError(f"unroutable message type {m.type}")
                target = m.to - 1
                if (slot, target) in drops:
                    continue
                prev = self.inbox[target][slot][kind]
                if prev is not None:
                    # The device coalesces same-round sends into one
                    # flag; the oracle may emit duplicates (hb-resp and
                    # app-resp both probing) or split one logical
                    # append across two messages (commit bcast +
                    # proposal bcast in one Ready). Coalesce both
                    # shapes; anything else is a real violation.
                    if _same_message(prev, m):
                        continue
                    merged = _merge_apps(prev, m)
                    if merged is not None and (
                        self.max_ents is None
                        or len(merged.entries) <= self.max_ents
                    ):
                        self.inbox[target][slot][kind] = merged
                        continue
                    # A snapshot supersedes an append in the same lane,
                    # exactly like the device's emit (snap_needed
                    # overrides the append send).
                    kinds = {prev.type, m.type}
                    if MessageType.MsgSnap in kinds and kinds <= {
                        MessageType.MsgSnap, MessageType.MsgApp
                    }:
                        snaps = [x for x in (prev, m)
                                 if x.type == MessageType.MsgSnap]
                        best = max(snaps,
                                   key=lambda x: x.snapshot.metadata.index)
                        self.inbox[target][slot][kind] = best
                        continue
                    raise AssertionError(
                        f"slot collision: {m.type} from {slot} to {target}; "
                        "schedule outside the differential envelope"
                    )
                self.inbox[target][slot][kind] = m
        for slot, rd in readys:
            self.nodes[slot].advance(rd)


    def _deliver_vectorized_target(self, target: int, msgs) -> None:
        """One target's inbox in the vectorized shape's order contract
        (step.py _deliver_vectorized): lanes in kind order; within the
        vote lane every T_VOTE (term desc, sender asc) before every
        T_PREVOTE (prevotes never mutate state); within the other
        request lanes the winner (term desc, sender asc) first, losers
        after — a loser the winner has not made stale would apply here
        but is dropped on device, so it raises as an envelope
        violation (two leaders at one term cannot exist in-protocol);
        within response lanes same-term effects first (commutative),
        then deposing messages ascending by term."""
        node = self.nodes[target]

        def step(m: Message) -> None:
            try:
                node.step(m)
            except RaftError:
                pass

        def lane(kind):
            return [(s, msgs[s][kind]) for s in range(self.r)
                    if msgs[s][kind] is not None]

        votes = sorted(
            (x for x in lane(KIND_VOTE)
             if x[1].type == MessageType.MsgVote),
            key=lambda sm: (-sm[1].term, sm[0]))
        pres = [x for x in lane(KIND_VOTE)
                if x[1].type != MessageType.MsgVote]
        for _, m in votes + pres:
            step(m)

        for kind in (KIND_APP, KIND_HB):
            ordered = sorted(lane(kind),
                             key=lambda sm: (-sm[1].term, sm[0]))
            for i, (sender, m) in enumerate(ordered):
                if i > 0 and m.term >= node.raft.term:
                    raise AssertionError(
                        f"vectorized deliver: request-lane loser from "
                        f"{sender} at term {m.term} not stale against "
                        f"the winner (node term {node.raft.term}); "
                        "schedule outside the vectorized envelope")
                step(m)

        for kind in (KIND_VOTE_RESP, KIND_APP_RESP, KIND_HB_RESP):
            t0 = node.raft.term
            eff, dep = [], []
            for s, m in lane(kind):
                deposes = m.term > t0 and not (
                    m.type == MessageType.MsgPreVoteResp and not m.reject)
                (dep if deposes else eff).append((s, m))
            dep.sort(key=lambda sm: (sm[1].term, sm[0]))
            for _, m in eff + dep:
                step(m)

    def _rematerialize(self, node: RawNode, m: Message) -> Message:
        """The device remembers only a send FLAG per peer and derives
        append content at emit time (end of round); the oracle bakes
        content at queue time (mid-deliver). Re-slice outbound MsgApp
        entries and commit from the sender's end-of-round log so both
        models emit identical bytes (e.g. a probe queued before this
        round's proposals still carries them)."""
        from ..raft.raft import StateType

        r = node.raft
        if (
            m.type != MessageType.MsgApp
            or m.term != r.term
            or r.state != StateType.StateLeader
        ):
            return m
        # Below the (just-advanced) floor the device sends a snapshot
        # instead (step.py _emit snap_needed after auto-compaction).
        floor = r.raft_log.storage.first_index() - 1
        if m.index < floor:
            snap = r.raft_log.storage.snapshot()
            return Message(
                type=MessageType.MsgSnap, to=m.to, from_=m.from_,
                term=m.term, snapshot=snap,
            )
        last = r.raft_log.last_index()
        want = last - m.index
        if self.max_ents is not None and want > self.max_ents:
            raise AssertionError(
                f"append of {want} entries exceeds the device cap "
                f"{self.max_ents}; schedule outside the differential "
                "envelope")
        if want <= len(m.entries) and m.commit == r.raft_log.committed:
            return m
        try:
            ents = r.raft_log.slice(m.index + 1, m.index + 1 + want, 1 << 62)
        except RaftError:
            return m
        return Message(
            type=m.type, to=m.to, from_=m.from_, term=m.term,
            log_term=m.log_term, index=m.index, entries=ents,
            commit=r.raft_log.committed,
        )

    # -- state vector for comparison ------------------------------------------

    def snapshot_state(self) -> List[Tuple[int, ...]]:
        """(term, role, lead, commit, last) per replica — the fields the
        batched engine must reproduce exactly."""
        out = []
        for node in self.nodes:
            r = node.raft
            out.append(
                (
                    r.term,
                    int(r.state),
                    r.lead,
                    r.raft_log.committed,
                    r.raft_log.last_index(),
                )
            )
        return out

    def log_terms(self, slot: int) -> List[Tuple[int, int]]:
        r = self.nodes[slot].raft
        lo = r.raft_log.first_index()
        hi = r.raft_log.last_index()
        return [(i, r.raft_log.term(i)) for i in range(lo, hi + 1)]
