"""Seeded fault injection for the batched multi-raft hosting path.

The reference ships a dedicated functional tester (tests/functional/
tester: kill/blackhole/delay cases, KV-hash checkers) for the single
server; this module is its analog for the layer the paper actually bets
on — ``MultiRaftMember`` over ``InProcRouter`` or the TCP fabric,
thousands of groups per member. Three planes:

* **message faults** — ``FaultPlan`` (one seed → per-link ``random``
  streams) decides drop / duplicate / delay / reorder per (src, dst)
  link; ``FaultyFabric`` interposes on each member's outbound send
  callables, so the SAME fault plane drives both the in-proc router and
  real TCP sockets. Symmetric and asymmetric partitions are directed
  link blocks on the plan.
* **storage faults** — the gofail-style failpoints hosting.py exposes on
  its persistence path (``hosting.m<id>.raftBeforeSave`` /
  ``raftAfterSave``, ref: etcdserver/raft.go raftBeforeSave &c) armed to
  ``MultiRaftMember.crash()``, plus torn-tail injection (truncate the
  last WAL segment at an arbitrary byte inside the written prefix).
* **disk faults** (ISSUE 15) — ``DiskFaultPlan``, an errfs-style shim
  at the ``native/walog.py`` + ``storage/snap.py`` file-op seam:
  one-shot/sticky fsync and write errors, sticky ENOSPC (armed/healed
  so the write-back-pressure contract is testable end to end), per-op
  latency injection (slow-disk as a *fault* — the gray-failure limp),
  and seeded at-rest bit-flips in mid-log records
  (``ChaosHarness.bit_rot``). The contract the shim tests lives in
  hosting.py: first failed fsync ⇒ member fail-stop releasing nothing
  from the failed window; ENOSPC at the seam ⇒ back-pressure that
  recovers with zero acked loss; mid-log CRC corruption ⇒ salvage +
  fenced boot + snapshot/probe heal.
* **process faults** — scripted kill/restart cycles: ``crash()`` then a
  fresh member on the same data_dir, booting through ``_replay``.

Determinism: one seed fixes every fault *decision* (which sends drop,
how long delays run, where the torn byte lands). Thread scheduling still
varies wall-clock interleavings run to run — the invariants the
checkers assert (``etcd_tpu.functional.checker``) hold for every
interleaving, which is exactly what makes them invariants.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import random
import threading
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..native.walog import DiskFullError, InjectedIOError
from ..pkg import failpoint
from ..pkg.failpoint import FailpointPanic
from .hosting import (
    GroupKV,
    InProcRouter,
    MultiRaftMember,
    TCPRouter,
    wait_group_leaders,
)
from .state import BatchedConfig, LEADER
from .telemetry import disk_fault_injected_counter

_log = logging.getLogger("etcd_tpu.batched.faults")


@dataclass(frozen=True)
class FaultSpec:
    """Per-link fault probabilities (drawn per message batch)."""

    drop: float = 0.0  # lose the batch
    dup: float = 0.0  # deliver it twice
    delay: float = 0.0  # hold it for uniform(1ms, delay_max_s)
    delay_max_s: float = 0.03
    # Brief hold (0.5–5 ms) WITHOUT the big delay: later sends on the
    # link overtake this one — cheap, frequent local reordering.
    reorder: float = 0.0


class FaultPlan:
    """Deterministic fault decisions: one seed → an independent
    ``random.Random`` stream per directed link, so the decision sequence
    on a link depends only on (seed, src, dst, #sends on that link),
    never on cross-thread interleaving. Partitions are a mutable set of
    blocked directed links layered on top."""

    def __init__(self, seed: int, spec: Optional[FaultSpec] = None) -> None:
        self.seed = seed
        self.spec = spec or FaultSpec()
        self._rngs: Dict[Tuple[int, int], random.Random] = {}
        self._lock = threading.Lock()
        self._blocked: set = set()  # directed (src, dst) links

    def link_rng(self, src: int, dst: int) -> random.Random:
        with self._lock:
            r = self._rngs.get((src, dst))
            if r is None:
                r = random.Random(f"{self.seed}/{src}->{dst}")
                self._rngs[(src, dst)] = r
            return r

    def derived_rng(self, tag: str) -> random.Random:
        """Seed-scoped stream for non-link decisions (torn-byte offset,
        victim choice, partition schedule)."""
        return random.Random(f"{self.seed}/{tag}")

    # -- partitions ------------------------------------------------------------

    def block_link(self, src: int, dst: int) -> None:
        with self._lock:
            self._blocked.add((src, dst))

    def partition(self, a: int, b: int, symmetric: bool = True) -> None:
        """Cut a<->b (or only a->b when symmetric=False — the asymmetric
        half-open link that message-reorder bugs love)."""
        self.block_link(a, b)
        if symmetric:
            self.block_link(b, a)

    def isolate_member(self, mid: int, peers) -> None:
        for p in peers:
            if p != mid:
                self.partition(mid, p, symmetric=True)

    def heal_link(self, src: int, dst: int) -> None:
        with self._lock:
            self._blocked.discard((src, dst))

    def heal_all(self) -> None:
        with self._lock:
            self._blocked.clear()

    def blocked(self, src: int, dst: int) -> bool:
        return (src, dst) in self._blocked

    def quiesce(self) -> None:
        """Episode end: zero the probabilistic faults and heal every
        partition so the cluster can converge for the checkers."""
        self.spec = FaultSpec()
        self.heal_all()

    # -- per-send decision -----------------------------------------------------

    def decide(self, src: int, dst: int) -> Tuple[bool, int, float]:
        """(drop, copies, delay_s) for the next batch on src->dst."""
        sp = self.spec
        r = self.link_rng(src, dst)
        drop = r.random() < sp.drop
        copies = 2 if r.random() < sp.dup else 1
        delay = 0.0
        if r.random() < sp.delay:
            delay = r.uniform(0.001, sp.delay_max_s)
        elif r.random() < sp.reorder:
            delay = r.uniform(0.0005, 0.005)
        return drop, copies, delay


class _MemberDiskState:
    """Armed disk faults for one member (DiskFaultPlan internal)."""

    __slots__ = ("fsync_errors", "fsync_sticky", "write_errors",
                 "write_sticky", "enospc", "delay_s", "delay_ops")

    def __init__(self) -> None:
        self.fsync_errors = 0
        self.fsync_sticky = False
        self.write_errors = 0
        self.write_sticky = False
        self.enospc = False
        self.delay_s = 0.0
        self.delay_ops: Tuple[str, ...] = ("fsync",)


class DiskFaultPlan:
    """Deterministic storage-fault decisions at the Walog/Snapshotter
    file-op seam (the errfs idea from "Can Applications Recover from
    fsync Failures?", ATC'19, as a Python shim): ``hook_for(mid)``
    returns the per-member ``fault_hook(op, nbytes)`` a member threads
    into its WAL handle; arming methods flip what the hook does.
    Seeded like FaultPlan — the seed scopes the derived rngs (bit-flip
    placement) so a failing episode replays from its seed.

    Faults raise AT THE SEAM, before the native call starts, which is
    what makes hosting's contracts sound: a DiskFullError provably
    wrote nothing (retry-same-record is legal), an InjectedIOError at
    op="fsync" models the kernel failing fdatasync with the dirty
    pages' fate unknown (fail-stop is the only safe answer). Latency
    injection sleeps at the seam — pure IO wait, generalizing
    ETCD_TPU_FSYNC_DELAY_MS to a per-member, per-op, runtime-armable
    fault (the gray-failure limp)."""

    def __init__(self, seed: int) -> None:
        self.seed = seed
        self._lock = threading.Lock()
        self._state: Dict[int, _MemberDiskState] = {}
        self._stats: Dict[str, int] = defaultdict(int)
        self._c_injected = disk_fault_injected_counter()

    def derived_rng(self, tag: str) -> random.Random:
        return random.Random(f"{self.seed}/disk/{tag}")

    def _st(self, mid: int) -> _MemberDiskState:
        st = self._state.get(mid)
        if st is None:
            st = self._state[mid] = _MemberDiskState()
        return st

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    # -- arming ----------------------------------------------------------------

    def arm_fsync_error(self, mid: int, count: int = 1,
                        sticky: bool = False) -> None:
        """Fail the member's next `count` fsyncs (or EVERY fsync when
        sticky) — the ATC'19 fault. The contract under test: the FIRST
        failure fail-stops the member; one-shot vs sticky only matters
        to stacks that (wrongly) retry."""
        with self._lock:
            st = self._st(mid)
            st.fsync_errors = int(count)
            st.fsync_sticky = bool(sticky)

    def arm_write_error(self, mid: int, count: int = 1,
                        sticky: bool = False) -> None:
        with self._lock:
            st = self._st(mid)
            st.write_errors = int(count)
            st.write_sticky = bool(sticky)

    def arm_enospc(self, mid: int) -> None:
        """Sticky disk-full on the member's WRITE path (append/flush,
        never fsync): writes refuse until heal_enospc — the graceful
        back-pressure episode."""
        with self._lock:
            self._st(mid).enospc = True

    def heal_enospc(self, mid: int) -> None:
        """Space returns: the member's dwelling write retries succeed
        and it resumes with zero acked loss."""
        with self._lock:
            self._st(mid).enospc = False

    def set_limp(self, mid: int, delay_s: float,
                 ops: Tuple[str, ...] = ("fsync",)) -> None:
        """Make the member LIMP: every op in `ops` takes an extra
        delay_s of pure IO wait. Not an error — the member stays alive
        and correct, just slow: the gray-failure shape the
        member_limping detector + rebalancer eviction close the loop
        on."""
        with self._lock:
            st = self._st(mid)
            st.delay_s = float(delay_s)
            st.delay_ops = tuple(ops)

    def heal_limp(self, mid: int) -> None:
        with self._lock:
            st = self._st(mid)
            st.delay_s = 0.0

    def quiesce(self) -> None:
        """Episode end: clear every armed fault (mirrors
        FaultPlan.quiesce)."""
        with self._lock:
            self._state.clear()

    # -- the seam --------------------------------------------------------------

    def hook_for(self, mid: int) -> Callable[[str, int], None]:
        def hook(op: str, nbytes: int, _mid: int = mid) -> None:
            self._decide(_mid, op, nbytes)

        return hook

    def _decide(self, mid: int, op: str, nbytes: int) -> None:
        delay = 0.0
        err: Optional[Exception] = None
        kind = None
        with self._lock:
            st = self._state.get(mid)
            if st is None:
                return
            if op in st.delay_ops and st.delay_s > 0:
                delay = st.delay_s
            if op in ("fsync", "snap_fsync") and (
                    st.fsync_sticky or st.fsync_errors > 0):
                if not st.fsync_sticky:
                    st.fsync_errors -= 1
                kind = "fsync_error"
                err = InjectedIOError(
                    f"injected fsync failure (member {mid}, {op})")
            elif op in ("append", "flush", "snap_write", "snap_rename"):
                if st.enospc:
                    kind = "enospc"
                    err = DiskFullError(
                        f"injected ENOSPC (member {mid}, {op})")
                elif st.write_sticky or st.write_errors > 0:
                    if not st.write_sticky:
                        st.write_errors -= 1
                    kind = "write_error"
                    err = InjectedIOError(
                        f"injected write failure (member {mid}, {op})")
            if kind is not None:
                self._stats[kind] += 1
            if delay > 0:
                self._stats["delay"] += 1
        if kind is not None:
            self._c_injected.labels(str(mid), op, kind).inc()
        if delay > 0:
            self._c_injected.labels(str(mid), op, "delay").inc()
            time.sleep(delay)  # pure IO wait, outside the plan lock
        if err is not None:
            raise err


class FaultyFabric:
    """Interposes the fault plane on member outbound sends. Works over
    BOTH routers because each programs ``member._send``/``_send_block``:
    the wrapper splits every outbound batch by destination, consults the
    plan per link, and forwards the surviving (possibly delayed or
    duplicated) sub-batches to the original callables. Delayed
    deliveries run on one pump thread ordered by due time; deliveries
    whose target crashed while they were in flight are dropped (and
    counted) — ``crash()`` tears the member's queues, and a harness
    that restarts the member must not have pre-crash frames leak into
    the fresh incarnation through the fabric's delay heap."""

    def __init__(self, plan: FaultPlan,
                 incarnation_fn: Optional[
                     Callable[[int], Optional[object]]] = None,
                 removed_fn: Optional[
                     Callable[[int], bool]] = None) -> None:
        self.plan = plan
        # Target-incarnation seam for the delayed-delivery pump: maps a
        # member id to an identity token for its CURRENT live
        # incarnation (None = crashed/stopped). The harness wires this
        # to its member table; the pump captures the token at enqueue
        # and re-resolves at fire, so a frame outlives neither a crash
        # NOR a crash+restart (a restarted member is a NEW incarnation
        # whose queues the crash tore). None = always deliver.
        self.incarnation_fn = incarnation_fn
        # Config-removal seam (ISSUE 11): a member that LEFT the
        # cluster config (removed voter) is treated like a crashed
        # incarnation — frames to it drop and count (removed_drop,
        # immediate and delayed paths both), and the harness issues a
        # fresh incarnation token on re-admission so frames enqueued
        # against the pre-removal identity can never leak into the
        # re-added successor. None = nobody is ever config-removed.
        self.removed_fn = removed_fn
        self._stats: Dict[str, int] = defaultdict(int)
        self._seq = itertools.count()
        self._cv = threading.Condition()
        # (due, seq, dst, token, n, deliver)
        self._heap: List[Tuple[float, int, int, object, int,
                               Callable[[], None]]] = []
        self._stopped = False
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return dict(self._stats)

    def _drop_kind(self, dst: int) -> str:
        """Classify a dead-target drop: config-removed vs crashed —
        the ONE classification site for both the enqueue-time and
        fire-time drops."""
        if self.removed_fn is not None and self.removed_fn(dst):
            return "removed_drop"
        return "crashed_drop"

    def _count(self, key: str, n: int = 1) -> None:
        with self._cv:
            self._stats[key] += n

    def wrap(self, member: MultiRaftMember) -> None:
        """Interpose on `member`'s send callables (call AFTER the router
        attached them; call again after a restart re-attaches)."""
        inner = member._send
        inner_blk = member._send_block
        src = member.id

        def send(from_id: int, batch) -> None:
            by_dst: Dict[int, list] = defaultdict(list)
            for g, m in batch:
                by_dst[m.to].append((g, m))
            for dst, sub in by_dst.items():
                self._ship(src, dst,
                           lambda s=sub: inner(from_id, s), len(sub))

        member._send = send
        if inner_blk is not None:
            def send_block(from_id: int, blk) -> None:
                for dst, sub in blk.split_by_target().items():
                    self._ship(src, dst,
                               lambda s=sub: inner_blk(from_id, s),
                               len(sub))

            member._send_block = send_block

    def _ship(self, src: int, dst: int, deliver: Callable[[], None],
              n: int) -> None:
        if self.removed_fn is not None and self.removed_fn(dst):
            # Removed members are out of the cluster, not just slow:
            # delivering would let a decommissioned replica keep
            # participating (and its successor inherit its traffic).
            self._count("removed_drop", n)
            return
        if self.plan.blocked(src, dst):
            self._count("partitioned", n)
            return
        drop, copies, delay = self.plan.decide(src, dst)
        if drop:
            self._count("dropped", n)
            return
        if copies > 1:
            self._count("duplicated", n)
            # The duplicate trails slightly — same-instant duplicates
            # would coalesce in the per-(row,sender,lane) inbox anyway.
            self._later(delay + 0.002, dst, n, deliver)
        if delay > 0:
            self._count("delayed", n)
            self._later(delay, dst, n, deliver)
        else:
            self._run(deliver)

    def _run(self, deliver: Callable[[], None]) -> None:
        try:
            deliver()
        except Exception:  # noqa: BLE001 — target died mid-delivery
            self._count("deliver_error")

    def _later(self, delay: float, dst: int, n: int,
               deliver: Callable[[], None]) -> None:
        tok = (self.incarnation_fn(dst)
               if self.incarnation_fn is not None else None)
        if self.incarnation_fn is not None and tok is None:
            # Target already crashed (or config-removed) at enqueue.
            self._count(self._drop_kind(dst), n)
            return
        with self._cv:
            if self._stopped:
                return
            heapq.heappush(
                self._heap,
                (time.monotonic() + delay, next(self._seq), dst, tok, n,
                 deliver))
            self._cv.notify()

    def _pump_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and (
                    not self._heap
                    or self._heap[0][0] > time.monotonic()
                ):
                    if self._heap:
                        self._cv.wait(
                            max(0.0, self._heap[0][0] - time.monotonic()))
                    else:
                        self._cv.wait()
                if self._stopped:
                    return
                (_due, _seq, dst, tok, n,
                 deliver) = heapq.heappop(self._heap)
            # Incarnation check AT FIRE TIME against the token captured
            # at enqueue: the member may have crashed — or crashed AND
            # restarted — while the frame sat in the heap. An identity
            # mismatch means the enqueue-time incarnation is gone, and
            # its torn-away queues must not leak frames into a
            # successor (observed as phantom traffic after crash()).
            # Config removal mismatches the same way: leaving the
            # config retires the token, re-admission mints a new one,
            # so a frame from the pre-removal era can never land in
            # the re-added member.
            if self.incarnation_fn is not None \
                    and self.incarnation_fn(dst) is not tok:
                self._count(self._drop_kind(dst), n)
                continue
            self._run(deliver)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._heap.clear()
            self._cv.notify_all()
        self._pump.join(timeout=5)


class LeaderObserver(threading.Thread):
    """Samples every member's atomic (term, role, lead) view and records
    which member claimed leadership of each (group, term). Any (group,
    term) claimed by two different members is an election-safety
    violation — the at-most-one-leader-per-term checker input (ref:
    functional tester's leader checks; Jepsen's leader analyses)."""

    def __init__(self, members_fn: Callable[[], List[MultiRaftMember]],
                 interval: float = 0.005) -> None:
        super().__init__(daemon=True)
        self.members_fn = members_fn
        self.interval = interval
        self.claims: Dict[Tuple[int, int], int] = {}
        self.conflicts: List[Tuple[int, int, int, int]] = []
        # NB: not `_stop` — threading.Thread defines a private _stop()
        # method that join() calls on interpreter edge paths.
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            for m in self.members_fn():
                term, role, _lead = m.rn.m_view
                for g in np.nonzero(role == LEADER)[0]:
                    key = (int(g), int(term[g]))
                    prev = self.claims.setdefault(key, m.id)
                    if prev != m.id:
                        self.conflicts.append((*key, prev, m.id))
            self._halt.wait(self.interval)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)


class ChaosHarness:
    """R members × G groups with a seeded fault plane, over the
    in-proc router (``transport='inproc'``), real TCP sockets
    (``transport='tcp'``), or the mmap'd shm ring fabric
    (``transport='shm'``); supports scripted crash/restart cycles
    (through ``_replay``), storage-failpoint crashes, torn-tail WAL
    injection, and an acked-write ledger for the committed-never-lost
    checker. One FaultyFabric drives all three transports through the
    same ``member._send``/``_send_block`` seam."""

    def __init__(self, data_dir: str, seed: int,
                 spec: Optional[FaultSpec] = None,
                 num_members: int = 3, num_groups: int = 8,
                 cfg: Optional[BatchedConfig] = None,
                 transport: str = "inproc",
                 tick_interval: float = 0.02,
                 pipeline: bool = True,
                 fence: bool = True,
                 trace: bool = False,
                 wal_pipeline: bool = False,
                 wal_group_max_delay: Optional[float] = None,
                 snap_cadence: Optional[int] = None,
                 snap_keep: int = 2,
                 wal_rotate_bytes: Optional[int] = None,
                 wal_pinned_segments: Optional[int] = None) -> None:
        assert transport in ("inproc", "tcp", "shm"), transport
        self.data_dir = data_dir
        self.seed = seed
        self.r = num_members
        self.g = num_groups
        # trace=True flies the episode with the proposal-lifecycle
        # tracer on every member (etcd_tpu.obs): the parity/invariant
        # bar is identical — tracing must be a pure observer even
        # under faults — and checker failures dump the span rings
        # alongside the flight recorders.
        self.trace = bool(trace)
        # fence=False disables the durability watermark + fenced-boot
        # path on every member — the pre-PR behavior, kept so the
        # torn-acked divergence stays demonstrable
        # (tools/repro_progress_wedge.py --torn-acked).
        self.fence = fence
        self.cfg = cfg or BatchedConfig(
            num_groups=num_groups, num_replicas=num_members,
            window=16, max_ents_per_msg=4, max_props_per_round=4,
            election_timeout=10, heartbeat_timeout=1,
            pre_vote=True, check_quorum=True, auto_compact=True,
            # The default chaos config flies with the kernel telemetry
            # plane on: the invariant sweep localizes device-side
            # illegal states (the PR 2 progress wedge took manual
            # instrumentation to even find) and a checker failure dumps
            # every member's flight recorder.
            telemetry=True,
            # ... and with the fleet observatory on (ISSUE 10): the
            # device summary must be a pure observer even under faults
            # (strict parity + invariant_trips()==0 holds with it on),
            # and a checker failure freezes the groups×time heatmap
            # rings beside the flight recorders.
            fleet_summary=True,
        )
        self.transport = transport
        self.tick_interval = tick_interval
        self.pipeline = pipeline
        # wal_pipeline=True flies the episode with the async
        # group-commit WAL pipeline (ISSUE 13) on every member: the
        # fsync runs decoupled from the round cadence and acks release
        # only at fsync completion — every chaos cell must close at the
        # same strict bar, or a pipeline reordering leaked.
        self.wal_pipeline = bool(wal_pipeline)
        self.wal_group_max_delay = wal_group_max_delay
        # Log-lifecycle plane knobs (ISSUE 17): with a cadence and a
        # rotation threshold set, every member snapshots/rotates/
        # releases DURING the chaos episode — restarts replay from
        # snapshot + rotated tail, and the same strict close applies.
        self.snap_cadence = snap_cadence
        self.snap_keep = snap_keep
        self.wal_rotate_bytes = wal_rotate_bytes
        self.wal_pinned_segments = wal_pinned_segments
        self.plan = FaultPlan(seed, spec)
        # Storage fault plane (ISSUE 15): every member's WAL handle is
        # born with this plan's hook threaded in (restarts re-thread it
        # in _boot), so fsync errors / ENOSPC / limp delays can be
        # armed mid-episode without touching the member.
        self.disk = DiskFaultPlan(seed)
        self.fabric = FaultyFabric(
            self.plan, incarnation_fn=self._member_incarnation,
            removed_fn=self.is_removed)
        self.members: Dict[int, MultiRaftMember] = {}
        # Incarnation tokens (fresh object per boot AND per config
        # re-admission) + the config-removed set: a member removed from
        # the cluster config is treated like a crashed incarnation by
        # the fabric (frames drop and count as removed_drop), and
        # mark_rejoined mints a NEW token so pre-removal frames in the
        # delay heap can never leak into the re-added successor.
        self._inc_tokens: Dict[int, object] = {}
        self._removed: set = set()
        # member id -> per-member fabric (TCPRouter or ShmFabric),
        # popped + stopped on crash; inproc members share one router.
        self.routers: Dict[int, object] = {}
        self._ports: Dict[int, int] = {}  # stable rebind port per member
        self._shm_dir = os.path.join(data_dir, "shmfabric")
        self.inproc: Optional[InProcRouter] = (
            InProcRouter() if transport == "inproc" else None
        )
        # (group, key) -> latest value the workload saw applied at its
        # proposer — committed by definition, so never losable — plus
        # the full acked-version history per key, so the checker can
        # tell a lagging member (holds an older acked version) from a
        # divergent one (holds a value never acked).
        self.acked: Dict[Tuple[int, bytes], bytes] = {}
        self.acked_history: Dict[Tuple[int, bytes], List[bytes]] = {}
        self._retired_trips = 0  # trips banked from replaced members
        for mid in range(1, num_members + 1):
            self._boot(mid)
        for m in self.members.values():
            m.start()

    # -- membership ------------------------------------------------------------

    def _boot(self, mid: int) -> MultiRaftMember:
        # A restart replaces the member object (and its telemetry
        # hub): bank the outgoing hub's invariant trips first, or
        # pre-crash illegal-progress evidence silently vanishes from
        # the episode-close trips==0 assertion.
        old = self.members.get(mid)
        if old is not None and getattr(old, "hub", None) is not None:
            self._retired_trips += old.hub.trips()
        m = MultiRaftMember(
            mid, self.r, self.g, self.data_dir, cfg=self.cfg,
            tick_interval=self.tick_interval, pipeline=self.pipeline,
            fence=self.fence, trace=self.trace or None,
            wal_pipeline=self.wal_pipeline or None,
            wal_group_max_delay=self.wal_group_max_delay,
            disk_fault_hook=self.disk.hook_for(mid),
            snap_cadence=self.snap_cadence,
            snap_keep=self.snap_keep,
            wal_rotate_bytes=self.wal_rotate_bytes,
            **({"wal_pinned_segments": self.wal_pinned_segments}
               if self.wal_pinned_segments is not None else {}),
        )
        if self.inproc is not None:
            self.inproc.attach(m)
        elif self.transport == "shm":
            from .shmfabric import ShmFabric

            # A restart reopens the SAME lane ring files: the writer
            # side resumes after its crashed incarnation's last
            # published frame, the reader side resyncs (stale frames
            # counted, never delivered) — see shmfabric.ShmRing.
            router = ShmFabric(m, self._shm_dir)
            for other, r2 in self.routers.items():
                router.add_peer(other)
                r2.add_peer(mid)
            self.routers[mid] = router
        else:
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    router = TCPRouter(
                        m, bind=("127.0.0.1", self._ports.get(mid, 0)))
                    break
                except OSError:
                    # Restart must rebind the crashed member's port
                    # (peer sender lanes captured its addr at thread
                    # start), but a peer's redial can momentarily squat
                    # the freed port as its EPHEMERAL source port —
                    # outbound sockets lack SO_REUSEADDR, which blocks
                    # the bind; the refused dial frees it right away.
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            self._ports[mid] = router.addr[1]
            for other, r2 in self.routers.items():
                router.add_peer(other, r2.addr)
                r2.add_peer(mid, router.addr)
            self.routers[mid] = router
        self.fabric.wrap(m)
        self.members[mid] = m
        self._inc_tokens[mid] = object()  # new incarnation per boot
        return m

    def alive(self) -> List[MultiRaftMember]:
        return [m for m in self.members.values()
                if not m._stopped.is_set()]

    def _member_incarnation(self, mid: int) -> Optional[object]:
        """Incarnation seam for the fabric's delayed-delivery pump: a
        fresh token object per boot AND per config re-admission (a
        restart replaces it, and so does mark_rejoined), or None when
        the current incarnation is crashed/stopped/config-removed."""
        m = self.members.get(mid)
        if m is None or m._stopped.is_set() or mid in self._removed:
            return None
        return self._inc_tokens.get(mid)

    def is_removed(self, mid: int) -> bool:
        """Whether `mid` is currently OUT of the cluster config (fully
        removed voter — the decommissioned state between remove and
        re-add)."""
        return mid in self._removed

    def mark_removed(self, mid: int) -> None:
        """Declare `mid` removed from the cluster config: the fabric
        drops (and counts) every frame to it, immediate and delayed —
        a decommissioned replica must not keep participating."""
        self._removed.add(mid)

    def mark_rejoined(self, mid: int) -> None:
        """Re-admit `mid` (e.g. re-added as learner): frames flow
        again, under a NEW incarnation token — anything enqueued
        against the pre-removal identity mismatches at fire time and
        drops instead of leaking into the successor."""
        self._inc_tokens[mid] = object()
        self._removed.discard(mid)

    # -- process faults --------------------------------------------------------

    def crash(self, mid: int) -> None:
        """Simulated kill -9 (see MultiRaftMember.crash)."""
        self.members[mid].crash()
        router = self.routers.pop(mid, None)
        if router is not None:
            router.stop()

    def crash_on_failpoint(self, mid: int, site: str = "before_save",
                           timeout: float = 15.0) -> None:
        """Arm a storage failpoint to crash `mid` at its next
        persistence pass (site: 'before_save' = the Ready batch is
        lost; 'after_save' = persisted but never applied before the
        crash — _replay must re-apply it; 'before_fsync_release' = the
        async WAL pipeline's window: records written to the fd, fsync
        not yet run, NOTHING released — the batch's acks/sends must
        never have escaped, and a tear of the written-unsynced suffix
        must cost only unacked bytes) and wait for the member to die."""
        m = self.members[mid]
        name = {
            "before_save": m._fp_before_save,
            "after_save": m._fp_after_save,
            "before_fsync_release": m._fp_before_release,
        }[site]

        def act(m=m, name=name):
            m.crash()
            raise FailpointPanic(name)

        failpoint.enable(name, act)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if m._stopped.is_set():
                break
            time.sleep(0.01)
        else:
            failpoint.disable(name)
            raise TimeoutError(
                f"member {mid} did not hit failpoint {name}")
        failpoint.disable(name)
        router = self.routers.pop(mid, None)
        if router is not None:
            router.stop()

    def restart(self, mid: int) -> MultiRaftMember:
        """Fresh member on the crashed member's data_dir: boots through
        _replay (WAL prefix + snapshots), re-attaches to the fabric."""
        old = self.members[mid]
        assert old._stopped.is_set(), f"member {mid} still running"
        # Never leave this member's crash failpoints armed across the
        # restart — the names are deterministic per member id, so the
        # NEW member would crash at its first persistence pass too.
        failpoint.disable(old._fp_before_save)
        failpoint.disable(old._fp_after_save)
        failpoint.disable(old._fp_before_release)
        m = self._boot(mid)
        m.start()
        return m

    # -- storage faults --------------------------------------------------------

    def torn_tail(self, mid: int, max_chop: int = 24) -> int:
        """Truncate the crashed member's LAST WAL segment at a
        seed-chosen byte inside the written prefix — the torn record a
        real crash mid-write leaves. Segments are preallocated, so the
        cut is taken from the tail OFFSET captured at crash time, not
        the file size. Returns the number of bytes chopped."""
        m = self.members[mid]
        assert m._stopped.is_set(), "torn_tail needs a crashed member"
        tail = m._wal_tail_at_crash
        wal_dir = os.path.join(self.data_dir, f"member-{mid}", "wal")
        segs = sorted(f for f in os.listdir(wal_dir)
                      if f.endswith(".wal"))
        assert segs, "no WAL segments to tear"
        path = os.path.join(wal_dir, segs[-1])
        if tail <= 64:
            return 0  # nothing beyond the segment header to tear
        rng = self.plan.derived_rng(f"torn/{mid}")
        chop = rng.randint(1, min(max_chop, tail - 64))
        os.truncate(path, tail - chop)
        _log.info("torn tail: member %d seg %s cut %d bytes at %d",
                  mid, segs[-1], chop, tail - chop)
        return chop

    def torn_acked_tail(self, mid: int) -> Tuple[int, int]:
        """DETERMINISTIC acked-loss tear: truncate the crashed member's
        last WAL segment a few bytes INTO its final entry record, so an
        fsync'd (and, if the write was acked, committed) entry is
        verifiably destroyed with a mid-record break — the fault class
        the durability fence exists for. Returns (bytes_chopped,
        group_of_the_torn_entry); (0, -1) when the tail segment holds
        no entry records (nothing acked to tear)."""
        from ..native.walog import segment_records
        from .hosting import (
            RT_ENTRY,
            RT_ENTRY_BATCH,
            WAL_ENT_DTYPE,
            _unpack_batch,
        )

        m = self.members[mid]
        assert m._stopped.is_set(), "torn_acked_tail needs a crashed member"
        wal_dir = os.path.join(self.data_dir, f"member-{mid}", "wal")
        segs = sorted(f for f in os.listdir(wal_dir)
                      if f.endswith(".wal"))
        assert segs, "no WAL segments to tear"
        path = os.path.join(wal_dir, segs[-1])
        recs = [r for r in segment_records(path)
                if r[1] in (RT_ENTRY, RT_ENTRY_BATCH)]
        if not recs:
            return 0, -1
        off, rt, ln, padded = recs[-1]
        with open(path, "rb") as f:
            f.seek(off + 12)  # record header: u32 len | u8 type | pad | crc
            body = f.read(ln)
        if rt == RT_ENTRY_BATCH:
            # A mid-record tear destroys the WHOLE batch record; report
            # the group of its last entry (the deepest demanded index —
            # any entry-carrying group in the batch boots fenced).
            group = int(_unpack_batch(body, WAL_ENT_DTYPE)["group"][-1])
        else:
            group = int.from_bytes(body[:4], "little")
        size = os.path.getsize(path)
        cut = off + 12 + 5  # mid-payload: header survives, bytes don't
        os.truncate(path, cut)
        _log.info(
            "torn acked tail: member %d seg %s cut %d bytes mid-entry "
            "(group %d, record at %d)", mid, segs[-1], size - cut,
            group, off)
        return size - cut, group

    # -- disk faults (ISSUE 15) ------------------------------------------------

    def bit_rot(self, mid: int) -> Tuple[int, int]:
        """At-rest corruption: flip one seeded bit inside a MID-LOG
        record of the crashed member's last WAL segment — not the tail
        (the torn-tail cells own that), a record the chain already
        fsync'd over. The native reader refuses such a log outright;
        the contract under test is hosting._replay's salvage +
        fenced-boot path. Returns (record_offset, byte_offset) of the
        flip, or (-1, -1) when the segment is too short to hold a
        strictly-mid-log record (caller should write more first)."""
        from ..native.walog import segment_records

        m = self.members[mid]
        assert m._stopped.is_set(), "bit_rot needs a crashed member"
        wal_dir = os.path.join(self.data_dir, f"member-{mid}", "wal")
        segs = sorted(f for f in os.listdir(wal_dir)
                      if f.endswith(".wal"))
        assert segs, "no WAL segments to rot"
        path = os.path.join(wal_dir, segs[-1])
        recs = segment_records(path)
        # Strictly mid-log: skip the CRC-seed record (index 0) and the
        # last record; payload-carrying records only (an empty payload
        # leaves nothing to flip).
        candidates = [r for r in recs[1:-1] if r[2] > 0]
        if not candidates:
            return -1, -1
        rng = self.disk.derived_rng(f"bitrot/{mid}")
        off, _rt, ln, _padded = rng.choice(candidates)
        byte_off = off + 12 + rng.randrange(ln)
        with open(path, "r+b") as f:
            f.seek(byte_off)
            b = f.read(1)
            f.seek(byte_off)
            f.write(bytes([b[0] ^ (1 << rng.randrange(8))]))
        _log.info("bit rot: member %d seg %s record at %d, byte %d "
                  "flipped", mid, segs[-1], off, byte_off)
        return off, byte_off

    def wait_fail_stop(self, mid: int, timeout: float = 20.0) -> str:
        """Wait for `mid` to die by the IO-error contract's fail-stop
        arm (crash-shaped death with a recorded cause); tears down its
        router like crash() does. Returns the recorded cause."""
        m = self.members[mid]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if m._stopped.is_set():
                break
            time.sleep(0.01)
        else:
            raise TimeoutError(f"member {mid} never fail-stopped")
        assert m._crashed, f"member {mid} stopped but not crash-style"
        assert m._fail_stop_cause, \
            f"member {mid} died without a fail-stop cause"
        router = self.routers.pop(mid, None)
        if router is not None:
            router.stop()
        return m._fail_stop_cause

    def failstop_envelope(self, mid: int) -> None:
        """Release-barrier audit for a fail-stopped member: replay its
        WAL host-side and assert every apply it ever RELEASED is
        covered by its durable log (checker.check_durability_envelope)
        — an apply escaping the failed fsync's window would put
        applied_index beyond what the log can replay. (Caveat: a
        snapshot install in flight at the kill can legally bump
        applied ahead of its record in pipeline mode; the
        deterministic fail-stop cells don't install snapshots.)"""
        from ..functional.checker import check_durability_envelope
        from ..native.walog import (
            WalogError,
            read_all_classified,
            salvage,
        )
        from .hosting import (
            RT_ENTRY,
            RT_ENTRY_BATCH,
            RT_SNAPSHOT,
            _iter_entry_batch,
            _unpack_entry,
            _unpack_snap,
        )

        m = self.members[mid]
        assert m._stopped.is_set(), "envelope audit needs a dead member"
        wal_dir = os.path.join(self.data_dir, f"member-{mid}", "wal")
        try:
            records, _ts = read_all_classified(wal_dir)
        except WalogError:
            assert salvage(wal_dir) is not None
            records, _ts = read_all_classified(wal_dir)
        durable: Dict[int, int] = {}
        for rtype, data, _seq, _meta in records:
            if rtype == RT_ENTRY:
                g, i, _t, _d, _et = _unpack_entry(data)
                durable[g] = max(durable.get(g, 0), i)
            elif rtype == RT_ENTRY_BATCH:
                for g, i, _t, _d, _et in _iter_entry_batch(data):
                    durable[g] = max(durable.get(g, 0), i)
            elif rtype == RT_SNAPSHOT:
                g, i, _t, _d, _et = _unpack_snap(data)
                durable[g] = max(durable.get(g, 0), i)
        applied = {g: int(a) for g, a in enumerate(m.applied_index)
                   if a > 0}
        check_durability_envelope(applied, durable)

    # -- workload --------------------------------------------------------------

    def wait_leaders(self, timeout: float = 60.0) -> np.ndarray:
        """Every group led by some live member (the shared
        campaign-nudge convergence loop from hosting.py, restricted to
        alive members)."""
        return wait_group_leaders(self.alive, self.g, timeout=timeout)

    def put(self, group: int, key: bytes, value: bytes,
            timeout: float = 10.0) -> bool:
        """Client write against whichever live member leads `group`;
        an ack (True) means the proposer applied it — i.e. the entry
        committed — and records it in the acked ledger. False = fate
        unknown (timeout), legitimately either committed or not.
        (Same propose/poll-apply retry discipline as
        MultiRaftCluster.put, which raises on timeout instead of
        returning False and keeps no ledger — under chaos a lost write
        is an expected outcome, not an error.)"""
        payload = GroupKV.put_payload(key, value)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for m in self.alive():
                if not m.propose(group, payload):
                    continue
                sub = min(deadline, time.monotonic() + 1.0)
                while time.monotonic() < sub:
                    if m.get(group, key) == value:
                        self.acked[(group, key)] = value
                        self.acked_history.setdefault(
                            (group, key), []).append(value)
                        return True
                    time.sleep(0.005)
            time.sleep(0.02)
        return False

    def run_workload(self, n_ops: int, prefix: bytes = b"w",
                     per_put_timeout: float = 8.0) -> int:
        """Seeded unique-key put stream over seed-chosen groups;
        returns the number of acked writes (the rest timed out under
        faults — allowed, their fate is unconstrained)."""
        rng = self.plan.derived_rng(f"workload/{prefix.decode()}")
        acked = 0
        for i in range(n_ops):
            g = rng.randrange(self.g)
            key = b"%s-%d" % (prefix, i)
            val = b"v%d-%d" % (self.seed, i)
            if self.put(g, key, val, timeout=per_put_timeout):
                acked += 1
        return acked

    def touch_all_groups(self, prefix: bytes = b"touch",
                         per_put_timeout: float = 10.0) -> int:
        """One put per group — a convergence pass after torn-tail
        recovery. Tearing bytes INSIDE the written (fsync'd, possibly
        acked) prefix voids the durability assumption the leader's
        progress tracker rests on: the leader still believes the torn
        member matches up to its pre-crash ack, so an idle group never
        gets re-replicated (there is no probe without traffic — real
        raft has the same hole, which is why real torn tails only ever
        lose UNsynced bytes). A write per group forces the append →
        reject → backtrack → resend cycle that re-heals every log."""
        acked = 0
        for g in range(self.g):
            if self.put(g, b"%s-g%d" % (prefix, g),
                        b"t%d" % self.seed, timeout=per_put_timeout):
                acked += 1
        return acked

    # -- membership churn (ISSUE 11) -------------------------------------------

    def reconfig_until(self, action: str, target: int,
                       groups=None, timeout: float = 60.0,
                       joint: bool = False) -> None:
        """Drive a membership `action` for member `target` across
        `groups` (default: all) until the change is APPLIED on each
        group's current leader — the retry loop a real operator runs
        under faults: "not-leader" redirects chase moving leaderships,
        "not-ready" waits out the learner catch-up gate, mid-joint
        refusals wait for auto-leave, and a leader that IS the removal
        target gets its leadership transferred away first."""
        groups = list(range(self.g)) if groups is None else \
            [int(g) for g in groups]
        t = int(target)
        pred = {
            "add-learner": lambda c, g: bool(c.learner[g, t - 1]),
            "promote": lambda c, g: bool(
                c.voter[g, t - 1] and not c.in_joint[g]),
            "remove": lambda c, g: bool(
                not c.voter[g, t - 1] and not c.learner[g, t - 1]
                and not c.in_joint[g]),
        }[action]
        pending = set(groups)
        deadline = time.monotonic() + timeout
        spin = 0
        # Re-propose a group's change only after a dwell: the apply
        # latency is rounds, the poll loop is 50ms, and every duplicate
        # proposal is a real log entry (refused idempotently at apply,
        # but churning the log and the joint windows for nothing).
        last_prop: Dict[int, float] = {}
        while pending:
            now = time.monotonic()
            for g in sorted(pending):
                for m in self.alive():
                    if not m.is_leader(g):
                        continue
                    # Predicate under the member's lock: conf applies
                    # are multi-step mutate-then-maybe-rollback, and an
                    # unlocked read can observe a half-entered joint
                    # (voter cleared, in_joint not yet set) as "done".
                    with m._lock:
                        satisfied = pred(m.conf, g)
                    if satisfied:
                        pending.discard(g)
                        break
                    if now - last_prop.get(g, -1e9) < 1.5:
                        break
                    last_prop[g] = now
                    res = m.reconfig(action, t, [g], joint=joint)[g]
                    if res == "self":
                        # Removing the leader itself: hand leadership
                        # to another voter first (etcd's discipline).
                        others = [o.id for o in self.alive()
                                  if o.id != t]
                        m.transfer_leader(
                            g, others[(g + spin) % len(others)])
                    break
            if not pending:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"reconfig {action} m{t}: groups {sorted(pending)[:8]} "
                    f"never converged")
            spin += 1
            time.sleep(0.05)

    def churn_member(self, mid: int, groups=None,
                     timeout_each: float = 60.0,
                     dwell: Optional[Callable[[], None]] = None) -> None:
        """One full decommission/re-admission cycle for `mid`: remove
        it as voter everywhere (joint-implicit change — enter-joint at
        apply, auto-leave on the joint commit), mark it config-removed
        on the fabric (frames drop like a crashed incarnation), run the
        optional `dwell` workload while it is out, then re-admit under
        a fresh incarnation token: add-as-learner → catch-up gate →
        promote back to voter. Ends at full membership, so strict
        checkers close."""
        self.reconfig_until("remove", mid, groups=groups,
                            timeout=timeout_each, joint=True)
        if groups is None:
            self.mark_removed(mid)
        if dwell is not None:
            dwell()
        if groups is None:
            self.mark_rejoined(mid)
        self.reconfig_until("add-learner", mid, groups=groups,
                            timeout=timeout_each)
        self.reconfig_until("promote", mid, groups=groups,
                            timeout=timeout_each, joint=True)

    def dump_flight_recorders(self, reason: str = "chaos") -> List[str]:
        """Dump every live member's telemetry flight recorder, fleet
        heatmap ring AND trace-span ring (no-ops for whichever plane
        is off); returns the paths. All three share the obs.artifacts
        naming scheme, so simultaneous multi-member dumps never
        overwrite each other."""
        paths = []
        for m in self.members.values():
            hub = getattr(m, "hub", None)
            if hub is not None:
                try:
                    paths.append(hub.dump(reason=reason))
                except OSError:
                    _log.exception("flight-recorder dump failed (m%d)",
                                   m.id)
            fleet = getattr(m, "fleet", None)
            if fleet is not None:
                try:
                    paths.append(fleet.dump(reason=reason))
                except OSError:
                    _log.exception("fleet-heatmap dump failed (m%d)",
                                   m.id)
            tracer = getattr(m, "tracer", None)
            if tracer is not None:
                try:
                    paths.append(tracer.dump(reason=reason))
                except OSError:
                    _log.exception("trace-ring dump failed (m%d)", m.id)
        return paths

    def invariant_trips(self) -> int:
        """Total on-device invariant trips across members — including
        members since replaced by a restart (0 when telemetry is off).
        Episodes assert this stays 0."""
        return self._retired_trips + sum(
            m.hub.trips() for m in self.members.values()
            if getattr(m, "hub", None) is not None
        )

    def stop(self) -> None:
        self.fabric.stop()
        for m in self.members.values():
            m.stop()
        for r in self.routers.values():
            r.stop()


def run_invariant_checks(harness: ChaosHarness,
                         observer: Optional[LeaderObserver],
                         expect_members: int,
                         hash_timeout: float = 45.0,
                         acked_timeout: float = 20.0,
                         allow_lag: int = 0) -> None:
    """Episode closer: the three chaos checkers in canonical order —
    per-group KV-hash parity, committed-never-lost, then (when an
    observer ran) at-most-one-leader-per-(group, term). Since ISSUE 5
    every episode class — torn tail included — closes STRICT
    (allow_lag=0, observer on): the durability fence keeps a member
    that verifiably lost fsync'd-acked bytes out of elections until it
    re-converges, which removes the one mechanism that made torn-tail
    divergence legal.

    ``allow_lag=1`` (legacy) relaxes both state checkers to quorum
    agreement — the pre-fence accommodation for torn-tail episodes:
    tearing fsync'd acked bytes let the torn member win an election
    with its shortened log and force a survivor to overwrite an entry
    it had already COMMITTED AND APPLIED, a KV divergence no protocol
    heals after the fact (root-caused with the ISSUE 4 flight
    recorder — the leader's match oscillates against the survivor's
    below-commit fast-path ack at the conflicted commit index). The
    knob remains for fence-disabled runs
    (tools/repro_progress_wedge.py --torn-acked keeps the failure
    demonstrable against ChaosHarness(fence=False)).

    When the harness flies with telemetry (the default config), the
    closer also asserts the on-device invariant sweep stayed clean —
    ZERO illegal-progress trips across every member and round. The
    pre-fix progress wedge trips `next_le_match`/`probe_wedge`
    persistently, so this is the regression tripwire for wedge-class
    kernel bugs even under relaxed state checks."""
    # Lazy: the checkers module pulls in the server stack, which the
    # batched package must not import at module load.
    from ..functional.checker import (
        check_leader_claims,
        committed_never_lost,
        multiraft_hash_check,
    )

    members = harness.alive()
    assert len(members) == expect_members, (
        f"{len(members)} members alive at episode close, "
        f"want {expect_members}")
    try:
        multiraft_hash_check(members, timeout=hash_timeout,
                             allow_lag=allow_lag)
        committed_never_lost(members, harness.acked,
                             timeout=acked_timeout,
                             allow_lag=allow_lag,
                             history=harness.acked_history)
        if observer is not None:
            observer.stop()
            check_leader_claims(observer.conflicts)
        trips = harness.invariant_trips()
        assert trips == 0, (
            f"{trips} on-device invariant trips during the episode — "
            "illegal kernel progress state (see the flight-recorder "
            "dumps in artifacts/)")
    except AssertionError:
        # Checker failure: freeze the evidence. Every member's flight
        # recorder (last K rounds of per-group kernel deltas + the
        # invariant sweep) lands in artifacts/flightrec_*.json before
        # the failure propagates.
        paths = harness.dump_flight_recorders(reason="checker-failure")
        if paths:
            _log.error("chaos checker failed; flight recorders: %s",
                       paths)
        raise
