"""Pallas TPU kernels for the batched engine's replica/window
reductions (SURVEY §7.3: the fused step(+quorum) kernel study).

Two fused kernels, batched over the instance axis N (laid out
lanes-minor, [R|W, N], so the 128-wide vector lanes fill with
instances — the same layout argument as BatchedConfig.lanes_minor):

* ``quorum_commit_vote`` — joint-config commit index AND vote result
  in one VMEM pass (ref: raft/quorum/majority.go:126-210,
  joint.go:49-75). The commit index uses the quorum-support
  formulation (the reference's cross-checked alternative definition,
  quorum/quick_test.go:85): the largest candidate match value
  supported by ≥ n//2+1 voters — an O(R²) elementwise form with no
  sort, which is what the VPU wants.
* ``term_at_batch`` — ring term lookup as a one-hot compare+reduce
  over the window axis (ref: the zero-term-outside-bounds contract of
  raft/log.go term()).

Both run under ``interpret=True`` on CPU for differential testing
against the XLA forms in kernels.py; on TPU they compile natively.
Integration into the round kernel is gated on TPU measurement (see
BENCH_NOTES.md): the XLA forms already fuse well, so the Pallas forms
must beat them on-device before they take over the hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .kernels import MAX_I32, VOTE_LOST, VOTE_PENDING, VOTE_WON
from .state import I32

_TILE_N = 512  # lane-axis tile: instances per grid step (multiple of 128)


def _committed_block(match, mask):
    """[R, T] masked quorum commit per lane column (support form)."""
    r = match.shape[0]
    n = jnp.sum(mask.astype(I32), axis=0, keepdims=True)  # [1, T]
    q = n // 2 + 1
    masked = jnp.where(mask, match, 0)
    best = jnp.zeros_like(masked[:1])
    for j in range(r):  # static unroll over the replica axis
        c = masked[j : j + 1]  # [1, T]
        support = jnp.sum(
            (mask & (match >= c)).astype(I32), axis=0, keepdims=True
        )
        best = jnp.maximum(best, jnp.where(support >= q, c, 0))
    return jnp.where(n == 0, MAX_I32, best)


def _vote_block(votes, mask):
    """[R, T] masked vote tally per lane column."""
    n = jnp.sum(mask.astype(I32), axis=0, keepdims=True)
    yes = jnp.sum((mask & (votes == 1)).astype(I32), axis=0, keepdims=True)
    no = jnp.sum((mask & (votes == 0)).astype(I32), axis=0, keepdims=True)
    missing = n - yes - no
    q = n // 2 + 1
    won = (yes >= q) | (n == 0)
    pending = yes + missing >= q
    return jnp.where(won, VOTE_WON, jnp.where(pending, VOTE_PENDING,
                                              VOTE_LOST))


def _quorum_kernel(match_ref, voter_ref, vout_ref, joint_ref, votes_ref,
                   commit_ref, vres_ref):
    match = match_ref[:]
    voter = voter_ref[:] != 0
    vout = vout_ref[:] != 0
    joint = joint_ref[:] != 0  # [1, T]
    votes = votes_ref[:]

    cm = _committed_block(match, voter)
    cj = jnp.minimum(cm, _committed_block(match, vout))
    commit_ref[:] = jnp.where(joint, cj, cm)

    a = _vote_block(votes, voter)
    b = jnp.where(joint, _vote_block(votes, vout), VOTE_WON)
    lost = (a == VOTE_LOST) | (b == VOTE_LOST)
    pending = (a == VOTE_PENDING) | (b == VOTE_PENDING)
    vres_ref[:] = jnp.where(lost, VOTE_LOST,
                            jnp.where(pending, VOTE_PENDING, VOTE_WON))


@functools.partial(jax.jit, static_argnames=("interpret",))
def quorum_commit_vote(match, voter, voter_out, in_joint, votes,
                       interpret: bool = False):
    """Fused joint commit index + vote result over [N, R] inputs.

    match [N, R] i32; voter/voter_out [N, R] bool; in_joint [N] bool;
    votes [N, R] i32 (-1 missing / 0 rejected / 1 granted).
    Returns (commit [N] i32, vote_result [N] i32)."""
    n, r = match.shape
    # Lanes-minor layout: [R, N] so N fills the vector lanes.
    mt = match.T.astype(I32)
    vt = voter.T.astype(I32)
    vo = voter_out.T.astype(I32)
    jt = in_joint.reshape(1, n).astype(I32)
    vs = votes.T.astype(I32)

    grid = (pl.cdiv(n, _TILE_N),)
    row_spec = pl.BlockSpec((r, _TILE_N), lambda i: (0, i))
    one_spec = pl.BlockSpec((1, _TILE_N), lambda i: (0, i))
    commit, vres = pl.pallas_call(
        _quorum_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((1, n), I32),
            jax.ShapeDtypeStruct((1, n), I32),
        ),
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, one_spec, row_spec],
        out_specs=(one_spec, one_spec),
        interpret=interpret,
    )(mt, vt, vo, jt, vs)
    return commit[0], vres[0]


def _term_kernel(log_ref, snapi_ref, snapt_ref, last_ref, idx_ref,
                 out_ref):
    log = log_ref[:]  # [W, T]
    snapi = snapi_ref[:]  # [1, T]
    snapt = snapt_ref[:]
    last = last_ref[:]
    idx = idx_ref[:]

    w = log.shape[0]
    rows = jax.lax.broadcasted_iota(I32, log.shape, 0)
    im = jnp.where(idx >= 0, idx % w, 0)
    ring_val = jnp.sum(jnp.where(rows == im, log, 0), axis=0,
                       keepdims=True)
    in_ring = (idx > snapi) & (idx <= last)
    out_ref[:] = jnp.where(
        idx == snapi, snapt, jnp.where(in_ring, ring_val, 0)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def term_at_batch(log_term, snap_index, snap_term, last, idx,
                  interpret: bool = False):
    """Ring term of entry ``idx[i]`` per instance, 0 outside
    (snap_index, last] and snap_term at the floor itself.

    log_term [N, W] i32; snap_index/snap_term/last/idx [N] i32.
    Returns term [N] i32."""
    n, w = log_term.shape
    assert w <= 2048, "window larger than one VMEM block"
    lt = log_term.T.astype(I32)  # [W, N]
    row = lambda x: x.reshape(1, n).astype(I32)

    grid = (pl.cdiv(n, _TILE_N),)
    log_spec = pl.BlockSpec((w, _TILE_N), lambda i: (0, i))
    one_spec = pl.BlockSpec((1, _TILE_N), lambda i: (0, i))
    out = pl.pallas_call(
        _term_kernel,
        out_shape=jax.ShapeDtypeStruct((1, n), I32),
        grid=grid,
        in_specs=[log_spec, one_spec, one_spec, one_spec, one_spec],
        out_specs=one_spec,
        interpret=interpret,
    )(lt, row(snap_index), row(snap_term), row(last), row(idx))
    return out[0]
