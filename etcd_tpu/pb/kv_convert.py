"""server.api KV dataclasses <-> etcdserverpb wire messages.

proto3 (like the reference's rpc.proto): zero-valued scalars are
omitted on the wire by BOTH the reference's gogo marshaler and python
protobuf, so no explicit-presence discipline is needed here (contrast
convert.py for the proto2 raftpb layer).
"""

from __future__ import annotations

from ..server.api import (
    Compare,
    CompareResult,
    CompareTarget,
    LeaseGrantRequest,
    LeaseGrantResponse,
    LeaseRevokeRequest,
    DeleteRangeRequest,
    DeleteRangeResponse,
    KeyValue,
    PutRequest,
    PutResponse,
    RangeRequest,
    RangeResponse,
    RequestOp,
    ResponseHeader,
    ResponseOp,
    SortOrder,
    SortTarget,
    TxnRequest,
    TxnResponse,
)
from . import kv_pb2 as kpb


def kv_to_pb(kv: KeyValue) -> "kpb.KeyValue":
    return kpb.KeyValue(
        key=kv.key, create_revision=kv.create_revision,
        mod_revision=kv.mod_revision, version=kv.version,
        value=kv.value, lease=kv.lease,
    )


def kv_from_pb(p: "kpb.KeyValue") -> KeyValue:
    return KeyValue(
        key=p.key, create_revision=p.create_revision,
        mod_revision=p.mod_revision, version=p.version,
        value=p.value, lease=p.lease,
    )


def header_to_pb(h: ResponseHeader) -> "kpb.ResponseHeader":
    return kpb.ResponseHeader(
        cluster_id=h.cluster_id, member_id=h.member_id,
        revision=h.revision, raft_term=h.raft_term,
    )


def header_from_pb(p: "kpb.ResponseHeader") -> ResponseHeader:
    return ResponseHeader(
        cluster_id=p.cluster_id, member_id=p.member_id,
        revision=p.revision, raft_term=p.raft_term,
    )


def put_request_to_pb(r: PutRequest) -> "kpb.PutRequest":
    return kpb.PutRequest(
        key=r.key, value=r.value, lease=r.lease, prev_kv=r.prev_kv,
        ignore_value=r.ignore_value, ignore_lease=r.ignore_lease,
    )


def put_request_from_pb(p: "kpb.PutRequest") -> PutRequest:
    return PutRequest(
        key=p.key, value=p.value, lease=p.lease, prev_kv=p.prev_kv,
        ignore_value=p.ignore_value, ignore_lease=p.ignore_lease,
    )


def put_response_to_pb(r: PutResponse) -> "kpb.PutResponse":
    out = kpb.PutResponse(header=header_to_pb(r.header))
    if r.prev_kv is not None:  # oneof-like presence: only set if given
        out.prev_kv.CopyFrom(kv_to_pb(r.prev_kv))
    return out


def put_response_from_pb(p: "kpb.PutResponse") -> PutResponse:
    return PutResponse(
        header=header_from_pb(p.header),
        prev_kv=kv_from_pb(p.prev_kv) if p.HasField("prev_kv") else None,
    )


def range_request_to_pb(r: RangeRequest) -> "kpb.RangeRequest":
    return kpb.RangeRequest(
        key=r.key, range_end=r.range_end, limit=r.limit,
        revision=r.revision, sort_order=int(r.sort_order),
        sort_target=int(r.sort_target), serializable=r.serializable,
        keys_only=r.keys_only, count_only=r.count_only,
        min_mod_revision=r.min_mod_revision,
        max_mod_revision=r.max_mod_revision,
        min_create_revision=r.min_create_revision,
        max_create_revision=r.max_create_revision,
    )


def _enum(cls, val, default):
    """proto3 enums are OPEN: unknown wire values parse fine and must
    not crash the decode path — fall back to the default (the
    reference's Go handlers see the raw int and likewise do not
    reject at decode time)."""
    try:
        return cls(val)
    except ValueError:
        return default


def range_request_from_pb(p: "kpb.RangeRequest") -> RangeRequest:
    return RangeRequest(
        key=p.key, range_end=p.range_end, limit=p.limit,
        revision=p.revision,
        sort_order=_enum(SortOrder, p.sort_order, SortOrder.NONE),
        sort_target=_enum(SortTarget, p.sort_target, SortTarget.KEY),
        serializable=p.serializable, keys_only=p.keys_only,
        count_only=p.count_only,
        min_mod_revision=p.min_mod_revision,
        max_mod_revision=p.max_mod_revision,
        min_create_revision=p.min_create_revision,
        max_create_revision=p.max_create_revision,
    )


def range_response_to_pb(r: RangeResponse) -> "kpb.RangeResponse":
    return kpb.RangeResponse(
        header=header_to_pb(r.header), more=r.more, count=r.count,
        kvs=[kv_to_pb(kv) for kv in r.kvs])


def range_response_from_pb(p: "kpb.RangeResponse") -> RangeResponse:
    return RangeResponse(
        header=header_from_pb(p.header),
        kvs=[kv_from_pb(kv) for kv in p.kvs],
        more=p.more, count=p.count,
    )


def delete_request_to_pb(r: DeleteRangeRequest) -> "kpb.DeleteRangeRequest":
    return kpb.DeleteRangeRequest(
        key=r.key, range_end=r.range_end, prev_kv=r.prev_kv)


def delete_request_from_pb(p: "kpb.DeleteRangeRequest") -> DeleteRangeRequest:
    return DeleteRangeRequest(
        key=p.key, range_end=p.range_end, prev_kv=p.prev_kv)


def delete_response_to_pb(r: DeleteRangeResponse) -> "kpb.DeleteRangeResponse":
    return kpb.DeleteRangeResponse(
        header=header_to_pb(r.header), deleted=r.deleted,
        prev_kvs=[kv_to_pb(kv) for kv in r.prev_kvs])


def delete_response_from_pb(p: "kpb.DeleteRangeResponse") -> DeleteRangeResponse:
    return DeleteRangeResponse(
        header=header_from_pb(p.header), deleted=p.deleted,
        prev_kvs=[kv_from_pb(kv) for kv in p.prev_kvs],
    )


def compare_to_pb(c: Compare) -> "kpb.Compare":
    out = kpb.Compare(result=int(c.result), target=int(c.target),
                      key=c.key)
    if c.range_end:
        out.range_end = c.range_end
    # The oneof member matching `target` carries the operand (how the
    # reference's clientv3 builds Compare, clientv3/compare.go).
    t = c.target
    if t == CompareTarget.VERSION:
        out.version = c.version
    elif t == CompareTarget.CREATE:
        out.create_revision = c.create_revision
    elif t == CompareTarget.MOD:
        out.mod_revision = c.mod_revision
    elif t == CompareTarget.VALUE:
        out.value = c.value
    elif t == CompareTarget.LEASE:
        out.lease = c.lease
    return out


def compare_from_pb(p: "kpb.Compare") -> Compare:
    c = Compare(
        result=_enum(CompareResult, p.result, CompareResult.EQUAL),
        target=_enum(CompareTarget, p.target, CompareTarget.VERSION),
        key=p.key, range_end=p.range_end,
    )
    which = p.WhichOneof("target_union")
    if which is not None:
        setattr(c, which, getattr(p, which))
    return c


def request_op_to_pb(op: RequestOp) -> "kpb.RequestOp":
    out = kpb.RequestOp()
    if op.request_range is not None:
        out.request_range.CopyFrom(range_request_to_pb(op.request_range))
    elif op.request_put is not None:
        out.request_put.CopyFrom(put_request_to_pb(op.request_put))
    elif op.request_delete_range is not None:
        out.request_delete_range.CopyFrom(
            delete_request_to_pb(op.request_delete_range))
    elif op.request_txn is not None:
        out.request_txn.CopyFrom(txn_request_to_pb(op.request_txn))
    return out


def request_op_from_pb(p: "kpb.RequestOp") -> RequestOp:
    which = p.WhichOneof("request")
    if which == "request_range":
        return RequestOp(request_range=range_request_from_pb(p.request_range))
    if which == "request_put":
        return RequestOp(request_put=put_request_from_pb(p.request_put))
    if which == "request_delete_range":
        return RequestOp(request_delete_range=delete_request_from_pb(
            p.request_delete_range))
    if which == "request_txn":
        return RequestOp(request_txn=txn_request_from_pb(p.request_txn))
    return RequestOp()


def response_op_to_pb(op: ResponseOp) -> "kpb.ResponseOp":
    out = kpb.ResponseOp()
    if op.response_range is not None:
        out.response_range.CopyFrom(
            range_response_to_pb(op.response_range))
    elif op.response_put is not None:
        out.response_put.CopyFrom(put_response_to_pb(op.response_put))
    elif op.response_delete_range is not None:
        out.response_delete_range.CopyFrom(
            delete_response_to_pb(op.response_delete_range))
    elif op.response_txn is not None:
        out.response_txn.CopyFrom(txn_response_to_pb(op.response_txn))
    return out


def response_op_from_pb(p: "kpb.ResponseOp") -> ResponseOp:
    which = p.WhichOneof("response")
    if which == "response_range":
        return ResponseOp(
            response_range=range_response_from_pb(p.response_range))
    if which == "response_put":
        return ResponseOp(response_put=put_response_from_pb(p.response_put))
    if which == "response_delete_range":
        return ResponseOp(response_delete_range=delete_response_from_pb(
            p.response_delete_range))
    if which == "response_txn":
        return ResponseOp(
            response_txn=txn_response_from_pb(p.response_txn))
    return ResponseOp()


def txn_request_to_pb(r: TxnRequest) -> "kpb.TxnRequest":
    return kpb.TxnRequest(
        compare=[compare_to_pb(c) for c in r.compare],
        success=[request_op_to_pb(op) for op in r.success],
        failure=[request_op_to_pb(op) for op in r.failure])


def txn_request_from_pb(p: "kpb.TxnRequest") -> TxnRequest:
    return TxnRequest(
        compare=[compare_from_pb(c) for c in p.compare],
        success=[request_op_from_pb(op) for op in p.success],
        failure=[request_op_from_pb(op) for op in p.failure],
    )


def txn_response_to_pb(r: TxnResponse) -> "kpb.TxnResponse":
    return kpb.TxnResponse(
        header=header_to_pb(r.header), succeeded=r.succeeded,
        responses=[response_op_to_pb(op) for op in r.responses])


def txn_response_from_pb(p: "kpb.TxnResponse") -> TxnResponse:
    return TxnResponse(
        header=header_from_pb(p.header), succeeded=p.succeeded,
        responses=[response_op_from_pb(op) for op in p.responses],
    )


# -- watch / lease -------------------------------------------------------------

def mvcc_kv_to_pb(kv) -> "kpb.KeyValue":
    # mvcc and server.api KeyValue are field-identical dataclasses, so
    # kv_to_pb serves both (duck-typed) — one copy site.
    return kv_to_pb(kv)


def mvcc_kv_from_pb(p: "kpb.KeyValue"):
    from ..storage.mvcc.kv import KeyValue as MvccKV

    k = kv_from_pb(p)
    return MvccKV(key=k.key, create_revision=k.create_revision,
                  mod_revision=k.mod_revision, version=k.version,
                  value=k.value, lease=k.lease)


def event_to_pb(ev) -> "kpb.Event":
    """mvcc.Event -> mvccpb.Event wire message."""
    out = kpb.Event(type=int(ev.type), kv=mvcc_kv_to_pb(ev.kv))
    if ev.prev_kv is not None:
        out.prev_kv.CopyFrom(mvcc_kv_to_pb(ev.prev_kv))
    return out


def event_from_pb(p: "kpb.Event"):
    from ..storage.mvcc.kv import Event, EventType

    return Event(
        type=EventType(p.type), kv=mvcc_kv_from_pb(p.kv),
        prev_kv=(mvcc_kv_from_pb(p.prev_kv)
                 if p.HasField("prev_kv") else None),
    )


def watch_events_to_pb(header: ResponseHeader, watch_id: int,
                       events) -> "kpb.WatchResponse":
    """One watch-stream delivery as an etcdserverpb WatchResponse."""
    return kpb.WatchResponse(
        header=header_to_pb(header), watch_id=watch_id,
        events=[event_to_pb(ev) for ev in events])


def lease_grant_request_to_pb(r) -> "kpb.LeaseGrantRequest":
    return kpb.LeaseGrantRequest(TTL=r.ttl, ID=r.id)


def lease_grant_request_from_pb(p: "kpb.LeaseGrantRequest"):
    return LeaseGrantRequest(ttl=p.TTL, id=p.ID)


def lease_grant_response_to_pb(r) -> "kpb.LeaseGrantResponse":
    return kpb.LeaseGrantResponse(
        header=header_to_pb(r.header), ID=r.id, TTL=r.ttl,
        error=r.error)


def lease_grant_response_from_pb(p: "kpb.LeaseGrantResponse"):
    return LeaseGrantResponse(header=header_from_pb(p.header), id=p.ID,
                              ttl=p.TTL, error=p.error)


def lease_revoke_request_to_pb(r) -> "kpb.LeaseRevokeRequest":
    return kpb.LeaseRevokeRequest(ID=r.id)


def lease_revoke_request_from_pb(p: "kpb.LeaseRevokeRequest"):
    return LeaseRevokeRequest(id=p.ID)
