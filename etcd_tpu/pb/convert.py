"""Dataclass wire types <-> reference-wire-compatible protobuf.

Every field the reference declares ``(gogoproto.nullable) = false`` is
set EXPLICITLY (including zeros): gogo's generated marshaler emits
those fields unconditionally (ref: raft/raftpb/raft.pb.go
MarshalToSizedBuffer), and matching that makes our serialization
byte-for-byte identical to Go's for the same logical message — a
property the golden tests pin down.
"""

from __future__ import annotations

from ..raft.types import (
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
)
from . import raft_pb2 as pb


def entry_to_pb(e: Entry) -> "pb.Entry":
    out = pb.Entry()
    out.Type = int(e.type)
    out.Term = e.term
    out.Index = e.index
    if e.data:
        out.Data = e.data
    return out


def entry_from_pb(p: "pb.Entry") -> Entry:
    return Entry(index=p.Index, term=p.Term, type=EntryType(p.Type),
                 data=p.Data)


def _confstate_to_pb(cs: ConfState) -> "pb.ConfState":
    out = pb.ConfState()
    out.voters.extend(cs.voters)
    out.learners.extend(cs.learners)
    out.voters_outgoing.extend(cs.voters_outgoing)
    out.learners_next.extend(cs.learners_next)
    out.auto_leave = cs.auto_leave
    return out


def _confstate_from_pb(p: "pb.ConfState") -> ConfState:
    return ConfState(
        voters=list(p.voters),
        learners=list(p.learners),
        voters_outgoing=list(p.voters_outgoing),
        learners_next=list(p.learners_next),
        auto_leave=p.auto_leave,
    )


def snapshot_to_pb(s: Snapshot) -> "pb.Snapshot":
    out = pb.Snapshot()
    if s.data:
        out.data = s.data
    out.metadata.conf_state.CopyFrom(
        _confstate_to_pb(s.metadata.conf_state))
    out.metadata.index = s.metadata.index
    out.metadata.term = s.metadata.term
    return out


def snapshot_from_pb(p: "pb.Snapshot") -> Snapshot:
    return Snapshot(
        data=p.data,
        metadata=SnapshotMetadata(
            conf_state=_confstate_from_pb(p.metadata.conf_state),
            index=p.metadata.index,
            term=p.metadata.term,
        ),
    )


def hardstate_to_pb(hs: HardState) -> "pb.HardState":
    out = pb.HardState()
    out.term = hs.term
    out.vote = hs.vote
    out.commit = hs.commit
    return out


def hardstate_from_pb(p: "pb.HardState") -> HardState:
    return HardState(term=p.term, vote=p.vote, commit=p.commit)


def message_to_pb(m: Message) -> "pb.Message":
    out = pb.Message()
    out.type = int(m.type)
    out.to = m.to
    setattr(out, "from", m.from_)  # 'from' is a Python keyword
    out.term = m.term
    out.logTerm = m.log_term
    out.index = m.index
    for e in m.entries:
        out.entries.append(entry_to_pb(e))
    out.commit = m.commit
    out.snapshot.CopyFrom(snapshot_to_pb(m.snapshot))
    out.reject = m.reject
    out.rejectHint = m.reject_hint
    if m.context:
        out.context = m.context
    return out


def message_from_pb(p: "pb.Message") -> Message:
    return Message(
        type=MessageType(p.type),
        to=p.to,
        from_=getattr(p, "from"),
        term=p.term,
        log_term=p.logTerm,
        index=p.index,
        entries=[entry_from_pb(e) for e in p.entries],
        commit=p.commit,
        snapshot=snapshot_from_pb(p.snapshot),
        reject=p.reject,
        reject_hint=p.rejectHint,
        context=p.context,
    )


def message_to_bytes(m: Message) -> bytes:
    return message_to_pb(m).SerializeToString()


def message_from_bytes(b: bytes) -> Message:
    return message_from_pb(pb.Message.FromString(b))


def confchange_to_pb(cc) -> "pb.ConfChange":
    out = pb.ConfChange()
    out.id = cc.id
    out.type = int(cc.type)
    out.node_id = cc.node_id
    if cc.context:
        out.context = cc.context
    return out


def confchange_from_pb(p: "pb.ConfChange"):
    from ..raft.types import ConfChange, ConfChangeType

    return ConfChange(id=p.id, type=ConfChangeType(p.type),
                      node_id=p.node_id, context=p.context)


def confchange_v2_to_pb(cc2) -> "pb.ConfChangeV2":
    out = pb.ConfChangeV2()
    out.transition = int(cc2.transition)
    for ch in cc2.changes:
        out.changes.add(type=int(ch.type), node_id=ch.node_id)
    if cc2.context:
        out.context = cc2.context
    return out


def confchange_v2_from_pb(p: "pb.ConfChangeV2"):
    from ..raft.types import (
        ConfChangeSingle,
        ConfChangeTransition,
        ConfChangeType,
        ConfChangeV2,
    )

    return ConfChangeV2(
        transition=ConfChangeTransition(p.transition),
        changes=[ConfChangeSingle(type=ConfChangeType(c.type),
                                  node_id=c.node_id)
                 for c in p.changes],
        context=p.context,
    )
