"""Wire-compatible raftpb message layer.

``raft_pb2`` is protoc-generated from ``raft.proto`` — a schema whose
field numbers replicate the reference's
``raft/raftpb/raft.proto`` (field numbers ARE the wire contract;
the gogoproto/versionpb options there are codegen-only and do not
affect the encoding). ``convert`` maps this repo's dataclass wire
types to/from the protobuf messages, emitting every non-nullable
field explicitly — the reference's gogo marshaler writes them
unconditionally, so explicit presence makes our bytes equal
byte-for-byte to Go's for the same logical message (decoding is
forgiving in both directions regardless).

``kv_pb2``/``kv_convert`` do the same for the etcdserverpb KV client
subset (KeyValue/ResponseHeader/Range/Put/DeleteRange plus the Txn
family: Compare with its target_union oneof, RequestOp/ResponseOp
unions, nested TxnRequest recursion — proto3, where
zero scalars are omitted by both sides, so no presence discipline is
needed). This closes the MESSAGE half of ecosystem interop; gRPC
transport framing remains descoped (README "Wire interop").
"""

from . import kv_pb2, raft_pb2  # noqa: F401
from . import kv_convert  # noqa: F401
from .convert import (  # noqa: F401
    confchange_from_pb,
    confchange_to_pb,
    confchange_v2_from_pb,
    confchange_v2_to_pb,
    entry_from_pb,
    entry_to_pb,
    hardstate_from_pb,
    hardstate_to_pb,
    message_from_bytes,
    message_from_pb,
    message_to_bytes,
    message_to_pb,
    snapshot_from_pb,
    snapshot_to_pb,
)
