"""KV value model + range options (analog of api/mvccpb/kv.proto and
server/storage/mvcc/kv.go RangeOptions/RangeResult)."""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional


class EventType(IntEnum):
    PUT = 0
    DELETE = 1


@dataclass
class KeyValue:
    key: bytes = b""
    create_revision: int = 0
    mod_revision: int = 0
    version: int = 0
    value: bytes = b""
    lease: int = 0

    _HDR = struct.Struct("<QQQq II")  # create, mod, version, lease, klen, vlen

    def marshal(self) -> bytes:
        return self._HDR.pack(
            self.create_revision, self.mod_revision, self.version,
            self.lease, len(self.key), len(self.value)
        ) + self.key + self.value

    @classmethod
    def unmarshal(cls, data: bytes) -> "KeyValue":
        cr, mr, ver, lease, klen, vlen = cls._HDR.unpack_from(data)
        off = cls._HDR.size
        return cls(
            key=data[off:off + klen],
            create_revision=cr,
            mod_revision=mr,
            version=ver,
            value=data[off + klen:off + klen + vlen],
            lease=lease,
        )


@dataclass
class Event:
    type: EventType = EventType.PUT
    kv: KeyValue = field(default_factory=KeyValue)
    prev_kv: Optional[KeyValue] = None


@dataclass
class RangeOptions:
    limit: int = 0
    rev: int = 0
    count_only: bool = False


@dataclass
class RangeResult:
    kvs: List[KeyValue]
    rev: int
    count: int
